"""All command state transitions, as static functions over a SafeCommandStore.

Reference: accord/local/Commands.java — preaccept (:131), accept (:219),
acceptInvalidate (:267), commit (:306), precommit (:371), commitInvalidate
(:463), apply (:491), maybeExecute (:656), initialiseWaitingOn (:735),
updateWaitingOn (:776), updateDependencyAndMaybeExecute (:832), truncation
(:879-967), setDurability (:978).

The WaitingOn graph walk these functions drive is north-star kernel #2: the
batched device equivalent (topological wavefront over the conflict graph) lives
in accord_tpu.ops.wavefront with this scalar path as its oracle.
"""

from __future__ import annotations

import enum
from collections import deque
from typing import Callable, List, Optional, Tuple

from accord_tpu.local.cfk import InternalStatus
from accord_tpu.local.command import (Command, WaitingOn,
                                      note_status_transition)
from accord_tpu.local.status import Durability, SaveStatus
from accord_tpu.local.store import SafeCommandStore
from accord_tpu.primitives.deps import Deps, KeyDeps
from accord_tpu.primitives.keys import Key, Keys, Ranges, Route
from accord_tpu.primitives.timestamp import Ballot, Timestamp, TxnId
from accord_tpu.primitives.txn import PartialTxn
from accord_tpu.primitives.writes import Writes
from accord_tpu.utils import invariants

# Scalar-walk work counters (the Commands.java:656,1011 walk the device
# wavefront planner aims to displace): incremented process-wide, reset by
# measurement harnesses per run (measure_device.py A/B evidence).
WORK = {"maybe_execute": 0, "notify": 0}


def reset_work_counters() -> None:
    WORK["maybe_execute"] = 0
    WORK["notify"] = 0


class AcceptOutcome(enum.Enum):
    SUCCESS = "SUCCESS"
    REDUNDANT = "REDUNDANT"          # already progressed past this phase
    REJECTED_BALLOT = "REJECTED_BALLOT"
    INSUFFICIENT = "INSUFFICIENT"
    TRUNCATED = "TRUNCATED"


class ApplyOutcome(enum.Enum):
    SUCCESS = "SUCCESS"
    REDUNDANT = "REDUNDANT"
    INSUFFICIENT = "INSUFFICIENT"


# ---------------------------------------------------------------- deps calc --

def calculate_deps(safe_store: SafeCommandStore, txn_id: TxnId, participants,
                   before: Timestamp) -> Deps:
    """Dependency set for txn_id over `participants` (Keys or Ranges, owned
    slice): every active conflicting txn with id < `before`
    (PreAccept.calculatePartialDeps -> CommandsForKey.mapReduceActive).
    Key-domain conflicts land in KeyDeps; range-domain conflicts in RangeDeps
    keyed by the overlap (reference Deps.Builder domain split)."""
    from accord_tpu.primitives.deps import RangeDeps
    builder = KeyDeps.builder()
    rbuilder = RangeDeps.builder()
    kinds = txn_id.kind.witnesses()

    def visit(key: Key, dep: TxnId):
        if dep != txn_id:
            builder.add(key, dep)

    def visit_range(overlap: Ranges, dep: TxnId):
        if dep != txn_id:
            for r in overlap:
                rbuilder.add(r, dep)

    # cfk stage fence (obs/cpuprof.py): the active-conflict scan is the
    # per-key conflict-index walk PAPER.md singles out as the hot kernel —
    # attribute it separately from the rest of the apply
    prof = safe_store.store.cpuprof
    t = prof.stage_begin() if prof is not None and prof.active else None
    safe_store.map_reduce_active(participants, before, kinds, visit,
                                 on_range_dep=visit_range, exclude=txn_id)
    if t is not None:
        prof.stage_end(t, "cfk")
    return Deps(builder.build(), rbuilder.build())


def propose_execute_at(safe_store: SafeCommandStore, txn_id: TxnId,
                       participants, permit_fast_path: bool,
                       permit_expiry: bool = True) -> Timestamp:
    """executeAt proposal (CommandStore.preaccept :320-345): txn_id itself when
    no conflict is newer AND the fast path is permitted (ballot zero — recovery
    must not mint fast-path votes — and txn_id's epoch is current), else a
    fresh HLC strictly after every known conflict."""
    node = safe_store.node
    # preaccept expiry: stale-clocked coordinators get a REJECTED proposal the
    # coordinator turns into invalidation (CommandStore.preaccept isExpired);
    # never applied to recovery witnesses — the txn may be long since decided
    if permit_expiry and not txn_id.kind.is_sync_point:
        elapsed_us = node.now_us() - txn_id.hlc
        if elapsed_us >= safe_store.agent.pre_accept_timeout() * 1e6:
            return node.unique_now_at_least(txn_id).as_rejected()
    max_conflict = safe_store.max_conflict(participants)
    if (max_conflict is None or max_conflict < txn_id) and permit_fast_path \
            and txn_id.epoch >= node.epoch:
        return txn_id
    floor = max_conflict if max_conflict is not None and max_conflict > txn_id \
        else txn_id
    return node.unique_now_at_least(floor)


def is_shard_fenced(safe_store: SafeCommandStore, txn_id: TxnId,
                    participants) -> bool:
    """TxnIds below the shard-applied fence can never newly commit: every
    replica of the shard applied an exclusive sync point that witnessed
    everything before it, and refuses to witness stragglers
    (RedundantBefore.shardAppliedOrInvalidatedBefore gating)."""
    rb = safe_store.store.redundant_before
    if isinstance(participants, Ranges):
        # fold every intersecting fence span — an interior fenced sub-range
        # must refuse the straggler even when the endpoints are unfenced
        return rb.is_any_shard_redundant(txn_id, participants)
    return any(rb.is_shard_redundant(txn_id, k) for k in participants)


def is_durably_fenced(safe_store: SafeCommandStore, txn_id: TxnId,
                      participants) -> bool:
    """The full Infer ladder's refusal rule (coordinate/infer.py): a replica
    must not FRESHLY witness, slow-path accept, or recovery-witness a txn
    below its majority-durable fence — everything beneath the fence is
    certified majority-applied-or-invalidated, so an unwitnessed straggler
    there can only be headed for invalidation, and refusing makes the
    quorum no-round invalidation provably safe (any future decision quorum
    must intersect an evidence quorum of refusing replicas).  Only applies
    to commands with NO local knowledge — a pre-fence witness stays live
    (a fence cannot advance past a genuinely in-flight accept, whose
    application the durability round awaits).  Off under
    ACCORD_INFER_FULL=0, restoring the r5 executeAt-above-fence behavior."""
    from accord_tpu.coordinate.infer import full_infer_enabled
    if not full_infer_enabled():
        return False
    db = safe_store.store.durable_before
    if isinstance(participants, Ranges):
        fenced = db.is_any_majority_durable(txn_id, participants)
    else:
        fenced = any(db.is_majority_durable(txn_id, k) for k in participants)
    if fenced:
        safe_store.node.infer_stats["fence_refusals"] += 1
    return fenced


# ---------------------------------------------------------------- preaccept --

def preaccept(safe_store: SafeCommandStore, txn_id: TxnId,
              partial_txn: Optional[PartialTxn], route: Route,
              ballot: Ballot = Ballot.ZERO
              ) -> Tuple[AcceptOutcome, Optional[Timestamp]]:
    """Witness the txn; propose executeAt (Commands.preaccept :131)."""
    cmd = safe_store.get(txn_id)
    if cmd.is_truncated or cmd.is_invalidated:
        return AcceptOutcome.TRUNCATED, None
    if not cmd.may_accept(ballot):
        return AcceptOutcome.REJECTED_BALLOT, None
    if cmd.has_been(SaveStatus.PRE_ACCEPTED):
        # competing recovery (ballot>0) still records its promise; a zero
        # ballot here is a replay (Commands.preacceptOrRecover :160-168)
        cmd.set_promised(ballot)
        return AcceptOutcome.REDUNDANT, cmd.execute_at_or_txn_id()

    cmd.update_route(route)
    cmd.set_promised(ballot)
    if partial_txn is not None:
        cmd.partial_txn = partial_txn
    participants = (partial_txn.keys if partial_txn is not None
                    else route.participants())
    if is_shard_fenced(safe_store, txn_id, participants) \
            or is_durably_fenced(safe_store, txn_id, participants):
        return AcceptOutcome.TRUNCATED, None
    witnessed_at = propose_execute_at(safe_store, txn_id, participants,
                                      permit_fast_path=ballot == Ballot.ZERO)
    cmd.execute_at = witnessed_at
    cmd.set_status(SaveStatus.PRE_ACCEPTED)
    # fence later proposals with the witnessed executeAt, not the txn id
    # (CommandStore.updateMaxConflicts :280-289 records executeAt)
    safe_store.update_max_conflicts(participants, witnessed_at)
    safe_store.register(cmd, InternalStatus.PREACCEPTED)
    if txn_id.is_range_domain and partial_txn is not None:
        safe_store.register_range_txn(cmd, partial_txn.keys)
    safe_store.progress_log.update(safe_store.store, txn_id, cmd)
    return AcceptOutcome.SUCCESS, witnessed_at


# ------------------------------------------------------------------ recover --

def recover(safe_store: SafeCommandStore, txn_id: TxnId,
            partial_txn: Optional[PartialTxn], route: Route, ballot: Ballot
            ) -> Tuple[AcceptOutcome, Command]:
    """Ballot-gated witness for BeginRecovery (Commands.preacceptOrRecover,
    Commands.java:160-217): promise `ballot`, witnessing the txn if this
    replica never saw it. Recovery proposals never mint fast-path votes
    (permit_fast_path=False), so a replica that first witnesses the txn here
    reports executeAt > txnId — a vote that the fast path did not happen.

    Returns the (possibly just-created) command so the caller can snapshot its
    pre-existing knowledge into the RecoverOk reply."""
    cmd = safe_store.get(txn_id)
    if cmd.is_truncated or cmd.is_invalidated:
        return AcceptOutcome.TRUNCATED, cmd
    if not cmd.may_accept(ballot):
        return AcceptOutcome.REJECTED_BALLOT, cmd
    cmd.set_promised(ballot)
    if cmd.has_been(SaveStatus.PRE_ACCEPTED):
        return AcceptOutcome.SUCCESS, cmd

    cmd.update_route(route)
    if partial_txn is not None:
        cmd.partial_txn = partial_txn
    participants = (partial_txn.keys if partial_txn is not None
                    else route.participants())
    # NB: no SHARD-fence gate here, unlike preaccept: a fresh recovery
    # witness votes slow-path with executeAt above the fence (safe), whereas
    # refusing could fabricate evidence against a decided-elsewhere txn.
    # The DURABLE fence is different (full Infer ladder): a decided txn
    # below it is majority-APPLIED, so a fresh local witness here proves
    # nothing was decided through us — refusal fabricates no evidence, and
    # is what makes the quorum no-round invalidation sound (the promise
    # above still stands, so the refusing reply keeps its ballot guard)
    if is_durably_fenced(safe_store, txn_id, participants):
        return AcceptOutcome.TRUNCATED, cmd
    witnessed_at = propose_execute_at(safe_store, txn_id, participants,
                                      permit_fast_path=False,
                                      permit_expiry=False)
    cmd.execute_at = witnessed_at
    cmd.set_status(SaveStatus.PRE_ACCEPTED)
    safe_store.update_max_conflicts(participants, witnessed_at)
    safe_store.register(cmd, InternalStatus.PREACCEPTED)
    if txn_id.is_range_domain and partial_txn is not None:
        safe_store.register_range_txn(cmd, partial_txn.keys)
    safe_store.progress_log.update(safe_store.store, txn_id, cmd)
    return AcceptOutcome.SUCCESS, cmd


# ------------------------------------------------------------------- accept --

def accept(safe_store: SafeCommandStore, txn_id: TxnId, ballot: Ballot,
           route: Route, participating_keys, execute_at: Timestamp,
           partial_deps: Deps) -> AcceptOutcome:
    """Slow-path acceptance of (executeAt, deps) at `ballot`
    (Commands.accept :219)."""
    cmd = safe_store.get(txn_id)
    if cmd.is_truncated or cmd.is_invalidated:
        return AcceptOutcome.TRUNCATED
    if not cmd.may_accept(ballot):
        return AcceptOutcome.REJECTED_BALLOT
    if cmd.has_been(SaveStatus.PRE_COMMITTED):
        return AcceptOutcome.REDUNDANT
    if not cmd.has_been(SaveStatus.PRE_ACCEPTED) \
            and is_durably_fenced(safe_store, txn_id, participating_keys):
        # full Infer ladder: an accept may not FRESHLY witness below the
        # durable fence either, or a recovery's Propose could complete a
        # decision quorum behind a quorum-established invalidation
        # inference (coordinate/infer.py safety argument)
        return AcceptOutcome.TRUNCATED

    cmd.update_route(route)
    cmd.set_promised(ballot)
    cmd.accepted_ballot = ballot
    cmd.execute_at = execute_at
    cmd.partial_deps = partial_deps
    cmd.set_status(SaveStatus.ACCEPTED)
    safe_store.update_max_conflicts(participating_keys, execute_at)
    safe_store.register(cmd, InternalStatus.ACCEPTED)
    safe_store.progress_log.update(safe_store.store, txn_id, cmd)
    return AcceptOutcome.SUCCESS


def preaccept_invalidate(safe_store: SafeCommandStore, txn_id: TxnId,
                         ballot: Ballot) -> bool:
    """Promise `ballot` toward invalidation without proposing anything:
    raises the command's promised ballot so neither the original coordinator
    nor a stale recovery can make progress beneath us
    (Commands.preacceptInvalidate :198-217). Returns False — promise
    refused — once a decision is durable (Committed+/truncated) or a higher
    ballot holds the promise."""
    cmd = safe_store.get(txn_id)
    if cmd.has_been(SaveStatus.COMMITTED) or cmd.is_truncated:
        return False
    if not cmd.may_accept(ballot):
        return False
    cmd.set_promised(ballot)
    return True


def accept_invalidate(safe_store: SafeCommandStore, txn_id: TxnId,
                      ballot: Ballot) -> AcceptOutcome:
    """Promise to invalidate (Commands.acceptInvalidate :267)."""
    cmd = safe_store.get(txn_id)
    if cmd.is_truncated:
        return AcceptOutcome.TRUNCATED
    if not cmd.may_accept(ballot):
        return AcceptOutcome.REJECTED_BALLOT
    if cmd.has_been(SaveStatus.PRE_COMMITTED):
        return AcceptOutcome.REDUNDANT
    cmd.set_promised(ballot)
    cmd.accepted_ballot = ballot
    # UNCONDITIONALLY supersede any prior accepted value with the
    # invalidate acceptance (reference Command.acceptInvalidated:1698 sets
    # Status.AcceptedInvalidate regardless of a prior Accepted — their
    # accepted register now holds "invalidate" at this ballot, executeAt /
    # definition retained).  The old `if save_status <
    # ACCEPTED_INVALIDATE` guard kept an ACCEPTED status while bumping
    # accepted_ballot, fabricating "original value accepted at this
    # ballot": a later recovery then preferred the stale value over the
    # invalidate accepted at the same ballot and re-proposed a txn an
    # invalidation had already decided against — a committed-vs-invalidated
    # divergence (soak seed 57012, triage_57012.py).  Direct assignment:
    # this is the one legal non-cleanup status "regression" (set_status
    # guards it), mirroring the reference's modelling of AcceptedInvalidate
    # as a fresh acceptance rather than a phase advance.
    note_status_transition(cmd.txn_id, cmd.save_status,
                           SaveStatus.ACCEPTED_INVALIDATE)
    cmd.save_status = SaveStatus.ACCEPTED_INVALIDATE
    return AcceptOutcome.SUCCESS


# ------------------------------------------------------------------- commit --

def commit(safe_store: SafeCommandStore, txn_id: TxnId, route: Route,
           partial_txn: Optional[PartialTxn], execute_at: Timestamp,
           deps: Deps, stable: bool, ballot: Ballot = Ballot.ZERO
           ) -> AcceptOutcome:
    """Commit (executeAt, deps); `stable=True` also freezes deps and starts
    execution tracking (Commands.commit :306)."""
    cmd = safe_store.get(txn_id)
    if cmd.is_truncated:
        return AcceptOutcome.TRUNCATED
    if cmd.is_invalidated:
        safe_store.agent.on_inconsistent_timestamp(cmd, None, execute_at)
        return AcceptOutcome.TRUNCATED
    target = SaveStatus.STABLE if stable else SaveStatus.COMMITTED
    if cmd.has_been(target):
        if cmd.execute_at is not None and cmd.execute_at != execute_at \
                and cmd.save_status.is_committed_to_execute:
            safe_store.agent.on_inconsistent_timestamp(cmd, cmd.execute_at,
                                                       execute_at)
        return AcceptOutcome.REDUNDANT

    cmd.update_route(route)
    if partial_txn is not None and cmd.partial_txn is None:
        cmd.partial_txn = partial_txn
    if stable and cmd.partial_txn is None and _needs_definition(cmd):
        return AcceptOutcome.INSUFFICIENT
    cmd.execute_at = execute_at
    if not stable:
        cmd.partial_deps = deps
        cmd.set_status(SaveStatus.COMMITTED)
        safe_store.register(cmd, InternalStatus.COMMITTED)
        safe_store.progress_log.update(safe_store.store, txn_id, cmd)
        return AcceptOutcome.SUCCESS

    cmd.stable_deps = deps
    cmd.set_status(SaveStatus.STABLE)
    # stable deps in hand: any staleness-escalation counter is moot
    safe_store.store.insufficient_catchups.pop(txn_id, None)
    safe_store.update_max_conflicts(
        cmd.partial_txn.keys if cmd.partial_txn is not None
        else route.participants(), execute_at)
    safe_store.register(cmd, InternalStatus.STABLE)
    _maybe_register_range_txn(safe_store, cmd)
    initialise_waiting_on(safe_store, cmd)
    safe_store.progress_log.update(safe_store.store, txn_id, cmd)
    maybe_execute(safe_store, cmd, always_notify=True)
    return AcceptOutcome.SUCCESS


def _maybe_register_range_txn(safe_store: SafeCommandStore, cmd: Command
                              ) -> None:
    """A range txn first learned of at commit/apply (Maximal paths) must still
    enter the range-conflict index."""
    if cmd.txn_id.is_range_domain and cmd.partial_txn is not None \
            and cmd.txn_id not in safe_store.store.range_commands:
        safe_store.register_range_txn(cmd, cmd.partial_txn.keys)


def _needs_definition(cmd: Command) -> bool:
    """Sync points and data txns need their definition to execute; reads of
    the definition come with the Stable/Apply message if missing."""
    return cmd.txn_id.kind.is_globally_visible


def precommit(safe_store: SafeCommandStore, txn_id: TxnId,
              execute_at: Timestamp) -> AcceptOutcome:
    """Record executeAt decision without deps (Commands.precommit :371)."""
    cmd = safe_store.get(txn_id)
    if cmd.is_truncated or cmd.is_invalidated:
        return AcceptOutcome.TRUNCATED
    if cmd.has_been(SaveStatus.PRE_COMMITTED):
        return AcceptOutcome.REDUNDANT
    cmd.execute_at = execute_at
    cmd.set_status(SaveStatus.PRE_COMMITTED)
    return AcceptOutcome.SUCCESS


def commit_invalidate(safe_store: SafeCommandStore, txn_id: TxnId) -> None:
    """Finalize invalidation (Commands.commitInvalidate :463)."""
    cmd = safe_store.get(txn_id)
    if cmd.has_been(SaveStatus.COMMITTED) and not cmd.is_invalidated:
        if cmd.save_status.is_committed_to_execute:
            safe_store.agent.on_inconsistent_timestamp(cmd, cmd.execute_at, None)
            return
    if cmd.is_invalidated:
        return
    note_status_transition(txn_id, cmd.save_status, SaveStatus.INVALIDATED)
    cmd.save_status = SaveStatus.INVALIDATED
    safe_store.store.insufficient_catchups.pop(txn_id, None)
    safe_store.register(cmd, InternalStatus.INVALID_OR_TRUNCATED)
    safe_store.progress_log.clear(txn_id)
    _notify_listeners(safe_store, cmd)


# -------------------------------------------------------------------- apply --

def apply(safe_store: SafeCommandStore, txn_id: TxnId, route: Route,
          execute_at: Timestamp, deps: Optional[Deps], writes: Optional[Writes],
          result, partial_txn: Optional[PartialTxn] = None) -> ApplyOutcome:
    """Record the outcome; execute once deps clear (Commands.apply :491)."""
    cmd = safe_store.get(txn_id)
    if cmd.has_been(SaveStatus.PRE_APPLIED) or cmd.is_truncated \
            or cmd.is_invalidated:
        return ApplyOutcome.REDUNDANT
    if cmd.execute_at is not None and cmd.has_been(SaveStatus.PRE_COMMITTED) \
            and cmd.execute_at != execute_at:
        safe_store.agent.on_inconsistent_timestamp(cmd, cmd.execute_at, execute_at)

    cmd.update_route(route)
    if partial_txn is not None and cmd.partial_txn is None:
        cmd.partial_txn = partial_txn
    if not cmd.has_been(SaveStatus.STABLE):
        if deps is None:
            return ApplyOutcome.INSUFFICIENT
        cmd.execute_at = execute_at
        cmd.stable_deps = deps
        cmd.set_status(SaveStatus.STABLE)
        safe_store.register(cmd, InternalStatus.STABLE)
        _maybe_register_range_txn(safe_store, cmd)
        initialise_waiting_on(safe_store, cmd)
    cmd.writes = writes
    cmd.result = result
    cmd.set_status(SaveStatus.PRE_APPLIED)
    safe_store.store.insufficient_catchups.pop(txn_id, None)
    safe_store.progress_log.update(safe_store.store, txn_id, cmd)
    maybe_execute(safe_store, cmd, always_notify=True)
    return ApplyOutcome.SUCCESS


# -------------------------------------------------- execution ordering core --

def initialise_waiting_on(safe_store: SafeCommandStore, cmd: Command) -> None:
    """Build the WaitingOn bitsets — over stable deps owned by this store AND
    over the command's own keys — and register as listener on each
    still-blocking dep (Commands.initialiseWaitingOn :735 + updateWaitingOn
    :776; the key dimension is the reference's txnIds ∪ keys bitset,
    Command.java:1425-1436, cleared per key by CommandsForKey)."""
    deps = cmd.stable_deps if cmd.stable_deps is not None else Deps.NONE
    local = deps.slice(safe_store.ranges) if not safe_store.ranges.is_empty else deps
    gate_keys = ()
    if cmd.txn_id.is_key_domain and cmd.txn_id.kind.is_globally_visible \
            and cmd.execute_at is not None:
        gate_keys = tuple(safe_store.owned_keys_of(cmd))
    waiting_on = WaitingOn.from_deps(local, keys=gate_keys)
    cmd.waiting_on = waiting_on
    for dep_id in list(waiting_on.txn_ids):
        _update_waiting_on_dep(safe_store, cmd, dep_id)
    for key in gate_keys:
        _initialise_key_wait(safe_store, cmd, key)


def _initialise_key_wait(safe_store: SafeCommandStore, cmd: Command,
                         key) -> None:
    """Arm the per-key execution gate: the key bit holds until the CFK
    certifies every earlier-executing entry applied.  Even a conflict the
    stable deps omit (e.g. under the unmerged-deps fault, or a commit that
    raced the accept round) cannot be overtaken: it lives in the CFK of some
    common replica and blocks there (the reference clears these bits via
    CommandsForKey.update -> removeWaitingOnKeyAndMaybeExecute,
    Commands.java:859)."""
    from accord_tpu.local.cfk import Unmanaged
    cfk = safe_store.cfk(key)
    blockers = _key_gate_blockers(safe_store, cfk, cmd, key)
    if not blockers:
        cmd.waiting_on.remove_waiting_on_key(key)
        return
    txn_id = cmd.txn_id

    def fired(ss, _key=key, _txn_id=txn_id):
        _enqueue_notify(ss, ("key_unblock", _txn_id, _key))

    cfk.register_unmanaged(
        Unmanaged(txn_id, Unmanaged.APPLY, cmd.execute_at, fired))
    safe_store.store.gated.setdefault(txn_id, set()).add(key)
    _chase_key_blocker(safe_store, cmd, blockers)


def _chase_key_blocker(safe_store: SafeCommandStore, cmd: Command,
                       blockers) -> None:
    """Chase the gate's CURRENT first blocker (the progress log drives it to
    Committed/Applied).  The chase is renewed each progress-log sweep
    (sweep_key_gates) so a multi-blocker gate keeps being driven after its
    first blocker resolves — a per-transition hand-over would fan out to
    every waiter of a hot key and go quadratic."""
    blocking_id, decided = blockers[0]
    safe_store.progress_log.waiting(
        blocking_id, safe_store.store,
        "Applied" if decided else "Committed", None,
        cmd.route.participants() if cmd.route else None)


def sweep_key_gates(safe_store: SafeCommandStore) -> None:
    """Periodic liveness pass over armed key gates (called from the progress
    log's recurring run): re-chase each gate's current first blocker, clear
    gates whose blockers are all gone (e.g. covered by an advanced
    redundancy watermark with no CFK transition to fire the heap)."""
    store = safe_store.store
    for txn_id in list(store.gated):
        cmd = store.commands.get(txn_id)
        waiting_on = cmd.waiting_on if cmd is not None else None
        if waiting_on is None or not waiting_on.is_waiting_on_key:
            # purged/truncated/executed with no live key bits: drop the
            # index entry, or the per-tick sweep runs forever
            store.gated.pop(txn_id, None)
            continue
        # snapshot: the drain triggered by _enqueue_notify below removes
        # cleared keys from the live store.gated set
        keys = list(store.gated.get(txn_id, ()))
        live = set()
        for key in keys:
            if not waiting_on.is_waiting_on_key_at(key):
                continue
            blockers = _key_gate_blockers(safe_store, safe_store.cfk(key),
                                          cmd, key)
            if blockers:
                live.add(key)
                _chase_key_blocker(safe_store, cmd, blockers)
            else:
                _enqueue_notify(safe_store, ("key_unblock", txn_id, key))
        if live:
            store.gated[txn_id] = live
        elif not store.gated.get(txn_id):
            store.gated.pop(txn_id, None)


def _key_gate_blockers(safe_store: SafeCommandStore, cfk, cmd: Command,
                       key):
    """The CFK's APPLY-rule blockers minus entries the redundancy watermark
    already covers (pre-bootstrap / GC'd — mirrors _is_redundant_dep)."""
    from accord_tpu.local.cfk import Unmanaged
    # Fast path: the CFK's min block point (lazy heap, O(log) amortised)
    # proves the gate clear without walking entries — our own entry cannot
    # be the sub-threshold min (its block point IS our executeAt).  The
    # exact walk runs only when genuinely blocked, to name a blocker to
    # chase and to apply per-store redundancy the CFK can't see.
    mbp = cfk._min_block_point()
    if mbp is None or mbp >= cmd.execute_at:
        return []
    rb = safe_store.store.redundant_before
    return cfk.blocking_ids(
        Unmanaged.APPLY, cmd.execute_at, cmd.txn_id, first_only=True,
        skip_pred=lambda t: rb.is_redundant(t, key))


def _recheck_key_gate(safe_store: SafeCommandStore, txn_id: TxnId,
                      key) -> None:
    """CFK notification: the key's wait rule may have cleared."""
    cmd = safe_store.if_present(txn_id)
    if cmd is None or cmd.waiting_on is None \
            or not cmd.waiting_on.is_waiting_on_key_at(key):
        return
    cfk = safe_store.cfk(key)
    blockers = _key_gate_blockers(safe_store, cfk, cmd, key)
    if blockers:
        # still blocked (e.g. a redundancy-aware recheck or a blocker
        # hand-over): re-arm the CFK registration if a fire consumed it,
        # and move the chase onto the current first blocker
        if not cfk.has_unmanaged(cmd.txn_id):
            _initialise_key_wait(safe_store, cmd, key)
        else:
            _chase_key_blocker(safe_store, cmd, blockers)
        return
    if cmd.waiting_on.remove_waiting_on_key(key):
        gated = safe_store.store.gated
        keys = gated.get(txn_id)
        if keys is not None:
            keys.discard(key)
            if not keys:
                gated.pop(txn_id, None)
        maybe_execute(safe_store, cmd, always_notify=False)


def _update_waiting_on_dep(safe_store: SafeCommandStore, cmd: Command,
                           dep_id: TxnId) -> None:
    """Evaluate one dep: clear it if terminal or ordered after us; otherwise
    listen for its transitions (Commands.shouldWaitOn semantics)."""
    waiting_on = cmd.waiting_on
    if waiting_on is None or not waiting_on.is_waiting_on(dep_id):
        return
    # paging fast path: a SPILLED dep is terminal by the eviction
    # eligibility rule (applied/invalidated/truncated/erased — exactly the
    # `is_applied_or_gone or is_truncated` branch below), so it clears
    # without faulting its frame back in — a sync point's dep walk over a
    # spilled million-key history must not thrash the resident tier
    pager = getattr(safe_store.store, "pager", None)
    if pager is not None and dep_id in pager.spilled:
        waiting_on.set_applied_or_invalidated(dep_id)
        return
    dep = safe_store.get(dep_id)
    if dep.is_applied_or_gone or dep.is_truncated:
        waiting_on.set_applied_or_invalidated(dep_id)
        return
    # redundant (GC'd / pre-bootstrap) deps need not be waited for
    if _is_redundant_dep(safe_store, cmd, dep_id):
        waiting_on.set_applied_or_invalidated(dep_id)
        return
    # sync points carry no writes: a dependency on one is satisfied once its
    # executeAt is decided — there is nothing of its to order reads/writes
    # against (the reference waits deps-only txns via WaitingOn.Commit)
    if dep_id.kind.is_sync_point and dep.has_been(SaveStatus.COMMITTED):
        waiting_on.remove_waiting_on(dep_id)
        dep.remove_listener(cmd.txn_id)
        return
    if dep.save_status.is_committed_to_execute and cmd.execute_at is not None \
            and dep.execute_at is not None and dep.execute_at > cmd.execute_at:
        # ordered after us; not our problem
        waiting_on.remove_waiting_on(dep_id)
        dep.remove_listener(cmd.txn_id)
        return
    dep.add_listener(cmd.txn_id)
    if not dep.has_been(SaveStatus.COMMITTED):
        safe_store.progress_log.waiting(
            dep_id, safe_store.store, "Committed", dep.route,
            cmd.route.participants() if cmd.route else None)
    elif not dep.has_been(SaveStatus.PRE_APPLIED):
        # committed here but the outcome never arrived (Apply lost): chase it
        # (the reference BlockedState with blockedUntil=HasOutcome)
        safe_store.progress_log.waiting(
            dep_id, safe_store.store, "Applied", dep.route,
            cmd.route.participants() if cmd.route else None)


def _is_redundant_dep(safe_store: SafeCommandStore, cmd: Command,
                      dep_id: TxnId) -> bool:
    """A dep below the local-applied or bootstrap watermark for EVERY
    participant through which we recorded it is already reflected in local
    state (applied, or frozen into the bootstrap snapshot) — don't wait on
    it (RedundantBefore dep pruning)."""
    rb = safe_store.store.redundant_before
    key_parts = None
    range_parts = None
    if cmd.stable_deps is not None:
        key_parts, range_parts = cmd.stable_deps.participants(dep_id)
        if not safe_store.ranges.is_empty:
            # only the locally-recorded participants matter: WaitingOn was
            # built from the store-sliced deps
            key_parts = key_parts.slice(safe_store.ranges)
            range_parts = range_parts.slice(safe_store.ranges)
    if (key_parts is None or len(key_parts) == 0) \
            and (range_parts is None or range_parts.is_empty):
        dep = safe_store.store.commands.get(dep_id)
        if dep is not None and dep.route is not None \
                and dep.route.is_key_domain:
            key_parts = dep.route.participants()
        else:
            return False
    if key_parts is not None:
        for k in key_parts:
            if not rb.is_redundant(dep_id, k):
                return False
    if range_parts is not None and not range_parts.is_empty:
        # every span intersecting the dep ranges must be covered AND
        # redundant — an interior never-bootstrapped sub-range keeps the
        # dependency live there (ADVICE r1: endpoint probes missed it)
        if not rb.is_all_redundant(dep_id, range_parts):
            return False
    return True


def update_dependency_and_maybe_execute(safe_store: SafeCommandStore,
                                        waiter: Command, dep: Command) -> None:
    """A dep transitioned; re-evaluate and maybe unblock the waiter
    (Commands.updateDependencyAndMaybeExecute :832)."""
    if waiter.has_been(SaveStatus.APPLIED) or waiter.waiting_on is None:
        return
    if dep.is_applied_or_gone or dep.is_truncated:
        if waiter.waiting_on.set_applied_or_invalidated(dep.txn_id):
            dep.remove_listener(waiter.txn_id)
            maybe_execute(safe_store, waiter, always_notify=False)
    else:
        _update_waiting_on_dep(safe_store, waiter, dep.txn_id)
        if not waiter.waiting_on.is_waiting:
            maybe_execute(safe_store, waiter, always_notify=False)


def re_evaluate_waiting(safe_store: SafeCommandStore) -> None:
    """Re-test every blocked dependency against the (advanced) redundancy
    watermarks — run after bootstrap completes, when deps below the fence
    became satisfiable-by-snapshot (Bootstrap.java markBootstrapComplete ->
    the reference's RedundantBefore-driven WaitingOn updates)."""
    for cmd in list(safe_store.store.commands.values()):
        waiting_on = cmd.waiting_on
        if waiting_on is not None and waiting_on.is_waiting:
            for dep_id in waiting_on.waiting_ids():
                _update_waiting_on_dep(safe_store, cmd, dep_id)
            for key in waiting_on.waiting_key_list():
                # advanced watermarks can satisfy a key gate without any CFK
                # transition (snapshot covers the blockers) — recheck
                _enqueue_notify(safe_store, ("key_unblock", cmd.txn_id, key))
        if cmd.save_status in (SaveStatus.STABLE, SaveStatus.PRE_APPLIED) \
                and (waiting_on is None or not waiting_on.is_waiting):
            # includes applies that were deferred on un-bootstrapped ranges
            maybe_execute(safe_store, cmd, always_notify=False)


def maybe_execute(safe_store: SafeCommandStore, cmd: Command,
                  always_notify: bool) -> bool:
    """Advance Stable->ReadyToExecute->apply when the WaitingOn set clears
    (Commands.maybeExecute :656)."""
    WORK["maybe_execute"] += 1
    if cmd.save_status not in (SaveStatus.STABLE, SaveStatus.PRE_APPLIED):
        if always_notify:
            _notify_listeners(safe_store, cmd)
        return False
    if cmd.waiting_on is not None and cmd.waiting_on.is_waiting:
        if always_notify:
            _notify_listeners(safe_store, cmd)
        return False

    if cmd.save_status == SaveStatus.STABLE:
        cmd.set_status(SaveStatus.READY_TO_EXECUTE)
        safe_store.progress_log.update(safe_store.store, cmd.txn_id, cmd)
        _notify_listeners(safe_store, cmd)
        return True

    # PRE_APPLIED with no outstanding deps: run the writes — but never onto
    # a range whose bootstrap hasn't installed its snapshot yet (applying
    # out-of-band would interleave with the snapshot and diverge the
    # replica; the reference defers via safeToRead/unavailableToExecute)
    if not _safe_to_apply(safe_store, cmd):
        return False  # re-driven by re_evaluate_waiting after bootstrap
    cmd.set_status(SaveStatus.APPLYING)
    _apply_writes(safe_store, cmd)
    return True


def _safe_to_apply(safe_store: SafeCommandStore, cmd: Command) -> bool:
    if safe_store.ranges.is_empty:
        return True
    sel = None
    if cmd.partial_txn is not None:
        if isinstance(cmd.partial_txn.keys, Keys):
            # key-domain: share the identity-memoized owned slice that
            # register computes per transition
            sel = safe_store.owned_keys_of(cmd)
        else:
            sel = cmd.partial_txn.keys.slice(safe_store.ranges)
    elif cmd.route is not None:
        sel = cmd.route.slice(safe_store.ranges).participants()
    if sel is None:
        return True
    return safe_store.is_safe_to_read(sel)


def _apply_writes(safe_store: SafeCommandStore, cmd: Command) -> None:
    """Writes.apply against the DataStore, then postApply (Commands.applyChain
    :565-654)."""
    store = safe_store.store

    def post_apply(_v=None, failure=None):
        if failure is not None:
            safe_store.agent.on_uncaught_exception(failure)
            return
        # record execution timestamps per owned key
        for key in safe_store.owned_keys_of(cmd):
            tfk = safe_store.tfk(key)
            tfk.on_executed(cmd.execute_at, cmd.txn_id.kind.is_write)
        # NB: a locally-applied ESP must NOT advance the locally-applied
        # watermark: the bound is by TxnId, but a lower-id txn that the
        # ESP never witnessed (preaccept in flight during its deps calc)
        # can commit with executeAt AFTER the ESP — an id-based "all
        # applied" claim would wrongly clear it from waiters and reorder
        # writes. Only the durability fence (SetShardDurable universal,
        # whose witness gate stops new lower-id commits) and bootstrap
        # snapshots may advance redundancy watermarks.
        cmd.set_status(SaveStatus.APPLIED)
        safe_store.register(cmd, InternalStatus.APPLIED)
        safe_store.progress_log.update(store, cmd.txn_id, cmd)
        store.node.events.on_applied(cmd)
        _notify_listeners(safe_store, cmd)

    if cmd.writes is None or cmd.writes.is_empty:
        post_apply()
    else:
        within = safe_store.ranges if not safe_store.ranges.is_empty else None
        cmd.writes.apply(store.data_store, within).add_callback(post_apply)


def _notify_listeners(safe_store: SafeCommandStore, cmd: Command) -> None:
    """Notify durable (dependent commands) and transient listeners of a
    transition (see _enqueue_notify for the constant-stack drain)."""
    _enqueue_notify(safe_store, cmd.txn_id)


def _enqueue_notify(safe_store: SafeCommandStore, item) -> None:
    """Enqueue a notification and drain unless already draining. Items are
    either a TxnId (notify its listeners) or ("key_unblock", txn_id, key)
    (re-check a key gate).  Re-entrant calls enqueue onto the store-level
    drain queue so arbitrarily deep apply cascades use constant stack (the
    reference's NotifyWaitingOn walker, Commands.java:1011, achieves the
    same by running each step as a separate executor task)."""
    WORK["notify"] += 1
    store = safe_store.store
    store.notify_queue.append(item)
    if store.notifying:
        return
    store.notifying = True
    try:
        while store.notify_queue:
            entry = store.notify_queue.popleft()
            if isinstance(entry, tuple) and entry[0] == "key_unblock":
                _recheck_key_gate(safe_store, entry[1], entry[2])
                continue
            c = store.commands.get(entry)
            if c is None:
                continue
            for listener in list(c.transient_listeners):
                listener.on_change(safe_store, c)
            for waiter_id in sorted(c.listeners):
                waiter = store.commands.get(waiter_id)
                if waiter is None:
                    c.listeners.discard(waiter_id)
                    continue
                update_dependency_and_maybe_execute(safe_store, waiter, c)
    finally:
        store.notifying = False


# --------------------------------------------------------------- durability --

def set_durability(safe_store: SafeCommandStore, txn_id: TxnId,
                   durability: Durability) -> None:
    """(Commands.setDurability :978)"""
    cmd = safe_store.get(txn_id)
    if durability > cmd.durability:
        cmd.durability = durability
        safe_store.progress_log.durable(cmd)


# --------------------------------------------------------------- truncation --

def set_truncated_remotely(safe_store: SafeCommandStore, txn_id: TxnId,
                           execute_at: Optional[Timestamp] = None) -> bool:
    """Install a truncation learned from peers (full Infer ladder,
    reference Propagate's Infer.safeToCleanup arm): the interrogated
    quorum showed the txn durably decided+applied and SHED, with no
    outcome left to fetch — the local undecided copy can never decide
    (fence refusal) and the txn will never execute here, so local waiters
    must stop chasing it.  Mirrors purge()'s TRUNCATED_APPLY terminal
    without its already-durable invariant (the durability here is the
    REMOTE quorum's, witnessed through CheckStatus).  Returns True when
    the truncation was installed."""
    cmd = safe_store.get(txn_id)
    if cmd.save_status.is_decided or cmd.is_truncated:
        return False
    if execute_at is not None and cmd.execute_at is None:
        cmd.execute_at = execute_at
    cmd.partial_txn = None
    cmd.partial_deps = None
    cmd.stable_deps = None
    cmd.waiting_on = None
    safe_store.store.gated.pop(txn_id, None)
    note_status_transition(txn_id, cmd.save_status,
                           SaveStatus.TRUNCATED_APPLY)
    cmd.save_status = SaveStatus.TRUNCATED_APPLY
    safe_store.store.insufficient_catchups.pop(txn_id, None)
    safe_store.register(cmd, InternalStatus.INVALID_OR_TRUNCATED)
    safe_store.progress_log.clear(txn_id)
    _notify_listeners(safe_store, cmd)
    return True


def purge(safe_store: SafeCommandStore, txn_id: TxnId,
          erase: bool = False, keep_outcome: bool = False) -> None:
    """Truncate a durably-applied (or invalidated) command's local state
    (Commands.purge :879-967). `keep_outcome` retains writes/result (the
    reference's TRUNCATE_WITH_OUTCOME) so lagging replicas can still fetch
    the outcome through CheckStatus."""
    cmd = safe_store.get(txn_id)
    invariants.check_state(
        cmd.is_applied_or_gone or cmd.durability.is_durable,
        "cannot purge %s in state %s", txn_id, cmd.save_status.name)
    cmd.partial_txn = None
    cmd.partial_deps = None
    cmd.stable_deps = None
    cmd.waiting_on = None
    safe_store.store.gated.pop(txn_id, None)
    if not keep_outcome:
        cmd.writes = None
        cmd.result = None
    if cmd.is_invalidated:
        pass  # keep INVALIDATED as terminal state
    else:
        target = SaveStatus.ERASED if erase else SaveStatus.TRUNCATED_APPLY
        note_status_transition(txn_id, cmd.save_status, target)
        cmd.save_status = target
    _notify_listeners(safe_store, cmd)
