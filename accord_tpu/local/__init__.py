"""Local replica state machine (reference: accord/local — SURVEY.md §2.3)."""

from accord_tpu.local.status import (
    SaveStatus, Phase, Durability, Known, KnownRoute, KnownDefinition,
    KnownExecuteAt, KnownDeps, KnownOutcome,
)
from accord_tpu.local.command import Command, WaitingOn
