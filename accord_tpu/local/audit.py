"""Live replica-state auditor: cross-replica range digests, drill-down
divergence forensics, and the state-lifecycle census.

No reference counterpart — the reference verifies correctness offline (the
deterministic sim's burn checkers + Elle); a production host serving real
traffic needs ONLINE verification: a replica that silently diverges (bad
replay, codec bug, cleanup error, bit rot) must be caught by the cluster
itself, not by a sim seed that happens to reproduce it.

Two always-on surfaces, one `Auditor` per node:

DIGESTS — for every shard this node replicates, fold the decided command
state per audited range into one order-insensitive 128-bit digest (XOR of
per-transaction leaves over canonical wire packings) and compare it with
every peer replica via the read-only AUDIT_DIGEST verbs.  The window is
bounded by NEGOTIATED watermarks so replicas at different cleanup /
truncation / bootstrap points still agree:

    lo = max over replicas of (bootstrapped_at | stale fence)   — below it
         a replica's history is legitimately a snapshot-shaped hole
    hi = min over replicas of the universal-durable floor       — below it
         EVERY replica is certified applied-or-invalidated, so the decided
         set in [lo, hi) is fixed and identical across replicas

Within the window only "committed" decisions (real executeAt) are folded;
INVALIDATED and truncated-with-unknown-decision entries are excluded from
the digest (their presence is legitimately asymmetric) but reported by the
drill-down, where invalidated-vs-committed IS a hard divergence.  On a
digest mismatch the auditor bisects the window by txn-id midpoint with
further digest requests until it is enumerable, fetches per-transaction
entries (AUDIT_ENTRIES), and classifies them (obs/audit.py): the first
divergent transaction, its kind, and the disagreeing replicas are recorded
(flight kind `audit_divergence`, trace id = the txn repr) so the stitched
cross-replica flight timeline names the exact history.

CENSUS — a periodic sweep over the command stores and CommandsForKey
exporting resident-count/byte gauges by status and durability class,
age-since-quiescence quantiles, and the cleanup/durability watermarks
(`RedundantBefore` / `DurableBefore` floors + their distance from the HLC)
as per-node gauges; a leak detector (obs/audit.LeakDetector) alarms when
quiescent-but-uncleaned state grows monotonically — the residency data the
ROADMAP's journal-backed bounded-memory command store needs.
"""

from __future__ import annotations

import hashlib
import os
from typing import Callable, Dict, List, Optional, Tuple

from accord_tpu.local.status import SaveStatus
from accord_tpu.messages.audit import (AuditDigest, AuditDigestOk,
                                       AuditEntries, AuditEntriesOk)
from accord_tpu.messages.base import FunctionCallback
from accord_tpu.obs.audit import LeakDetector, classify_entry_sets
from accord_tpu.primitives.keys import Ranges
from accord_tpu.primitives.timestamp import Timestamp, TXNID_NONE

_LEAF_VERSION = b"accord-audit-v1"


# ------------------------------------------------------------ digest walk --

def entry_leaf(txn_id, execute_at) -> int:
    """128-bit leaf for one decided transaction, over the canonical wire
    packings (Timestamp.pack is the $T/$I wire form) — replicas hash the
    DECISION (txn_id, executeAt), never local progress, so APPLIED here and
    ERASED there fold identically."""
    a = txn_id.pack()
    b = execute_at.pack()
    blob = b"%s|%d:%d:%d|%d:%d:%d" % (_LEAF_VERSION, a[0], a[1], a[2],
                                      b[0], b[1], b[2])
    return int.from_bytes(hashlib.blake2b(blob, digest_size=16).digest(),
                          "big")


def _audit_scope(cmd):
    """The command's participants as known locally (route fallback)."""
    if cmd.partial_txn is not None:
        return cmd.partial_txn.keys
    if cmd.route is not None:
        return cmd.route.participants()
    return None


def _in_ranges(parts, ranges: Ranges) -> bool:
    if parts is None:
        return False
    if isinstance(parts, Ranges):
        return ranges.intersects(parts)
    return any(ranges.contains(k) for k in parts)


def _owns_min_token(owned: Ranges, parts, ranges: Ranges) -> bool:
    """Worker-runtime dedup (shard/): does `owned` cover the MINIMAL token
    of `parts` ∩ `ranges`?  The in-process walk dedups a cross-store txn
    with a `seen` set, but per-shard worker processes cannot share one —
    instead every worker applies this filter, and since exactly one worker
    owns any given token, each txn contributes exactly one leaf node-wide
    (XOR folds would cancel pairwise on double-count)."""
    if parts is None:
        return False
    best = None
    if isinstance(parts, Ranges):
        for a in parts:
            for b in ranges:
                s = max(a.start, b.start)
                if s < min(a.end, b.end) and (best is None or s < best):
                    best = s
    else:
        for k in parts:
            if ranges.contains(k) and (best is None or k.token < best):
                best = k.token
    return best is not None and owned.contains_token(best)


def entry_class(cmd) -> Optional[Tuple[str, Optional[Timestamp]]]:
    """Auditable decision of a command, or None when undecided.

    ("committed", executeAt) — decided to execute (PreCommitted..Erased);
    ("invalidated", None)    — decided against;
    ("unknown", None)        — truncated with the decision shed
                               (set_truncated_remotely): compatible with
                               anything, never digested."""
    st = cmd.save_status
    if st < SaveStatus.PRE_COMMITTED:
        return None
    if st == SaveStatus.INVALIDATED:
        return ("invalidated", None)
    if cmd.execute_at is None:
        return ("unknown", None)
    return ("committed", cmd.execute_at)


def node_floors(node, ranges: Ranges) -> Tuple[Timestamp, Timestamp]:
    """(lo, hi) digest floors for this replica over `ranges`: lo = the
    bootstrap/staleness bound (holes below it are legitimate), hi = the
    universal-durable floor (below it this replica is certified complete).
    Uncovered spans floor hi to NONE — no certificate, no window."""
    lo: Timestamp = TXNID_NONE
    hi: Optional[Timestamp] = None
    for store in node.command_stores.all():
        owned = ranges.slice(store.ranges) if not store.ranges.is_empty \
            else ranges
        if owned.is_empty:
            continue
        b = store.redundant_before.audit_low_bound(owned)
        if b > lo:
            lo = b
        _maj, uni = store.durable_before.min_bounds(owned)
        hi = uni if hi is None else min(hi, uni)
    return lo, (hi if hi is not None else TXNID_NONE)


def _walk_decided(node, ranges: Ranges, lo: Timestamp, hi: Timestamp,
                  owned: Ranges = None):
    """Yield (txn_id, cls, at) once per transaction across the node's
    stores (a multi-key command registered in several stores must
    contribute ONE leaf, or XOR folds would cancel pairwise).  `owned`
    engages the worker-runtime min-token filter (_owns_min_token) so the
    same dedup holds across per-shard processes."""
    seen = set()
    for store in node.command_stores.all():
        for txn_id, cmd in list(store.commands.items()):
            if txn_id in seen or txn_id < lo or not (txn_id < hi):
                continue
            ec = entry_class(cmd)
            if ec is None:
                continue
            scope = _audit_scope(cmd)
            if owned is not None:
                if not _owns_min_token(owned, scope, ranges):
                    continue
            elif not _in_ranges(scope, ranges):
                continue
            seen.add(txn_id)
            yield txn_id, ec[0], ec[1]
        # the paging tier (local/paging.py): spilled commands contribute
        # the SAME (class, executeAt, scope) their resident husks would —
        # captured at spill time, which is sound because every evictable
        # status is decision-terminal (eviction must never perturb the
        # cross-replica digests)
        pager = getattr(store, "pager", None)
        if pager is None:
            continue
        for txn_id, m in list(pager.meta.items()):
            if txn_id in seen or txn_id < lo or not (txn_id < hi):
                continue
            ec = m[0]
            if ec is None:
                continue
            if owned is not None:
                if not _owns_min_token(owned, m[1], ranges):
                    continue
            elif not _in_ranges(m[1], ranges):
                continue
            seen.add(txn_id)
            yield txn_id, ec[0], ec[1]


def digest_node(node, ranges: Ranges, lo: Timestamp, hi: Timestamp,
                owned: Ranges = None) -> Tuple[int, int]:
    """(digest, count): XOR-fold the committed decisions in the window."""
    acc = 0
    count = 0
    for txn_id, cls, at in _walk_decided(node, ranges, lo, hi, owned=owned):
        if cls != "committed":
            continue
        acc ^= entry_leaf(txn_id, at)
        count += 1
    return acc, count


def digest_reply(node, ranges: Ranges, lo: Timestamp, hi: Timestamp,
                 owned: Ranges = None) -> AuditDigestOk:
    """Serve one AUDIT_DIGEST_REQ: digest over the REQUESTED window plus
    this replica's own floors for the negotiation."""
    acc, count = digest_node(node, ranges, lo, hi, owned=owned)
    flo, fhi = node_floors(node, ranges)
    return AuditDigestOk(f"{acc:032x}", count, flo, fhi)


def collect_entries(node, ranges: Ranges, lo: Timestamp, hi: Timestamp,
                    owned: Ranges = None) -> List[tuple]:
    """Drill-down entry list for the window, sorted by txn id."""
    out = [(txn_id, cls, at)
           for txn_id, cls, at in _walk_decided(node, ranges, lo, hi,
                                                owned=owned)]
    out.sort(key=lambda e: e[0])
    return out


def local_digest(node, ranges: Ranges, lo: Timestamp, hi: Timestamp,
                 done: Callable) -> None:
    """Serve the auditor's LOCAL digest leg, calling done(AuditDigestOk).
    Synchronous in-loop; under the worker runtime the walk fans over the
    shard pipes (supervisor merge) and `done` fires when they answer."""
    cs = node.command_stores
    if cs.remote:
        from accord_tpu.messages.audit import AuditDigest
        cs.audit_local(AuditDigest(ranges, lo, hi), done)
        return
    done(digest_reply(node, ranges, lo, hi))


def local_entries(node, ranges: Ranges, lo: Timestamp, hi: Timestamp,
                  done: Callable) -> None:
    """Serve the auditor's LOCAL entry-list leg, calling
    done(AuditEntriesOk); worker-aware like local_digest."""
    cs = node.command_stores
    if cs.remote:
        from accord_tpu.messages.audit import AuditEntries
        cs.audit_local(AuditEntries(ranges, lo, hi), done)
        return
    done(AuditEntriesOk(tuple(collect_entries(node, ranges, lo, hi))))


def _midpoint(lo: Timestamp, hi: Timestamp) -> Optional[Timestamp]:
    """A split point strictly inside (lo, hi), or None when the window is
    no longer splittable (bisection then falls back to enumeration)."""
    if lo.epoch == hi.epoch:
        mid_hlc = (lo.hlc + hi.hlc) // 2
        mid = Timestamp(lo.epoch, mid_hlc, 0, 0)
    else:
        mid = Timestamp(hi.epoch, 0, 0, 0)
    if lo < mid < hi:
        return mid
    return None


# ---------------------------------------------------------------- census --

# SaveStatus -> census class (coarse lifecycle buckets; README table)
_STATUS_CLASS = {
    SaveStatus.NOT_DEFINED: "undecided",
    SaveStatus.PRE_ACCEPTED: "undecided",
    SaveStatus.ACCEPTED_INVALIDATE: "undecided",
    SaveStatus.ACCEPTED: "undecided",
    SaveStatus.PRE_COMMITTED: "decided",
    SaveStatus.COMMITTED: "decided",
    SaveStatus.STABLE: "executing",
    SaveStatus.READY_TO_EXECUTE: "executing",
    SaveStatus.PRE_APPLIED: "executing",
    SaveStatus.APPLYING: "executing",
    SaveStatus.APPLIED: "applied",
    SaveStatus.TRUNCATED_APPLY: "truncated",
    SaveStatus.ERASED: "erased",
    SaveStatus.INVALIDATED: "invalidated",
}

# terminal-but-uncleaned: what the cleanup ladder should eventually purge;
# monotonic growth here is the leak the census alarms on
_QUIESCENT_UNCLEANED = (SaveStatus.APPLIED, SaveStatus.INVALIDATED)

_WATERMARK_KINDS = ("locally_applied", "shard_applied", "durable_majority",
                    "durable_universal")


def _quantile(sorted_vals: List[int], q: float) -> int:
    if not sorted_vals:
        return 0
    rank = max(1, min(len(sorted_vals), int(q * len(sorted_vals) + 0.9999999)))
    return int(sorted_vals[rank - 1])


# the retention-heavy Command fields, all wire-registered — what the
# bounded-memory store would have to spill; WaitingOn bitsets / listener
# sets are small and not wire types, charged as a flat overhead
_BYTE_FIELDS = ("txn_id", "execute_at", "route", "partial_txn",
                "partial_deps", "stable_deps", "writes", "result")
_BYTE_OVERHEAD = 64


def _approx_cmd_bytes(cmd) -> int:
    """Wire-encoding size of one command's retained payload fields (the
    census byte estimator's per-sample probe)."""
    from accord_tpu.host.wire import encode
    import json as _json
    total = _BYTE_OVERHEAD
    for attr in _BYTE_FIELDS:
        v = getattr(cmd, attr, None)
        if v is None:
            continue
        try:
            total += len(_json.dumps(encode(v)))
        except TypeError:
            total += _BYTE_OVERHEAD  # host-specific unregistered payload
    return total


def census_node(node, byte_sample: int = 48) -> dict:
    """One sampled lifecycle sweep over the node's command stores and
    CommandsForKey indexes.  Counts are exact; resident bytes are estimated
    from a bounded sample of canonical encodings (the sweep must stay
    inside the always-on <2% budget, tests/test_obs_budget.py)."""

    cs = node.command_stores
    if cs.remote:
        # worker runtime: the stores live in per-shard processes — fold the
        # cached worker censuses (stats poll, ~2s fresh); before the first
        # poll lands, fall through to the storeless walk (a zeroed census)
        merged = cs.merged_census()
        if merged is not None:
            return merged

    now_us = node.obs.now_us()
    by_class: Dict[str, int] = {}
    by_durability: Dict[str, int] = {}
    ages: List[int] = []
    quiescent_uncleaned = 0
    total = 0
    sampled_bytes = 0
    sampled_n = 0
    cfk_keys = 0
    cfk_entries = 0
    gated = 0
    range_cmds = 0
    spilled_total = 0
    spilled_by_class: Dict[str, int] = {}
    spilled_uncleaned = 0
    cfk_spilled = 0
    paging = None
    # per-store breakdown (store.id == shard index node-wide): the paging
    # budget satellite's shard-labeled accord_pager_*/tier gauges read this
    per_shard: Dict[int, dict] = {}
    floors = {k: None for k in _WATERMARK_KINDS}
    for store in node.command_stores.all():
        # the paging tier: spilled state is evicted, NOT leaked — it must
        # stay visible to the census (and count against the leak detector
        # exactly as if resident).  Aggregates are maintained incrementally
        # by the pager so this sweep stays O(stores), not O(spilled).
        pager = getattr(store, "pager", None)
        if pager is not None:
            spilled_total += len(pager.meta)
            for cls, n in pager.spilled_by_class.items():
                spilled_by_class[cls] = spilled_by_class.get(cls, 0) + n
            spilled_uncleaned += pager.spilled_uncleaned
            cfk_spilled += len(pager.cfk_residuals)
            s = pager.stats()
            if paging is None:
                paging = dict(s)
            else:
                for k, v in s.items():
                    paging[k] += v
        cfk_keys += len(store.cfks)
        cfk_entries += sum(cfk.size() for cfk in store.cfks.values())
        gated += len(store.gated)
        range_cmds += len(store.range_commands)
        if not store.ranges.is_empty:
            rb, db = store.redundant_before, store.durable_before
            maj, uni = db.min_bounds(store.ranges)
            for kind, wm in (
                    ("locally_applied",
                     rb.min_locally_applied_before(store.ranges)),
                    ("shard_applied",
                     rb.min_shard_applied_before(store.ranges)),
                    ("durable_majority", maj),
                    ("durable_universal", uni)):
                cur = floors[kind]
                floors[kind] = wm if cur is None else min(cur, wm)
        n = len(store.commands)
        per_shard[store.id] = {
            "resident": n,
            "spilled": len(pager.meta) if pager is not None else 0,
            "paging": dict(pager.stats()) if pager is not None else None,
        }
        stride = max(1, n // max(1, byte_sample))
        for i, cmd in enumerate(list(store.commands.values())):
            total += 1
            st = cmd.save_status
            cls = _STATUS_CLASS.get(st, "other")
            by_class[cls] = by_class.get(cls, 0) + 1
            dname = cmd.durability.name
            by_durability[dname] = by_durability.get(dname, 0) + 1
            if st in _QUIESCENT_UNCLEANED:
                quiescent_uncleaned += 1
            if st >= SaveStatus.APPLIED:
                ref = cmd.execute_at if cmd.execute_at is not None \
                    else cmd.txn_id
                ages.append(max(0, now_us - ref.hlc))
            if i % stride == 0 and sampled_n < byte_sample:
                sampled_n += 1
                sampled_bytes += _approx_cmd_bytes(cmd)
    ages.sort()
    est_bytes = int(sampled_bytes / sampled_n * total) if sampled_n else 0
    watermarks = {}
    for kind in _WATERMARK_KINDS:
        wm = floors[kind] if floors[kind] is not None else TXNID_NONE
        watermarks[kind] = {
            "hlc": wm.hlc,
            # distance of the cleanup/durability fence from the HLC now:
            # the "cleanup lag" the bounded-memory store will size against
            # (-1 = no fact recorded yet for some owned span)
            "lag_us": (max(0, now_us - wm.hlc) if wm.hlc > 0 else -1),
        }
    return {
        "node": node.id,
        "at_us": now_us,
        "resident": total,
        "by_class": by_class,
        "by_durability": by_durability,
        # quiescent-but-uncleaned counts BOTH tiers: eviction moves a
        # command resident->spilled without changing this total, so the
        # leak detector cannot false-trip on paging (nor can paging hide
        # a genuine cleanup strand)
        "quiescent_uncleaned": quiescent_uncleaned + spilled_uncleaned,
        "resident_bytes_est": est_bytes,
        "spilled": spilled_total,
        "spilled_by_class": spilled_by_class,
        "spilled_quiescent_uncleaned": spilled_uncleaned,
        "paging": paging,
        "age_us": {"p50": _quantile(ages, 0.50),
                   "p95": _quantile(ages, 0.95),
                   "max": ages[-1] if ages else 0,
                   "count": len(ages)},
        "cfk": {"keys": cfk_keys, "entries": cfk_entries,
                "spilled": cfk_spilled},
        "gated": gated,
        "range_commands": range_cmds,
        "watermarks": watermarks,
        "per_shard": per_shard,
    }


def _merge_int_dicts(acc: Dict[str, int], d: Optional[Dict[str, int]]
                     ) -> Dict[str, int]:
    for k, v in (d or {}).items():
        acc[k] = acc.get(k, 0) + v
    return acc


def merge_censuses(censuses: List[dict], node_id: int, at_us: int) -> dict:
    """Fold per-worker censuses into one node view (worker runtime).
    Counts are exact sums; age quantiles cannot be merged exactly, so each
    is the max across workers (a conservative upper bound); watermark
    floors take the weakest shard (min hlc / max lag — a floor is only as
    good as the shard furthest behind)."""
    out = {
        "node": node_id, "at_us": at_us,
        "resident": sum(c["resident"] for c in censuses),
        "by_class": {}, "by_durability": {},
        "quiescent_uncleaned": sum(c["quiescent_uncleaned"]
                                   for c in censuses),
        "resident_bytes_est": sum(c["resident_bytes_est"]
                                  for c in censuses),
        "spilled": sum(c["spilled"] for c in censuses),
        "spilled_by_class": {},
        "spilled_quiescent_uncleaned": sum(
            c["spilled_quiescent_uncleaned"] for c in censuses),
        "paging": None,
        "gated": sum(c["gated"] for c in censuses),
        "range_commands": sum(c["range_commands"] for c in censuses),
        "per_shard": {},
    }
    for c in censuses:
        _merge_int_dicts(out["by_class"], c["by_class"])
        _merge_int_dicts(out["by_durability"], c["by_durability"])
        _merge_int_dicts(out["spilled_by_class"], c["spilled_by_class"])
        if c.get("paging") is not None:
            if out["paging"] is None:
                out["paging"] = {}
            _merge_int_dicts(out["paging"], c["paging"])
        for sid, ps in (c.get("per_shard") or {}).items():
            out["per_shard"][sid] = ps
    out["age_us"] = {
        q: max((c["age_us"][q] for c in censuses), default=0)
        for q in ("p50", "p95", "max")}
    out["age_us"]["count"] = sum(c["age_us"]["count"] for c in censuses)
    out["cfk"] = {
        k: sum(c["cfk"][k] for c in censuses)
        for k in ("keys", "entries", "spilled")}
    watermarks: Dict[str, dict] = {}
    for kind in _WATERMARK_KINDS:
        wms = [c["watermarks"][kind] for c in censuses
               if kind in c.get("watermarks", {})]
        if not wms:
            watermarks[kind] = {"hlc": 0, "lag_us": -1}
            continue
        watermarks[kind] = {
            "hlc": min(w["hlc"] for w in wms),
            "lag_us": (-1 if any(w["lag_us"] < 0 for w in wms)
                       else max(w["lag_us"] for w in wms)),
        }
    out["watermarks"] = watermarks
    return out


# --------------------------------------------------------------- auditor --

class _ShardAudit:
    """One digest round for one shard: floor negotiation, digest compare,
    and — on mismatch — the bisecting drill-down to the first divergent
    transaction.  All callbacks run on the node's single loop thread (sim
    queue / host dispatch loop), so there is no locking."""

    __slots__ = ("auditor", "ranges", "replicas", "peers", "on_done",
                 "outcome", "window", "rounds", "_settled")

    MAX_FLOOR_RETRIES = 2
    MAX_DEPTH = 48

    def __init__(self, auditor: "Auditor", shard, on_done: Callable):
        self.auditor = auditor
        self.ranges = Ranges([shard.range])
        self.replicas = sorted(shard.nodes)
        self.peers = [n for n in self.replicas if n != auditor.node.id]
        self.on_done = on_done
        self.outcome = None
        self.window: Tuple[Timestamp, Timestamp] = (TXNID_NONE, TXNID_NONE)
        self.rounds = 0
        self._settled = False

    # -- generic fan-out of one request to every replica (self served
    # locally: no loopback round trip, and an rf=1 shard still audits) --
    def _fan(self, make_req, local_fn, on_all) -> None:
        # `local_fn(done)` serves the local leg and calls done(reply):
        # synchronous in-loop, but asynchronous under the worker runtime
        # (the walk fans over the shard pipes before the reply exists)
        node = self.auditor.node
        replies: Dict[int, object] = {}
        missing = [0]  # failed/timed-out replicas (self included)
        outstanding = [len(self.peers) + 1]
        self.rounds += 1

        def settle():
            if outstanding[0] == 0:
                on_all(replies, missing[0])

        def local_done(reply):
            if type(reply) in (AuditDigestOk, AuditEntriesOk):
                replies[node.id] = reply
            else:
                missing[0] += 1
            outstanding[0] -= 1
            settle()

        def ok(from_id, reply):
            if type(reply) in (AuditDigestOk, AuditEntriesOk):
                replies[from_id] = reply
            else:
                missing[0] += 1
            outstanding[0] -= 1
            settle()

        def fail(from_id, _failure):
            missing[0] += 1
            outstanding[0] -= 1
            settle()

        for to in self.peers:
            node.send(to, make_req(), FunctionCallback(ok, fail))
        local_fn(local_done)

    def _finish(self, outcome: str) -> None:
        if self._settled:
            return
        self._settled = True
        self.outcome = outcome
        a = self.auditor
        a.registry.counter("accord_audit_rounds_total",
                           outcome=outcome).inc()
        r = self.ranges[0]
        a.node.obs.flight.record(
            "audit_digest", None,
            (r.start, r.end, len(self.replicas), outcome))
        self.on_done(self)

    # -- phase 1: floor-negotiated digest compare --
    def start(self) -> None:
        lo, hi = node_floors(self.auditor.node, self.ranges)
        self._digest_round(lo, hi, retries=self.MAX_FLOOR_RETRIES)

    def _digest_round(self, lo: Timestamp, hi: Timestamp,
                      retries: int) -> None:
        node = self.auditor.node
        self._fan(lambda: AuditDigest(self.ranges, lo, hi),
                  lambda done: local_digest(node, self.ranges, lo, hi, done),
                  lambda replies, missing: self._on_digests(
                      lo, hi, retries, replies, missing))

    def _on_digests(self, lo, hi, retries, replies, missing) -> None:
        if missing:
            return self._finish("inconclusive")
        nlo = max(r.lo_floor for r in replies.values())
        nlo = max(nlo, lo)
        nhi = min(r.hi_floor for r in replies.values())
        if (nlo, nhi) != (lo, hi):
            if not (nlo < nhi):
                self.window = (nlo, nhi)
                return self._finish("agree")  # empty certified window
            if retries > 0:
                return self._digest_round(nlo, nhi, retries - 1)
            return self._finish("inconclusive")  # floors kept moving
        self.window = (lo, hi)
        if not (lo < hi):
            return self._finish("agree")
        if len({r.digest for r in replies.values()}) == 1:
            return self._finish("agree")
        self.auditor.registry.counter("accord_audit_mismatch_total").inc()
        count = max(r.count for r in replies.values())
        self._drill(lo, hi, count, depth=0)

    # -- phase 2: bisect to an enumerable window, then diff entries --
    def _drill(self, lo, hi, count_hint, depth) -> None:
        a = self.auditor
        a.registry.counter("accord_audit_drill_total").inc()
        mid = _midpoint(lo, hi) if count_hint > a.entry_limit else None
        if mid is None or depth >= self.MAX_DEPTH:
            return self._fetch_entries(lo, hi, depth)
        node = a.node

        def on_half(half_lo, half_hi, next_fn):
            def handler(replies, missing):
                if missing:
                    return self._finish("inconclusive")
                if len({r.digest for r in replies.values()}) > 1:
                    self._drill(half_lo, half_hi,
                                max(r.count for r in replies.values()),
                                depth + 1)
                else:
                    next_fn()
            return handler

        def try_right():
            self._fan(lambda: AuditDigest(self.ranges, mid, hi),
                      lambda done: local_digest(node, self.ranges, mid, hi,
                                                done),
                      on_half(mid, hi,
                              lambda: self._finish("inconclusive")))

        # lowest mismatching half first: the drill lands on the FIRST
        # divergent transaction in the window
        self._fan(lambda: AuditDigest(self.ranges, lo, mid),
                  lambda done: local_digest(node, self.ranges, lo, mid,
                                            done),
                  on_half(lo, mid, try_right))

    def _fetch_entries(self, lo, hi, depth) -> None:
        node = self.auditor.node
        self._fan(lambda: AuditEntries(self.ranges, lo, hi),
                  lambda done: local_entries(node, self.ranges, lo, hi,
                                             done),
                  lambda replies, missing: self._on_entries(
                      lo, hi, depth, replies, missing))

    def _on_entries(self, lo, hi, depth, replies, missing) -> None:
        a = self.auditor
        if missing:
            return self._finish("inconclusive")
        if any(r.truncated for r in replies.values()):
            mid = _midpoint(lo, hi)
            if mid is not None and depth < self.MAX_DEPTH:
                # over the serving cap: keep splitting rather than diffing
                # a partial list
                return self._drill(lo, hi, AuditEntries.LIMIT * 2, depth + 1)
            return self._finish("inconclusive")
        by_node = {n: {t: (cls, at) for t, cls, at in r.entries}
                   for n, r in replies.items()}
        a.registry.counter("accord_audit_entries_total").inc(
            sum(len(m) for m in by_node.values()))
        hard, lag = classify_entry_sets(by_node)
        for txn_id, kind, vals in hard:
            a._record_divergence(self, txn_id, kind, vals)
        escalated = a._note_lag(self, lag)
        if hard or escalated:
            return self._finish("divergence")
        return self._finish("mismatch_lag")


class Auditor:
    """Per-node audit + census driver.

    `audit_once` runs one digest round per shard this node replicates
    (skipped while a previous invocation is still in flight); `census_once`
    runs one lifecycle sweep.  `start()` arms recurring timers for either
    surface whose interval is > 0 — both are OFF by default so harnesses
    opt in explicitly (hosts default them on via auditor_from_env)."""

    def __init__(self, node, interval_s: float = 0.0,
                 census_interval_s: Optional[float] = None,
                 entry_limit: int = 1024, lag_rounds: int = 3,
                 leak_min_growth: int = 64, leak_sweeps: int = 20):
        self.node = node
        self.interval_s = interval_s
        self.census_interval_s = (census_interval_s
                                  if census_interval_s is not None
                                  else interval_s)
        self.entry_limit = entry_limit
        self.lag_rounds = lag_rounds
        self.registry = node.obs.registry
        self.leak = LeakDetector(min_growth=leak_min_growth,
                                 sweeps=leak_sweeps)
        self.divergences: List[dict] = []
        self.last_report: Optional[dict] = None
        self.last_census: Optional[dict] = None
        # (txn repr, node) -> consecutive rounds a committed-below-universal
        # entry was absent on that node; escalates at lag_rounds
        self._lag: Dict[tuple, int] = {}
        # a persistent divergence is re-confirmed by every later round:
        # count each re-detection (the metric is the liveness signal) but
        # record one row per distinct (txn, kind)
        self._div_seen: set = set()
        self._timers: list = []
        self._busy = False
        # live view for the metrics endpoint's /audit route + host frames
        node.obs.audit_view = self.view

    # ------------------------------------------------------------- audit --
    def audit_once(self, on_done: Optional[Callable] = None) -> bool:
        """One full pass over this node's shards; False when a previous
        pass is still in flight (on_done then fires with None)."""
        if self._busy:
            if on_done is not None:
                on_done(None)
            return False
        topo = self.node.topology.current()
        shards = [s for s in topo.shards if self.node.id in s.nodes]
        if not shards:
            if on_done is not None:
                on_done({"at_us": self.node.obs.now_us(), "rounds": []})
            return True
        self._busy = True
        results: List[_ShardAudit] = []

        def next_shard(i: int) -> None:
            if i >= len(shards):
                self._busy = False
                report = {
                    "at_us": self.node.obs.now_us(),
                    "rounds": [{"range": [r.ranges[0].start,
                                          r.ranges[0].end],
                                "replicas": r.replicas,
                                "outcome": r.outcome,
                                "window": [repr(r.window[0]),
                                           repr(r.window[1])],
                                "requests": r.rounds}
                               for r in results],
                }
                self.last_report = report
                if on_done is not None:
                    on_done(report)
                return
            audit = _ShardAudit(self, shards[i],
                                lambda r: (results.append(r),
                                           next_shard(i + 1)))
            audit.start()

        next_shard(0)
        return True

    def _record_divergence(self, shard_audit: _ShardAudit, txn_id, kind,
                           vals) -> None:
        tid = repr(txn_id)
        r = shard_audit.ranges[0]
        self.registry.counter("accord_audit_divergence_total",
                              kind=kind).inc()
        # every (re-)confirmation goes on the bounded flight ring — a
        # persistent divergence must still be visible when the ring has
        # wrapped past its first detection
        self.node.obs.flight.record(
            "audit_divergence", tid,
            (kind, r.start, r.end,
             tuple(n for n, v in sorted(vals.items()) if v is not None)))
        if (tid, kind) in self._div_seen:
            return
        self._div_seen.add((tid, kind))
        row = {
            "txn": tid,
            "kind": kind,
            "range": [r.start, r.end],
            "replicas": shard_audit.replicas,
            "nodes": {str(n): (None if v is None
                               else [v[0], repr(v[1]) if v[1] is not None
                                     else None])
                      for n, v in vals.items()},
            "at_us": self.node.obs.now_us(),
        }
        self.divergences.append(row)

    def _note_lag(self, shard_audit: _ShardAudit, lag) -> bool:
        """Track committed-below-universal entries absent on some replica;
        persistent absence across `lag_rounds` consecutive drill-downs is
        itself a divergence (the universal certificate says every replica
        applied it — a healthy replica mid-catch-up clears in one round)."""
        escalated = False
        seen = set()
        for txn_id, absent_nodes in lag:
            for n in absent_nodes:
                key = (repr(txn_id), n)
                seen.add(key)
                self._lag[key] = self._lag.get(key, 0) + 1
                if self._lag[key] == self.lag_rounds:
                    self._record_divergence(
                        shard_audit, txn_id, "missing_below_universal",
                        {n: None})
                    escalated = True
        # any (txn, node) no longer lagging resolved itself: forget it
        for key in [k for k in self._lag if k not in seen]:
            del self._lag[key]
        return escalated

    # ------------------------------------------------------------ census --
    def census_once(self) -> dict:
        census = census_node(self.node)
        self.last_census = census
        reg = self.registry
        nid = self.node.id
        reg.counter("accord_census_sweeps_total").inc()
        for cls, n in census["by_class"].items():
            reg.gauge("accord_census_resident", node=nid, cls=cls).set(n)
        # tier-labeled view (resident|spilled): evicted-but-live state must
        # not vanish from accord_census_* — the spilled tier is published
        # beside the resident one under the same class buckets
        for cls, n in census["by_class"].items():
            reg.gauge("accord_census_commands", node=nid, cls=cls,
                      tier="resident").set(n)
        for cls, n in census["spilled_by_class"].items():
            reg.gauge("accord_census_commands", node=nid, cls=cls,
                      tier="spilled").set(n)
        reg.gauge("accord_census_spilled_total", node=nid).set(
            census["spilled"])
        paging = census.get("paging")
        if paging is not None:
            for k in ("hits", "misses", "evictions", "refaults",
                      "resident", "resident_high_water", "spilled",
                      "cfk_evictions", "cfk_restores", "spill_disk_bytes",
                      "spill_compactions"):
                reg.gauge(f"accord_pager_{k}", node=nid).set(paging[k])
        # per-shard paging budgets: the same tier/pager surfaces labeled by
        # shard (store.id == shard index, in-loop and worker mode alike)
        for sid, ps in (census.get("per_shard") or {}).items():
            reg.gauge("accord_census_commands", node=nid, tier="resident",
                      shard=sid).set(ps["resident"])
            reg.gauge("accord_census_commands", node=nid, tier="spilled",
                      shard=sid).set(ps["spilled"])
            pg = ps.get("paging")
            if pg is not None:
                for k in ("hits", "misses", "evictions", "refaults",
                          "resident", "resident_high_water", "spilled",
                          "cfk_evictions", "cfk_restores",
                          "spill_disk_bytes", "spill_compactions"):
                    reg.gauge(f"accord_pager_{k}", node=nid,
                              shard=sid).set(pg.get(k, 0))
        for d, n in census["by_durability"].items():
            reg.gauge("accord_census_resident_by_durability", node=nid,
                      durability=d).set(n)
        reg.gauge("accord_census_resident_total", node=nid).set(
            census["resident"])
        reg.gauge("accord_census_resident_bytes_est", node=nid).set(
            census["resident_bytes_est"])
        reg.gauge("accord_census_quiescent_uncleaned", node=nid).set(
            census["quiescent_uncleaned"])
        reg.gauge("accord_census_cfk_entries", node=nid).set(
            census["cfk"]["entries"])
        for q in ("p50", "p95", "max"):
            reg.gauge("accord_census_age_us", node=nid, q=q).set(
                census["age_us"][q])
        # satellite: the cleanup/durability watermarks finally reach
        # /metrics — floor HLC and its distance from now, per node
        for kind, wm in census["watermarks"].items():
            reg.gauge("accord_watermark_hlc", node=nid, kind=kind).set(
                wm["hlc"])
            reg.gauge("accord_watermark_lag_us", node=nid, kind=kind).set(
                wm["lag_us"])
        alarm = self.leak.observe(census["quiescent_uncleaned"])
        if alarm:
            reg.counter("accord_census_leak_alarms_total").inc()
        census["leak_alarm"] = alarm
        census["leak_alarms_total"] = self.leak.alarms
        self.node.obs.flight.record(
            "census_sweep", None,
            (census["resident"], census["quiescent_uncleaned"],
             census["resident_bytes_est"]))
        return census

    # --------------------------------------------------------- lifecycle --
    def start(self) -> None:
        sched = self.node.scheduler
        if self.interval_s and self.interval_s > 0:
            self._timers.append(
                sched.recurring(self.interval_s,
                                lambda: self.audit_once()))
        if self.census_interval_s and self.census_interval_s > 0:
            self._timers.append(
                sched.recurring(self.census_interval_s,
                                lambda: self.census_once()))

    def stop(self) -> None:
        for t in self._timers:
            try:
                t.cancel()
            except AttributeError:
                pass
        self._timers = []

    def view(self) -> dict:
        """JSON-safe live view (httpd /audit, the tcp "audit" frame)."""
        return {
            "node": self.node.id,
            "divergences": list(self.divergences),
            "last_report": self.last_report,
            "census": self.last_census,
            "leak_alarms": self.leak.alarms,
        }


def auditor_from_env(node, default_interval_s: float = 5.0
                     ) -> Optional[Auditor]:
    """Host wiring: ACCORD_AUDIT_S tunes the periodic audit+census interval
    (seconds; 0 disables, default 5).  Census runs on the same cadence."""
    raw = os.environ.get("ACCORD_AUDIT_S", "")
    try:
        interval = float(raw) if raw else default_interval_s
    except ValueError:
        interval = default_interval_s
    if interval <= 0:
        return None
    auditor = Auditor(node, interval_s=interval)
    auditor.start()
    return auditor
