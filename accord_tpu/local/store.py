"""CommandStore / SafeCommandStore / CommandStores: the sharded replica state.

Reference: accord/local/CommandStore.java:80-727 (single-threaded metadata
shard), SafeCommandStore.java:56+ (the transactional view and conflict query
API), CommandStores.java:78-726 (range-sharded fan-out with map-reduce),
ShardDistributor.EvenSplit (ShardDistributor.java:33-46), PreLoadContext
(PreLoadContext.java:42).

Intra-node parallelism model is the reference's: the node's owned keyspace is
split over N logically single-threaded CommandStore shards; every operation
declares what it touches (PreLoadContext) and runs on each intersecting shard
via `execute`, with replies reduced across shards.
"""

from __future__ import annotations

from bisect import bisect_left
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple, TYPE_CHECKING

from accord_tpu.local.cfk import CommandsForKey, InternalStatus, TimestampsForKey, Unmanaged
from accord_tpu.local.command import Command
from accord_tpu.local.status import SaveStatus
from accord_tpu.local.watermarks import DurableBefore, MaxConflicts, RedundantBefore
from accord_tpu.primitives.deps import Deps
from accord_tpu.primitives.keys import (EMPTY_KEYS, Key, Keys, Range, Ranges,
                                        RoutingKey, _SortedKeyList)
from accord_tpu.primitives.timestamp import KindSet, Timestamp, TxnId
from accord_tpu.utils import invariants
from accord_tpu.utils.async_chains import AsyncResult

if TYPE_CHECKING:
    from accord_tpu.api.spi import Agent, DataStore, ProgressLog


class PreLoadContext:
    """Declares the TxnIds/keys an operation touches so async store
    implementations can page them in (PreLoadContext.java:42). The in-memory
    store ignores it; the simulator uses it to model cache-miss delays.

    `deps_probes` — optional (before, KindSet, keys) tuples declaring the
    active-conflict scans the operation will run, letting a batched device
    store precompute them for the whole flush window in one kernel call."""

    __slots__ = ("txn_ids", "keys", "deps_probes", "recovery_probes",
                 "execute_probes")

    def __init__(self, txn_ids: Sequence[TxnId] = (), keys=None,
                 deps_probes: Sequence = (), recovery_probes: Sequence = (),
                 execute_probes: Sequence = ()):
        self.txn_ids = tuple(txn_ids)
        self.keys = keys if keys is not None else EMPTY_KEYS
        self.deps_probes = tuple(deps_probes)
        # (txn_id, Keys) of BeginRecovery's mapReduceFull predicate scans —
        # the batched device store precomputes them per flush window
        self.recovery_probes = tuple(recovery_probes)
        # (txn_id, execute_at, Keys) of executions this operation delivers
        # (Apply messages): the batched device store plans the window's
        # apply order with the wavefront kernel (ops/wavefront.py)
        self.execute_probes = tuple(execute_probes)

    @classmethod
    def empty(cls) -> "PreLoadContext":
        return cls()

    @classmethod
    def for_txn(cls, txn_id: TxnId, keys=None,
                deps_probes: Sequence = (),
                recovery_probes: Sequence = (),
                execute_probes: Sequence = ()) -> "PreLoadContext":
        return cls((txn_id,), keys, deps_probes, recovery_probes,
                   execute_probes)


class SafeCommandStore:
    """The view handed to operations executing on a shard
    (SafeCommandStore.java:56). Provides command access, CFK registration, and
    the conflict query API (mapReduceActive / recovery scans)."""

    def __init__(self, store: "CommandStore", context: PreLoadContext):
        self.store = store
        self.context = context

    # -- command access --
    def get(self, txn_id: TxnId) -> Command:
        return self.store._get_or_create(txn_id)

    def if_present(self, txn_id: TxnId) -> Optional[Command]:
        return self.store.commands.get(txn_id)

    def if_initialised(self, txn_id: TxnId) -> Optional[Command]:
        c = self.store.commands.get(txn_id)
        return c if c is not None and c.save_status != SaveStatus.NOT_DEFINED \
            else None

    # -- environment --
    @property
    def ranges(self) -> Ranges:
        return self.store.ranges

    @property
    def agent(self) -> "Agent":
        return self.store.agent

    @property
    def data_store(self) -> "DataStore":
        return self.store.data_store

    @property
    def progress_log(self) -> "ProgressLog":
        return self.store.progress_log

    @property
    def node(self):
        return self.store.node

    def time_now(self) -> Timestamp:
        return self.store.unique_now()

    # -- CFK maintenance --
    def cfk(self, key: Key) -> CommandsForKey:
        return self.store._cfk(key)

    def tfk(self, key: Key) -> TimestampsForKey:
        return self.store._tfk(key)

    def is_safe_to_read(self, selection) -> bool:
        """Is the data for `selection` (Keys or Ranges, already owned-sliced)
        locally complete? (reference SafeToRead epochs)"""
        safe = self.store.safe_to_read
        if isinstance(selection, Ranges):
            return selection.subtract(safe).is_empty
        return all(safe.contains(k) for k in selection)

    def owned_keys_of(self, command: Command) -> Keys:
        """The command's participating data keys owned by this store. For
        range-domain commands, the keys with local conflict state inside the
        owned ranges (range txns have no enumerable key set of their own)."""
        if command.partial_txn is not None and isinstance(command.partial_txn.keys, Keys):
            # identity-memoized: register() recomputes this slice on every
            # transition of the same command over the same immutable
            # (keys, ranges) pair — both are replaced wholesale on change
            keys, ranges = command.partial_txn.keys, self.ranges
            memo = command.owned_keys_memo
            if memo is not None and memo[0] is keys and memo[1] is ranges:
                return memo[2]
            owned = keys.slice(ranges)
            command.owned_keys_memo = (keys, ranges, owned)
            return owned
        if command.txn_id.is_range_domain:
            ranges = None
            if command.partial_txn is not None:
                ranges = command.partial_txn.keys
            elif command.route is not None and not command.route.is_key_domain:
                ranges = command.route.ranges
            if ranges is None:
                return Keys(())
            owned = self._owned_participants(ranges)
            return Keys(self._owned_cfk_keys(owned))
        if command.route is not None and command.route.is_key_domain:
            return Keys([Key(k.token) for k in command.route.keys]).slice(self.ranges)
        return Keys(())

    def register(self, command: Command, status: InternalStatus) -> None:
        """Reflect a command transition into every owned CFK
        (SafeCommandStore registration / CommandsForKey.update). When the
        transition carries deps (ACCEPTED+), each key's CFK receives the
        command's dependency ids AT THAT KEY so it can maintain the
        missing[] divergence encoding."""
        if command.txn_id.is_range_domain:
            return  # range txns are tracked via rangeCommands, not per-key CFK
        key_deps = None
        if status.has_info:
            deps = command.stable_deps if command.stable_deps is not None \
                else command.partial_deps
            key_deps = deps.key_deps if deps is not None else None
        prof = self.store.cpuprof
        txn_id, execute_at = command.txn_id, command.execute_at
        # cfk stage fence (obs/cpuprof.py): ONE batched fence brackets the
        # whole per-key registration walk (not a fence re-entry per key);
        # fired Unmanaged callbacks still run OUTSIDE the fence (they are
        # execution work, not index maintenance) and keep their per-key
        # interleaving — the fence is suspended around them and resumed
        cfks = self.store.cfks
        # owned-key routing resolves OUTSIDE the fence: the cfk stage
        # measures conflict-index maintenance, not key-set slicing
        keys = self.owned_keys_of(command)
        t = prof.stage_begin() if prof is not None and prof.active else None
        for key in keys:
            dep_ids = key_deps.txn_ids_for_key(key) \
                if key_deps is not None else None
            cfk = cfks.get(key)
            if cfk is None:
                cfk = self.store._cfk(key)
            fired = cfk.update(txn_id, status, execute_at, dep_ids=dep_ids)
            if fired:
                if t is not None:
                    prof.stage_end(t, "cfk")
                    t = None
                for u in fired:
                    u.callback(self)
                if prof is not None and prof.active:
                    t = prof.stage_begin()
        if t is not None:
            prof.stage_end(t, "cfk")

    def register_range_txn(self, command: Command, ranges: Ranges) -> None:
        self.store.range_version += 1
        # append-only registration log: lets a device range probe taken at
        # an older version serve by unioning the additions since its
        # snapshot (deletions are dropped by the live activity filter)
        if self.store.range_log is not None:
            self.store.range_log.append(command.txn_id)
        self.store.range_commands[command.txn_id] = ranges.slice(self.ranges) \
            if not self.ranges.is_empty else ranges

    # -- conflict queries --
    def _owned_participants(self, participants):
        """Slice a Keys/Ranges selection to this store's ranges."""
        if self.ranges.is_empty:
            return participants
        return participants.slice(self.ranges)

    def _owned_cfk_keys(self, ranges: Ranges) -> List[Key]:
        """Data keys with conflict state inside `ranges` (the per-key walk a
        range txn makes over CommandsForKey, CommandsForKey.java range-txn
        registration).  Served by the store's maintained sorted key index —
        two bisects per range instead of a full-dict scan per query."""
        return self.store.cfk_keys_in(ranges)

    def _active_range_conflict(self, txn_id: TxnId, before: Timestamp,
                               kinds: KindSet) -> bool:
        cmd = self.store.commands.get(txn_id)
        if cmd is None or cmd.save_status == SaveStatus.INVALIDATED \
                or cmd.save_status == SaveStatus.ERASED:
            return False
        # TRUNCATED_APPLY (majority-durable, outcome retained) remains a
        # conflict: a lower-id straggler must still witness it so lagging
        # replicas order their writes after it; only ERASE (universal tier,
        # shard fence installed) removes it from witnessing entirely
        return txn_id < before and txn_id.kind in kinds

    def map_reduce_active(self, participants, before: Timestamp,
                          kinds: KindSet,
                          fn: Callable[[Key, TxnId], None],
                          on_range_dep: Callable[[Ranges, TxnId], None] = None,
                          exclude: Optional[TxnId] = None) -> None:
        """Active-conflict scan — the deps calculation
        (SafeCommandStore.mapReduceActive -> CommandsForKey.mapReduceActive).

        `participants` is Keys (key-domain txn) or Ranges (range-domain /
        sync point). Key-domain conflicts are reported per key via `fn`;
        range-domain conflicts via `on_range_dep(overlap_ranges, dep_id)`
        (they become RangeDeps entries, reference Deps.Builder domain split).

        `exclude` — the querying txn's own id, which the caller filters from
        the result anyway (calculate_deps). The scalar scan ignores it; the
        device store uses it to recognise that the only CFK mutation since
        its snapshot was the querier's own registration.
        """
        is_range = isinstance(participants, Ranges)
        owned = self._owned_participants(participants)
        keys = self._owned_cfk_keys(owned) if is_range else owned

        for key in keys:
            cfk = self.store.cfks.get(key)
            if cfk is not None:
                cfk.map_reduce_active(before, kinds,
                                      lambda t, k=key: fn(k, t))
        self._map_range_conflicts(owned, is_range, before, kinds, fn,
                                  on_range_dep)

    def _map_range_conflicts(self, owned, is_range: bool, before: Timestamp,
                             kinds: KindSet, fn, on_range_dep) -> None:
        """Range-domain txns intersecting the participants are conflicts too.
        Split out so the device store can serve the per-key tier from its
        batched kernel while keeping this tier on the live scalar scan."""
        for txn_id, ranges in self.store.range_commands.items():
            if not self._active_range_conflict(txn_id, before, kinds):
                continue
            if is_range:
                overlap = ranges.intersection(owned)
            else:
                overlap = Ranges([r for r in ranges
                                  if any(r.contains(k) for k in owned)])
            if overlap.is_empty:
                continue
            if on_range_dep is not None:
                on_range_dep(overlap, txn_id)
            else:
                for key in (self._owned_cfk_keys(overlap) if is_range
                            else [k for k in owned if overlap.contains(k)]):
                    fn(key, txn_id)

    def max_conflict(self, participants) -> Optional[Timestamp]:
        return self.store.max_conflicts.get(participants)

    def update_max_conflicts(self, participants, ts: Timestamp) -> None:
        self.store.max_conflicts.update(participants, ts)

    def _witnessed_by(self, by: TxnId, target: TxnId) -> bool:
        """Does `by`'s dependency set include `target`?"""
        cmd = self.store.commands.get(by)
        if cmd is None:
            return False
        for deps in (cmd.stable_deps, cmd.partial_deps):
            if deps is not None and deps.contains(target):
                return True
        return False

    # recovery predicates (BeginRecovery.java:104-190 via mapReduceFull)
    def _participant_cfks(self, participants):
        owned = self._owned_participants(participants)
        keys = (self._owned_cfk_keys(owned) if isinstance(owned, Ranges)
                else owned)
        for key in keys:
            cfk = self.store.cfks.get(key)
            if cfk is not None:
                yield cfk

    def _conflicting_range_cmds(self, txn_id: TxnId, participants):
        """(dep_cmd, overlap Ranges) for every live range-domain command whose
        registered ranges intersect `participants`, excluding txn_id itself."""
        owned = self._owned_participants(participants)
        is_range = isinstance(owned, Ranges)
        for dep_id, ranges in self.store.range_commands.items():
            if dep_id == txn_id:
                continue
            cmd = self.store.commands.get(dep_id)
            if cmd is None:
                continue
            if is_range:
                overlap = ranges.intersection(owned)
            else:
                overlap = Ranges([r for r in ranges
                                  if any(r.contains(k) for k in owned)])
            if not overlap.is_empty:
                yield cmd, overlap

    # The recovery predicates split into a key tier (CommandsForKey scans —
    # overridable by the batched device store) and a range tier (the
    # range-command walk, always live).

    def rejects_fast_path(self, txn_id: TxnId, participants) -> bool:
        return self.decipher_fast_path(txn_id, participants)[0]

    def decipher_fast_path(self, txn_id: TxnId, participants
                           ) -> Tuple[bool, "Deps"]:
        """(rejects, unresolved_covers): the fast-path reject predicates
        with the elision classifier's third verdict surfaced.  `rejects`
        is definite evidence; `unresolved_covers` are key-associated write
        deps whose commit status must resolve before omission evidence at
        this replica can be read either way (CommandsForKey.
        omission_covers) — the recovery coordinator awaits their commit
        and retries, exactly like earlier-accepted-no-witness deps
        (Recover.java:322-336)."""
        rejects, unresolved = self._decipher_fast_path_keys(txn_id,
                                                            participants)
        if not rejects and self._rejects_fast_path_ranges(txn_id,
                                                          participants):
            rejects = True
        if rejects or not unresolved:
            return rejects, Deps.NONE
        from accord_tpu.primitives.deps import KeyDeps
        builder = KeyDeps.builder()
        for key, cover in unresolved:
            builder.add(key, cover)
        return False, Deps(builder.build(), None)

    def _cover_resolver(self):
        """Resolve a cover candidate against the store-wide command
        registry (the per-key view conflates invalidated with
        truncated-applied and drops pruned entries wholesale)."""
        commands = self.store.commands

        def resolve(w: TxnId):
            cmd = commands.get(w)
            if cmd is None:
                return None  # never materialised here / erased: CFK decides
            if cmd.is_invalidated:
                return ("invalid", None)
            if cmd.execute_at is not None \
                    and cmd.has_been(SaveStatus.PRE_COMMITTED):
                return ("committed", cmd.execute_at)
            if cmd.is_truncated:
                return None  # applied-and-shed: executeAt unobservable
            return ("undecided", None)

        return resolve

    def _decipher_fast_path_keys(self, txn_id: TxnId, participants
                                 ) -> Tuple[bool, List[Tuple[Key, TxnId]]]:
        served_a: Dict[Key, List[TxnId]] = {}
        served_b: Dict[Key, List[TxnId]] = {}
        for cfk in self._participant_cfks(participants):
            raw = cfk.started_after_without_witnessing_ids(txn_id, raw=True)
            if raw:
                served_a[cfk.key] = raw
            raw = cfk.executes_after_without_witnessing_ids(txn_id, raw=True)
            if raw:
                served_b[cfk.key] = raw
        return self._classify_omission_maps((served_a, served_b), txn_id)

    def _classify_omission_maps(self, served_maps, txn_id: TxnId
                                ) -> Tuple[bool, List[Tuple[Key, TxnId]]]:
        """The shared host-side classification step over {key: raw
        candidate ids} maps — ONE implementation for the scalar and
        device-served paths, so the soundness-critical evidence /
        elided / unresolved triage cannot diverge between them."""
        resolve = self._cover_resolver()
        unresolved: List[Tuple[Key, TxnId]] = []
        for mapping in served_maps:
            for key, ids in mapping.items():
                evidence, covers = self.cfk(key).classify_omissions(
                    list(ids), txn_id, resolve)
                if evidence:
                    return True, []
                unresolved.extend((key, w) for w in covers)
        return False, unresolved

    def _rejects_fast_path_ranges(self, txn_id: TxnId, participants) -> bool:
        wb = lambda t: self._witnessed_by(t, txn_id)
        for cmd, _ in self._conflicting_range_cmds(txn_id, participants):
            if not cmd.txn_id.witnesses(txn_id) or wb(cmd.txn_id) \
                    or cmd.is_invalidated or cmd.is_truncated:
                continue
            if cmd.txn_id > txn_id and cmd.has_been(SaveStatus.ACCEPTED):
                return True
            if cmd.has_been(SaveStatus.STABLE) and cmd.execute_at is not None \
                    and cmd.execute_at > txn_id:
                return True
        return False

    def earlier_committed_witness(self, txn_id: TxnId, participants) -> Deps:
        """Key/range-associated, so recovery can await on the dep's own shards
        (reference returns Deps, BeginRecovery.java:344)."""
        from accord_tpu.primitives.deps import KeyDeps
        builder = KeyDeps.builder()
        self._earlier_committed_witness_keys(txn_id, participants, builder)
        return Deps(builder.build(),
                    self._earlier_committed_witness_ranges(txn_id,
                                                           participants))

    def _earlier_committed_witness_keys(self, txn_id, participants,
                                        builder) -> None:
        for cfk in self._participant_cfks(participants):
            for t in cfk.stable_started_before_and_witnessed(txn_id):
                builder.add(cfk.key, t)

    def _earlier_committed_witness_ranges(self, txn_id, participants):
        from accord_tpu.primitives.deps import RangeDeps
        wb = lambda t: self._witnessed_by(t, txn_id)
        rbuilder = RangeDeps.builder()
        for cmd, overlap in self._conflicting_range_cmds(txn_id, participants):
            if cmd.txn_id < txn_id and cmd.has_been(SaveStatus.STABLE) \
                    and not cmd.is_invalidated and not cmd.is_truncated \
                    and wb(cmd.txn_id):
                for r in overlap:
                    rbuilder.add(r, cmd.txn_id)
        return rbuilder.build()

    def earlier_accepted_no_witness(self, txn_id: TxnId, participants) -> Deps:
        from accord_tpu.primitives.deps import KeyDeps
        builder = KeyDeps.builder()
        self._earlier_accepted_no_witness_keys(txn_id, participants, builder)
        return Deps(builder.build(),
                    self._earlier_accepted_no_witness_ranges(txn_id,
                                                             participants))

    def _earlier_accepted_no_witness_keys(self, txn_id, participants,
                                          builder) -> None:
        for cfk in self._participant_cfks(participants):
            for t in cfk.accepted_started_before_without_witnessing(txn_id):
                builder.add(cfk.key, t)

    def _earlier_accepted_no_witness_ranges(self, txn_id, participants):
        from accord_tpu.primitives.deps import RangeDeps
        wb = lambda t: self._witnessed_by(t, txn_id)
        rbuilder = RangeDeps.builder()
        for cmd, overlap in self._conflicting_range_cmds(txn_id, participants):
            if cmd.txn_id < txn_id \
                    and cmd.save_status == SaveStatus.ACCEPTED \
                    and cmd.execute_at is not None \
                    and cmd.execute_at > txn_id \
                    and txn_id.witnesses(cmd.txn_id) \
                    and not wb(cmd.txn_id):
                for r in overlap:
                    rbuilder.add(r, cmd.txn_id)
        return rbuilder.build()


class CommandStore:
    """One logically single-threaded metadata shard (CommandStore.java:80).

    `execute(context, fn)` is the only entry point for mutations; the base
    implementation runs inline (synchronous in-memory store). Subclasses
    (accord_tpu.impl / the simulator's DelayedCommandStore) override
    `_submit` to add executor hops, async-load delays, and thread checks.
    """

    def __init__(self, store_id: int, node, ranges: Ranges):
        self.id = store_id
        self.node = node
        self.ranges = ranges
        # ranges whose data is locally complete (initial ownership, or
        # bootstrap finished); reads outside it nack so the coordinator
        # retries a caught-up replica (the reference SafeToRead epochs)
        self.safe_to_read: Ranges = ranges
        self.commands: Dict[TxnId, Command] = {}
        self.cfks: Dict[Key, CommandsForKey] = {}
        # sorted index over cfks (tokens + keys in lockstep): CFKs are only
        # ever created (never dropped — pruning empties them in place), so
        # _cfk() maintains it exactly and range-bounded key queries bisect
        # instead of scanning the whole dict (cfk_keys_in)
        self._cfk_tokens: List[int] = []
        self._cfk_keys: List[Key] = []
        self.tfks: Dict[Key, TimestampsForKey] = {}
        self.range_commands: Dict[TxnId, Ranges] = {}
        # bumped on any range_commands mutation (register/cleanup): the
        # device store's batched range-stab probes are version-gated on it
        self.range_version = 0
        # append-only log of range-txn registrations (incl. re-registered
        # ids): a device probe serves across version bumps by unioning the
        # log suffix past its snapshot into its candidate set.  None on
        # stores with no consumer (the device store enables it and clears
        # it at every flush-window boundary, so it stays bounded)
        self.range_log: Optional[List[TxnId]] = None
        self.max_conflicts = MaxConflicts()
        self.redundant_before = RedundantBefore()
        self.durable_before = DurableBefore()
        # listener-notification drain queue (see commands._notify_listeners)
        from collections import deque
        self.notify_queue = deque()
        # txn_id -> keys with an armed per-key execution gate; swept by the
        # progress log (commands.sweep_key_gates) to keep chasing blockers
        self.gated: Dict[TxnId, set] = {}
        self.notifying = False
        # per-txn count of failed catch-ups where every peer had truncated
        # the deps (Propagate INSUFFICIENT): drives staleness escalation
        self.insufficient_catchups: Dict[TxnId, int] = {}
        # the owning node's protocol-CPU profiler (obs/cpuprof.py), cached
        # so the per-key CFK fences in register/calculate_deps cost one
        # attribute check when profiling is off; None on bare-store
        # harnesses whose node stub carries no obs facade
        obs = getattr(node, "obs", None)
        self.cpuprof = getattr(obs, "cpuprof", None)
        # the flight ring, cached for the same reason as cpuprof: status
        # transitions record per command transition and must not re-walk
        # the node->obs->flight attribute chain each time
        self._flight = getattr(obs, "flight", None)
        # bounded-memory paging tier (local/paging.py): only when a
        # resident budget is configured does `commands` become the
        # fault-on-access mapping — unset budget keeps the PLAIN dict
        # above, so paging off is bit-identical to the pre-paging store
        from accord_tpu.local.paging import pager_from_env
        self.pager = pager_from_env(self)
        if self.pager is not None:
            self.commands = self.pager.commands

    # -- environment plumbing --
    @property
    def agent(self):
        return self.node.agent

    @property
    def flight(self):
        """The owning node's flight recorder (obs/flight.py); None on
        bare-store harnesses whose node stub carries no obs facade."""
        return self._flight

    @property
    def data_store(self):
        return self.node.data_store

    @property
    def progress_log(self):
        return self.node.progress_log_for(self)

    def unique_now(self) -> Timestamp:
        return self.node.unique_now()

    # -- state access (only from within execute) --
    def _get_or_create(self, txn_id: TxnId) -> Command:
        cmd = self.commands.get(txn_id)
        if cmd is None:
            cmd = self.commands[txn_id] = Command(txn_id)
        return cmd

    def _cfk(self, key: Key) -> CommandsForKey:
        cfk = self.cfks.get(key)
        if cfk is None:
            cfk = self.cfks[key] = CommandsForKey(key)
            # an evicted-empty CFK (local/paging.py) left its key in the
            # sorted index: restore its residual watermarks instead of
            # double-inserting the index entry
            if self.pager is not None \
                    and self.pager.restore_cfk(key, cfk):
                return cfk
            i = bisect_left(self._cfk_tokens, key.token)
            self._cfk_tokens.insert(i, key.token)
            self._cfk_keys.insert(i, key)
        return cfk

    def cfk_keys_in(self, ranges: Ranges) -> List[Key]:
        """Sorted CFK keys inside `ranges`: two bisects per range over the
        maintained index.  Ranges are normalized (sorted, disjoint), so the
        concatenated slices are exactly
        ``sorted(k for k in cfks if ranges.contains(k))``."""
        toks = self._cfk_tokens
        keys = self._cfk_keys
        out: List[Key] = []
        for r in ranges:
            lo = bisect_left(toks, r.start)
            hi = bisect_left(toks, r.end, lo)
            if lo < hi:
                out.extend(keys[lo:hi])
        return out

    def _tfk(self, key: Key) -> TimestampsForKey:
        tfk = self.tfks.get(key)
        if tfk is None:
            tfk = self.tfks[key] = TimestampsForKey(key)
        return tfk

    # -- execution --
    def execute(self, context: PreLoadContext,
                fn: Callable[[SafeCommandStore], None]) -> None:
        self._submit(context, fn, None)

    def submit(self, context: PreLoadContext,
               fn: Callable[[SafeCommandStore], object]) -> AsyncResult:
        result: AsyncResult = AsyncResult()
        self._submit(context, fn, result)
        return result

    # the store whose task is currently running — the single-threaded-shard
    # affinity check of the reference (CommandStore.current(),
    # CommandStore.java:228; enforced by the Debug store variant)
    _current: Optional["CommandStore"] = None

    @classmethod
    def current(cls) -> Optional["CommandStore"]:
        return cls._current

    def _make_safe(self, context: PreLoadContext) -> SafeCommandStore:
        """The view handed to operations; subclasses may specialise it."""
        return SafeCommandStore(self, context)

    def _submit(self, context: PreLoadContext, fn, result: Optional[AsyncResult]
                ) -> None:
        """Base: run inline. Overridden by async/simulated stores.

        Outcome delivery happens AFTER _current/released are restored so
        success and failure callbacks see identical (post-task) state — a
        failure callback must trip the Debug leak checks exactly like a
        success callback would."""
        value = error = None
        prev = CommandStore._current
        safe = None
        try:
            CommandStore._current = self
            safe = self._make_safe(context)
            value = fn(safe)
        except BaseException as e:  # noqa: BLE001
            error = e
        finally:
            CommandStore._current = prev
            if safe is not None:
                safe.released = True  # leak detection (Debug variant checks)
        if error is not None:
            if result is not None:
                result.set_failure(error)
            else:
                self.agent.on_uncaught_exception(error)
        elif result is not None:
            result.set_success(value)
        # paging-tier evictions are deferred to the TOP-LEVEL operation
        # boundary (after outcome delivery): nested submits and callbacks
        # running under this frame never see a command evicted from under
        # a live reference
        if prev is None and self.pager is not None:
            self.pager.on_op_boundary()

    # -- flush-window pinning (batch envelopes) --
    # A MultiPreAccept envelope (messages/multi.py) pins every store's
    # flush window while its parts apply, so a batching store resolves the
    # whole envelope as ONE fused window.  The base store runs inline and
    # has no window: no-ops.  (DeviceCommandStore implements them.)
    def hold_flush(self) -> None:
        pass

    def release_flush(self) -> None:
        pass

    def update_ranges(self, ranges: Ranges, unsafe: Ranges = None) -> None:
        """Add the current epoch's assignment. Serving ranges only GROW (the
        reference's per-epoch RangesForEpoch, CommandStore.java:96): old-epoch
        messages — recovery of era transactions, fetches of their outcomes —
        must still reach the command state this store accumulated while it
        owned them. Routing for new epochs is the sender's job (scope
        computation against the current topology). `unsafe` = node-level
        newly-acquired ranges pending bootstrap."""
        self.ranges = self.ranges.union(ranges)
        fresh = ranges.subtract(unsafe) if unsafe is not None else ranges
        self.safe_to_read = self.safe_to_read.union(fresh)

    def mark_safe_to_read(self, ranges: Ranges) -> None:
        self.safe_to_read = self.safe_to_read.union(
            ranges.slice(self.ranges) if not self.ranges.is_empty else ranges)

    def __repr__(self):
        return f"CommandStore#{self.id}({self.ranges!r})"


class EvenSplit:
    """ShardDistributor.EvenSplit: split owned token span evenly over N shards
    (ShardDistributor.java:33-46)."""

    def __init__(self, count: int):
        invariants.check_argument(count > 0, "need at least one shard")
        self.count = count

    def split(self, ranges: Ranges) -> List[Ranges]:
        total = sum(r.end - r.start for r in ranges)
        if total == 0 or self.count == 1:
            return [ranges] + [Ranges.EMPTY] * (self.count - 1)
        out: List[Ranges] = []
        per = total / self.count
        flat: List[Range] = list(ranges)
        acc: List[Range] = []
        acc_len = 0
        target = per
        taken = 0
        for r in flat:
            start = r.start
            while start < r.end:
                remaining_here = r.end - start
                need = target - (taken + acc_len)
                if remaining_here <= need or len(out) == self.count - 1:
                    acc.append(Range(start, r.end))
                    acc_len += r.end - start
                    start = r.end
                else:
                    take = max(1, int(need))
                    acc.append(Range(start, start + take))
                    acc_len += take
                    start += take
                    taken += acc_len
                    out.append(Ranges(acc, _normalized=True))
                    acc, acc_len = [], 0
                    target = per * (len(out) + 1)
        out.append(Ranges(acc))
        while len(out) < self.count:
            out.append(Ranges.EMPTY)
        return out[:self.count]


class EmptyFanout(RuntimeError):
    """A fanned-out request found no intersecting store on this node."""


def _flatten_reply(result: AsyncResult) -> AsyncResult:
    """Requests may return a Reply or an AsyncResult[Reply]; flatten."""
    from accord_tpu.utils.async_chains import success
    return result.flat_map(
        lambda v: v if isinstance(v, AsyncResult) else success(v))


class CommandStores:
    """The node's shard manager (CommandStores.java:78): owns N CommandStores
    over an EvenSplit of the node's ranges; fans operations out over
    intersecting shards and chains the reduce."""

    # True on the worker-runtime tier (shard/supervisor.WorkerCommandStores):
    # stores live in per-shard processes and `all()` has nothing to walk —
    # callers that need node-wide store folds (audit digests, census) must
    # go through the supervisor's fan-out instead
    remote = False

    def __init__(self, node, num_shards: int = 1,
                 store_factory: Callable[[int, object, Ranges], CommandStore] = None):
        self.node = node
        self.num_shards = num_shards
        self.store_factory = store_factory or CommandStore
        self.stores: List[CommandStore] = []
        self._splitter = EvenSplit(num_shards)

    def initialize(self, ranges: Ranges) -> None:
        splits = self._splitter.split(ranges)
        self.stores = [self.store_factory(i, self.node, splits[i])
                       for i in range(self.num_shards)]

    def update_topology(self, ranges: Ranges) -> Ranges:
        """Re-split on topology change; returns ranges newly added to this node
        (which require bootstrap). Reference CommandStores.updateTopology
        (:401-481) — our EvenSplit re-splits in place; stores keep their
        existing state and simply gain/lose ranges."""
        if not self.stores:
            self.initialize(ranges)
            return ranges
        old = Ranges.EMPTY
        for s in self.stores:
            old = old.union(s.ranges)
        added = ranges.subtract(old)
        splits = self._splitter.split(ranges)
        for i, s in enumerate(self.stores):
            s.update_ranges(splits[i], unsafe=added)
        return added

    def all(self) -> List[CommandStore]:
        return list(self.stores)

    def intersecting(self, participants) -> List[CommandStore]:
        if participants is None:
            return self.all()
        out = []
        for s in self.stores:
            if s.ranges.is_empty:
                continue
            if isinstance(participants, _SortedKeyList):
                if participants.intersects_ranges(s.ranges):
                    out.append(s)
            elif isinstance(participants, Ranges):
                if s.ranges.intersects(participants):
                    out.append(s)
            else:
                raise TypeError(type(participants))
        return out

    def shard_of(self, participants) -> int:
        """Index of the first shard a participant set lands on (admission
        accounting: per-(tenant, shard) QoS buckets key on this)."""
        for i, s in enumerate(self.stores):
            if s.ranges.is_empty:
                continue
            if isinstance(participants, _SortedKeyList):
                if participants.intersects_ranges(s.ranges):
                    return i
            elif isinstance(participants, Ranges):
                if s.ranges.intersects(participants):
                    return i
        return 0

    def map_reduce_request(self, request, consume) -> None:
        """Fan a TxnRequest out over intersecting command stores and chain
        the reduce (CommandStores.mapReduceConsume, :546-640), delivering
        (value, failure) to `consume` exactly once.  The worker runtime
        overrides this to ship the same request over per-shard pipes."""
        participants = request.participants()
        probe = request.deps_probe()
        rprobe = request.recovery_probe()
        xprobe = request.execute_probe()
        context = PreLoadContext.for_txn(
            request.txn_id, deps_probes=(probe,) if probe is not None else (),
            recovery_probes=(rprobe,) if rprobe is not None else (),
            execute_probes=(xprobe,) if xprobe is not None else ())
        stores = self.intersecting(participants)
        if not stores:
            consume(None, EmptyFanout("no intersecting store"))
            return
        if len(stores) == 1:
            raw = stores[0].submit(context, request.apply)
            if raw._done and raw._failure is None \
                    and not isinstance(raw._value, AsyncResult):
                # synchronous single-shard dispatch (the host-tier common
                # case): the reply is already in hand — skip the
                # flatten/all_of chain machinery entirely
                consume(raw._value, None)
                return
            pending: List[AsyncResult] = [_flatten_reply(raw)]
        else:
            pending = [_flatten_reply(s.submit(context, request.apply))
                       for s in stores]
        from accord_tpu.utils import async_chains

        def finish(values, failure):
            if failure is not None:
                consume(None, failure)
                return
            acc = values[0]
            for v in values[1:]:
                acc = request.reduce(acc, v)
            consume(acc, None)

        async_chains.all_of(pending).add_callback(finish)

    def for_each(self, context: PreLoadContext, participants,
                 fn: Callable[[SafeCommandStore], None]) -> None:
        for s in self.intersecting(participants):
            s.execute(context, fn)

    def map_reduce(self, context: PreLoadContext, participants,
                   map_fn: Callable[[SafeCommandStore], object],
                   reduce_fn: Callable[[object, object], object]) -> AsyncResult:
        """Fan out over intersecting shards; chain the reduce
        (CommandStores.mapReduceConsume, :546-640)."""
        stores = self.intersecting(participants)
        if not stores:
            from accord_tpu.utils.async_chains import success
            return success(None)
        results = [s.submit(context, map_fn) for s in stores]
        from accord_tpu.utils import async_chains
        return async_chains.reduce(results, reduce_fn)


# rebind the flight-recorder hook in local.command (which cannot import this
# module — store.py imports Command above): status transitions resolve the
# store they run inside via CommandStore.current()
from accord_tpu.local import command as _command_module  # noqa: E402

_command_module._current_store = CommandStore.current
