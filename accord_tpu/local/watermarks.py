"""Per-store range-map watermarks: MaxConflicts, RedundantBefore, DurableBefore.

Reference: accord/local/MaxConflicts.java:28, RedundantBefore.java:37-120,
DurableBefore.java:39-180, all backed by ReducingRangeMap (SURVEY.md §2.3/§2.8).
"""

from __future__ import annotations

import enum
from bisect import bisect_left, insort
from typing import Optional, Tuple

from accord_tpu.primitives.keys import Keys, Ranges, RoutingKey, _SortedKeyList
from accord_tpu.primitives.timestamp import Timestamp, TxnId, TXNID_NONE
from accord_tpu.utils.interval_map import ReducingRangeMap


class MaxConflicts:
    """token-range -> max conflict Timestamp; consulted for executeAt proposal
    (MaxConflicts.java:28).

    Split representation: single-key advances (every preaccept/commit of a
    key txn — the host hot path) land in a plain token -> max dict with a
    sorted-token sidecar for range folds, while range-shaped advances
    (range txns / sync points) keep the immutable ReducingRangeMap.  A
    query folds both; the old all-interval-map form rebuilt the whole
    boundary tuple per key per commit."""

    def __init__(self):
        self._map: ReducingRangeMap = ReducingRangeMap()
        self._points: dict = {}          # token -> max Timestamp
        self._point_toks: list = []      # sorted tokens (range-fold sidecar)

    def get(self, participants) -> Optional[Timestamp]:
        """Max conflict over a Keys/Ranges selection."""
        best: Optional[Timestamp] = None
        points = self._points
        if isinstance(participants, _SortedKeyList):
            for k in participants:
                v = points.get(k.token)
                if v is not None and (best is None or v > best):
                    best = v
                v = self._map.get(k.token)
                if v is not None and (best is None or v > best):
                    best = v
        else:
            toks = self._point_toks
            for r in participants:
                lo = bisect_left(toks, r.start)
                hi = bisect_left(toks, r.end, lo)
                for i in range(lo, hi):
                    v = points[toks[i]]
                    if best is None or v > best:
                        best = v
                v = self._map.fold_max(r.start, r.end)
                if v is not None and (best is None or v > best):
                    best = v
        return best

    def update(self, participants, ts: Timestamp) -> None:
        if isinstance(participants, _SortedKeyList):
            points = self._points
            toks = self._point_toks
            for k in participants:
                tok = k.token
                cur = points.get(tok)
                if cur is None:
                    points[tok] = ts
                    insort(toks, tok)
                elif ts > cur:
                    points[tok] = ts
        else:
            for r in participants:
                self._map = self._map.update(r.start, r.end, ts, max)


class PreBootstrapOrStale(enum.Enum):
    """Classification of a TxnId against a range's bootstrap/staleness state
    (RedundantBefore.PreBootstrapOrStale)."""

    FULLY = "FULLY"
    PARTIALLY = "PARTIALLY"
    POST_BOOTSTRAP = "POST_BOOTSTRAP"


class RedundantEntry:
    """Per-range redundancy facts (RedundantBefore.Entry)."""

    __slots__ = ("locally_applied_before", "shard_applied_before",
                 "bootstrapped_at", "stale_until_at_least")

    def __init__(self, locally_applied_before: TxnId = TXNID_NONE,
                 shard_applied_before: TxnId = TXNID_NONE,
                 bootstrapped_at: TxnId = TXNID_NONE,
                 stale_until_at_least: Optional[Timestamp] = None):
        self.locally_applied_before = locally_applied_before
        self.shard_applied_before = shard_applied_before
        self.bootstrapped_at = bootstrapped_at
        self.stale_until_at_least = stale_until_at_least

    @staticmethod
    def merge(a: "RedundantEntry", b: "RedundantEntry") -> "RedundantEntry":
        return RedundantEntry(
            max(a.locally_applied_before, b.locally_applied_before),
            max(a.shard_applied_before, b.shard_applied_before),
            max(a.bootstrapped_at, b.bootstrapped_at),
            Timestamp.non_null_or_max(a.stale_until_at_least,
                                      b.stale_until_at_least))

    def __eq__(self, other):
        return (isinstance(other, RedundantEntry)
                and self.locally_applied_before == other.locally_applied_before
                and self.shard_applied_before == other.shard_applied_before
                and self.bootstrapped_at == other.bootstrapped_at
                and self.stale_until_at_least == other.stale_until_at_least)

    def __repr__(self):
        return (f"RedundantEntry(local<{self.locally_applied_before!r}, "
                f"shard<{self.shard_applied_before!r}, "
                f"boot@{self.bootstrapped_at!r})")


class RedundantBefore:
    """Range map of RedundantEntry: classifies TxnIds as live / redundant /
    pre-bootstrap per range; prunes deps and gates GC (RedundantBefore.java)."""

    def __init__(self):
        self._map: ReducingRangeMap = ReducingRangeMap()

    def _entry_for_key(self, key: RoutingKey) -> Optional[RedundantEntry]:
        return self._map.get(key.token)

    def update_locally_applied(self, ranges: Ranges, before: TxnId) -> None:
        e = RedundantEntry(locally_applied_before=before)
        for r in ranges:
            self._map = self._map.update(r.start, r.end, e, RedundantEntry.merge)

    def update_shard_applied(self, ranges: Ranges, before: TxnId) -> None:
        e = RedundantEntry(shard_applied_before=before)
        for r in ranges:
            self._map = self._map.update(r.start, r.end, e, RedundantEntry.merge)

    def set_bootstrapped_at(self, ranges: Ranges, at: TxnId) -> None:
        e = RedundantEntry(bootstrapped_at=at)
        for r in ranges:
            self._map = self._map.update(r.start, r.end, e, RedundantEntry.merge)

    def set_stale_until(self, ranges: Ranges, until: Timestamp) -> None:
        e = RedundantEntry(stale_until_at_least=until)
        for r in ranges:
            self._map = self._map.update(r.start, r.end, e, RedundantEntry.merge)

    def is_redundant(self, txn_id: TxnId, key: RoutingKey) -> bool:
        e = self._entry_for_key(key)
        return e is not None and txn_id < max(e.locally_applied_before,
                                              e.bootstrapped_at)

    def is_shard_redundant(self, txn_id: TxnId, key: RoutingKey) -> bool:
        e = self._entry_for_key(key)
        return e is not None and txn_id < e.shard_applied_before

    def shard_applied_before(self, key: RoutingKey) -> TxnId:
        """The shard-applied fence at `key` (NONE when no fact recorded)."""
        e = self._entry_for_key(key)
        return e.shard_applied_before if e is not None else TXNID_NONE

    def is_any_shard_redundant(self, txn_id: TxnId, ranges: Ranges) -> bool:
        """Does ANY span intersecting `ranges` place txn_id below the
        shard-applied fence? Folds every intersecting map span — an interior
        fenced span must be seen even when the range endpoints are not fenced
        (RedundantBefore fold semantics, not endpoint probing)."""
        def f(acc, v):
            return acc or (v is not None and txn_id < v.shard_applied_before)

        return any(self._map.fold_intersecting(r.start, r.end, f, False)
                   for r in ranges)

    def is_all_redundant(self, txn_id: TxnId, ranges: Ranges) -> bool:
        """Is txn_id below the locally-applied/bootstrap watermark on EVERY
        span intersecting `ranges`? Uncovered (None) spans are NOT redundant:
        an interior sub-range with no bootstrap/applied fact must keep the
        dependency live there (ADVICE r1: endpoint probes missed interiors)."""
        if ranges.is_empty:
            return False

        def f(acc, v):
            return acc and v is not None and txn_id < max(
                v.locally_applied_before, v.bootstrapped_at)

        return all(self._map.fold_intersecting(r.start, r.end, f, True)
                   for r in ranges)

    def pre_bootstrap_or_stale(self, txn_id: TxnId, participants
                               ) -> PreBootstrapOrStale:
        """Is txn_id before the bootstrap fence / within a stale window for
        (some of) its participants?"""
        def probe(e: Optional[RedundantEntry]) -> bool:
            return e is not None and (
                txn_id < e.bootstrapped_at
                or (e.stale_until_at_least is not None
                    and txn_id < e.stale_until_at_least))

        pre = post = False
        if isinstance(participants, _SortedKeyList):
            probes = [probe(self._entry_for_key(k)) for k in participants]
        else:
            # evaluate every map span intersecting each range, so a fence
            # covering only part of the span is seen
            probes = []
            for r in participants:
                self._map.fold_intersecting(
                    r.start, r.end, lambda acc, v: probes.append(probe(v)), None)
        pre = any(probes)
        post = not all(probes) or not probes
        if pre and not post:
            return PreBootstrapOrStale.FULLY
        if pre:
            return PreBootstrapOrStale.PARTIALLY
        return PreBootstrapOrStale.POST_BOOTSTRAP

    def min_locally_applied_before(self, ranges: Ranges) -> TxnId:
        """Floor watermark across `ranges`: any uncovered span floors the
        result to NONE (for GC gating)."""
        def fold(acc, v):
            w = v.locally_applied_before if v is not None else TXNID_NONE
            return w if acc is None else min(acc, w)

        result: Optional[TxnId] = None
        for r in ranges:
            result = self._map.fold_intersecting(r.start, r.end, fold, result)
        return result if result is not None else TXNID_NONE

    def min_shard_applied_before(self, ranges: Ranges) -> TxnId:
        """Floor of the shard-applied fence across `ranges` (census gauge;
        uncovered spans floor to NONE like min_locally_applied_before)."""
        def fold(acc, v):
            w = v.shard_applied_before if v is not None else TXNID_NONE
            return w if acc is None else min(acc, w)

        result: Optional[TxnId] = None
        for r in ranges:
            result = self._map.fold_intersecting(r.start, r.end, fold, result)
        return result if result is not None else TXNID_NONE

    def audit_low_bound(self, ranges: Ranges) -> Timestamp:
        """The replica-state auditor's LOW digest bound for this replica
        over `ranges`: the max, over every intersecting span, of
        bootstrapped_at and any staleness fence.  Below it this replica's
        history may legitimately be a snapshot-shaped hole (bootstrap
        installed data, not command metadata; a stale span is mid-reacquire)
        — cross-replica digests must not cover it (local/audit.py)."""
        bound: Timestamp = TXNID_NONE

        def fold(acc, v):
            if v is None:
                return acc
            m = v.bootstrapped_at
            if v.stale_until_at_least is not None \
                    and v.stale_until_at_least > m:
                m = v.stale_until_at_least
            return m if m > acc else acc

        for r in ranges:
            bound = self._map.fold_intersecting(r.start, r.end, fold, bound)
        return bound


class DurableBefore:
    """Range map -> {majority_before, universal_before} TxnId durability bounds
    (DurableBefore.java:39-180): NotDurable / MajorityOrInvalidated /
    UniversalOrInvalidated classes for GC."""

    class Entry:
        __slots__ = ("majority_before", "universal_before")

        def __init__(self, majority_before: TxnId = TXNID_NONE,
                     universal_before: TxnId = TXNID_NONE):
            self.majority_before = majority_before
            self.universal_before = universal_before

        @staticmethod
        def merge_max(a: "DurableBefore.Entry", b: "DurableBefore.Entry"):
            return DurableBefore.Entry(
                max(a.majority_before, b.majority_before),
                max(a.universal_before, b.universal_before))

    def __init__(self):
        self._map: ReducingRangeMap = ReducingRangeMap()

    def update(self, ranges: Ranges, majority_before: TxnId,
               universal_before: TxnId = TXNID_NONE) -> None:
        e = DurableBefore.Entry(majority_before, universal_before)
        for r in ranges:
            self._map = self._map.update(r.start, r.end, e,
                                         DurableBefore.Entry.merge_max)

    def is_majority_durable(self, txn_id: TxnId, key: RoutingKey) -> bool:
        e = self._map.get(key.token)
        return e is not None and txn_id < e.majority_before

    def is_any_majority_durable(self, txn_id: TxnId, ranges: Ranges) -> bool:
        """Does some span of `ranges` hold a majority bound above txn_id?"""
        def fold(acc, _s, _e, v):
            return acc or txn_id < v.majority_before

        return any(self._map.fold(fold, False, start=r.start, end=r.end)
                   for r in ranges)

    def is_universally_durable(self, txn_id: TxnId, key: RoutingKey) -> bool:
        e = self._map.get(key.token)
        return e is not None and txn_id < e.universal_before

    def majority_before(self, key: RoutingKey) -> TxnId:
        e = self._map.get(key.token)
        return e.majority_before if e is not None else TXNID_NONE

    def universal_before(self, key: RoutingKey) -> TxnId:
        e = self._map.get(key.token)
        return e.universal_before if e is not None else TXNID_NONE

    def min_bounds(self, ranges: Ranges) -> Tuple[TxnId, TxnId]:
        """Floor (majority, universal) bounds across `ranges`; any uncovered
        span floors to NONE (the min-merge of DurableBefore.java's global
        aggregation)."""
        def fold(acc, v):
            maj = v.majority_before if v is not None else TXNID_NONE
            uni = v.universal_before if v is not None else TXNID_NONE
            if acc is None:
                return (maj, uni)
            return (min(acc[0], maj), min(acc[1], uni))

        result = None
        for r in ranges:
            result = self._map.fold_intersecting(r.start, r.end, fold, result)
        return result if result is not None else (TXNID_NONE, TXNID_NONE)
