"""The command status lattice.

Reference: accord/local/Status.java:47-86 (Phase x Known vector),
SaveStatus.java:52-116 (local-knowledge refinements), Command.java state docs.

SaveStatus is the totally-ordered local progression
    NotDefined -> PreAccepted -> AcceptedInvalidate -> Accepted -> PreCommitted
    -> Committed -> Stable -> ReadyToExecute -> PreApplied -> Applying -> Applied
    -> TruncatedApply -> Erased | Invalidated
and Known is the partial-order knowledge vector {route, definition, executeAt,
deps, outcome} used by status interrogation / propagation (CheckStatus,
FetchData) to describe *what is known* independently of local progress.
"""

from __future__ import annotations

import enum
from typing import Optional


class Phase(enum.IntEnum):
    NONE = 0
    PRE_ACCEPT = 1
    ACCEPT = 2
    COMMIT = 3
    EXECUTE = 4
    PERSIST = 5
    CLEANUP = 6


class SaveStatus(enum.IntEnum):
    NOT_DEFINED = 0
    PRE_ACCEPTED = 10
    ACCEPTED_INVALIDATE = 20     # promised to invalidate; no executeAt proposed
    ACCEPTED = 30                # slow-path (executeAt, deps) accepted at ballot
    PRE_COMMITTED = 40           # executeAt known, definition maybe not
    COMMITTED = 50               # executeAt + deps known (not yet stable)
    STABLE = 60                  # deps stable; WaitingOn initialised
    READY_TO_EXECUTE = 65        # all waiting deps cleared
    PRE_APPLIED = 70             # outcome (writes/result) known, not yet applied
    APPLYING = 75
    APPLIED = 80
    TRUNCATED_APPLY = 90         # outcome durable elsewhere; local state shed
    ERASED = 95
    INVALIDATED = 100

    @property
    def phase(self) -> Phase:
        if self <= SaveStatus.NOT_DEFINED:
            return Phase.NONE
        if self <= SaveStatus.PRE_ACCEPTED:
            return Phase.PRE_ACCEPT
        if self <= SaveStatus.ACCEPTED:
            return Phase.ACCEPT
        if self <= SaveStatus.COMMITTED:
            return Phase.COMMIT
        if self <= SaveStatus.READY_TO_EXECUTE:
            return Phase.EXECUTE
        if self <= SaveStatus.APPLIED:
            return Phase.PERSIST
        return Phase.CLEANUP

    # -- knowledge predicates (Status.java hasBeen idiom) --
    def has_been(self, other: "SaveStatus") -> bool:
        return self >= other

    @property
    def is_defined(self) -> bool:
        """Definition (PartialTxn) is locally known (between PreAccepted and
        truncation)."""
        return (SaveStatus.PRE_ACCEPTED <= self < SaveStatus.TRUNCATED_APPLY
                and self != SaveStatus.ACCEPTED_INVALIDATE)

    @property
    def is_at_least_committed(self) -> bool:
        return self >= SaveStatus.COMMITTED and self != SaveStatus.INVALIDATED

    @property
    def is_at_least_stable(self) -> bool:
        return (SaveStatus.STABLE <= self <= SaveStatus.TRUNCATED_APPLY)

    @property
    def is_decided(self) -> bool:
        """Outcome decided: executeAt fixed (PreCommitted+) or invalidated."""
        return self >= SaveStatus.PRE_COMMITTED

    @property
    def is_truncated(self) -> bool:
        return self in (SaveStatus.TRUNCATED_APPLY, SaveStatus.ERASED)

    @property
    def is_invalidated(self) -> bool:
        return self == SaveStatus.INVALIDATED

    @property
    def is_applied_or_gone(self) -> bool:
        """Terminal for execution ordering: dependents need not wait."""
        return self >= SaveStatus.APPLIED

    @property
    def is_committed_to_execute(self) -> bool:
        """Committed with a real executeAt (not invalidated)."""
        return (self >= SaveStatus.COMMITTED and self <= SaveStatus.TRUNCATED_APPLY)

    def known(self) -> "Known":
        """Project local progress onto the Known knowledge vector."""
        if self == SaveStatus.NOT_DEFINED:
            return Known.NOTHING
        if self == SaveStatus.INVALIDATED:
            return Known.INVALIDATED
        if self.is_truncated:
            # decision reached but deps cleaned up: ERASED, not NO — so a
            # per-range knowledge reduce over a truncated source degrades
            # below STABLE instead of masquerading as decided deps
            return Known(KnownRoute.MAYBE, KnownDefinition.NO,
                         KnownExecuteAt.YES, KnownDeps.ERASED,
                         KnownOutcome.APPLY)
        route = KnownRoute.FULL
        definition = (KnownDefinition.YES if self.is_defined else KnownDefinition.NO)
        if self >= SaveStatus.PRE_APPLIED:
            return Known(route, definition, KnownExecuteAt.YES,
                         KnownDeps.STABLE, KnownOutcome.APPLY)
        if self >= SaveStatus.STABLE:
            return Known(route, definition, KnownExecuteAt.YES,
                         KnownDeps.STABLE, KnownOutcome.UNKNOWN)
        if self >= SaveStatus.COMMITTED:
            return Known(route, definition, KnownExecuteAt.YES,
                         KnownDeps.COMMITTED, KnownOutcome.UNKNOWN)
        if self >= SaveStatus.PRE_COMMITTED:
            return Known(route, definition, KnownExecuteAt.YES,
                         KnownDeps.UNKNOWN, KnownOutcome.UNKNOWN)
        if self >= SaveStatus.ACCEPTED:
            return Known(route, definition, KnownExecuteAt.PROPOSED,
                         KnownDeps.PROPOSED, KnownOutcome.UNKNOWN)
        # PRE_ACCEPTED / ACCEPTED_INVALIDATE: no coordinator proposal held —
        # deps are unknown here (reference Status.java:51: only Accepted
        # carries DepsProposed)
        if self >= SaveStatus.PRE_ACCEPTED:
            return Known(route, definition, KnownExecuteAt.PROPOSED,
                         KnownDeps.UNKNOWN, KnownOutcome.UNKNOWN)
        return Known.NOTHING


class Durability(enum.IntEnum):
    """Global durability classification (reference Status.Durability:
    NotDurable / Local / ShardUniversal / MajorityOrInvalidated /
    UniversalOrInvalidated — the top two absorb invalidation)."""

    NOT_DURABLE = 0
    LOCAL = 1                    # applied locally
    SHARD_UNIVERSAL = 2          # applied at every live replica of home shard
    MAJORITY = 3                 # applied at a majority of every shard (or invalidated)
    UNIVERSAL = 4                # applied at every replica of every shard (or invalidated)

    @property
    def is_durable(self) -> bool:
        return self >= Durability.MAJORITY


class ProgressToken:
    """Comparable progress summary (primitives/ProgressToken.java): ordered
    by durability, then status, then promised ballot, then whether the
    promise was accepted — so a liveness monitor can tell 'someone is
    moving this txn' even when only durability or a ballot advanced."""

    __slots__ = ("durability", "status", "promised", "is_accepted")

    NONE: "ProgressToken"

    def __init__(self, durability: "Durability", status: "SaveStatus",
                 promised, is_accepted: bool):
        self.durability = durability
        self.status = status
        self.promised = promised
        self.is_accepted = is_accepted

    @classmethod
    def of(cls, durability: "Durability", status: "SaveStatus", promised,
           accepted) -> "ProgressToken":
        """The one place the is-accepted rule lives: the promise counts as
        accepted once the Accept phase ratified that very ballot."""
        return cls(durability, status, promised,
                   status.phase >= Phase.ACCEPT and accepted == promised)

    @property
    def phase(self) -> Phase:
        return self.status.phase

    def _key(self):
        return (self.durability, self.status, self.promised,
                self.is_accepted)

    def __lt__(self, other):
        return self._key() < other._key()

    def __le__(self, other):
        return self._key() <= other._key()

    def __gt__(self, other):
        return self._key() > other._key()

    def __ge__(self, other):
        return self._key() >= other._key()

    def __eq__(self, other):
        return isinstance(other, ProgressToken) and self._key() == other._key()

    def __hash__(self):
        return hash(self._key())

    def __repr__(self):
        return (f"ProgressToken({self.durability.name}, {self.status.name}, "
                f"{self.promised!r}{', accepted' if self.is_accepted else ''})")


def _progress_token_none() -> ProgressToken:
    from accord_tpu.primitives.timestamp import Ballot
    return ProgressToken.of(Durability.NOT_DURABLE, SaveStatus.NOT_DEFINED,
                            Ballot.ZERO, Ballot.ZERO)


class KnownRoute(enum.IntEnum):
    MAYBE = 0
    COVERING = 1
    FULL = 2


class KnownDefinition(enum.IntEnum):
    NO = 0
    YES = 1


class KnownExecuteAt(enum.IntEnum):
    UNKNOWN = 0
    PROPOSED = 1
    YES = 2
    NO = 3          # invalidated


class KnownDeps(enum.IntEnum):
    """Reference Status.KnownDeps:539 order: ERASED (deps cleaned up by
    truncation) sorts BELOW STABLE so min-style reduces degrade a
    stable∧erased mix to not-stable, while NO (invalidated — deps never
    needed) sorts above everything."""
    UNKNOWN = 0
    PROPOSED = 1
    COMMITTED = 2
    ERASED = 3      # decision reached, deps cleaned up (DepsErased)
    STABLE = 4
    NO = 5          # invalidated (NoDeps)


class KnownOutcome(enum.IntEnum):
    UNKNOWN = 0
    APPLY = 1       # writes/result known
    INVALIDATED = 2
    ERASED = 3


class InvalidIf(enum.IntEnum):
    """Invalidation-evidence lattice carried per range on CheckStatus
    replies (reference coordinate/Infer.InvalidIf): each point names the
    CONDITION under which the replying replica's durability state proves
    the transaction invalid.  Totally ordered by evidence strength —
    lattice join is max — so merging replies keeps the strongest proof.

    IF_UNDECIDED: the txn sits below the replica's majority-durable fence
    (DurableBefore), which certifies everything beneath it as
    majority-applied-or-invalidated; a quorum of such replies that all
    find the txn undecided therefore proves it was never decided — and,
    with the fence-refusal rule (local/commands.py is_durably_fenced),
    never can be.  IF_UNCOMMITTED: additionally below the shard-applied
    fence (every replica applied the exclusive sync point and refuses new
    witnesses).  IS_INVALID: locally known invalidated."""

    NOT_KNOWN_TO_BE_INVALID = 0
    IF_UNDECIDED = 1
    IF_UNCOMMITTED = 2
    IS_INVALID = 3


class Known:
    """The knowledge vector lattice (Status.java:124+): per-field max-merge."""

    __slots__ = ("route", "definition", "execute_at", "deps", "outcome",
                 "invalid_if")

    NOTHING: "Known"
    INVALIDATED: "Known"

    def __init__(self, route: KnownRoute, definition: KnownDefinition,
                 execute_at: KnownExecuteAt, deps: KnownDeps,
                 outcome: KnownOutcome,
                 invalid_if: InvalidIf = InvalidIf.NOT_KNOWN_TO_BE_INVALID):
        self.route = route
        self.definition = definition
        self.execute_at = execute_at
        self.deps = deps
        self.outcome = outcome
        self.invalid_if = invalid_if

    def with_invalid_if(self, invalid_if: InvalidIf) -> "Known":
        return Known(self.route, self.definition, self.execute_at,
                     self.deps, self.outcome, invalid_if)

    def at_least(self, other: "Known") -> "Known":
        return Known(max(self.route, other.route),
                     max(self.definition, other.definition),
                     max(self.execute_at, other.execute_at),
                     max(self.deps, other.deps),
                     max(self.outcome, other.outcome),
                     max(self.invalid_if, other.invalid_if))

    merge = at_least

    def reduce(self, other: "Known") -> "Known":
        """The knowledge valid across BOTH sources' ranges (reference
        Status.Known.reduce:171): per-range facts — the definition body and
        the dependency set — take the minimum, because each range only knows
        what its own replica reported; global facts — executeAt and the
        outcome — take the maximum, because deciding either anywhere decides
        it everywhere; and the route is FULL only if some source held the
        full route (a COVERING route covers only its own ranges)."""
        if self.route == other.route:
            route = self.route
        elif KnownRoute.FULL in (self.route, other.route):
            route = KnownRoute.FULL
        else:
            route = KnownRoute.MAYBE
        return Known(route,
                     min(self.definition, other.definition),
                     max(self.execute_at, other.execute_at),
                     min(self.deps, other.deps),
                     max(self.outcome, other.outcome),
                     # invalidation evidence is GLOBAL (a txn commits
                     # everywhere or nowhere): one range's durability fence
                     # condemns the whole txn, so the reduce joins like
                     # executeAt/outcome rather than taking the minimum
                     max(self.invalid_if, other.invalid_if))

    def satisfies(self, required: "Known") -> bool:
        return (self.route >= required.route
                and self.definition >= required.definition
                and self.execute_at >= required.execute_at
                and self.deps >= required.deps
                and self.outcome >= required.outcome
                and self.invalid_if >= required.invalid_if)

    @property
    def is_invalidated(self) -> bool:
        return self.outcome == KnownOutcome.INVALIDATED

    def __eq__(self, other):
        return (isinstance(other, Known)
                and self.route == other.route
                and self.definition == other.definition
                and self.execute_at == other.execute_at
                and self.deps == other.deps
                and self.outcome == other.outcome
                and self.invalid_if == other.invalid_if)

    def __hash__(self):
        return hash((self.route, self.definition, self.execute_at, self.deps,
                     self.outcome, self.invalid_if))

    def __repr__(self):
        return (f"Known(route={self.route.name}, def={self.definition.name}, "
                f"at={self.execute_at.name}, deps={self.deps.name}, "
                f"out={self.outcome.name}"
                + (f", inv={self.invalid_if.name}"
                   if self.invalid_if != InvalidIf.NOT_KNOWN_TO_BE_INVALID
                   else "") + ")")


Known.NOTHING = Known(KnownRoute.MAYBE, KnownDefinition.NO,
                      KnownExecuteAt.UNKNOWN, KnownDeps.UNKNOWN,
                      KnownOutcome.UNKNOWN)
Known.INVALIDATED = Known(KnownRoute.MAYBE, KnownDefinition.NO,
                          KnownExecuteAt.NO, KnownDeps.NO,
                          KnownOutcome.INVALIDATED, InvalidIf.IS_INVALID)

# Common knowledge targets used by FetchData/CheckStatus (reference Known statics)
KNOWN_COMMITTED = Known(KnownRoute.COVERING, KnownDefinition.NO,
                        KnownExecuteAt.YES, KnownDeps.UNKNOWN,
                        KnownOutcome.UNKNOWN)
KNOWN_STABLE = Known(KnownRoute.COVERING, KnownDefinition.YES,
                     KnownExecuteAt.YES, KnownDeps.STABLE,
                     KnownOutcome.UNKNOWN)
KNOWN_APPLY = Known(KnownRoute.COVERING, KnownDefinition.YES,
                    KnownExecuteAt.YES, KnownDeps.STABLE, KnownOutcome.APPLY)

ProgressToken.NONE = _progress_token_none()
