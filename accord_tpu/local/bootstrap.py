"""Bootstrap: live acquisition of newly-owned ranges.

Reference: accord/local/Bootstrap.java:81-483 — each attempt fences the
ranges with an ExclusiveSyncPoint (everything ordered below it is frozen into
the source snapshot; everything above flows through normal replication to the
new owner), copies the data via the DataStore fetch protocol, then marks the
ranges safe to read and records `bootstrapped_at` in RedundantBefore so deps
below the fence are treated as already-satisfied locally.
"""

from __future__ import annotations

from typing import Optional

from accord_tpu.api.spi import DataStore
from accord_tpu.coordinate.errors import Timeout
from accord_tpu.coordinate.syncpoint import CoordinateSyncPoint, SyncPoint
from accord_tpu.primitives.keys import Ranges
from accord_tpu.primitives.timestamp import TxnKind
from accord_tpu.utils.async_chains import AsyncResult


class _AttemptFetchRanges(DataStore.FetchRanges):
    """Bootstrap's view of fetch progress (the FetchRanges callbacks of
    DataStore.java:74-99): accumulate fetched coverage as sub-ranges land so
    a later attempt only re-fetches what is still missing."""

    def __init__(self, attempt: "Bootstrap"):
        self.attempt = attempt

    def starting(self, ranges: Ranges):
        return None  # the default coordinator manages its own tokens

    def fetched(self, ranges: Ranges) -> None:
        self.attempt.covered = self.attempt.covered.union(ranges)

    def fail(self, ranges: Ranges, failure: BaseException) -> None:
        pass  # the attempt-level future failing drives the retry


class Bootstrap:
    """One bootstrap attempt chain for `ranges` (Bootstrap.Attempt): fence,
    then DataStore.fetch (the ranged FetchCoordinator with per-shard source
    failover), then the conflict-watermark fence and safe-to-read flip.
    Retries itself (fresh fence, missing ranges only) on failure — the
    reference defers the retry policy to Agent.onFailedBootstrap."""

    def __init__(self, node, ranges: Ranges, epoch: int,
                 result: Optional[AsyncResult] = None, attempt: int = 1):
        self.node = node
        self.RETRY_DELAY_S = node.config.bootstrap_retry_delay_s
        self.ranges = ranges
        self.epoch = epoch
        self.result = result if result is not None else AsyncResult()
        self.attempt = attempt
        self.max_retries = node.config.bootstrap_max_retries
        self.sp: Optional[SyncPoint] = None
        self.covered = Ranges.EMPTY
        self.fetch_result: Optional[DataStore.FetchResult] = None
        self.max_applied = None
        self.done = False

    def start(self) -> "Bootstrap":
        self.node.obs.flight.record("bootstrap_begin", None,
                                    (self.epoch, self.attempt))
        CoordinateSyncPoint.coordinate(
            self.node, TxnKind.EXCLUSIVE_SYNC_POINT, self.ranges,
            await_applied=False).add_callback(self._on_fence)
        return self

    def _retry(self) -> None:
        if self.done:
            return
        if self.attempt >= self.max_retries:
            # bounded: exhausting the budget fails the epoch-level result
            # (the caller's start_sync stays honest — no sync-complete
            # broadcast for data we never acquired)
            self.node.obs.flight.record(
                "bootstrap_done", None, (self.epoch, self.attempt, "failed"))
            self.result.try_failure(Timeout(
                f"bootstrap of {self.ranges.subtract(self.covered)!r} for "
                f"epoch {self.epoch} failed after {self.attempt} attempts"))
            return
        delay = min(self.RETRY_DELAY_S * (2 ** (self.attempt - 1)),
                    self.node.config.bootstrap_retry_delay_cap_s)
        self.node.scheduler.once(
            delay,
            lambda: Bootstrap(self.node, self.ranges.subtract(self.covered),
                              self.epoch, self.result,
                              attempt=self.attempt + 1).start()
            if not self.result.is_done else None)

    def abort(self, ranges: Ranges) -> None:
        """The ranges moved away under a newer topology: stop fetching them
        (FetchResult.abort passthrough)."""
        if self.fetch_result is not None:
            self.fetch_result.abort(ranges)

    # ------------------------------------------------------------- fence --
    def _on_fence(self, sp: Optional[SyncPoint], failure) -> None:
        if failure is not None:
            self._retry()
            return
        self.sp = sp
        self.fetch_result = self.node.data_store.fetch(
            self.node, None, self.ranges.subtract(self.covered), sp,
            _AttemptFetchRanges(self))
        self.fetch_result.add_callback(self._on_fetched)

    def _on_fetched(self, fetched: Optional[Ranges], failure) -> None:
        if self.done:
            return
        self.max_applied = getattr(self.fetch_result, "max_applied", None)
        if failure is not None:
            # finalize what DID land (watermarks + safe-to-read for the
            # covered sub-ranges — leaving them un-flipped would wedge reads
            # on data we installed), then retry the remainder under a fresh
            # fence
            self._retry()
            self.done = True
            if not self.covered.is_empty:
                self._fetch_max_conflict(complete=False)
            return
        self._finish()

    # ------------------------------------------------------------- finish --
    def _finish(self) -> None:
        if self.done:
            return
        self.done = True
        self._fetch_max_conflict(complete=True)

    def _fetch_max_conflict(self, complete: bool) -> None:
        """Before declaring ranges readable, learn the highest conflict any
        quorum witnessed for them (reference Bootstrap.java:234
        FetchMaxConflict): raising our HLC and MaxConflicts above it keeps
        every timestamp we mint for the new ranges after the handoff point.

        Always finalizes the FETCHED coverage only (self.covered): after a
        partial fetch the failed remainder is retried by a new attempt, and
        after an abort the dropped sub-ranges hold no data — flipping either
        safe-to-read would serve history we do not have."""
        from accord_tpu.coordinate.fetch import fetch_max_conflict
        from accord_tpu.primitives.keys import Route
        finalize = self.covered
        if finalize.is_empty:
            if complete:
                self.result.try_success(Ranges.EMPTY)
            return
        fetch_max_conflict(self.node, Route.probe(finalize),
                           finalize).add_callback(
            lambda mc, f: self._on_max_conflict(finalize, complete, mc, f))

    def _on_max_conflict(self, finalize: Ranges, complete: bool,
                         max_conflict, failure) -> None:
        if failure is not None:
            self.node.scheduler.once(
                self.RETRY_DELAY_S,
                lambda: self._fetch_max_conflict(complete))
            return
        from accord_tpu.local import commands as C
        from accord_tpu.local.store import PreLoadContext
        from accord_tpu.primitives.timestamp import NONE as TS_NONE

        if self.max_applied is not None:
            # source-supplied bound (StartingRangeFetch.started(maxApplied)):
            # raise our clocks above everything the snapshot contains even if
            # the global probe raced below it
            self.node.on_remote_timestamp(self.max_applied)
        if max_conflict > TS_NONE:
            self.node.on_remote_timestamp(max_conflict)
        for store in self.node.command_stores.intersecting(finalize):
            owned = finalize.slice(store.ranges)
            if owned.is_empty:
                continue
            store.redundant_before.set_bootstrapped_at(owned, self.sp.txn_id)
            if max_conflict > TS_NONE:
                store.max_conflicts.update(owned, max_conflict)
            store.mark_safe_to_read(owned)
            # deps below the fence are now satisfied by the snapshot:
            # re-evaluate everything blocked on them
            store.execute(PreLoadContext.empty(), C.re_evaluate_waiting)
        self._journal_checkpoint(
            finalize, max_conflict if max_conflict > TS_NONE else None)
        if complete:
            self.node.obs.flight.record(
                "bootstrap_done", None, (self.epoch, self.attempt, "ok"))
            self.result.try_success(finalize)

    def _journal_checkpoint(self, finalize: Ranges, max_conflict) -> None:
        """WAL progress record for the finalized coverage: a crash after
        this point resumes from here (BootstrapCheckpoint replay reinstalls
        the snapshot + watermarks) instead of re-fetching the ranges."""
        node = self.node
        if node.journal is None or getattr(node, "replaying", False):
            return
        from accord_tpu.messages.admin import BootstrapCheckpoint
        snapshot = node.data_store.snapshot_ranges(finalize) \
            if hasattr(node.data_store, "snapshot_ranges") else {}
        node.journal.record(node.id, BootstrapCheckpoint(
            self.epoch, self.sp.txn_id, finalize, snapshot,
            max_conflict=max_conflict, max_applied=self.max_applied))
        node.obs.flight.record(
            "bootstrap_checkpoint", None,
            (self.epoch, self.attempt, len(finalize)))
