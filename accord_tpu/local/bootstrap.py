"""Bootstrap: live acquisition of newly-owned ranges.

Reference: accord/local/Bootstrap.java:81-483 — each attempt fences the
ranges with an ExclusiveSyncPoint (everything ordered below it is frozen into
the source snapshot; everything above flows through normal replication to the
new owner), copies the data via the DataStore fetch protocol, then marks the
ranges safe to read and records `bootstrapped_at` in RedundantBefore so deps
below the fence are treated as already-satisfied locally.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from accord_tpu.coordinate.syncpoint import CoordinateSyncPoint, SyncPoint
from accord_tpu.messages.base import Callback
from accord_tpu.messages.epoch import (FetchSnapshot, FetchSnapshotNack,
                                       FetchSnapshotOk)
from accord_tpu.primitives.keys import Ranges
from accord_tpu.primitives.timestamp import TxnKind
from accord_tpu.utils.async_chains import AsyncResult


class Bootstrap(Callback):
    """One bootstrap attempt chain for `ranges` (Bootstrap.Attempt). Retries
    itself (fresh fence) on failure — the reference defers the retry policy
    to Agent.onFailedBootstrap."""

    def __init__(self, node, ranges: Ranges, epoch: int,
                 result: Optional[AsyncResult] = None):
        self.node = node
        self.RETRY_DELAY_S = node.config.bootstrap_retry_delay_s
        self.ranges = ranges
        self.epoch = epoch
        self.result = result if result is not None else AsyncResult()
        self.sp: Optional[SyncPoint] = None
        self.covered = Ranges.EMPTY
        self.pending: Dict[int, Ranges] = {}
        self.tried: set = set()
        self.done = False

    def start(self) -> "Bootstrap":
        CoordinateSyncPoint.coordinate(
            self.node, TxnKind.EXCLUSIVE_SYNC_POINT, self.ranges,
            await_applied=False).add_callback(self._on_fence)
        return self

    def _retry(self) -> None:
        if self.done:
            return
        self.node.scheduler.once(
            self.RETRY_DELAY_S,
            lambda: Bootstrap(self.node, self.ranges.subtract(self.covered),
                              self.epoch, self.result).start()
            if not self.result.is_done else None)

    # ------------------------------------------------------------- fence --
    def _on_fence(self, sp: Optional[SyncPoint], failure) -> None:
        if failure is not None:
            self._retry()
            return
        self.sp = sp
        self._fetch_missing()

    def _fetch_missing(self) -> None:
        missing = self.ranges.subtract(self.covered)
        if missing.is_empty:
            self._finish()
            return
        # one source per shard: any current replica other than ourselves has
        # the full sub-range once the fence applied there
        topology = self.node.topology.for_epoch(self.epoch)
        requested = False
        sources_exist = False
        for shard in topology.for_selection(missing).shards:
            want = Ranges([shard.range]).slice(missing)
            if want.is_empty:
                continue
            if any(n != self.node.id for n in shard.nodes):
                sources_exist = True
            source = self._pick_source(shard)
            if source is None:
                continue
            requested = True
            self.pending[source] = want
            self.node.send(source, FetchSnapshot(self.sp.txn_id, want),
                           callback=self, timeout_s=10.0)
        if not requested and self.pending:
            return  # earlier requests for other sub-ranges still in flight
        if not requested:
            if sources_exist:
                # every source tried and failed this round: retry — finishing
                # without the data would mark the range safe while missing
                # history and diverge the replica
                self.tried.clear()
                self.node.scheduler.once(self.RETRY_DELAY_S,
                                         self._fetch_missing)
            else:
                # genuinely no peer holds it (we are the only replica)
                self._finish()

    def _pick_source(self, shard) -> Optional[int]:
        for n in shard.nodes:
            if n != self.node.id and (n, shard.range.start) not in self.tried:
                self.tried.add((n, shard.range.start))
                return n
        return None

    # ------------------------------------------------------------ replies --
    def on_success(self, from_id: int, reply) -> None:
        if self.done:
            return
        want = self.pending.pop(from_id, None)
        if isinstance(reply, FetchSnapshotOk):
            self.node.data_store.install_snapshot(reply.snapshot)
            self.covered = self.covered.union(reply.ranges)
            if want is not None and not want.subtract(reply.ranges).is_empty:
                self._fetch_missing()  # partial coverage: try another source
            elif self.ranges.subtract(self.covered).is_empty:
                self._finish()
            elif not self.pending:
                self._fetch_missing()
            return
        # nack: try the next source for that sub-range
        self._fetch_missing()

    def on_failure(self, from_id: int, failure: BaseException) -> None:
        if self.done:
            return
        self.pending.pop(from_id, None)
        self._fetch_missing()

    # ------------------------------------------------------------- finish --
    def _finish(self) -> None:
        if self.done:
            return
        self.done = True
        self._fetch_max_conflict()

    def _fetch_max_conflict(self) -> None:
        """Before declaring the ranges readable, learn the highest conflict
        any quorum witnessed for them (reference Bootstrap.java:234
        FetchMaxConflict): raising our HLC and MaxConflicts above it keeps
        every timestamp we mint for the new ranges after the handoff point."""
        from accord_tpu.coordinate.fetch import fetch_max_conflict
        from accord_tpu.primitives.keys import Route
        fetch_max_conflict(self.node, Route.probe(self.ranges),
                           self.ranges).add_callback(self._on_max_conflict)

    def _on_max_conflict(self, max_conflict, failure) -> None:
        if failure is not None:
            self.node.scheduler.once(self.RETRY_DELAY_S,
                                     self._fetch_max_conflict)
            return
        from accord_tpu.local import commands as C
        from accord_tpu.local.store import PreLoadContext
        from accord_tpu.primitives.timestamp import NONE as TS_NONE

        if max_conflict > TS_NONE:
            self.node.on_remote_timestamp(max_conflict)
        for store in self.node.command_stores.intersecting(self.ranges):
            owned = self.ranges.slice(store.ranges)
            if owned.is_empty:
                continue
            store.redundant_before.set_bootstrapped_at(owned, self.sp.txn_id)
            if max_conflict > TS_NONE:
                store.max_conflicts.update(owned, max_conflict)
            store.mark_safe_to_read(owned)
            # deps below the fence are now satisfied by the snapshot:
            # re-evaluate everything blocked on them
            store.execute(PreLoadContext.empty(), C.re_evaluate_waiting)
        self.result.try_success(self.ranges)
