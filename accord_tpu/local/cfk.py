"""CommandsForKey: the per-key conflict index — north-star kernel #1.

Reference: accord/local/CommandsForKey.java:132 (TxnInfo :194-293, the
mapReduceActive deps scan :614-650, mapReduceFull recovery queries :553-612,
incremental update :652, Unmanaged registrations :140-184,1270) and
accord/impl/TimestampsForKey.java:33.

Host-side scalar implementation; the batched device equivalent (one XLA call
computing deps for a whole window of transactions) lives in
accord_tpu.ops.deps_kernel and must stay bit-identical to this path.
"""

from __future__ import annotations

import enum
from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

from accord_tpu.primitives.keys import Key
from accord_tpu.primitives.timestamp import Timestamp, TxnId, TxnKind, KindSet
from accord_tpu.utils import invariants
from accord_tpu.utils.sorted_arrays import find_ceil


class InternalStatus(enum.IntEnum):
    """Compressed per-key view of a command's state
    (CommandsForKey.InternalStatus, CommandsForKey.java:194)."""

    TRANSITIVELY_KNOWN = 0   # known only via deps; never witnessed directly
    HISTORICAL = 1
    PREACCEPTED = 2
    ACCEPTED = 3
    COMMITTED = 4
    STABLE = 5
    APPLIED = 6
    INVALID_OR_TRUNCATED = 7

    @property
    def is_committed(self) -> bool:
        return InternalStatus.COMMITTED <= self <= InternalStatus.APPLIED

    @property
    def is_decided(self) -> bool:
        return self >= InternalStatus.COMMITTED

    @property
    def is_terminal(self) -> bool:
        return self in (InternalStatus.APPLIED, InternalStatus.INVALID_OR_TRUNCATED)


class TxnInfo:
    __slots__ = ("txn_id", "status", "execute_at", "ballot_accepted")

    def __init__(self, txn_id: TxnId, status: InternalStatus,
                 execute_at: Optional[Timestamp] = None):
        self.txn_id = txn_id
        self.status = status
        self.execute_at = execute_at

    def execute_at_or_txn_id(self) -> Timestamp:
        return self.execute_at if self.execute_at is not None else self.txn_id

    def __repr__(self):
        return f"TxnInfo({self.txn_id!r}, {self.status.name}, at={self.execute_at!r})"


class Unmanaged:
    """A pending notification for a range/sync-point txn waiting on this key
    (CommandsForKey.Unmanaged, :140-184): fire when every cross-key dep at this
    key with executeAt <= `waiting_until` reaches COMMIT or APPLY."""

    __slots__ = ("txn_id", "pending", "waiting_until", "callback")

    COMMIT = "COMMIT"
    APPLY = "APPLY"

    def __init__(self, txn_id: TxnId, pending: str, waiting_until: Timestamp,
                 callback: Callable[[], None]):
        self.txn_id = txn_id
        self.pending = pending
        self.waiting_until = waiting_until
        self.callback = callback


class CommandsForKey:
    """All transactions witnessed at one key, ordered by TxnId, with a
    committed-by-executeAt view for execution ordering."""

    __slots__ = ("key", "_by_id", "_ids", "_unmanaged", "redundant_before")

    def __init__(self, key: Key):
        self.key = key
        self._by_id: Dict[TxnId, TxnInfo] = {}
        self._ids: List[TxnId] = []          # sorted
        self._unmanaged: List[Unmanaged] = []
        self.redundant_before: Optional[TxnId] = None

    # -- maintenance --
    def update(self, txn_id: TxnId, status: InternalStatus,
               execute_at: Optional[Timestamp] = None) -> None:
        """Incremental maintenance on a command transition
        (CommandsForKey.update, :652)."""
        info = self._by_id.get(txn_id)
        if info is None:
            info = TxnInfo(txn_id, status, execute_at)
            self._by_id[txn_id] = info
            i = find_ceil(self._ids, txn_id)
            self._ids.insert(i, txn_id)
        else:
            # per-key status only advances (monotone view of the command;
            # INVALID_OR_TRUNCATED is the maximum so it always applies)
            if status < info.status:
                return
            info.status = status
            if execute_at is not None:
                info.execute_at = execute_at
        if status.is_committed or status == InternalStatus.INVALID_OR_TRUNCATED:
            self._notify_unmanaged()

    def register_historical(self, txn_id: TxnId) -> None:
        """Witness a txn known only transitively (registerHistorical)."""
        if txn_id not in self._by_id:
            self.update(txn_id, InternalStatus.TRANSITIVELY_KNOWN)

    def prune_redundant(self, before: TxnId) -> None:
        """Drop applied/invalidated txns below the redundancy watermark."""
        self.redundant_before = (before if self.redundant_before is None
                                 else max(self.redundant_before, before))
        keep = [t for t in self._ids
                if not (t < before and self._by_id[t].status.is_terminal)]
        for t in set(self._ids) - set(keep):
            del self._by_id[t]
        self._ids = keep

    # -- introspection --
    def get(self, txn_id: TxnId) -> Optional[TxnInfo]:
        return self._by_id.get(txn_id)

    def size(self) -> int:
        return len(self._ids)

    def all_ids(self) -> List[TxnId]:
        return list(self._ids)

    def min_uncommitted(self) -> Optional[TxnId]:
        for t in self._ids:
            if not self._by_id[t].status.is_decided:
                return t
        return None

    def max_committed_write_at(self) -> Optional[Timestamp]:
        best: Optional[Timestamp] = None
        for t in self._ids:
            info = self._by_id[t]
            if info.status.is_committed and t.kind.is_write:
                at = info.execute_at_or_txn_id()
                best = at if best is None or at > best else best
        return best

    def max_applied_write_at(self) -> Optional[Timestamp]:
        best: Optional[Timestamp] = None
        for t in self._ids:
            info = self._by_id[t]
            if info.status == InternalStatus.APPLIED and t.kind.is_write:
                at = info.execute_at_or_txn_id()
                best = at if best is None or at > best else best
        return best

    def max_conflict(self) -> Optional[Timestamp]:
        """Max (txnId | committed executeAt) at this key — executeAt proposal
        input."""
        best: Optional[Timestamp] = None
        for t in self._ids:
            at = self._by_id[t].execute_at_or_txn_id()
            best = at if best is None or at > best else best
        return best

    # -- the deps scan (mapReduceActive, CommandsForKey.java:614-650) --
    def _prune_bound(self, before: Timestamp):
        """The max committed WRITE started AND executing below `before`:
        every decided txn it witnesses that executes before it is
        transitively covered by depending on it (the reference's pruning
        below the max committed write, CommandsForKey.java:614-650).

        BOTH bounds matter. The cover argument is: dependent D (deps
        bounded by `before` = D's executeAt) waits on the bound W*, and W*
        waits on the pruned txn t, so t applies before D everywhere. A
        committed write whose executeAt was bumped ABOVE `before` is ordered
        after D — D's WaitingOn drops it ("not our problem") — so it covers
        nothing for D; choosing it as the bound silently dropped t from D's
        execution order (burn seed 7 drop 0.1: recovered txn pruned behind a
        later-executing bound, read missed its write)."""
        bound_id = None
        bound_at = None
        for t in self._ids:
            if t >= before or not t.kind.is_write:
                continue
            info = self._by_id[t]
            if not info.status.is_committed:
                continue
            at = info.execute_at_or_txn_id()
            if at >= before:
                continue  # executes after the querying txn: cannot cover
            if bound_at is None or at > bound_at:
                bound_at, bound_id = at, t
        return bound_id, bound_at

    def map_reduce_active(self, before: Timestamp, kinds: KindSet,
                          fn: Callable[[TxnId], None],
                          prune: bool = True,
                          deps_of: Callable[[TxnId], object] = None) -> None:
        """Visit every active txn with txnId < `before` whose kind is in
        `kinds` — the dependency calculation for a new txn at this key.

        'Active' excludes invalidated/truncated txns, those pruned as
        redundant, and (when `prune` and `deps_of` is given) txns
        *provably* covered by the max committed write W*: t is pruned iff
        W*'s locally-known committed deps CONTAIN t and t is decided to
        execute before W* — then depending on W* transitively orders us
        after t. Keeping deps bounded this way is what stops dependency sets
        growing without limit between durability sweeps. The containment
        check matters: inferring coverage from timestamps alone can prune a
        txn the bound never actually witnessed, silently dropping it from
        the execution order (the reference tracks exact witnessing via the
        per-txn missing[] arrays, CommandsForKey.java:412-420).
        """
        bound_id, bound_at = self._prune_bound(before) if prune \
            else (None, None)
        bound_deps = deps_of(bound_id) \
            if bound_id is not None and deps_of is not None else None
        hi = find_ceil(self._ids, before)
        for i in range(hi):
            t = self._ids[i]
            info = self._by_id[t]
            if info.status == InternalStatus.INVALID_OR_TRUNCATED:
                continue
            if t.kind not in kinds:
                continue
            if bound_deps is not None and t != bound_id \
                    and info.status.is_decided \
                    and info.execute_at_or_txn_id() < bound_at \
                    and bound_deps.contains(t):
                continue  # provably covered by the bound write
            fn(t)

    # -- recovery queries (mapReduceFull, CommandsForKey.java:553-612) --
    def committed_executes_after_without_witnessing(
            self, txn_id: TxnId, witnessed_by: Callable[[TxnId], bool]) -> bool:
        """Any STABLE-or-later txn executing after txn_id whose deps omit it?
        (rejectsFastPath input: hasStableExecutesAfterWithoutWitnessing)"""
        for t in self._ids:
            info = self._by_id[t]
            if (InternalStatus.STABLE <= info.status <= InternalStatus.APPLIED
                    and info.execute_at_or_txn_id() > txn_id
                    and t.witnesses(txn_id) and not witnessed_by(t)):
                return True
        return False

    def accepted_or_committed_started_after_without_witnessing(
            self, txn_id: TxnId, witnessed_by: Callable[[TxnId], bool]) -> bool:
        """Any ACCEPTED+ txn with txnId > txn_id whose deps omit it?
        (rejectsFastPath input)"""
        lo = find_ceil(self._ids, txn_id)
        for i in range(lo, len(self._ids)):
            t = self._ids[i]
            if t == txn_id:
                continue
            info = self._by_id[t]
            if InternalStatus.ACCEPTED <= info.status <= InternalStatus.APPLIED \
                    and t.witnesses(txn_id) and not witnessed_by(t):
                return True
        return False

    def stable_started_before_and_witnessed(
            self, txn_id: TxnId, witnessed_by: Callable[[TxnId], bool]
    ) -> List[TxnId]:
        """STABLE+ txns with txnId < txn_id that DID witness it
        (earlierCommittedWitness: evidence the fast path was taken)."""
        hi = find_ceil(self._ids, txn_id)
        out = []
        for i in range(hi):
            t = self._ids[i]
            info = self._by_id[t]
            if info.status >= InternalStatus.STABLE \
                    and info.status != InternalStatus.INVALID_OR_TRUNCATED \
                    and witnessed_by(t):
                out.append(t)
        return out

    def accepted_started_before_without_witnessing(
            self, txn_id: TxnId, witnessed_by: Callable[[TxnId], bool]
    ) -> List[TxnId]:
        """ACCEPTED (deps still *proposed*, not yet committed) txns with
        txnId < txn_id, proposed to execute after txn_id, whose deps omit it
        (earlierAcceptedNoWitness: recovery must await their commit before
        deciphering the fast path — BeginRecovery.java:329-342, TestStatus
        IS_PROPOSED + executeAt > startedBefore filter; once such a txn
        commits it leaves this set, so the await/retry loop terminates)."""
        hi = find_ceil(self._ids, txn_id)
        out = []
        for i in range(hi):
            t = self._ids[i]
            info = self._by_id[t]
            if info.status == InternalStatus.ACCEPTED \
                    and info.execute_at_or_txn_id() > txn_id \
                    and txn_id.witnesses(t) and not witnessed_by(t):
                out.append(t)
        return out

    # -- unmanaged (cross-key) waits --
    def register_unmanaged(self, unmanaged: Unmanaged) -> None:
        self._unmanaged.append(unmanaged)
        self._notify_unmanaged()

    def _notify_unmanaged(self) -> None:
        if not self._unmanaged:
            return
        fire: List[Unmanaged] = []
        keep: List[Unmanaged] = []
        for u in self._unmanaged:
            if self._unmanaged_satisfied(u):
                fire.append(u)
            else:
                keep.append(u)
        self._unmanaged = keep
        for u in fire:
            u.callback()

    def _unmanaged_satisfied(self, u: Unmanaged) -> bool:
        for t in self._ids:
            if t >= u.waiting_until or t == u.txn_id:
                continue
            info = self._by_id[t]
            if not t.is_visible:
                continue
            if u.pending == Unmanaged.COMMIT:
                if not info.status.is_decided:
                    return False
            else:  # APPLY
                if not info.status.is_terminal:
                    if not (info.status.is_committed
                            and info.execute_at_or_txn_id() > u.waiting_until):
                        return False
        return True

    def __repr__(self):
        return f"CFK({self.key!r}, {len(self._ids)} txns)"


class TimestampsForKey:
    """Per-key execution timestamps (reference impl/TimestampsForKey.java:33):
    lastExecutedTimestamp / lastWriteTimestamp feed executeAt validation and
    the read-timestamp watermark."""

    __slots__ = ("key", "last_executed", "last_write", "raw_hlc")

    def __init__(self, key: Key):
        self.key = key
        self.last_executed: Optional[Timestamp] = None
        self.last_write: Optional[Timestamp] = None
        self.raw_hlc = 0

    def on_executed(self, at: Timestamp, is_write: bool) -> None:
        if self.last_executed is None or at > self.last_executed:
            self.last_executed = at
        if is_write and (self.last_write is None or at > self.last_write):
            self.last_write = at
        self.raw_hlc = max(self.raw_hlc, at.hlc)

    def validate_execute_at(self, at: Timestamp) -> None:
        invariants.check_state(
            self.last_write is None or at >= self.last_write,
            "executeAt %s precedes last write %s at %s", at, self.last_write,
            self.key)
