"""CommandsForKey: the per-key conflict index — north-star kernel #1.

Reference: accord/local/CommandsForKey.java:132 (design doc :74-131, TxnInfo
:194-293, the missing[] divergence encoding :412-470, mapReduceActive deps
scan :614-650, mapReduceFull recovery queries :553-612, incremental update
with missing maintenance :652-1000, Unmanaged registrations :140-184).

Representation (the reference's packed TxnInfo[] re-designed as parallel
arrays, which is also the zero-copy device format for accord_tpu.ops):

  _ids[i]      sorted TxnIds — every globally-visible key-domain txn witnessed
               at this key that is not shard-redundant
  _status[i]   InternalStatus (compressed per-key view)
  _eat[i]      executeAt, or None meaning "executes at its own TxnId"
  _missing[i]  sorted tuple of TxnIds DIVERGING from the implied deps, or ()

The collection IMPLIES deps: a command with known deps (status.has_info) is
assumed to depend on every id in the collection below its depsKnownBefore
that its kind witnesses; only divergences are stored, in missing[i]. Ids
recorded COMMITTED-or-higher are elided from every missing collection (a
recovery coordinator that sees the committed status never needs to decipher
fast-path votes for it, CommandsForKey.java:82-88).

A committed-by-executeAt view (_committed) drives execution-order queries and
the transitive-dependency elision in map_reduce_active.

Host-side scalar implementation; the batched device equivalent (one XLA call
computing deps for a whole window of transactions) lives in
accord_tpu.ops.deps_kernel and must stay bit-identical to this path.
"""

from __future__ import annotations

import enum
import heapq
from bisect import bisect_left, bisect_right, insort
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from accord_tpu import native
from accord_tpu.primitives.keys import Key
from accord_tpu.primitives.timestamp import (_KIND_MASK, _KIND_SHIFT,
                                             _WITNESS_BITS, Timestamp, TxnId,
                                             TxnKind, KindSet)
from accord_tpu.utils import invariants

# the native CommandsForKey core (native/_cfk_core.cpp): one C pass for each
# of the three hot loops over the SAME parallel arrays this class owns; None
# means the bit-identical Python tier (no toolchain, ACCORD_NATIVE=0, or
# ACCORD_NO_NATIVE=1).  tests/test_cfk_native.py cross-checks the tiers on
# randomized op sequences; monkeypatching this global selects a tier.
_NATIVE = native.get_cfk()

# kinds visible in other txns' deps (TxnKind.is_globally_visible), as a bit
# mask over kind ints — the per-entry visibility test in _block_point
_VISIBLE_MASK = sum(1 << int(k) for k in TxnKind if k.is_globally_visible)


class InternalStatus(enum.IntEnum):
    """Compressed per-key view of a command's state
    (CommandsForKey.InternalStatus, CommandsForKey.java:194-236)."""

    TRANSITIVELY_KNOWN = 0   # known only via deps; never witnessed directly
    HISTORICAL = 1
    PREACCEPTED = 2
    ACCEPTED = 3
    COMMITTED = 4
    STABLE = 5
    APPLIED = 6
    INVALID_OR_TRUNCATED = 7

    @property
    def is_committed(self) -> bool:
        return InternalStatus.COMMITTED <= self <= InternalStatus.APPLIED

    @property
    def is_decided(self) -> bool:
        return self >= InternalStatus.COMMITTED

    @property
    def is_terminal(self) -> bool:
        return self in (InternalStatus.APPLIED,
                        InternalStatus.INVALID_OR_TRUNCATED)

    @property
    def has_info(self) -> bool:
        """Deps (and a meaningful executeAt) are recorded from ACCEPTED on
        (InternalStatus.hasInfo): these are the entries whose missing[]
        answers recovery's dep-membership tests."""
        return InternalStatus.ACCEPTED <= self <= InternalStatus.APPLIED


class TestStartedAt(enum.Enum):
    STARTED_BEFORE = "STARTED_BEFORE"
    STARTED_AFTER = "STARTED_AFTER"
    ANY = "ANY"


class TestDep(enum.Enum):
    WITH = "WITH"
    WITHOUT = "WITHOUT"
    ANY_DEPS = "ANY_DEPS"


class TestStatus(enum.Enum):
    ANY_STATUS = "ANY_STATUS"
    IS_PROPOSED = "IS_PROPOSED"   # ACCEPTED or COMMITTED
    IS_STABLE = "IS_STABLE"       # STABLE..APPLIED


class TxnInfo:
    """Materialised view of one entry (the packed arrays are authoritative)."""

    __slots__ = ("txn_id", "status", "execute_at", "missing")

    def __init__(self, txn_id: TxnId, status: InternalStatus,
                 execute_at: Optional[Timestamp], missing: Tuple[TxnId, ...]):
        self.txn_id = txn_id
        self.status = status
        self.execute_at = execute_at
        self.missing = missing

    def execute_at_or_txn_id(self) -> Timestamp:
        return self.execute_at if self.execute_at is not None else self.txn_id

    def __repr__(self):
        return (f"TxnInfo({self.txn_id!r}, {self.status.name}, "
                f"at={self.execute_at!r}, missing={self.missing!r})")


class Unmanaged:
    """A pending notification for a txn waiting on this key
    (CommandsForKey.Unmanaged, :140-184): fire when every entry at this key
    ordered before `waiting_until` reaches COMMIT or APPLY.  Used both for
    range/sync-point txns and for the key dimension of WaitingOn (the
    reference's bitset spans txnIds AND keys, Command.java:1294-1643): a
    Stable key txn holds a key bit until the CFK certifies every
    earlier-executing entry applied.

    Callbacks take the live SafeCommandStore: the CFK itself is a pure data
    structure, so `update`/`prune_redundant` RETURN the fired registrations
    and the calling store context invokes them."""

    __slots__ = ("txn_id", "pending", "waiting_until", "callback")

    COMMIT = "COMMIT"
    APPLY = "APPLY"

    def __init__(self, txn_id: TxnId, pending: str, waiting_until: Timestamp,
                 callback: Callable[["object"], None]):
        self.txn_id = txn_id
        self.pending = pending
        self.waiting_until = waiting_until
        self.callback = callback


def _deps_known_before(txn_id: TxnId, status: InternalStatus,
                       execute_at: Optional[Timestamp]) -> Timestamp:
    """The bound below which this entry's deps are complete
    (InternalStatus.depsKnownBefore): txnId until commit, executeAt after."""
    if status.is_committed and execute_at is not None:
        return execute_at
    return txn_id


class CommandsForKey:
    """All transactions witnessed at one key, ordered by TxnId, with the
    missing[] divergence encoding and a committed-by-executeAt view."""

    __slots__ = ("key", "_ids", "_status", "_eat", "_missing", "_wdeps",
                 "_committed",
                 "redundant_before", "version", "last_mutator",
                 "committed_version", "_block_heap", "_wait_heap", "_wait_seq")

    def __init__(self, key: Key):
        self.key = key
        self._ids: List[TxnId] = []
        self._status: List[InternalStatus] = []
        self._eat: List[Optional[Timestamp]] = []
        self._missing: List[Tuple[TxnId, ...]] = []
        # the WRITE ids among each entry's registered deps at this key —
        # the entry's potential elision covers.  Resolved to timestamps at
        # QUERY time (locally-known executeAt when committed; reported
        # unresolved to the recovery coordinator otherwise) so a dep that
        # commits after registration contributes its real executeAt (see
        # omission_covers)
        self._wdeps: List[Tuple[TxnId, ...]] = []
        # (executeAt, txn_id) sorted, for entries COMMITTED..APPLIED
        self._committed: List[Tuple[Timestamp, TxnId]] = []
        # lazy min-heap of (block_point, txn_id) over non-terminal entries —
        # see _block_point; stale items are dropped at query time
        self._block_heap: List[Tuple[Timestamp, TxnId]] = []
        # APPLY-pending registrations as (waiting_until, seq, Unmanaged)
        self._wait_heap: List[Tuple[Timestamp, int, Unmanaged]] = []
        self._wait_seq = 0
        self.redundant_before: Optional[TxnId] = None
        # bumped on every mutation; device-store snapshots validate against it.
        # last_mutator = the txn of the latest update(), letting a snapshot
        # tolerate exactly one bump when it is the querying txn's own
        # registration (invisible to its deps scan, which excludes itself).
        # committed_version guards the tolerance: a bump that changed the
        # committed view moved the transitive-elision bound, which affects
        # OTHER entries' visibility — never tolerable.
        self.version = 0
        self.last_mutator: Optional[TxnId] = None
        self.committed_version = 0

    # ------------------------------------------------------------ plumbing --
    def _pos(self, txn_id: TxnId) -> int:
        """Index of txn_id, or -(insert_pos)-1 if absent."""
        if _NATIVE is not None:
            return _NATIVE.pos(self._ids, txn_id)
        i = bisect_left(self._ids, txn_id)
        if i < len(self._ids) and self._ids[i] == txn_id:
            return i
        return -i - 1

    def _eat_of(self, i: int) -> Timestamp:
        e = self._eat[i]
        return e if e is not None else self._ids[i]

    def _committed_add(self, txn_id: TxnId, at: Timestamp) -> None:
        self.committed_version += 1
        insort(self._committed, (at, txn_id))

    def _committed_remove(self, txn_id: TxnId, at: Timestamp) -> None:
        i = bisect_left(self._committed, (at, txn_id))
        if i < len(self._committed) and self._committed[i] == (at, txn_id):
            self.committed_version += 1
            del self._committed[i]

    # -------------------------------------------------------- maintenance --
    def update(self, txn_id: TxnId, status: InternalStatus,
               execute_at: Optional[Timestamp] = None,
               dep_ids: Optional[Sequence[TxnId]] = None
               ) -> List["Unmanaged"]:
        """Incremental maintenance on a command transition
        (CommandsForKey.update, :652-770 + the insert/update helpers).

        `dep_ids` — the command's key-domain dependency TxnIds AT THIS KEY
        (from its partial/stable deps), required to compute the missing[]
        divergence when `status.has_info`; ignored otherwise.

        Returns newly-satisfied Unmanaged registrations; the caller must
        invoke their callbacks with its SafeCommandStore.
        """
        pos = self._pos(txn_id)
        if pos >= 0:
            cur = self._status[pos]
            if status < cur:
                return []  # per-key view is monotone
            if status == cur and not InternalStatus.ACCEPTED <= status \
                    <= InternalStatus.APPLIED:  # not has_info
                return []
            self.version += 1
            self.last_mutator = txn_id
            # status-band tests inlined (enum <=> enum is a C int compare;
            # the properties cost a descriptor dispatch per call and update
            # consulted them three times per transition)
            was_committed = InternalStatus.COMMITTED <= cur \
                <= InternalStatus.APPLIED
            now_committed = InternalStatus.COMMITTED <= status \
                <= InternalStatus.APPLIED
            old_eat = self._eat_of(pos)
            if was_committed and now_committed \
                    and execute_at is not None \
                    and old_eat != execute_at:
                # executeAt is fixed at commit; keep the committed view exact
                self._committed_remove(txn_id, old_eat)
                self._committed_add(txn_id, execute_at)
            self._status[pos] = status
            if execute_at is not None:
                self._eat[pos] = None if execute_at == txn_id else execute_at
            # pos is stable through this branch (no inserts): reuse it
            # instead of re-bisecting per step
            if now_committed and not was_committed:
                self._committed_add(txn_id, self._eat_of(pos))
            if status == InternalStatus.INVALID_OR_TRUNCATED and was_committed:
                # use the eat recorded before the mutation above, so the exact
                # (eat, txn_id) pair leaves _committed even if the caller
                # passed a differing execute_at
                self._committed_remove(txn_id, old_eat)
            if status >= InternalStatus.COMMITTED \
                    and cur < InternalStatus.COMMITTED:
                # newly Committed-or-higher: elide from all missing[]
                self._remove_missing(txn_id)
            self._push_block_point(pos)
        else:
            self.version += 1
            self.last_mutator = txn_id
            pos = -pos - 1
            self._insert(pos, txn_id, status, execute_at)
            if status.is_committed:
                self._committed_add(txn_id, self._eat_of(pos))

        if status.has_info and dep_ids is not None:
            self._apply_deps(txn_id, status, dep_ids, pos=pos)

        if status.is_committed or status == InternalStatus.INVALID_OR_TRUNCATED:
            return self._notify_unmanaged()
        return []

    def _insert(self, i: int, txn_id: TxnId, status: InternalStatus,
                execute_at: Optional[Timestamp]) -> None:
        self._ids.insert(i, txn_id)
        self._status.insert(i, status)
        self._eat.insert(i, None if execute_at is None or execute_at == txn_id
                         else execute_at)
        self._missing.insert(i, ())
        self._wdeps.insert(i, ())
        self._push_block_point(i)
        if not status.is_decided:
            # every existing entry with known deps whose bound should have
            # witnessed this id did not (it was unknown until now): record
            # the divergence (insertInfoAndOneMissing, :897-960)
            self._add_missing_everywhere(txn_id)

    def _add_missing_everywhere(self, new_id: TxnId) -> None:
        if _NATIVE is not None:
            _NATIVE.add_missing_everywhere(self._ids, self._status, self._eat,
                                           self._missing, new_id,
                                           _WITNESS_BITS)
            return
        for j in range(len(self._ids)):
            if self._ids[j] == new_id or not self._status[j].has_info:
                continue
            bound = _deps_known_before(self._ids[j], self._status[j],
                                       self._eat[j])
            if bound > new_id and self._ids[j].witnesses(new_id):
                m = self._missing[j]
                k = bisect_left(m, new_id)
                if k >= len(m) or m[k] != new_id:
                    self._missing[j] = m[:k] + (new_id,) + m[k:]

    def _remove_missing(self, txn_id: TxnId) -> None:
        """Elide a newly-committed id from every missing collection
        (removeMissing, :962-987)."""
        if _NATIVE is not None:
            _NATIVE.remove_missing(self._missing, txn_id)
            return
        for j in range(len(self._missing)):
            m = self._missing[j]
            if not m:
                continue
            k = bisect_left(m, txn_id)
            if k < len(m) and m[k] == txn_id:
                self._missing[j] = m[:k] + m[k + 1:]

    def _apply_deps(self, txn_id: TxnId, status: InternalStatus,
                    dep_ids: Sequence[TxnId],
                    pos: Optional[int] = None) -> None:
        """Install the entry's own missing[] divergence and insert any dep
        ids not yet witnessed here (the additions path, :738-860).  `pos` —
        txn_id's known index in the arrays (update just positioned it),
        adjusted here as additions land below it."""
        if _NATIVE is not None:
            _NATIVE.apply_deps(self._ids, self._status, self._eat,
                               self._missing, self._wdeps, txn_id,
                               int(status), dep_ids,
                               InternalStatus.TRANSITIVELY_KNOWN,
                               _WITNESS_BITS)
            return
        dep_set = set(dep_ids)
        if pos is None:
            pos = self._pos(txn_id)
        # additions: deps referencing ids this key has never witnessed —
        # one bisect each (walking sorted keeps later probes exact), with
        # txn_id's index shifted as inserts land below it
        for t in sorted(dep_set):
            if not t.is_key_domain:
                continue
            p = self._pos(t)
            if p >= 0:
                continue
            i = -p - 1
            self._insert(i, t, InternalStatus.TRANSITIVELY_KNOWN, None)
            if i <= pos:
                pos += 1
        bound = _deps_known_before(txn_id, status, self._eat[pos])
        missing: List[TxnId] = []
        hi = bisect_left(self._ids, bound)
        for j in range(hi):
            t = self._ids[j]
            if t == txn_id or t in dep_set:
                continue
            if self._status[j].is_decided:
                continue  # elided: recovery sees the committed status
            if txn_id.witnesses(t):
                missing.append(t)
        self._missing[pos] = tuple(missing)
        self._wdeps[pos] = tuple(sorted(
            t for t in dep_set if t.is_key_domain and t.kind.is_write))

    def register_historical(self, txn_id: TxnId) -> None:
        """Witness a txn known only through another replica's deps
        (registerHistorical)."""
        if self._pos(txn_id) < 0:
            self.update(txn_id, InternalStatus.HISTORICAL)

    def prune_redundant(self, before: TxnId) -> List["Unmanaged"]:
        """Drop applied/invalidated txns below the redundancy watermark.
        Returns newly-satisfied Unmanaged registrations (the watermark can
        raise the min block point); caller dispatches the callbacks."""
        self.version += 1
        self.last_mutator = None
        self.redundant_before = (before if self.redundant_before is None
                                 else max(self.redundant_before, before))
        drop = [i for i, t in enumerate(self._ids)
                if t < before and self._status[i].is_terminal]
        if drop:
            dropped = {self._ids[i] for i in drop}
            for i in reversed(drop):
                if self._status[i].is_committed:
                    self._committed_remove(self._ids[i], self._eat_of(i))
                del self._ids[i], self._status[i], self._eat[i], \
                    self._missing[i], self._wdeps[i]
            for j in range(len(self._missing)):
                m = self._missing[j]
                if m and any(t in dropped for t in m):
                    self._missing[j] = tuple(t for t in m if t not in dropped)
        return self._notify_unmanaged()

    # ------------------------------------------------------ introspection --
    def get(self, txn_id: TxnId) -> Optional[TxnInfo]:
        i = self._pos(txn_id)
        if i < 0:
            return None
        return TxnInfo(self._ids[i], self._status[i], self._eat[i],
                       self._missing[i])

    def size(self) -> int:
        return len(self._ids)

    def all_ids(self) -> List[TxnId]:
        return list(self._ids)

    def as_arrays(self):
        """The packed representation, for the device encoder: parallel
        (ids, status, execute_at_or_txn_id, missing) sequences."""
        return (self._ids, self._status,
                [self._eat_of(i) for i in range(len(self._ids))],
                self._missing)

    def min_uncommitted(self) -> Optional[TxnId]:
        for i, t in enumerate(self._ids):
            if not self._status[i].is_decided:
                return t
        return None

    def max_committed_write_at(self) -> Optional[Timestamp]:
        for at, t in reversed(self._committed):
            if t.kind.is_write:
                return at
        return None

    def max_conflict(self) -> Optional[Timestamp]:
        """Max (txnId | committed executeAt) at this key — executeAt proposal
        input."""
        best: Optional[Timestamp] = None
        if self._ids:
            best = self._ids[-1]
        if self._committed and (best is None or self._committed[-1][0] > best):
            best = self._committed[-1][0]
        return best

    # ------------- the deps scan (mapReduceActive, CommandsForKey.java:614) --
    def max_committed_write_before(self, before: Timestamp
                                   ) -> Optional[Timestamp]:
        """Max executeAt among committed WRITES executing strictly before
        `before` — the transitive-elision bound."""
        if not self._committed:
            return None
        i = bisect_left(self._committed, (before,))
        i -= 1
        while i >= 0 and not self._committed[i][1].kind.is_write:
            i -= 1
        return self._committed[i][0] if i >= 0 else None

    def map_reduce_active(self, before: Timestamp, kinds: KindSet,
                          fn: Callable[[TxnId], None],
                          prune: bool = True) -> None:
        """Visit every active txn with txnId < `before` whose kind is in
        `kinds` — the dependency calculation for a new txn at this key.

        Transitive elision (mapReduceActive :614-650): establish the
        last-executing committed write below `before`; any COMMITTED-or-later
        txn with a lower executeAt is elided — its stable deps are complete,
        so depending on the bound write transitively orders us after it; for
        recovery, the committed status reported by this replica means no
        fast-path deciphering will consult these deps (design doc :101-112).
        TRANSITIVELY_KNOWN ids are unwitnessed (they exist only to track
        missing[] divergence) and never become deps themselves.
        """
        bound = self.max_committed_write_before(before) if prune else None
        if _NATIVE is not None:
            for t in _NATIVE.map_reduce_active(self._ids, self._status,
                                               self._eat, before,
                                               kinds.mask(), bound):
                fn(t)
            return
        hi = bisect_left(self._ids, before)
        for i in range(hi):
            t = self._ids[i]
            if t.kind not in kinds:
                continue
            st = self._status[i]
            if st == InternalStatus.TRANSITIVELY_KNOWN \
                    or st == InternalStatus.INVALID_OR_TRUNCATED:
                continue
            if st.is_committed and bound is not None \
                    and self._eat_of(i) < bound:
                continue  # transitively covered by the bound write
            fn(t)

    # ------------- recovery queries (mapReduceFull, CommandsForKey.java:553) --
    def map_reduce_full(self, test_txn_id: TxnId, kinds: KindSet,
                        test_started_at: TestStartedAt, test_dep: TestDep,
                        test_status: TestStatus,
                        fn: Callable[[TxnId, Timestamp], None]) -> None:
        """The recovery query family. Dep tests consult the missing[]
        divergence encoding: an entry with known deps (has_info) and
        executeAt > test_txn_id has test_txn_id as a dependency iff it is
        NOT listed in its missing collection (:598-608)."""
        pos = self._pos(test_txn_id)
        is_known = pos >= 0
        if not is_known and test_dep == TestDep.WITH:
            return
        insert_pos = pos if is_known else -pos - 1
        if test_started_at == TestStartedAt.STARTED_BEFORE:
            start, end = 0, insert_pos
        elif test_started_at == TestStartedAt.STARTED_AFTER:
            start, end = insert_pos, len(self._ids)
        else:
            start, end = 0, len(self._ids)

        kmask = kinds.mask()
        for i in range(start, end):
            t = self._ids[i]
            if t == test_txn_id \
                    or not (kmask >> ((t.flags & _KIND_MASK)
                                      >> _KIND_SHIFT)) & 1:
                continue
            st = self._status[i]
            if test_status == TestStatus.IS_PROPOSED:
                if st not in (InternalStatus.ACCEPTED,
                              InternalStatus.COMMITTED):
                    continue
            elif test_status == TestStatus.IS_STABLE:
                if not (InternalStatus.STABLE <= st
                        <= InternalStatus.APPLIED):
                    continue
            else:
                if st == InternalStatus.TRANSITIVELY_KNOWN:
                    continue
            execute_at = self._eat_of(i)
            if test_dep != TestDep.ANY_DEPS:
                if not st.has_info:
                    continue
                if execute_at <= test_txn_id:
                    continue
                m = self._missing[i]
                k = bisect_left(m, test_txn_id)
                has_as_dep = not (k < len(m) and m[k] == test_txn_id)
                if has_as_dep != (test_dep == TestDep.WITH):
                    continue
            fn(t, execute_at)

    # the four BeginRecovery predicates (BeginRecovery.java:329-380).
    # The *_ids variants return the matching ids (the batched device store
    # verifies its precomputed masks against them); the bool forms delegate.
    def omission_covers(self, i: int, txn_id: TxnId,
                        resolve=None) -> Optional[Tuple[TxnId, ...]]:
        """Entry i carries deps that omit `txn_id` — classify the omission.

        The deps calc (map_reduce_active) elides any committed entry whose
        executeAt lies below the last-executing committed write, so a
        fast-path-committed txn_id (executeAt == txn_id) is legally ABSENT
        from a later entry's deps wherever a committed write bound covered
        it.  The recovery reject predicates consult the missing[] encoding
        under exactly the fast-path hypothesis; reading an elision-shaped
        omission as a fast-path refutation invalidated a COMMITTED txn in
        a soak burn (seed 16005: fast commit on a reduced electorate, a
        later committed write as the elision bound, and a recovery quorum
        that avoided every committed copy).  The reference ships the same
        elision with an unproven-correctness TODO
        (CommandsForKey.java:640 PRUNE_TRANSITIVE_DEPENDENCIES).

        Returns a three-way verdict mirroring the exact elision rule:

        * ``None`` — ELIDED.  Some registered write dep of entry i is
          COMMITTED with executeAt strictly between the hypothesised
          fast-path timestamp and the entry's deps-known-before bound —
          exactly the window in which map_reduce_active elides: the
          bound write is always itself visited (only entries strictly
          below the bound are pruned), so if txn_id was elided, its
          cover IS among the entry's registered write deps.  The
          omission is no evidence either way; suppress it.
        * ``()`` — EVIDENCE.  Every registered write dep is resolved
          (committed outside the window, or invalidated) and none could
          have been a legal elision bound; the omission genuinely
          refutes the fast path.
        * non-empty tuple — INCONCLUSIVE.  The listed write deps are not
          decided locally, and any of them may yet commit (possibly on
          the slow path, with an executeAt well above its id) into the
          covering window.  The caller must NOT read the omission as
          evidence host-side: the recovery coordinator awaits these
          covers' commits and retries, by which time they resolve into
          one of the two definite verdicts.  This also closes the
          residual soundness edge recorded in round 3's SOAK_NOTES: a
          cover whose id is below the hypothesised timestamp but whose
          slow-path executeAt (above it) is not locally known used to
          be mis-read as reject evidence (its id was used as the
          resolution), re-opening the seed-16005 hazard; now it is
          reported unresolved and resolved by the coordinator.

        LIVENESS (await acyclicity): only undecided covers with id
        STRICTLY BELOW txn_id are reported unresolved.  Awaiting a cover
        triggers recovery of the cover if its coordinator died, and that
        recovery may itself await covers — were awaits unordered, two
        undecided writes could await each other through crossing deps
        (x deps=[b] omitting w, y deps=[w] omitting b: Recovery(w) parks
        on b while Recovery(b) parks on w, both wedged forever, the
        seed-15003 acked-write-loss class).  Restricting awaits to
        strictly-smaller ids makes every await chain strictly
        decreasing, hence finite and cycle-free.  An undecided cover
        with id ABOVE txn_id instead suppresses the omission (its
        eventual executeAt necessarily exceeds the hypothesis, so it
        may legally have elided txn_id at a replica that saw it
        committed): the fail-safe direction — reading the omission as
        evidence risks invalidating a committed txn (seed 16005), the
        strictly worse failure — and exactly round 3's behaviour for
        this sub-case, soaked over ~226 hostile seeds.  When the cover
        later resolves, a retried recovery reads the omission
        definitively.

        `resolve(w) -> ('committed', executeAt) | ('invalid', None) |
        ('undecided', None) | None` lets the store consult its command
        registry for deps this CFK no longer tracks precisely
        (INVALID_OR_TRUNCATED conflates invalidated with
        truncated-applied; prune_redundant drops entries wholesale).  A
        cover that is untrackable even there — pruned below the
        redundancy watermark AND erased from the registry — is treated
        as a cover (suppress): erasure requires the shard's durable
        frontier to have advanced past it, so it was applied at some
        executeAt we can no longer observe; reading its omission as
        reject evidence risks invalidating a committed txn (the
        seed-16005 class, the strictly worse failure), while awaiting
        it would livelock (it is already durably decided everywhere, so
        a WaitOnCommit acks instantly and a retry learns nothing new).

        The write-dep ids were recorded from the true dep list at
        registration (the missing[] encoding can't answer this because
        decided ids are exempt from it); each is resolved HERE so a dep
        that committed after registration contributes its real executeAt
        (its id alone is only a lower bound on where it executes)."""
        hyp = txn_id.as_timestamp()
        bound = _deps_known_before(self._ids[i], self._status[i],
                                   self._eat[i])
        unresolved: List[TxnId] = []
        for t in self._wdeps[i]:
            if t == txn_id:
                continue
            p = self._pos(t)
            if p >= 0 and self._status[p].is_committed:
                e = self._eat_of(p)
                if hyp < e < bound:
                    return None  # definite elision cover
                continue  # committed outside the window: no cover
            if p >= 0 and self._status[p] != InternalStatus.INVALID_OR_TRUNCATED:
                # witnessed here but undecided: consult the registry (it may
                # know a commit this per-key view hasn't absorbed yet)
                r = resolve(t) if resolve is not None else None
                if r is None or r[0] == "undecided":
                    if t > txn_id:
                        return None  # suppress: see LIVENESS note above
                    unresolved.append(t)
                    continue
            else:
                # INVALID_OR_TRUNCATED in place, or pruned entirely: the
                # per-key view can't distinguish invalidated (no cover)
                # from truncated-applied (possible cover)
                r = resolve(t) if resolve is not None else None
                if r is None:
                    return None  # untrackable: suppress (see docstring)
            kind, eat = r
            if kind == "committed":
                if eat is not None and hyp < eat < bound:
                    return None
                continue
            if kind == "invalid":
                continue
            if t > txn_id:
                return None  # suppress: see LIVENESS note above
            unresolved.append(t)
        return tuple(unresolved)

    def classify_omissions(self, found: List[TxnId], txn_id: TxnId,
                           resolve=None
                           ) -> Tuple[List[TxnId], List[TxnId]]:
        """Partition raw omission candidates into (evidence, unresolved
        cover ids).  An entry whose omission is elision-shaped contributes
        to neither; an entry with undecided cover candidates contributes
        those covers to `unresolved` instead of itself to `evidence`."""
        evidence: List[TxnId] = []
        unresolved: List[TxnId] = []
        for t in found:
            covers = self.omission_covers(self._pos(t), txn_id, resolve)
            if covers is None:
                continue
            if covers:
                unresolved.extend(covers)
            else:
                evidence.append(t)
        return evidence, unresolved

    def _filter_elided(self, found: List[TxnId], txn_id: TxnId
                       ) -> List[TxnId]:
        """Definite-evidence filter (no resolver): entries whose omission is
        elided OR inconclusive are dropped.  Callers that can act on
        inconclusiveness use classify_omissions instead."""
        return self.classify_omissions(found, txn_id)[0]

    def started_after_without_witnessing_ids(self, txn_id: TxnId,
                                             raw: bool = False
                                             ) -> List[TxnId]:
        """`raw=True` returns the unsuppressed candidates (the device tier's
        batched masks compute exactly these; suppression is a shared
        host-side post-filter on both paths)."""
        found: List[TxnId] = []
        self.map_reduce_full(txn_id, txn_id.kind.witnessed_by(),
                             TestStartedAt.STARTED_AFTER, TestDep.WITHOUT,
                             TestStatus.IS_PROPOSED,
                             lambda t, at: found.append(t))
        return found if raw else self._filter_elided(found, txn_id)

    def accepted_or_committed_started_after_without_witnessing(
            self, txn_id: TxnId) -> bool:
        return bool(self.started_after_without_witnessing_ids(txn_id))

    def executes_after_without_witnessing_ids(self, txn_id: TxnId,
                                              raw: bool = False
                                              ) -> List[TxnId]:
        """hasStableExecutesAfterWithoutWitnessing (ANY started-at; the dep
        test already restricts to executeAt > txn_id).  Elision-shaped
        omissions are inconclusive (see omission_covers)."""
        found: List[TxnId] = []
        self.map_reduce_full(txn_id, txn_id.kind.witnessed_by(),
                             TestStartedAt.ANY, TestDep.WITHOUT,
                             TestStatus.IS_STABLE,
                             lambda t, at: found.append(t))
        return found if raw else self._filter_elided(found, txn_id)

    def committed_executes_after_without_witnessing(self, txn_id: TxnId
                                                    ) -> bool:
        return bool(self.executes_after_without_witnessing_ids(txn_id))

    def stable_started_before_and_witnessed(self, txn_id: TxnId
                                            ) -> List[TxnId]:
        out: List[TxnId] = []
        self.map_reduce_full(txn_id, txn_id.kind.witnessed_by(),
                             TestStartedAt.STARTED_BEFORE, TestDep.WITH,
                             TestStatus.IS_STABLE,
                             lambda t, at: out.append(t))
        return out

    def accepted_started_before_without_witnessing(self, txn_id: TxnId
                                                   ) -> List[TxnId]:
        """acceptedOrCommittedStartedBeforeWithoutWitnessing: proposed to
        execute after txn_id with deps omitting it — recovery must await
        their commit before deciphering the fast path (:329-342)."""
        out: List[TxnId] = []
        self.map_reduce_full(txn_id, txn_id.kind.witnessed_by(),
                             TestStartedAt.STARTED_BEFORE, TestDep.WITHOUT,
                             TestStatus.IS_PROPOSED,
                             lambda t, at: out.append(t) if at > txn_id
                             else None)
        return out

    # ---------------------------------------- unmanaged (cross-key) waits --
    #
    # Efficiency: an entry's *block point* — the lowest waiting_until it can
    # block — is its id while undecided, its executeAt while committed, and
    # gone once terminal/invisible/redundant.  Transitions only ever RAISE
    # it, so a lazy min-heap over block points plus a min-heap of
    # registrations by waiting_until makes each update O(log n) amortised:
    # a registration fires exactly when min-block-point >= its
    # waiting_until.  (A notify-all-per-update formulation is quadratic on
    # a deep same-key chain — 3000 committed writes at one key wedged the
    # burn for minutes.)

    def register_unmanaged(self, unmanaged: Unmanaged) -> None:
        """Record an APPLY wait.  Caller contract: register only after
        proving blockers exist (commands._initialise_key_wait does) — the
        satisfaction check is the caller's, so no walk happens here."""
        invariants.check_state(unmanaged.pending == Unmanaged.APPLY,
                               "only APPLY waits are registrable; COMMIT is "
                               "a query mode (blocking_ids)")
        self._wait_seq += 1
        heapq.heappush(self._wait_heap,
                       (unmanaged.waiting_until, self._wait_seq, unmanaged))

    def has_unmanaged(self, txn_id: TxnId) -> bool:
        return any(w[2].txn_id == txn_id for w in self._wait_heap)

    def _block_point(self, i: int) -> Optional[Timestamp]:
        st = self._status[i]
        t = self._ids[i]
        # int-band tests instead of enum property dispatch: this runs per
        # lazy-heap pop and per update (terminal = APPLIED|INVALID = >= 6;
        # visibility via the precomputed kind mask)
        if st >= InternalStatus.APPLIED \
                or st == InternalStatus.TRANSITIVELY_KNOWN \
                or not (_VISIBLE_MASK
                        >> ((t.flags & _KIND_MASK) >> _KIND_SHIFT)) & 1:
            return None
        if self.redundant_before is not None and t < self.redundant_before:
            return None
        return self._eat_of(i) \
            if InternalStatus.COMMITTED <= st else t

    def _push_block_point(self, i: int) -> None:
        bp = self._block_point(i)
        if bp is not None:
            heapq.heappush(self._block_heap, (bp, self._ids[i]))

    def _min_block_point(self) -> Optional[Timestamp]:
        """Current minimum block point (None = nothing blocks).  Stale heap
        items — transitions pushed fresh copies — are popped lazily."""
        while self._block_heap:
            bp, t = self._block_heap[0]
            i = self._pos(t)
            cur = self._block_point(i) if i >= 0 else None
            if cur is not None and cur == bp:
                return bp
            heapq.heappop(self._block_heap)
            if cur is not None:
                # moved (committed: id -> executeAt); reinsert at the new point
                heapq.heappush(self._block_heap, (cur, t))
        return None

    def _notify_unmanaged(self) -> List[Unmanaged]:
        fired: List[Unmanaged] = []
        if self._wait_heap:
            mbp = self._min_block_point()
            while self._wait_heap and (mbp is None
                                       or self._wait_heap[0][0] <= mbp):
                fired.append(heapq.heappop(self._wait_heap)[2])
        return fired

    def blocking_ids(self, pending: str, waiting_until: Timestamp,
                     exclude: Optional[TxnId] = None,
                     first_only: bool = False,
                     skip_pred: Optional[Callable[[TxnId], bool]] = None
                     ) -> List[Tuple[TxnId, bool]]:
        """Entries currently failing the wait rule: for APPLY, every visible
        entry ordered before `waiting_until` must be terminal or committed
        with executeAt after it; for COMMIT, merely decided.  Returns
        (txn_id, is_decided) pairs — the progress log chases undecided
        blockers to Committed and decided ones to Applied.  Entries below
        the redundancy watermark (or matching `skip_pred`, e.g. the
        per-store RedundantBefore) are already reflected in local state
        (snapshot or GC) and never block."""
        out: List[Tuple[TxnId, bool]] = []
        # ids are sorted: only the prefix strictly below waiting_until can
        # block, and everything below the redundancy watermark never does
        lo = (bisect_left(self._ids, self.redundant_before)
              if self.redundant_before is not None else 0)
        hi = bisect_left(self._ids, waiting_until)
        for i in range(lo, hi):
            t = self._ids[i]
            if t == exclude:
                continue
            st = self._status[i]
            if not t.is_visible or st == InternalStatus.TRANSITIVELY_KNOWN:
                continue
            if pending == Unmanaged.COMMIT:
                if not st.is_decided:
                    out.append((t, False))
            else:  # APPLY
                if not st.is_terminal:
                    if not (st.is_committed
                            and self._eat_of(i) > waiting_until):
                        out.append((t, st.is_decided))
            if out and skip_pred is not None and skip_pred(out[-1][0]):
                out.pop()
            if out and first_only:
                return out
        return out

    def __repr__(self):
        return f"CFK({self.key!r}, {len(self._ids)} txns)"


class TimestampsForKey:
    """Per-key execution timestamps (reference impl/TimestampsForKey.java:33):
    lastExecutedTimestamp / lastWriteTimestamp feed executeAt validation and
    the read-timestamp watermark."""

    __slots__ = ("key", "last_executed", "last_write", "raw_hlc")

    def __init__(self, key: Key):
        self.key = key
        self.last_executed: Optional[Timestamp] = None
        self.last_write: Optional[Timestamp] = None
        self.raw_hlc = 0

    def on_executed(self, at: Timestamp, is_write: bool) -> None:
        if self.last_executed is None or at > self.last_executed:
            self.last_executed = at
        if is_write and (self.last_write is None or at > self.last_write):
            self.last_write = at
        self.raw_hlc = max(self.raw_hlc, at.hlc)

    def validate_execute_at(self, at: Timestamp) -> None:
        invariants.check_state(
            self.last_write is None or at >= self.last_write,
            "executeAt %s precedes last write %s at %s", at, self.last_write,
            self.key)
