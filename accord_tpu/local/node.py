"""Node: the per-process runtime (reference: accord/local/Node.java:100-780).

Wires MessageSink / ConfigurationService / TopologyManager / CommandStores /
Agent / Scheduler; owns the HLC (uniqueNow, Node.java:341-366), txn-id
allocation (:562), coordination entry (:567-596), routing helpers (:598-673),
message receive + epoch gating (:715-736), and send helpers with
store-affine callbacks (:431-533).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from accord_tpu.api.spi import (
    Agent, EventsListener, LocalConfig, MessageSink, ProgressLog, Scheduler,
)
from accord_tpu.coordinate.errors import Timeout
from accord_tpu.local.store import CommandStores, EmptyFanout, PreLoadContext
from accord_tpu.obs.spans import trace_key as _trace_key
from accord_tpu.messages.base import Callback, FailureReply, Reply, Request, TxnRequest
from accord_tpu.primitives.keys import Keys, Ranges, Route, RoutingKey
from accord_tpu.primitives.timestamp import Domain, Timestamp, TxnId, TxnKind
from accord_tpu.primitives.txn import Txn
from accord_tpu.topology.manager import TopologyManager
from accord_tpu.topology.topology import Topology
from accord_tpu.utils import invariants
from accord_tpu.utils.async_chains import AsyncResult, success
from accord_tpu.utils.random_source import RandomSource


class _SafeCallback:
    """Once-only callback wrapper with timeout arming (reference
    SafeCallback + Node timeout registration)."""

    def __init__(self, node: "Node", to: int, callback: Callback,
                 txn_id=None):
        self.node = node
        self.to = to
        self.callback = callback
        self.txn_id = txn_id  # watched coordination to credit progress to
        self.done = False
        self.timer = None

    def arm_timeout(self, delay_s: float) -> None:
        self.timer = self.node.scheduler.once(delay_s, self._on_timeout)

    def _on_timeout(self) -> None:
        if not self.done:
            self.done = True
            unregister = getattr(self, "sink_unregister", None)
            if unregister is not None:
                unregister()  # release the sink's msg-id entry (CallbackSink)
            try:
                self.callback.on_failure(self.to, Timeout())
            except BaseException as e:  # noqa: BLE001
                self.callback.on_callback_failure(self.to, e)

    def deliver(self, reply) -> None:
        if self.done:
            return
        self.done = True
        if self.timer is not None:
            self.timer.cancel()
        if self.txn_id is not None:
            # any genuine reply (even a remote failure) is liveness
            # evidence for the coordination's inactivity watchdog
            self.node.note_coordination_progress(self.txn_id)
        try:
            if isinstance(reply, FailureReply):
                self.callback.on_failure(self.to, reply.failure)
            elif isinstance(reply, BaseException):
                self.callback.on_failure(self.to, reply)
            else:
                self.callback.on_success(self.to, reply)
        except BaseException as e:  # noqa: BLE001
            self.callback.on_callback_failure(self.to, e)


class Node:
    def __init__(self, node_id: int, sink: MessageSink, agent: Agent,
                 scheduler: Scheduler, data_store, random: RandomSource,
                 num_shards: int = 1, config: LocalConfig = None,
                 progress_log_factory: Callable = None,
                 store_factory: Callable = None,
                 now_us: Callable[[], int] = None,
                 events: EventsListener = None,
                 trace=None, obs=None):
        from accord_tpu.obs import CounterDict, NodeObs
        from accord_tpu.utils.tracing import NO_TRACE
        self.id = node_id
        self.sink = sink
        self.agent = agent
        self.scheduler = scheduler
        self.data_store = data_store
        self.random = random
        self.trace = trace if trace is not None else NO_TRACE
        self.config = config or LocalConfig.default()
        # observability: one metrics registry + span store per node
        # (obs/ — instrumented by coordinators, stores, pipeline, hosts).
        # The clock indirection lets _now_us be assigned below.
        self.obs = obs if obs is not None else NodeObs(
            node_id, clock_us=lambda: self._now_us())
        self.topology = TopologyManager(node_id)
        self.command_stores = CommandStores(self, num_shards,
                                            store_factory=store_factory)
        self.events = events or EventsListener()
        self._progress_log_factory = progress_log_factory
        self._progress_logs: Dict[int, ProgressLog] = {}
        self._now_us = now_us or (lambda: 0)
        if obs is None:
            # the indirection above only existed because _now_us was not
            # yet assigned; rebind the obs/flight clocks directly so every
            # span/flight event saves a lambda hop (~37k clock reads per
            # 400-txn TCP run)
            self.obs._clock_us = self._now_us
            self.obs.flight._clock_us = self._now_us
        self._hlc = 0
        # (stripe, mod) congruence class for minted HLCs, or None: set only
        # by the shard worker runtime (set_hlc_stripe) — in-loop nodes mint
        # exactly as before
        self._hlc_stripe = None
        # optional side-effecting-message journal (sim/journal.Journal);
        # when set, every has_side_effects request is recorded at processing
        self.journal = None
        self.coordinating: Dict[TxnId, AsyncResult] = {}
        # txn_id -> last observable-progress time (s) for watched
        # coordinations; see _arm_coordination_watchdog
        self._coordination_activity: Dict[TxnId, float] = {}
        # txn_id -> recovery rounds started here, pruned once the txn's
        # local recovery future settles for good (storm-boundedness
        # metric: watchdog-driven retry must not mask livelock;
        # recovery_attempts_max keeps the high-water mark, burn-asserted)
        self.recovery_attempts: Dict[TxnId, int] = {}
        self.recovery_attempts_max = 0
        # the Infer ladder's A/B counters (coordinate/infer.py, reference
        # Infer.inferInvalidWithQuorum): evidence = CheckStatus merges whose
        # replies carried InvalidIf evidence; quorum_evidence = merges where
        # a per-shard QUORUM carried it (resolvable with ZERO extra rounds);
        # inferred_rounds = ballot-protected Invalidate rounds still paid on
        # evidence (sub-quorum, or the ACCORD_INFER_FULL=0 escape hatch);
        # no_round_commits = invalidations committed directly off quorum
        # evidence; fence_refusals = fresh witnesses refused below the
        # durable fence (local/commands.is_durably_fenced); safe_to_clean =
        # stragglers the cleanup sweep inferred invalid and erased.
        # Registry-backed with the old dict shape preserved (the r5 Infer
        # A/B harness reads these keys).
        self.infer_stats = CounterDict(
            self.obs.registry, "accord_infer_total",
            ("evidence", "quorum_evidence", "inferred_rounds",
             "no_round_commits", "fence_refusals", "safe_to_clean"))
        self._reply_seq = 0
        # epochs with a live shared refetch timer chain (_ensure_epoch_fetch)
        self._epoch_refetch: set = set()
        # spans with a staleness-escalation bootstrap in flight (dedup), and
        # spans that re-escalated while covered by an in-flight attempt
        # (needing a fresh fence once it completes)
        self._stale_bootstrapping: Ranges = Ranges.EMPTY
        self._stale_requeue: Ranges = Ranges.EMPTY
        # -- live elasticity (messages/admin.py, impl/config_service.py) --
        # the attached configuration service (set by attach_node), the
        # replay/defer flags crash-restart uses to suspend live side effects,
        # and the drain state the scale-in protocol maintains
        self.config_service = None
        self.replaying = False
        # while True, on_topology_update records newly-owned ranges instead
        # of starting live bootstraps (journal replay / restart feed);
        # resume_bootstraps() then starts only what checkpoints left uncovered
        self.defer_bootstrap = False
        self._deferred_bootstrap: Dict[int, Ranges] = {}
        # epoch -> coverage restored from journaled BootstrapCheckpoint
        # records; epochs whose BootstrapDone marker replayed
        self._ckpt_bootstrapped: Dict[int, Ranges] = {}
        self._bootstrap_complete: set = set()
        self.draining = False   # this node is fenced against new client work
        self.drained = False    # drain handoff finished; safe to retire
        self.draining_peers: set = set()  # peers to deprioritize as sources

    # ------------------------------------------------------------ lifecycle --
    def on_topology_update(self, topology: Topology, start_sync: bool = True
                           ) -> Ranges:
        """Feed a new epoch (reference Node.onTopologyUpdate :247-255):
        re-range the stores, bootstrap newly-owned ranges behind an
        ExclusiveSyncPoint fence, then broadcast epoch-sync completion so
        peers' TopologyManagers can unlock the epoch (§3.4). Returns the
        ranges newly owned by this node."""
        first = not self.topology.has_epoch(topology.epoch - 1) \
            and self.topology.min_epoch in (0, topology.epoch)
        if self.trace.enabled:
            self.trace.event("topology_update", epoch=topology.epoch)
        self.topology.on_topology_update(topology)
        owned = topology.ranges_for_node(self.id)
        added = self.command_stores.update_topology(owned)
        epoch = topology.epoch

        def synced(_v=None, _f=None):
            # honest start_sync: a FAILED bootstrap (bounded retries
            # exhausted) must not report the epoch synced — peers would
            # route reads at data this node never acquired
            if _f is None:
                self._broadcast_sync_complete(epoch, topology)

        if self.defer_bootstrap and not first and start_sync:
            # journal replay / restart feed: record what this epoch added
            # (stores are already re-ranged above) and let
            # resume_bootstraps() reconcile it against checkpointed
            # coverage once the journal has finished replaying
            self._deferred_bootstrap[epoch] = added
            return added
        if added.is_empty or first or not start_sync:
            # nothing to copy (or the genesis epoch: there is no data yet)
            for store in self.command_stores.intersecting(added):
                store.mark_safe_to_read(added)
            if start_sync:
                synced()
        else:
            from accord_tpu.local.bootstrap import Bootstrap
            attempt = Bootstrap(self, added, epoch)
            attempt.result.add_callback(
                lambda v, f, e=epoch, r=added:
                self._journal_bootstrap_done(e, r) if f is None else None)
            attempt.result.add_callback(synced)
            attempt.start()
        return added

    def _broadcast_sync_complete(self, epoch: int, topology: Topology) -> None:
        from accord_tpu.messages.epoch import EpochSyncComplete
        self.topology.on_epoch_sync_complete(self.id, epoch)
        for to in sorted(topology.nodes()):
            if to != self.id:
                self.send(to, EpochSyncComplete(epoch))

    def _journal_bootstrap_done(self, epoch: int, ranges: Ranges) -> None:
        self._bootstrap_complete.add(epoch)
        if self.journal is None or self.replaying:
            return
        from accord_tpu.messages.admin import BootstrapDone
        self.journal.record(self.id, BootstrapDone(epoch, ranges))

    def resume_bootstraps(self) -> None:
        """End defer mode after a journal replay / restart feed: reconcile
        each deferred epoch's newly-owned ranges against the coverage its
        journaled BootstrapCheckpoint records restored, and bootstrap ONLY
        the remainder — a crash mid-bootstrap resumes from the checkpointed
        watermark instead of re-fetching completed ranges."""
        self.defer_bootstrap = False
        deferred, self._deferred_bootstrap = self._deferred_bootstrap, {}
        for epoch in sorted(deferred):
            added = deferred[epoch]
            restored = self._ckpt_bootstrapped.pop(epoch, Ranges.EMPTY)
            remaining = added.subtract(restored)
            topology = self.topology.for_epoch(epoch)
            if remaining.is_empty or epoch in self._bootstrap_complete:
                # every owned range is covered (checkpoints, or nothing was
                # added): the epoch is synced as far as this node goes
                for store in self.command_stores.intersecting(remaining):
                    store.mark_safe_to_read(remaining)
                self._broadcast_sync_complete(epoch, topology)
                continue
            from accord_tpu.local.bootstrap import Bootstrap
            attempt = Bootstrap(self, remaining, epoch)

            def finished(_v, _f, e=epoch, t=topology, r=added):
                if _f is None:
                    self._journal_bootstrap_done(e, r)
                    self._broadcast_sync_complete(e, t)

            attempt.result.add_callback(finished)
            attempt.start()

    def mark_stale_and_bootstrap(self, ranges: Ranges) -> None:
        """Re-acquire `ranges` wholesale after local per-txn catch-up proved
        impossible (peers truncated the deps): the staleness escalation path
        (reference Agent.onStale / markShardStale -> Bootstrap).

        Spans already being bootstrapped are not dropped — the in-flight
        attempt's ESP fence may PREDATE the txn that just wedged (its
        snapshot will not contain it), so they are queued and re-escalated
        with a fresh fence once the in-flight attempt finishes."""
        overlapping = ranges.slice(self._stale_bootstrapping)
        if not overlapping.is_empty:
            self._stale_requeue = self._stale_requeue.union(overlapping)
        remaining = ranges.subtract(self._stale_bootstrapping)
        if remaining.is_empty:
            return
        self._stale_bootstrapping = self._stale_bootstrapping.union(remaining)
        self.agent.on_stale(self.unique_now(), remaining)
        from accord_tpu.local.bootstrap import Bootstrap
        attempt = Bootstrap(self, remaining, self.epoch)
        attempt.result.add_callback(
            lambda v, f: self._stale_bootstrap_done(remaining))
        attempt.start()

    def _stale_bootstrap_done(self, finished: Ranges) -> None:
        self._stale_bootstrapping = self._stale_bootstrapping.subtract(finished)
        requeue = self._stale_requeue.slice(finished)
        if not requeue.is_empty:
            self._stale_requeue = self._stale_requeue.subtract(requeue)
            self.mark_stale_and_bootstrap(requeue)

    def progress_log_for(self, store) -> ProgressLog:
        pl = self._progress_logs.get(store.id)
        if pl is None:
            if self._progress_log_factory is None:
                pl = ProgressLog.__new__(_NullProgressLog)
            else:
                pl = self._progress_log_factory(self, store)
            self._progress_logs[store.id] = pl
        return pl

    # ------------------------------------------------------------------ HLC --
    def now_us(self) -> int:
        """Wall (or virtual) clock in microseconds."""
        return self._now_us()

    def unique_now(self) -> Timestamp:
        """Monotonic unique HLC (Node.uniqueNow CAS loop, :341-366)."""
        self._hlc = self._striped(max(self._hlc + 1, self._now_us()))
        return Timestamp(self.epoch, self._hlc, 0, self.id)

    def unique_now_at_least(self, at_least: Timestamp) -> Timestamp:
        self._hlc = self._striped(
            max(self._hlc + 1, self._now_us(), at_least.hlc + 1))
        epoch = max(self.epoch, at_least.epoch)
        return Timestamp(epoch, self._hlc, 0, self.id)

    def set_hlc_stripe(self, stripe: int, mod: int) -> None:
        """Worker runtime (shard/): N processes mint under ONE node id, so
        each confines its HLCs to a congruence class — same-id collisions
        become impossible without any cross-process clock coordination."""
        self._hlc_stripe = (stripe, mod)

    def _striped(self, hlc: int) -> int:
        if self._hlc_stripe is None:
            return hlc
        s, m = self._hlc_stripe
        return hlc + ((s - hlc) % m)

    def on_remote_timestamp(self, ts: Timestamp) -> None:
        """Merge a remote HLC observation (epoch/hlc propagation)."""
        if ts.hlc > self._hlc:
            self._hlc = ts.hlc

    @property
    def epoch(self) -> int:
        return max(1, self.topology.epoch)

    def next_txn_id(self, kind: TxnKind, domain: Domain) -> TxnId:
        now = self.unique_now()
        return TxnId.create(now.epoch, now.hlc, kind, domain, self.id)

    # -------------------------------------------------------------- routing --
    def compute_route(self, txn: Txn) -> Route:
        """Home-key selection (Node.java:598-617): a routing key from the
        txn's participants, preferring one this node owns."""
        if isinstance(txn.keys, Keys):
            routing = txn.keys.as_routing()
            invariants.check_argument(len(routing) > 0, "txn has no keys")
            home = self._select_home_key(list(routing))
            return Route.of_keys(home, routing)
        ranges = txn.keys
        invariants.check_argument(len(ranges) > 0, "txn has no ranges")
        home = self._select_home_key(
            [RoutingKey(r.start) for r in ranges])
        return Route.of_ranges(home, ranges)

    def _select_home_key(self, candidates: List[RoutingKey]) -> RoutingKey:
        local = self.topology.current().ranges_for_node(self.id)
        for k in candidates:
            if local.contains(k):
                return k
        return candidates[0]

    # --------------------------------------------------------- coordination --
    def coordinate(self, txn: Txn, txn_id: Optional[TxnId] = None
                   ) -> AsyncResult:
        """Client entry: coordinate a transaction to its Result
        (Node.coordinate :567-596)."""
        from accord_tpu.coordinate.ephemeral import CoordinateEphemeralRead
        from accord_tpu.coordinate.transaction import CoordinateTransaction
        domain = Domain.KEY if isinstance(txn.keys, Keys) else Domain.RANGE
        if txn_id is None:
            txn_id = self.next_txn_id(txn.kind, domain)
        result = AsyncResult()
        if self.trace.enabled:
            self.trace.event("coordinate", txn_id=txn_id, kind=txn.kind.name)
        self.obs.txn_begin(txn_id, kind=txn.kind.name)
        result.add_callback(lambda v, f: self.obs.txn_end(txn_id, f))
        if txn.kind == TxnKind.EPHEMERAL_READ:
            # invisible single-round read: no recovery registration
            self.with_epoch(txn_id.epoch,
                            lambda: CoordinateEphemeralRead(
                                self, txn_id, txn, result).start())
            return result
        self.coordinating[txn_id] = result
        result.add_callback(lambda v, f: self.coordinating.pop(txn_id, None))
        self._arm_coordination_watchdog(txn_id, result, "coordination")
        self.with_epoch(txn_id.epoch,
                        lambda: CoordinateTransaction(self, txn_id, txn,
                                                      result).start())
        return result

    def recover(self, txn_id: TxnId, route: Route) -> AsyncResult:
        """Recovery entry (Node.recover :685)."""
        from accord_tpu.coordinate.recover import Recover
        existing = self.coordinating.get(txn_id)
        if existing is not None:
            return existing
        result = AsyncResult()
        self.coordinating[txn_id] = result
        result.add_callback(lambda v, f: self.coordinating.pop(txn_id, None))
        self._arm_coordination_watchdog(txn_id, result, "recovery")
        n_attempts = self.recovery_attempts.get(txn_id, 0) + 1
        self.recovery_attempts[txn_id] = n_attempts
        self.recovery_attempts_max = max(self.recovery_attempts_max,
                                         n_attempts)
        result.add_callback(
            lambda v, f: None if f is not None
            else self.recovery_attempts.pop(txn_id, None))
        if self.trace.enabled:
            self.trace.event("recover", txn_id=txn_id)
        self.obs.txn_begin(txn_id, path="recovery")
        result.add_callback(
            lambda v, f: self.obs.txn_end(txn_id, f, path="recovery"))
        self.with_epoch(txn_id.epoch,
                        lambda: Recover(self, txn_id, route, result).start())
        return result

    def invalidate(self, txn_id: TxnId, some_route: Route) -> AsyncResult:
        """Multi-shard invalidation entry, for txns we hold only partial
        route knowledge of (Invalidate.invalidate); doubles as route
        discovery and escalates to Recover if anything was witnessed."""
        from accord_tpu.coordinate.invalidate import Invalidate
        existing = self.coordinating.get(txn_id)
        if existing is not None:
            return existing
        result = AsyncResult()
        self.coordinating[txn_id] = result
        result.add_callback(lambda v, f: self.coordinating.pop(txn_id, None))
        self._arm_coordination_watchdog(txn_id, result, "invalidation")
        if self.trace.enabled:
            self.trace.event("invalidate", txn_id=txn_id)
        self.obs.txn_begin(txn_id, path="invalidation")
        result.add_callback(
            lambda v, f: self.obs.txn_end(txn_id, f, path="invalidation"))
        self.with_epoch(txn_id.epoch,
                        lambda: Invalidate(self, txn_id, some_route,
                                           result).start())
        return result

    def _arm_coordination_watchdog(self, txn_id: TxnId, result: AsyncResult,
                                   what: str) -> None:
        """Force-fail a coordination/recovery/invalidation future that
        outlives every plausible sequence of its RPC rounds.  These futures are
        deduplicated through `coordinating`, so ANY code path that fails to
        settle (a round that sent zero messages, a reply handler that
        returns without continuing) otherwise pins a dead future there
        forever — after which the progress log's escalations all no-op and
        a wedged txn is never repaired (seed-15003 soak: an acked write
        was lost to exactly that).  The watchdog converts such a bug into a
        bounded stall: the failure pops the dedup entry and the next
        escalation starts a fresh coordinator."""
        timeout_s = (self.agent.pre_accept_timeout()
                     * self.config.rpc_timeout_multiplier
                     * self.config.coordination_watchdog_multiplier)
        hard_s = timeout_s \
            * self.config.coordination_watchdog_hard_cap_multiplier
        start = self.now_us() / 1e6
        self._coordination_activity[txn_id] = start
        state = {}

        def fire():
            now = self.now_us() / 1e6
            last = self._coordination_activity.get(txn_id, start)
            if now - last < timeout_s and now - start < hard_s:
                # observable progress since the last check (replies
                # received, retries started): a slow-but-live coordination
                # must not be force-failed (ADVICE r3) — re-arm for the
                # remaining inactivity window, bounded by the hard cap
                remaining = min(timeout_s - (now - last),
                                hard_s - (now - start))
                state["timer"] = self.scheduler.once(max(remaining, 1e-3),
                                                     fire)
                return
            if now - start >= hard_s and now - last < timeout_s:
                reason = (f"exceeded the {hard_s:.1f}s hard cap while still "
                          f"exchanging messages (livelocked coordination)")
            else:
                reason = (f"saw no progress for {timeout_s:.1f}s "
                          f"(non-settling coordination path)")
            result.try_failure(Timeout(f"{what} of {txn_id} {reason}"))

        state["timer"] = self.scheduler.once(timeout_s, fire)
        result.add_callback(lambda v, f: (
            state["timer"].cancel(),
            self._coordination_activity.pop(txn_id, None)))

    def note_coordination_progress(self, txn_id: TxnId) -> None:
        """Record observable progress on a watched coordination so its
        inactivity watchdog re-arms instead of firing (see
        _arm_coordination_watchdog).  Called on every reply delivered to a
        send carrying a coordinating txn's id."""
        if txn_id in self._coordination_activity:
            self._coordination_activity[txn_id] = self.now_us() / 1e6

    def with_epoch(self, epoch: int, fn: Callable[[], None]) -> None:
        """Run fn once `epoch` is locally known (Node.withEpoch)."""
        if self.topology.has_epoch(epoch):
            fn()
            return
        self.topology.await_epoch(epoch).add_callback(
            lambda v, f: fn() if f is None else self.agent
            .on_uncaught_exception(f))
        self._ensure_epoch_fetch(epoch)

    def _ensure_epoch_fetch(self, epoch: int) -> None:
        """ONE 1 Hz refetch chain per pending epoch, shared by every waiter
        (with_epoch and receive()'s gate alike): a transient topology-fetch
        failure must not wedge waiters, so the (deduplicated) fetch re-arms
        until the epoch lands; gossip resolving the pending future first
        stops the chain."""
        if epoch in self._epoch_refetch or self.topology.has_epoch(epoch):
            return
        self._epoch_refetch.add(epoch)
        pending = self.topology.await_epoch(epoch)

        def tick():
            if pending.is_done:
                self._epoch_refetch.discard(epoch)
                return
            self.topology.await_epoch(epoch)       # re-triggers the hook
            self.scheduler.once(1.0, tick)

        self.scheduler.once(1.0, tick)

    # ------------------------------------------------------------ messaging --
    def send(self, to_nodes, request: Request,
             callback: Optional[Callback] = None,
             timeout_s: Optional[float] = None) -> None:
        """Send to one or many nodes, optionally registering a reply callback
        with timeout (Node.send helpers :431-533)."""
        if isinstance(to_nodes, int):
            to_nodes = [to_nodes]
        watched = getattr(request, "txn_id", None)
        if watched is not None and getattr(request, "trace_id", None) is None:
            # stamp the trace id once: the structural wire codec round-trips
            # instance attributes, so every replica can stitch this request
            # into the transaction's span (obs/spans.py)
            try:
                request.trace_id = _trace_key(watched)
            except AttributeError:
                pass  # slotted request without __dict__: not traceable
        if watched is not None and watched not in self._coordination_activity:
            watched = None
        mt = request.type
        verb = mt.label if mt is not None else type(request).__name__
        flight = self.obs.flight
        tid = getattr(request, "trace_id", None)
        for to in to_nodes:
            flight.record("tx", tid, (to, verb))
            if callback is not None:
                safe = _SafeCallback(self, to, callback, txn_id=watched)
                safe.arm_timeout(timeout_s if timeout_s is not None
                                 else self.agent.pre_accept_timeout()
                                 * self.config.rpc_timeout_multiplier)
                self.sink.send_with_callback(to, request, safe)
            else:
                self.sink.send(to, request)

    def send_to_route(self, route, min_epoch: int, max_epoch: int, make_msg,
                      callback=None):
        """Fan a message out to every node owning part of `route` across the
        epoch window, with per-destination scope slicing; returns the
        Topologies used (for tracker construction). `make_msg(to, scope)`
        builds each message; None skips that destination."""
        from accord_tpu.messages.base import TxnRequest
        topologies = self.topology.with_unsynced_epochs(
            route.participants(), min_epoch, max_epoch)
        for to in topologies.nodes():
            scope = TxnRequest.compute_scope(to, topologies, route)
            if scope is None:
                continue
            msg = make_msg(to, scope)
            if msg is not None:
                self.send(to, msg, callback=callback)
        return topologies

    def reply(self, to: int, reply_context, reply: Reply) -> None:
        mt = reply.type
        self.obs.flight.record(
            "reply", None,
            (to, mt.label if mt is not None else type(reply).__name__))
        prof = self.obs.cpuprof
        if prof.active:
            # inside a sampled dispatch (obs/cpuprof.py): the sink's encode
            # + egress work is the "reply_encode" stage of the waterfall.
            # (Binary-tier TCP packs at flush time, outside the dispatch —
            # that cost shows in the loop tick gauge instead.)
            t = prof.stage_begin()
            self.sink.reply(to, reply_context, reply)
            prof.stage_end(t, "reply_encode")
            return
        self.sink.reply(to, reply_context, reply)

    def receive(self, request: Request, from_id: int, reply_context) -> None:
        """Inbound dispatch with epoch gating (Node.receive :715-736)."""
        wait_for = request.wait_for_epoch
        if wait_for and not self.topology.has_epoch(wait_for):
            self.topology.await_epoch(wait_for).add_callback(
                lambda v, f: self._process(request, from_id, reply_context))
            self._ensure_epoch_fetch(wait_for)
            return
        self._process(request, from_id, reply_context)

    def _process(self, request: Request, from_id: int, reply_context) -> None:
        # HLC merge on receipt: every timestamp this node witnesses must be
        # absorbed so its next mint sorts after it.  Witnessing used to
        # absorb incidentally (propose_execute_at's unique_now_at_least),
        # but the Infer ladder's fence refusal declines to witness at all —
        # without the explicit merge a refused replica's clock could trail
        # journaled remote timestamps, and a crash between the refusal and
        # the next local mint would rely solely on the replay HLC fold for
        # the never-reissue-a-used-TxnId guarantee (tests/test_wal.py pins
        # the live half of it).
        req_txn_id = getattr(request, "txn_id", None)
        if req_txn_id is not None:
            self.on_remote_timestamp(req_txn_id)
        req_execute_at = getattr(request, "execute_at", None)
        if req_execute_at is not None:
            self.on_remote_timestamp(req_execute_at)
        tid = getattr(request, "trace_id", None)
        mt = request.type
        verb = mt.label if mt is not None else type(request).__name__
        self.obs.flight.record("rx", tid, (from_id, verb))
        if tid is not None:
            # stitch this replica into the transaction's cross-node span
            self.obs.rx(tid, verb, from_id)
        if self.journal is not None and request.type is not None \
                and request.type.has_side_effects \
                and not (self.command_stores.remote
                         and isinstance(request, TxnRequest)):
            # journal-where-processed: under the shard worker runtime a
            # TxnRequest's side effects land in a WORKER's stores, and the
            # worker appends it to its own WAL band before executing — the
            # parent journaling it too would double-replay on restart
            self.journal.record(self.id, request)
        # protocol-CPU attribution (obs/cpuprof.py, ACCORD_CPU_PROFILE=N):
        # bracket the dispatch so its wall time decomposes into the
        # decode/apply/cfk/reply-encode waterfall, labeled by verb.  With
        # profiling off this is ONE attribute check (obs-budget-gated).
        prof = self.obs.cpuprof
        sampled = prof.enabled and prof.dispatch_begin(verb)
        try:
            request.process(self, from_id, reply_context)
        except BaseException as e:  # noqa: BLE001
            if reply_context is not None:
                self.reply(from_id, reply_context, FailureReply(e))
            else:
                self.agent.on_uncaught_exception(e)
        finally:
            if sampled:
                prof.dispatch_end()

    def local_request(self, request: Request) -> None:
        """Apply a local-only request (PROPAGATE_*) to our own stores."""
        if self.journal is not None and request.type is not None \
                and request.type.has_side_effects \
                and not (self.command_stores.remote
                         and isinstance(request, TxnRequest)):
            self.journal.record(self.id, request)
        request.process(self, self.id, None)

    # ------------------------------------------------- store fan-out/reduce --
    def map_reduce_consume_local(self, request: TxnRequest, from_id: int,
                                 reply_context) -> None:
        """Fan a TxnRequest out over intersecting command stores, reduce the
        replies (async-aware), reply to the sender
        (Node.mapReduceConsumeLocal :405 -> CommandStores.mapReduceConsume).
        The fan-out itself lives on CommandStores so the worker runtime
        (shard/) can route it across per-shard processes unchanged."""

        def consume(value, failure):
            if reply_context is None:
                if failure is not None and not isinstance(failure, EmptyFanout):
                    self.agent.on_uncaught_exception(failure)
                return
            if failure is not None:
                self.reply(from_id, reply_context, FailureReply(failure))
                return
            self.reply(from_id, reply_context, value)

        self.command_stores.map_reduce_request(request, consume)


class _NullProgressLog(ProgressLog):
    pass
