"""Cleanup: the GC decision ladder and the store truncation sweep.

Reference: accord/local/Cleanup.java:37-44 — NO / TRUNCATE_WITH_OUTCOME /
TRUNCATE / ERASE computed from durability class + redundancy; applied by
Commands.purge (Commands.java:879-967). A command may only be truncated once
its outcome is durable at a majority of every participating shard (it can
then be reconstructed from peers), and only erased once universally durable
(no peer will ever ask for it again).
"""

from __future__ import annotations

import enum
from typing import List

from accord_tpu.local.status import SaveStatus
from accord_tpu.primitives.keys import Keys, Ranges
from accord_tpu.primitives.timestamp import TxnId


class Cleanup(enum.Enum):
    NO = "NO"
    # metadata (deps/txn/waiting) dropped, outcome (writes/result) kept: a
    # lagging replica of this or another shard can still fetch the outcome
    TRUNCATE_WITH_OUTCOME = "TRUNCATE_WITH_OUTCOME"
    ERASE = "ERASE"


def should_cleanup(store, cmd) -> Cleanup:
    """GC decision for one command (Cleanup.shouldCleanup)."""
    if cmd.is_truncated:
        return Cleanup.NO
    if cmd.is_invalidated:
        # invalidated txns are safe to erase once universally durable bounds
        # pass them (nobody can resurrect a lower ballot)
        participants = _participants(store, cmd)
        if participants is not None and _fully(
                store.durable_before.is_universally_durable, cmd.txn_id,
                participants):
            return Cleanup.ERASE
        return Cleanup.NO
    if not cmd.has_been(SaveStatus.APPLIED):
        return Cleanup.NO
    participants = _participants(store, cmd)
    if participants is None:
        return Cleanup.NO
    if _fully(store.durable_before.is_universally_durable, cmd.txn_id,
              participants):
        # every replica of this shard applied it; peers of other shards ask
        # their own shard for the outcome — nothing can need ours again
        return Cleanup.ERASE
    if _fully(store.durable_before.is_majority_durable, cmd.txn_id,
              participants):
        return Cleanup.TRUNCATE_WITH_OUTCOME
    return Cleanup.NO


def _participants(store, cmd):
    """Local slice of the command's participants: the durable bounds in this
    store's map only ever cover its own ranges."""
    parts = None
    if cmd.partial_txn is not None:
        parts = cmd.partial_txn.keys
    elif cmd.route is not None:
        parts = cmd.route.participants()
    if parts is None or store.ranges.is_empty:
        return parts
    sliced = parts.slice(store.ranges)
    if isinstance(sliced, Ranges):
        return sliced if not sliced.is_empty else None
    return sliced if len(sliced) > 0 else None


def _fully(pred, txn_id: TxnId, participants) -> bool:
    if isinstance(participants, Ranges):
        if participants.is_empty:
            return False
        # probe both edges of every range (bounds are range-mapped)
        from accord_tpu.primitives.keys import RoutingKey
        return all(pred(txn_id, RoutingKey(r.start))
                   and pred(txn_id, RoutingKey(r.end - 1))
                   for r in participants)
    if len(participants) == 0:
        return False
    return all(pred(txn_id, k) for k in participants)


def sweep(store) -> int:
    """Truncate/erase everything the durable bounds allow; prune the per-key
    conflict indexes below the majority bound. Returns commands purged
    (the restoreInvalidated/purge sweep driven by SetShardDurable /
    SetGloballyDurable in the reference)."""
    from accord_tpu.local import commands as C
    from accord_tpu.local.store import SafeCommandStore, PreLoadContext

    safe = SafeCommandStore(store, PreLoadContext.empty())
    purged = 0
    for txn_id in list(store.commands):
        cmd = store.commands[txn_id]
        decision = should_cleanup(store, cmd)
        if decision == Cleanup.NO:
            continue
        C.purge(safe, txn_id, erase=decision == Cleanup.ERASE,
                keep_outcome=decision == Cleanup.TRUNCATE_WITH_OUTCOME)
        purged += 1
        if txn_id in store.range_commands:
            del store.range_commands[txn_id]
    # prune conflict indexes below each key's majority bound: everything
    # below it is decided and reconstructible from a majority elsewhere
    for key, cfk in store.cfks.items():
        bound = store.durable_before.majority_before(key)
        if bound.hlc > 0:
            cfk.prune_redundant(bound)
    return purged
