"""Cleanup: the GC decision ladder and the store truncation sweep.

Reference: accord/local/Cleanup.java:37-44 — NO / TRUNCATE_WITH_OUTCOME /
TRUNCATE / ERASE computed from durability class + redundancy; applied by
Commands.purge (Commands.java:879-967). A command may only be truncated once
its outcome is durable at a majority of every participating shard (it can
then be reconstructed from peers), and only erased once universally durable
(no peer will ever ask for it again).
"""

from __future__ import annotations

import enum
from typing import List

from accord_tpu.local.status import SaveStatus
from accord_tpu.primitives.keys import Keys, Ranges
from accord_tpu.primitives.timestamp import TxnId


class Cleanup(enum.Enum):
    NO = "NO"
    # metadata (deps/txn/waiting) dropped, outcome (writes/result) kept: a
    # lagging replica of this or another shard can still fetch the outcome
    TRUNCATE_WITH_OUTCOME = "TRUNCATE_WITH_OUTCOME"
    ERASE = "ERASE"
    # safe-to-clean inference (coordinate/infer.py, reference
    # Infer.safeToCleanup): an UNDECIDED local straggler below the
    # universal durable bound is provably invalidated — had it been
    # decided, it would have applied at EVERY replica including this one —
    # so the sweep commits the invalidation locally and erases in one step
    # instead of leaving it truncated-but-witnessable
    INVALIDATE_THEN_ERASE = "INVALIDATE_THEN_ERASE"


def should_cleanup(store, cmd) -> Cleanup:
    """GC decision for one command (Cleanup.shouldCleanup)."""
    from accord_tpu.coordinate.infer import full_infer_enabled
    if cmd.is_truncated:
        return Cleanup.NO
    if cmd.is_invalidated:
        participants = _participants(store, cmd)
        if participants is None:
            return Cleanup.NO
        # invalidated txns are safe to erase once universally durable
        # bounds pass them (nobody can resurrect a lower ballot); the full
        # Infer ladder erases already at the MAJORITY bound — resurrection
        # would need a fresh witness quorum, which the fence-refusal rule
        # (local/commands.is_durably_fenced) denies below that bound
        if _fully(store, "universal", cmd.txn_id, participants):
            return Cleanup.ERASE
        if full_infer_enabled() and _fully(store, "majority", cmd.txn_id,
                                           participants):
            return Cleanup.ERASE
        return Cleanup.NO
    if not cmd.has_been(SaveStatus.APPLIED):
        if not full_infer_enabled() or cmd.save_status.is_decided:
            return Cleanup.NO
        participants = _participants(store, cmd)
        if participants is None:
            return Cleanup.NO
        if _fully(store, "universal", cmd.txn_id, participants) \
                and _post_bootstrap(store, cmd.txn_id, participants):
            # undecided below the universal bound (and the range is not a
            # gap in OUR history — post-bootstrap, not stale): every
            # replica applied everything decided beneath the bound, we
            # did not apply this, hence it was invalidated
            return Cleanup.INVALIDATE_THEN_ERASE
        return Cleanup.NO
    participants = _participants(store, cmd)
    if participants is None:
        return Cleanup.NO
    if _fully(store, "universal", cmd.txn_id, participants):
        # every replica of this shard applied it; peers of other shards ask
        # their own shard for the outcome — nothing can need ours again
        return Cleanup.ERASE
    if _fully(store, "majority", cmd.txn_id, participants):
        return Cleanup.TRUNCATE_WITH_OUTCOME
    return Cleanup.NO


def _post_bootstrap(store, txn_id: TxnId, participants) -> bool:
    """The local-inference gate: a pre-bootstrap or stale span is a hole in
    OUR apply history, not evidence the txn never applied anywhere."""
    from accord_tpu.local.watermarks import PreBootstrapOrStale
    return store.redundant_before.pre_bootstrap_or_stale(
        txn_id, participants) == PreBootstrapOrStale.POST_BOOTSTRAP


def _participants(store, cmd):
    """Local slice of the command's participants: the durable bounds in this
    store's map only ever cover its own ranges."""
    parts = None
    if cmd.partial_txn is not None:
        parts = cmd.partial_txn.keys
    elif cmd.route is not None:
        parts = cmd.route.participants()
    if parts is None or store.ranges.is_empty:
        return parts
    sliced = parts.slice(store.ranges)
    if isinstance(sliced, Ranges):
        return sliced if not sliced.is_empty else None
    return sliced if len(sliced) > 0 else None


def _fully(store, which: str, txn_id: TxnId, participants) -> bool:
    """Is txn_id durable at `which` tier across ALL of `participants`?

    For Ranges this folds the piecewise DurableBefore map over every span
    intersecting each range (DurableBefore.min: uncovered spans floor the
    bound to NONE), so an interior span with a lower/no durable bound blocks
    cleanup — endpoint probing missed those (ADVICE r1, high)."""
    db = store.durable_before
    if isinstance(participants, Ranges):
        if participants.is_empty:
            return False
        majority, universal = db.min_bounds(participants)
        bound = universal if which == "universal" else majority
        return txn_id < bound
    if len(participants) == 0:
        return False
    pred = (db.is_universally_durable if which == "universal"
            else db.is_majority_durable)
    return all(pred(txn_id, k) for k in participants)


def sweep(store) -> int:
    """Truncate/erase everything the durable bounds allow; prune the per-key
    conflict indexes below the majority bound. Returns commands purged
    (the restoreInvalidated/purge sweep driven by SetShardDurable /
    SetGloballyDurable in the reference)."""
    from accord_tpu.local import commands as C
    from accord_tpu.local.store import SafeCommandStore, PreLoadContext

    safe = SafeCommandStore(store, PreLoadContext.empty())
    purged = 0
    for txn_id in list(store.commands):
        cmd = store.commands[txn_id]
        decision = should_cleanup(store, cmd)
        if decision == Cleanup.NO:
            continue
        if decision == Cleanup.INVALIDATE_THEN_ERASE:
            # safe-to-clean inference: settle the straggler as INVALIDATED
            # first (terminal, listeners notified, progress log cleared),
            # then erase — purge alone would stamp TRUNCATED_APPLY, whose
            # Known projection falsely claims an applied outcome
            obs = getattr(store.node, "obs", None)
            if obs is not None:
                obs.flight.record("infer_invalidate", repr(txn_id),
                                  ("safe_to_clean", cmd.save_status.name))
            store.node.infer_stats["safe_to_clean"] += 1
            C.commit_invalidate(safe, txn_id)
            decision = Cleanup.ERASE  # falls through to the common purge
        C.purge(safe, txn_id, erase=decision == Cleanup.ERASE,
                keep_outcome=decision == Cleanup.TRUNCATE_WITH_OUTCOME)
        purged += 1
        # the range-conflict index entry may only be dropped once the shard
        # fence guarantees no lower-id straggler can newly commit and rely on
        # witnessing this txn (universal tier installs the fence); at the
        # majority tier the command truncates but stays witnessable
        if decision == Cleanup.ERASE and txn_id in store.range_commands:
            store.range_version += 1
            del store.range_commands[txn_id]
    # prune conflict indexes below each key's shard-applied fence: the fence
    # ESP witnessed everything below it on every replica AND preaccept refuses
    # lower-id stragglers, so nothing pruned can be needed by a new deps calc.
    # (Majority durability alone is NOT enough: a low-id straggler the fence
    # never saw could still commit and miss the pruned entries — ADVICE r1.)
    for key, cfk in store.cfks.items():
        bound = store.redundant_before.shard_applied_before(key)
        if bound.hlc > 0:
            for u in cfk.prune_redundant(bound):
                u.callback(safe)
    return purged
