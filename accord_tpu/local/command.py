"""Command: one transaction's replica-local record, and WaitingOn — the
execution-order resolver state.

Reference: accord/local/Command.java (record hierarchy :681-1216, WaitingOn
:1294-1643, listeners :72-90). The reference uses immutable records swapped
via SafeCommand; our stores are single-threaded (enforced by CommandStore), so
Command is a mutable record whose every transition flows through the static
functions in accord_tpu.local.commands — the moral equivalent of the
reference's update() chain, with the same transition invariants.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set, Tuple

from accord_tpu.local.status import Durability, Known, SaveStatus
from accord_tpu.primitives.deps import Deps
from accord_tpu.primitives.keys import Route
from accord_tpu.primitives.timestamp import Ballot, Timestamp, TxnId
from accord_tpu.primitives.txn import PartialTxn
from accord_tpu.primitives.writes import Writes
from accord_tpu.utils import invariants
from accord_tpu.utils.bitset import SimpleBitSet
from accord_tpu.utils.sorted_arrays import find_ceil

# flight-recorder hook: local.store rebinds this to CommandStore.current at
# import time (command.py cannot import store.py — circular).  Transitions
# always run inside a store task, so the current store's node carries the
# ring; bare Command objects (unit tests) record nothing.
_current_store: Callable[[], Optional[object]] = lambda: None


# enum .name goes through DynamicClassAttribute per access; transitions
# record two names each, so resolve them once
_STATUS_NAME = {s: s.name for s in SaveStatus}


def note_status_transition(txn_id: TxnId, prev: SaveStatus,
                           new: SaveStatus) -> None:
    """Record a command status transition on the owning node's flight ring
    (obs/flight.py).  Shared by Command.set_status and the few direct
    save_status assignments in local.commands (supersession/truncation
    paths that legally bypass the monotonicity check)."""
    store = _current_store()
    if store is None:
        return
    flight = getattr(store, "flight", None)
    if flight is not None:
        flight.record("status", repr(txn_id),
                      (store.id, _STATUS_NAME[prev], _STATUS_NAME[new]))


class WaitingOn:
    """Bitsets over the stable deps AND the participating keys this command
    must see cleared before it can execute (Command.java:1294-1643 — the
    reference bitset spans txnIds ∪ keys).

    A dep blocks until it is (a) committed with executeAt AFTER ours — then it
    is ordered after us and removed; or (b) applied / invalidated / truncated.
    A key blocks until the CommandsForKey certifies every earlier-executing
    entry at that key applied (the per-key execution gate that holds even for
    conflicts the deps happen to omit).
    """

    __slots__ = ("txn_ids", "waiting", "applied_or_invalidated", "keys",
                 "waiting_keys")

    def __init__(self, txn_ids: Tuple[TxnId, ...], keys: Tuple = ()):
        self.txn_ids = txn_ids
        self.waiting = SimpleBitSet.full(len(txn_ids)) if txn_ids else SimpleBitSet(0)
        self.applied_or_invalidated = SimpleBitSet(len(txn_ids))
        self.keys = keys
        self.waiting_keys = (SimpleBitSet.full(len(keys)) if keys
                             else SimpleBitSet(0))

    @classmethod
    def from_deps(cls, deps: Deps, keys: Tuple = ()) -> "WaitingOn":
        return cls(tuple(deps.sorted_txn_ids()), keys)

    @property
    def is_waiting(self) -> bool:
        return not self.waiting.is_empty() \
            or not self.waiting_keys.is_empty()

    @property
    def is_waiting_on_key(self) -> bool:
        return not self.waiting_keys.is_empty()

    def key_index_of(self, key) -> int:
        try:
            return self.keys.index(key)
        except ValueError:
            return -1

    def is_waiting_on_key_at(self, key) -> bool:
        i = self.key_index_of(key)
        return i >= 0 and self.waiting_keys.get(i)

    def remove_waiting_on_key(self, key) -> bool:
        i = self.key_index_of(key)
        return i >= 0 and self.waiting_keys.unset(i)

    def waiting_key_list(self):
        return [self.keys[i] for i in self.waiting_keys]

    def index_of(self, txn_id: TxnId) -> int:
        i = find_ceil(self.txn_ids, txn_id)
        if i < len(self.txn_ids) and self.txn_ids[i] == txn_id:
            return i
        return -1

    def is_waiting_on(self, txn_id: TxnId) -> bool:
        i = self.index_of(txn_id)
        return i >= 0 and self.waiting.get(i)

    def remove_waiting_on(self, txn_id: TxnId) -> bool:
        i = self.index_of(txn_id)
        return i >= 0 and self.waiting.unset(i)

    def set_applied_or_invalidated(self, txn_id: TxnId) -> bool:
        i = self.index_of(txn_id)
        if i < 0:
            return False
        self.applied_or_invalidated.set(i)
        return self.waiting.unset(i)

    def next_waiting(self) -> Optional[TxnId]:
        """Lowest still-waiting dep (the NotifyWaitingOn walker chases this)."""
        i = self.waiting.first_set()
        return self.txn_ids[i] if i >= 0 else None

    def waiting_ids(self) -> List[TxnId]:
        return [self.txn_ids[i] for i in self.waiting]

    def __repr__(self):
        return (f"WaitingOn({self.waiting_ids()!r}"
                + (f", keys={self.waiting_key_list()!r}"
                   if self.is_waiting_on_key else "") + ")")


class TransientListener:
    """Non-durable callback registered on a command (e.g. ReadData waiting for
    ReadyToExecute). Reference Command.TransientListener (Command.java:72-90)."""

    def on_change(self, safe_store, command: "Command") -> None:
        raise NotImplementedError


class OnAppliedListener(TransientListener):
    """Fire `on_fired(command)` once the command is applied / invalidated /
    truncated — the shared termination predicate behind WaitUntilApplied,
    local barriers, and ephemeral dep waits."""

    __slots__ = ("on_fired", "fired")

    def __init__(self, on_fired):
        self.on_fired = on_fired
        self.fired = False

    @classmethod
    def arm(cls, command: "Command", on_fired) -> "OnAppliedListener":
        listener = cls(on_fired)
        command.add_transient_listener(listener)
        listener.maybe_fire(command)
        return listener

    def on_change(self, safe_store, command: "Command") -> None:
        self.maybe_fire(command)

    def maybe_fire(self, command: "Command") -> None:
        if self.fired:
            return
        if command.is_applied_or_gone or command.is_truncated:
            self.fired = True
            command.remove_transient_listener(self)
            self.on_fired(command)


class Command:
    __slots__ = (
        "txn_id", "save_status", "durability",
        "route", "partial_txn", "execute_at", "execute_at_least",
        "promised", "accepted_ballot",
        "partial_deps", "stable_deps", "waiting_on",
        "writes", "result",
        "listeners", "transient_listeners",
        "owned_keys_memo",
    )

    def __init__(self, txn_id: TxnId):
        self.txn_id = txn_id
        self.save_status = SaveStatus.NOT_DEFINED
        self.durability = Durability.NOT_DURABLE
        self.route: Optional[Route] = None
        self.partial_txn: Optional[PartialTxn] = None
        self.execute_at: Optional[Timestamp] = None
        self.execute_at_least: Optional[Timestamp] = None
        self.promised: Ballot = Ballot.ZERO
        self.accepted_ballot: Ballot = Ballot.ZERO
        self.partial_deps: Optional[Deps] = None   # proposed (Accept round)
        self.stable_deps: Optional[Deps] = None    # stable (Commit round)
        self.waiting_on: Optional[WaitingOn] = None
        self.writes: Optional[Writes] = None
        self.result = None
        self.listeners: Set[TxnId] = set()         # durable: commands waiting on us
        self.transient_listeners: List[TransientListener] = []
        # (keys, ranges, owned-slice) identity memo for owned_keys_of: the
        # slice is recomputed per CFK registration (every transition), but
        # partial_txn.keys and the store's Ranges are both immutable objects
        # replaced wholesale on change — identity captures staleness exactly
        self.owned_keys_memo: Optional[Tuple] = None

    # -- status predicates --
    @property
    def status(self) -> SaveStatus:
        return self.save_status

    def has_been(self, status: SaveStatus) -> bool:
        return self.save_status >= status

    @property
    def is_defined(self) -> bool:
        return self.save_status.is_defined and self.partial_txn is not None

    @property
    def is_stable(self) -> bool:
        return self.save_status.is_at_least_stable

    @property
    def is_applied_or_gone(self) -> bool:
        return (self.save_status.is_applied_or_gone
                or self.save_status == SaveStatus.INVALIDATED)

    @property
    def is_truncated(self) -> bool:
        return self.save_status.is_truncated

    @property
    def is_invalidated(self) -> bool:
        return self.save_status == SaveStatus.INVALIDATED

    def known(self) -> Known:
        return self.save_status.known()

    def execute_at_or_txn_id(self) -> Timestamp:
        return self.execute_at if self.execute_at is not None else self.txn_id

    # -- ballot gates (promise protocol; Command.java preacceptedOrLater etc.) --
    def may_accept(self, ballot: Ballot) -> bool:
        return self.promised <= ballot

    def may_promise(self, ballot: Ballot) -> bool:
        return self.promised < ballot or (self.promised == ballot)

    def set_promised(self, ballot: Ballot) -> None:
        invariants.check_state(ballot >= self.promised,
                               "promise may only advance")
        self.promised = ballot

    # -- status transition (called only from local.commands) --
    def set_status(self, status: SaveStatus) -> None:
        if status < self.save_status:
            # regressions are only legal into cleanup states
            invariants.check_state(
                status.is_truncated,
                "illegal status regression %s -> %s for %s",
                self.save_status.name, status.name, self.txn_id)
        prev = self.save_status
        self.save_status = status
        if status is not prev:
            note_status_transition(self.txn_id, prev, status)

    def update_route(self, route: Optional[Route]) -> None:
        if route is None:
            return
        if self.route is None:
            self.route = route
        elif route.is_full and not self.route.is_full:
            self.route = route

    # -- listeners --
    def add_listener(self, waiter: TxnId) -> None:
        self.listeners.add(waiter)

    def remove_listener(self, waiter: TxnId) -> None:
        self.listeners.discard(waiter)

    def add_transient_listener(self, listener: TransientListener) -> None:
        self.transient_listeners.append(listener)

    def remove_transient_listener(self, listener: TransientListener) -> None:
        try:
            self.transient_listeners.remove(listener)
        except ValueError:
            pass

    def __repr__(self):
        return (f"Command({self.txn_id!r}, {self.save_status.name}, "
                f"at={self.execute_at!r})")
