"""Evictable paging tier: bounded-memory command store over the spill store.

Reference: accord's pluggable storage layer (accord/api/*, PAPER.md §1) —
the reference makes command storage an interface precisely so real hosts
page command state to disk instead of holding millions of Commands
resident.  This module is that tier for our CommandStore: quiescent
commands spill to `journal/fault_index.SpillStore` frames and fault back on
access BEHIND the existing access paths, so the protocol never observes a
missing command.

Residency policy
----------------
`ACCORD_RESIDENT_CMDS` (commands per CommandStore) and/or
`ACCORD_RESIDENT_BYTES` (estimated payload bytes per CommandStore, divided
by the running average spill-frame size) bound the resident tier; unset or
0 means unbounded — `pager_from_env` then returns None and the store keeps
a PLAIN dict, so paging off is bit-identical to the pre-paging code, not
merely equivalent.

Eviction eligibility — a command may leave memory only when nothing can
still mutate or synchronously reference it:

  * terminal save status (APPLIED / INVALIDATED / TRUNCATED_APPLY /
    ERASED) — the quiescent set the census tracks;
  * no listeners and no transient listeners;
  * no armed per-key execution gate (store.gated);
  * key-domain only (range commands stay resident: the range-conflict
    scans walk `range_commands` against live Command state).

Within the eligible set, cleanup's bounds order the victims: commands
below the shard-applied `RedundantBefore` fence or already
majority-durable evict first (cleanup would truncate them anyway), the
rest only when the budget still overflows.  Age-since-quiescence is
approximated by dict insertion order (oldest first — the census age
signal's cheap stand-in) with a second-chance set: a refaulted command
survives one sweep before it is eligible again (clock/LRU second chance).

Evictions are DEFERRED to operation boundaries: `CommandStore._submit`
calls `on_op_boundary()` only when returning to the top level (nested
submits skip it), so no live SafeCommandStore can hold a reference to a
command evicted under it.

Faults are single-frame point reads (the fault index maps TxnId to an
exact segment offset).  A fault REMOVES the spill entry: the resident copy
becomes the single source of truth and a later re-eviction re-spills the
then-current state — which is what makes refault-then-truncate ordering
safe by construction (the truncation mutates the resident copy; the stale
frame is already dead).

Cold CommandsForKey entries page too: an EMPTY cfk (fully pruned, no
pending waits) is dropped from `store.cfks`, leaving its key in the
store's sorted key index and a residual (redundant_before, version,
committed_version) here; `CommandStore._cfk` restores the residual on next
touch without re-inserting the index entry.

Audit/census contract: for every spilled command the pager retains the
audit metadata the resident husk would have reported — (entry_class,
audit scope, census class, durability, quiescent-uncleaned flag) — so
cross-replica digests, drill-downs, and `accord_census_*` see identical
state whether a command is resident or spilled, and eviction is
count-neutral for the leak detector.
"""

from __future__ import annotations

import os
import tempfile
from typing import Dict, Optional, Tuple

from accord_tpu.local.status import Durability, SaveStatus

# the quiescent terminal statuses (superset of the census's
# _QUIESCENT_UNCLEANED: truncated/erased husks are also evictable)
_EVICTABLE = frozenset((SaveStatus.APPLIED, SaveStatus.INVALIDATED,
                        SaveStatus.TRUNCATED_APPLY, SaveStatus.ERASED))

# sweep down to budget - budget/8 so sweeps amortize to O(1) per op
_HYSTERESIS_SHIFT = 3


def pager_from_env(store) -> Optional["Pager"]:
    """A Pager when a resident budget is configured, else None (the store
    then keeps its plain dict — zero indirection when paging is off)."""
    cmds = _env_int("ACCORD_RESIDENT_CMDS")
    byts = _env_int("ACCORD_RESIDENT_BYTES")
    if cmds <= 0 and byts <= 0:
        return None
    return Pager(store, max_cmds=cmds, max_bytes=byts)


def _env_int(name: str) -> int:
    try:
        return int(os.environ.get(name, "0") or "0")
    except ValueError:
        return 0


class PagedCommands(dict):
    """The store's `commands` mapping with fault-on-access.

    Iteration / len / values cover the RESIDENT tier only (cleanup sweeps,
    census, and the audit walk handle the spilled tier explicitly via the
    pager); membership and item access cover BOTH tiers, so every protocol
    path — all of which reach commands via get()/[]/in — transparently
    faults spilled state back in."""

    __slots__ = ("pager",)

    def __init__(self, pager: "Pager"):
        super().__init__()
        self.pager = pager

    def get(self, key, default=None):
        v = dict.get(self, key)
        if v is not None:
            self.pager.hits += 1
            return v
        pager = self.pager
        pager.misses += 1
        if key in pager.spilled:
            return pager.fault(key)
        return default

    def __getitem__(self, key):
        try:
            v = dict.__getitem__(self, key)
        except KeyError:
            self.pager.misses += 1
            if key in self.pager.spilled:
                return self.pager.fault(key)
            raise
        self.pager.hits += 1
        return v

    def __contains__(self, key):
        return dict.__contains__(self, key) or key in self.pager.spilled

    def pop(self, key, *default):
        # the single removal path (ephemeral reads): fault first so the
        # spill entry cannot survive its command
        if not dict.__contains__(self, key) and key in self.pager.spilled:
            self.pager.fault(key)
        return dict.pop(self, key, *default)

    def setdefault(self, key, default=None):
        v = self.get(key)
        if v is None:
            dict.__setitem__(self, key, default)
            return default
        return v


class Pager:
    """Residency policy + spill/fault machinery for ONE CommandStore."""

    def __init__(self, store, max_cmds: int = 0, max_bytes: int = 0):
        self.store = store
        self.max_cmds = max_cmds
        self.max_bytes = max_bytes
        self.commands = PagedCommands(self)
        # TxnId -> (seg, off) mirror of the SpillStore index; also the
        # "is spilled" membership test before the store is even created
        self.spilled: Dict = {}
        # TxnId -> (entry_class, audit_scope, census_class, durability
        #           name, quiescent_uncleaned) captured at spill time —
        # byte-for-byte what the resident husk would report to the audit
        # walk and the census
        self.meta: Dict = {}
        # evicted-empty CFK residuals: Key -> (redundant_before, version,
        # committed_version)
        self.cfk_residuals: Dict = {}
        # second-chance set: faulted since the last sweep
        self.referenced: set = set()
        # incrementally maintained census aggregates (a sweep must stay
        # O(stores), not O(spilled))
        self.spilled_by_class: Dict[str, int] = {}
        self.spilled_uncleaned = 0
        # counters (exported by the census as accord_pager_* gauges)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.refaults = 0
        self.cfk_evictions = 0
        self.cfk_restores = 0
        self.resident_high_water = 0
        self._spill_store = None
        self._spill_dir: Optional[str] = None

    # ----------------------------------------------------------- budget --
    def budget(self) -> int:
        """Effective resident-command budget: the command cap and/or the
        byte cap divided by the running average spill-frame size."""
        b = self.max_cmds if self.max_cmds > 0 else 0
        if self.max_bytes > 0:
            avg = 512
            s = self._spill_store
            if s is not None and s.frames_written:
                avg = max(64, s.disk_bytes // s.frames_written)
            by = max(1, self.max_bytes // avg)
            b = by if b <= 0 else min(b, by)
        return b

    def spill_store(self):
        """Lazily created on first eviction: under the node WAL's directory
        when one exists (journal-backed), else a scratch tempdir.  Always a
        fresh per-incarnation store — WAL replay re-derives residency."""
        if self._spill_store is None:
            from accord_tpu.journal.fault_index import SpillStore
            journal = getattr(self.store.node, "journal", None)
            base = getattr(journal, "directory", None)
            if base is None:
                base = tempfile.mkdtemp(prefix="accord-spill-")
            directory = os.path.join(base, f"spill-{self.store.id}")
            self._spill_dir = directory
            self._spill_store = SpillStore(directory, fresh=True,
                                           flight=self.store._flight)
        return self._spill_store

    # ------------------------------------------------------------ fault --
    def fault(self, txn_id):
        """Bring one spilled command back resident (single-frame read).
        The frame goes dead; the resident copy is now the only truth."""
        cmd = self._spill_store.fault(txn_id)
        del self.spilled[txn_id]
        del self.meta[txn_id]
        self._note_unspilled(cmd.save_status)
        dict.__setitem__(self.commands, txn_id, cmd)
        self.refaults += 1
        self.referenced.add(txn_id)
        n = len(self.commands)
        if n > self.resident_high_water:
            self.resident_high_water = n
        flight = self.store._flight
        if flight is not None:
            flight.record("cmd_fault", str(txn_id),
                          (self.store.id, cmd.save_status.name))
        return cmd

    # --------------------------------------------------------- eviction --
    def on_op_boundary(self) -> None:
        """Called by CommandStore._submit when returning to the top level
        (after outcome delivery): the only point evictions run."""
        n = len(self.commands)
        if n > self.resident_high_water:
            self.resident_high_water = n
        budget = self.budget()
        if budget <= 0:
            return
        if n > budget:
            low = max(1, budget - (budget >> _HYSTERESIS_SHIFT))
            self._sweep(n - low)
        # the CFK shell count gets the same budget but its own trigger: a
        # quiesced store (commands under budget) must still shed the
        # million cold per-key shells cleanup just emptied
        if len(self.store.cfks) > budget:
            self._sweep_cfks(budget)

    def _sweep(self, want: int) -> None:
        store = self.store
        gated = store.gated
        range_cmds = store.range_commands
        fence = None
        if not store.ranges.is_empty:
            fence = store.redundant_before.min_shard_applied_before(
                store.ranges)
        bounded = []   # below cleanup fence / majority-durable: evict first
        rest = []
        referenced = self.referenced
        for txn_id, cmd in list(self.commands.items()):
            if cmd.save_status not in _EVICTABLE:
                continue
            if cmd.listeners or cmd.transient_listeners:
                continue
            if txn_id in gated or txn_id in range_cmds \
                    or txn_id.is_range_domain:
                continue
            if txn_id in referenced:
                referenced.discard(txn_id)  # second chance: survive once
                continue
            if (fence is not None and txn_id < fence) \
                    or cmd.durability >= Durability.MAJORITY:
                bounded.append((txn_id, cmd))
            else:
                rest.append((txn_id, cmd))
        evicted = 0
        for txn_id, cmd in bounded:
            if evicted >= want:
                break
            self._evict(txn_id, cmd)
            evicted += 1
        for txn_id, cmd in rest:
            if evicted >= want:
                break
            self._evict(txn_id, cmd)
            evicted += 1

    def _evict(self, txn_id, cmd) -> None:
        from accord_tpu.local.audit import (_QUIESCENT_UNCLEANED,
                                            _STATUS_CLASS, _audit_scope,
                                            entry_class)
        st = cmd.save_status
        cls = _STATUS_CLASS.get(st, "other")
        uncleaned = st in _QUIESCENT_UNCLEANED
        self.meta[txn_id] = (entry_class(cmd), _audit_scope(cmd), cls,
                             cmd.durability.name, uncleaned)
        self.spilled[txn_id] = self.spill_store().spill(cmd)
        self.spilled_by_class[cls] = self.spilled_by_class.get(cls, 0) + 1
        if uncleaned:
            self.spilled_uncleaned += 1
        dict.__delitem__(self.commands, txn_id)
        self.evictions += 1
        flight = self.store._flight
        if flight is not None:
            flight.record("cmd_evict", str(txn_id),
                          (self.store.id, st.name))

    def _note_unspilled(self, save_status) -> None:
        from accord_tpu.local.audit import (_QUIESCENT_UNCLEANED,
                                            _STATUS_CLASS)
        cls = _STATUS_CLASS.get(save_status, "other")
        n = self.spilled_by_class.get(cls, 0) - 1
        if n > 0:
            self.spilled_by_class[cls] = n
        else:
            self.spilled_by_class.pop(cls, None)
        if save_status in _QUIESCENT_UNCLEANED:
            self.spilled_uncleaned -= 1

    # ------------------------------------------------------------- CFKs --
    def _sweep_cfks(self, budget: int) -> None:
        """Page out EMPTY CommandsForKey shells (fully pruned, no pending
        waits) once their count exceeds the same budget: the object is
        dropped, its key stays in the store's sorted index, and a tiny
        residual preserves the pruning watermarks for restoration."""
        store = self.store
        cfks = store.cfks
        n = len(cfks)
        if n <= budget:
            return
        low = max(1, budget - (budget >> _HYSTERESIS_SHIFT))
        want = n - low
        victims = []
        for key, cfk in cfks.items():
            if len(victims) >= want:
                break
            if cfk.size() != 0 or cfk._wait_heap:
                continue
            if cfk._block_heap and cfk._min_block_point() is not None:
                # a LIVE block point pins the shell; _min_block_point also
                # lazily drains heap debris left by prune_redundant, so a
                # fully-pruned shell comes back None with an empty heap
                continue
            victims.append((key, cfk))
        for key, cfk in victims:
            self.cfk_residuals[key] = (cfk.redundant_before, cfk.version,
                                       cfk.committed_version)
            del cfks[key]
            self.cfk_evictions += 1

    def restore_cfk(self, key, cfk) -> bool:
        """Re-arm a freshly created CFK from an eviction residual; True
        when `key` was evicted (its sorted-index entry already exists, so
        `_cfk` must NOT insert it again)."""
        residual = self.cfk_residuals.pop(key, None)
        if residual is None:
            return False
        cfk.redundant_before, cfk.version, cfk.committed_version = residual
        self.cfk_restores += 1
        return True

    # ------------------------------------------------------------ stats --
    def stats(self) -> Dict[str, int]:
        s = self._spill_store
        return {
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "refaults": self.refaults,
            "resident": len(self.commands),
            "resident_high_water": self.resident_high_water,
            "spilled": len(self.spilled),
            "cfk_evictions": self.cfk_evictions,
            "cfk_restores": self.cfk_restores,
            "spill_disk_bytes": s.disk_bytes if s is not None else 0,
            "spill_compactions": s.compactions if s is not None else 0,
        }

    def close(self) -> None:
        if self._spill_store is not None:
            self._spill_store.close(final_checkpoint=False)


def node_paging_stats(node) -> Optional[Dict[str, int]]:
    """Summed pager stats across a node's command stores, or None when
    paging is off (no store has a pager)."""
    total: Optional[Dict[str, int]] = None
    for store in node.command_stores.all():
        pager = getattr(store, "pager", None)
        if pager is None:
            continue
        s = pager.stats()
        if total is None:
            total = dict(s)
        else:
            for k, v in s.items():
                total[k] += v
    return total
