"""Snapshot compaction: fold retired segments into one compact file.

Compaction rewrites the journal's tail-heavy history into its minimal
equivalent: all records of the snapshot plus every CLOSED segment are folded
per transaction — duplicates (retransmissions, per-store redeliveries)
collapse to one copy, and messages wholly subsumed by a maximal Apply are
dropped — then written to a fresh snapshot file (tmp + fsync + atomic
rename) and the covered segments deleted.

The fold is ORDER-INSENSITIVE and is verified against the validator's own
reconstruction fold (sim/journal.reconstruct): a transaction's folded
message set must yield bit-identical reconstructed knowledge (definition
keys, executeAts, accept evidence, stable dep ids, outcome, invalidation)
or the fold for that transaction reverts to the unfolded set.  Compaction
can therefore never weaken what a crash-restart replay can rebuild.

Replay order within a transaction follows protocol bands (PreAccept <
Accept < Commit < Apply < Propagate), so a restart replays each txn's
messages in the order its handlers expect regardless of arrival order.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Tuple

from accord_tpu.journal.segment import (frame, fsync_dir, list_segments,
                                        read_segment, scan_segment)

_META_KEY = "__accord_snapshot__"


def _band(msg) -> int:
    """Protocol band of a journaled message (replay + fold ordering)."""
    from accord_tpu.messages.accept import Accept, AcceptInvalidate
    from accord_tpu.messages.apply_msg import Apply
    from accord_tpu.messages.commit import Commit, CommitInvalidate
    from accord_tpu.messages.invalidate_msg import BeginInvalidation
    from accord_tpu.messages.preaccept import PreAccept
    from accord_tpu.messages.propagate import Propagate
    from accord_tpu.messages.recover import BeginRecovery

    # admin-plane records (messages/admin.py) pin their own band: epoch
    # installs / bootstrap checkpoints must replay BEFORE protocol messages
    # gated on the epochs and watermarks they establish
    band = getattr(msg, "replay_band", None)
    if band is not None:
        return band
    if isinstance(msg, PreAccept):
        return 0
    if isinstance(msg, (Accept, AcceptInvalidate, BeginInvalidation,
                        BeginRecovery)):
        return 1
    if isinstance(msg, Commit):
        return 2 if not msg.kind.is_stable else 3
    if isinstance(msg, CommitInvalidate):
        return 3
    if isinstance(msg, Apply):
        return 4
    if isinstance(msg, Propagate):
        return 5
    return 6


def canonical_encoding(msg) -> str:
    """Order-normalized wire encoding: the dedupe identity (and the
    round-trip test's comparison key).  Unordered containers ($s sets, $d
    dict pairs) are sorted by their JSON dump so two structurally equal
    messages canonicalize identically."""
    from accord_tpu.host.wire import encode_message
    return json.dumps(_canon(encode_message(msg)), sort_keys=True)


def _canon(data):
    if isinstance(data, list):
        return [_canon(x) for x in data]
    if isinstance(data, dict):
        if len(data) == 1 and "$s" in data:
            items = [_canon(x) for x in data["$s"]]
            return {"$s": sorted(items, key=lambda x: json.dumps(
                x, sort_keys=True))}
        if len(data) == 1 and "$d" in data:
            pairs = [[_canon(k), _canon(v)] for k, v in data["$d"]]
            return {"$d": sorted(pairs, key=lambda kv: json.dumps(
                kv[0], sort_keys=True))}
        return {k: _canon(v) for k, v in data.items()}
    return data


def _recon_key(r) -> tuple:
    """Comparable digest of one txn's reconstructed knowledge
    (sim/journal.Reconstruction): what the fold must preserve exactly."""
    return (r.witnessed, frozenset(r.definition_keys),
            frozenset(r.execute_ats), r.accept_evidence,
            frozenset(r.stable_dep_ids), frozenset(r.write_keys),
            r.has_outcome, r.invalidated)


def fold_messages(msgs: List[object], verify: bool = True) -> List[object]:
    """Order-insensitive compaction fold over one node's journal records.

    Groups by txn, dedupes by canonical encoding, then attempts the
    aggressive drop (messages subsumed by a maximal Apply) guarded by
    reconstruction equality when `verify` is set."""
    from accord_tpu.sim.journal import reconstruct

    by_txn: Dict[object, List[Tuple[int, str, object]]] = {}
    no_txn: List[object] = []
    for m in msgs:
        txn_id = getattr(m, "txn_id", None)
        if txn_id is None:
            no_txn.append(m)
            continue
        by_txn.setdefault(txn_id, []).append(
            (_band(m), canonical_encoding(m), m))
    out: List[object] = list(no_txn)
    for txn_id in sorted(by_txn, key=repr):
        entries = sorted(by_txn[txn_id], key=lambda e: (e[0], e[1]))
        deduped, seen = [], set()
        for band, canon, m in entries:
            if canon not in seen:
                seen.add(canon)
                deduped.append((band, m))
        candidate = _drop_subsumed(deduped)
        if len(candidate) < len(deduped) and verify:
            want = reconstruct([m for _b, m in deduped]).get(txn_id)
            got = reconstruct([m for _b, m in candidate]).get(txn_id)
            if want is None or got is None \
                    or _recon_key(want) != _recon_key(got):
                candidate = deduped  # the drop would lose knowledge
        out.extend(m for _b, m in candidate)
    return out


def _drop_subsumed(entries: List[Tuple[int, object]]
                   ) -> List[Tuple[int, object]]:
    """Drop pre-decision rounds once a MAXIMAL Apply (definition + deps +
    writes) is journaled for the txn: replaying the Apply alone rebuilds at
    least as much knowledge.  Callers verify with the reconstruction fold
    and revert on any mismatch, so this only needs to be usually-right."""
    from accord_tpu.messages.apply_msg import Apply

    maximal = [m for _b, m in entries
               if isinstance(m, Apply) and m.partial_txn is not None
               and m.deps is not None and m.writes is not None]
    if not maximal:
        return entries
    return [(b, m) for b, m in entries if b >= 3 or isinstance(m, Apply)]


# ------------------------------------------------------------- file format --

def write_snapshot(path: str, covers: int, msgs: List[object],
                   fsync: bool = True) -> None:
    """Atomically (tmp + rename) write a snapshot covering segment indexes
    <= `covers`.  First frame is the meta record; the rest are ordinary
    wire-encoded records."""
    from accord_tpu.journal.wal import encode_record
    meta = json.dumps({_META_KEY: 1, "covers": covers,
                       "count": len(msgs)}).encode()
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(frame(meta))
        for m in msgs:
            f.write(frame(encode_record(m)))
        f.flush()
        if fsync:
            os.fsync(f.fileno())
    os.replace(tmp, path)
    if fsync:
        fsync_dir(os.path.dirname(path) or ".")


def read_snapshot(path: str) -> Tuple[int, List[object]]:
    """(covers, messages) of a snapshot file.  The rename is atomic, so a
    snapshot is either whole or absent; a torn one (should not happen) is
    read up to the tear."""
    from accord_tpu.journal.wal import decode_record
    payloads, _good, _torn = scan_segment(path)
    if not payloads:
        return -1, []
    meta = json.loads(payloads[0].decode())
    assert meta.get(_META_KEY), f"not a snapshot file: {path}"
    return meta["covers"], [decode_record(p) for p in payloads[1:]]


class CompactionStats:
    __slots__ = ("records_in", "records_out", "segments_retired")

    def __init__(self, records_in: int, records_out: int,
                 segments_retired: int):
        self.records_in = records_in
        self.records_out = records_out
        self.segments_retired = segments_retired

    def __repr__(self):
        return (f"CompactionStats(in={self.records_in} "
                f"out={self.records_out} "
                f"segments_retired={self.segments_retired})")


def compact(directory: str, upto_index: int, verify: bool = True,
            fsync: bool = True) -> CompactionStats:
    """Fold the existing snapshot plus every segment with index <=
    `upto_index` into a fresh snapshot, then delete the covered segments.
    Crash-safe: snapshot replaced before segments are unlinked — a crash
    between the two leaves duplicates, which replay (idempotent message
    redelivery) and the next compaction's dedupe both absorb."""
    from accord_tpu.journal.wal import SNAPSHOT_NAME, decode_record
    snap_path = os.path.join(directory, SNAPSHOT_NAME)
    msgs: List[object] = []
    if os.path.exists(snap_path):
        _covers, prev = read_snapshot(snap_path)
        msgs.extend(prev)
    covered = [(idx, path) for idx, path in list_segments(directory)
               if idx <= upto_index]
    for _idx, path in covered:
        for payload in read_segment(path, truncate=True):
            msgs.append(decode_record(payload))
    folded = fold_messages(msgs, verify=verify)
    write_snapshot(snap_path, upto_index, folded, fsync=fsync)
    for _idx, path in covered:
        os.unlink(path)
    if fsync:
        fsync_dir(directory)
    return CompactionStats(len(msgs), len(folded), len(covered))
