"""Crash-restart replay: rebuild a node from its on-disk journal.

The journal IS the recovery story (sim/journal.py proves every live command
reconstructible from it); replay turns that proof operational: each
surviving record is fed back through the node's ordinary message processing
(`Node.receive` with no reply context), so CommandStore state, CFK
registrations, data-store content and execution ordering are rebuilt by the
same handlers that built them the first time — no parallel rehydration code
path to drift.  Records are band-ordered first (PreAccept < Accept <
Commit < Apply < Propagate, snapshot.py's fold order), making replay
insensitive to the order segments captured them in.

Before any record is processed the node's HLC is advanced past every
timestamp in the journal: a restarted node whose wall clock regressed must
never re-issue a TxnId below one it already used (the reference persists
its HLC watermark for the same reason).

Replay runs with the journal detached — re-processing a journaled request
must not re-append it.
"""

from __future__ import annotations

import time
from typing import List, Optional


class ReplayStats:
    __slots__ = ("records", "txns", "duration_us")

    def __init__(self, records: int, txns: int, duration_us: int):
        self.records = records
        self.txns = txns
        self.duration_us = duration_us

    def __repr__(self):
        return (f"ReplayStats(records={self.records} txns={self.txns} "
                f"duration_us={self.duration_us})")


def _fold_hlc(node, records) -> None:
    """Advance the node's HLC past every journaled timestamp."""
    for msg in records:
        for ts in (getattr(msg, "txn_id", None),
                   getattr(msg, "execute_at", None)):
            if ts is not None:
                node.on_remote_timestamp(ts)
        known = getattr(msg, "known", None)
        if known is not None and getattr(known, "execute_at", None) is not None:
            node.on_remote_timestamp(known.execute_at)


def replay_node(node, records: List[object], registry=None,
                flight=None) -> ReplayStats:
    """Feed `records` through `node`'s normal message dispatch (the node
    should be freshly constructed with its topology already reported).
    Deferred work the handlers schedule (execution waiting on deps, reads)
    drains on the node's own scheduler afterwards — sim restart drains the
    virtual queue, hosts their loop thread."""
    from accord_tpu.sim.journal import reconstruct

    t0 = time.monotonic()
    if flight is not None:
        flight.record("journal_replay_begin", None, (len(records),))
    _fold_hlc(node, records)
    from accord_tpu.journal.snapshot import _band
    ordered = sorted(records, key=_band)
    prev_journal, node.journal = node.journal, None
    # replay mode: suppress live side effects of admin records — epoch
    # installs must not re-gossip, and newly-owned ranges must not start
    # live bootstraps until resume_bootstraps() reconciles them against
    # the checkpoint coverage restored further down the same journal
    node.replaying = True
    node.defer_bootstrap = True
    try:
        for req in ordered:
            node.receive(req, 0, None)
    finally:
        node.journal = prev_journal
        node.replaying = False
    txns = len(reconstruct(records))
    duration_us = int((time.monotonic() - t0) * 1e6)
    if registry is not None:
        registry.counter("accord_journal_replay_records_total") \
            .inc(len(records))
        registry.histogram("accord_journal_replay_duration_us") \
            .observe(duration_us)
    if flight is not None:
        flight.record("journal_replay_end", None, (len(records), txns))
    return ReplayStats(len(records), txns, duration_us)
