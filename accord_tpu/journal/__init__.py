"""Durable write-ahead journal: the on-disk form of the durability contract.

The sim has always *validated* the journal-replay contract (sim/journal.py:
every live command reconstructible from the node's retained side-effecting
messages — reference SerializerSupport.java:60-557, burn-test
Journal.java:82-303); this package makes it *real*:

  * segment.py  — append-only segment files, length+CRC32-framed records
                  serialized with the structural wire codec (host/wire.py),
                  rotation at a size threshold, torn-tail truncation on open
  * wal.py      — the per-node journal: every `has_side_effects` request is
                  appended before it is acked, with GROUP COMMIT — a flush
                  thread coalesces concurrent appends into one fsync per
                  deadline/batch-bounded window (mirroring the ingest
                  pipeline's micro-batch windows), so durability costs one
                  fsync per window, not per txn
  * snapshot.py — periodic compaction: fold retired segments' records into
                  a snapshot file (verified lossless against sim/journal.py's
                  reconstruction fold) and delete the covered segments
  * replay.py   — on restart, load snapshot + surviving segments and replay
                  them through the node's ordinary message processing to
                  rebuild CommandStore state, then rejoin

Hosts opt in with `ACCORD_JOURNAL=<dir>` (see attach_journal_from_env);
the sim's crash-restart nemesis (`BurnRun --restart`) kills a node with
process-death semantics and restarts it from its journal directory.
"""

from __future__ import annotations

import os

from accord_tpu.journal.segment import SegmentWriter, read_segment
from accord_tpu.journal.wal import (DurableAckSink, JournalConfig,
                                    WriteAheadLog)


def journal_env_dir() -> str:
    """The ACCORD_JOURNAL base directory, or '' when journaling is off."""
    return os.environ.get("ACCORD_JOURNAL", "")


def attach_journal_from_env(node, band: str = None):
    """Host-side wiring: when ACCORD_JOURNAL=<dir> is set, open (or create)
    this node's journal under <dir>/node-<id>, replay any surviving state
    into the freshly built node, attach the WAL as `node.journal` (every
    has_side_effects request is appended by Node._process before the ack),
    and — when group commit is on — gate outbound replies on the fsync
    watermark with DurableAckSink.  Returns the WAL, or None when off.

    `band` names a sub-journal under the node's directory: the shard worker
    runtime journals where it processes, so each worker owns the WAL band
    <dir>/node-<id>/<band> and replays exactly its own shard's history on
    respawn while the parent keeps the node-plane band at the root."""
    base = journal_env_dir()
    if not base:
        return None
    path = os.path.join(base, f"node-{node.id}")
    if band:
        path = os.path.join(path, band)
    cfg = JournalConfig.from_env(path)
    wal = WriteAheadLog(path, node_id=node.id, config=cfg,
                        registry=node.obs.registry, flight=node.obs.flight,
                        retain=False)
    records = wal.load_records()
    if records:
        from accord_tpu.journal.replay import replay_node
        replay_node(node, records,
                    registry=node.obs.registry, flight=node.obs.flight)
    node.journal = wal
    if cfg.group_commit:
        node.sink = DurableAckSink(node.sink, wal)
    # end replay's defer mode: start bootstraps for whatever the journaled
    # checkpoints left uncovered (with the WAL attached, so fresh progress
    # is checkpointed too)
    node.resume_bootstraps()
    return wal
