"""Per-node write-ahead log with group commit.

Every `has_side_effects` request is appended (Node._process journals before
processing, so the append precedes the ack by construction); durability is
certified by fsync.  Two modes:

  * group commit (fsync_window_us > 0, hosts): `append` enqueues and
    returns immediately; a flush thread coalesces everything that arrives
    within a deadline/batch-bounded window — the same micro-batch
    discipline as the ingest pipeline (pipeline/ingest.py, whose default
    window this one mirrors) — into ONE segment write + ONE fsync.  Acks
    are released by DurableAckSink once the covering fsync lands, so a
    window's worth of transactions shares one fsync instead of paying one
    each.
  * synchronous (fsync_window_us == 0): `append` writes and syncs inline —
    the fsync-per-append baseline the bench lane compares against, and the
    deterministic mode the sim's crash-restart nemesis runs (no threads;
    the sim only simulates PROCESS death, so `fsync=False` there skips the
    physical disk barrier while keeping write-before-ack ordering exact).

Observability: `accord_journal_*` registry metrics (appends, bytes, fsyncs,
group-commit batch-size histogram, rotations, snapshots) and flight-ring
events (journal_append / journal_rotate / journal_snapshot) ride the node's
obs facade; burn `--metrics` and bench rows surface them via
obs/report.summarize.
"""

from __future__ import annotations

import heapq
import json
import os
import threading
import time
from typing import Dict, List, Optional

from accord_tpu.journal.segment import (SegmentWriter, fsync_dir,
                                        list_segments, read_segment,
                                        segment_name)

SNAPSHOT_NAME = "snapshot.snap"


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


class JournalConfig:
    """Knobs (env-overridable on hosts; README "Durability & crash-restart").

    fsync_window_us defaults to the ingest pipeline's micro-batch window
    (ACCORD_PIPELINE_MAX_WAIT_US default 2000): a batch admitted together is
    then typically made durable by one shared fsync."""

    def __init__(self, directory: str, segment_bytes: int = 4 << 20,
                 fsync_window_us: int = 2000, max_batch: int = 256,
                 snapshot_segments: int = 4, fsync: bool = True,
                 verify_compaction: bool = True, stall_us: int = 0,
                 stall_after: int = 0):
        self.directory = directory
        self.segment_bytes = max(4096, segment_bytes)
        self.fsync_window_us = max(0, fsync_window_us)
        self.max_batch = max(1, max_batch)
        # compact once this many CLOSED segments accumulate behind the
        # active one (0 disables snapshotting)
        self.snapshot_segments = snapshot_segments
        self.fsync = fsync
        self.verify_compaction = verify_compaction
        # fsync-stall injection (the SLO harness's durability-tier arm):
        # once `stall_after` appends have landed, the FLUSH THREAD sleeps
        # `stall_us` exactly once before its next fsync — a stuck disk as
        # the ack path observes it (durability-gated replies back up behind
        # the stalled group commit; open-loop latency charges the stall)
        self.stall_us = max(0, stall_us)
        self.stall_after = max(0, stall_after)

    @property
    def group_commit(self) -> bool:
        return self.fsync_window_us > 0

    @classmethod
    def from_env(cls, directory: str) -> "JournalConfig":
        return cls(
            directory,
            segment_bytes=_env_int("ACCORD_JOURNAL_SEGMENT_BYTES", 4 << 20),
            fsync_window_us=_env_int("ACCORD_JOURNAL_FSYNC_US", 2000),
            max_batch=_env_int("ACCORD_JOURNAL_MAX_BATCH", 256),
            snapshot_segments=_env_int("ACCORD_JOURNAL_SNAPSHOT_SEGMENTS",
                                       4),
            stall_us=_env_int("ACCORD_JOURNAL_STALL_US", 0),
            stall_after=_env_int("ACCORD_JOURNAL_STALL_AFTER", 0))

    def __repr__(self):
        return (f"JournalConfig({self.directory!r} "
                f"segment_bytes={self.segment_bytes} "
                f"fsync_window_us={self.fsync_window_us} "
                f"max_batch={self.max_batch})")


def encode_record(request) -> bytes:
    from accord_tpu.host.wire import encode_message
    return json.dumps(encode_message(request),
                      separators=(",", ":")).encode()


def decode_record(payload: bytes):
    from accord_tpu.host.wire import decode_message
    return decode_message(json.loads(payload.decode()))


class WriteAheadLog:
    """One node's durable journal over a directory of segments + snapshot.

    Drop-in for the sim journal's record/for_node surface (Node._process
    calls `journal.record(node_id, request)`), plus the durability plumbing
    DurableAckSink and the bench lane use (`append`/`wait_durable`/
    `on_durable`)."""

    def __init__(self, directory: str, node_id: int = 0,
                 config: Optional[JournalConfig] = None, registry=None,
                 flight=None, retain: bool = True):
        self.directory = directory
        self.node_id = node_id
        self.config = config if config is not None else JournalConfig(directory)
        os.makedirs(directory, exist_ok=True)
        self.flight = flight
        if registry is None:
            from accord_tpu.obs.registry import Registry
            registry = Registry()
        self.registry = registry
        self._c_appends = registry.counter("accord_journal_appends_total")
        self._c_bytes = registry.counter("accord_journal_append_bytes_total")
        self._c_fsync = registry.counter("accord_journal_fsync_total")
        self._c_rotate = registry.counter("accord_journal_rotations_total")
        self._c_snapshots = registry.counter("accord_journal_snapshots_total")
        self._c_stalls = registry.counter("accord_journal_stall_total")
        self._h_batch = registry.histogram("accord_journal_group_commit_batch")
        # one-shot fsync-stall injection armed by config (SLO stall arm)
        self._stall_pending = self.config.stall_us > 0
        # retain=True keeps every appended request in memory so the sim's
        # journal validator can fold for_node() without re-reading disk;
        # hosts pass retain=False (they never fold, and must not grow
        # without bound)
        self._retain = retain
        self._retained: List[object] = []
        self._lock = threading.Lock()
        # two conditions on one lock: appends wake only the flusher
        # (notify(1) on _work), the flusher's fsync wakes only durability
        # waiters (notify_all on _durable_cv) — one shared condition would
        # thundering-herd every blocked appender on every append
        self._work = threading.Condition(self._lock)
        self._durable_cv = threading.Condition(self._lock)
        self._seq = 0
        self.durable_seq = 0
        self._buffer: List[tuple] = []       # (seq, payload, enqueued_mono)
        self._on_durable: List[tuple] = []   # heap of (seq, tie, fn)
        self._tie = 0
        self._closing = False
        segs = list_segments(directory)
        self._index = segs[-1][0] if segs else 0
        # the writer opens lazily on the first write: load_records must be
        # able to truncate a torn tail (and drop snapshot-covered segments)
        # before an appender holds the file open
        self._writer: Optional[SegmentWriter] = None
        self._flusher = None
        if self.config.group_commit:
            self._flusher = threading.Thread(target=self._flush_loop,
                                             daemon=True)
            self._flusher.start()

    # ---------------------------------------------------------------- load --
    def load_records(self) -> List[object]:
        """Decode snapshot + surviving segment records (torn tails truncated
        in place), ready for replay.  Segments wholly covered by the
        snapshot (a crash between snapshot rename and segment unlink can
        leave some) are deleted, not double-replayed."""
        from accord_tpu.journal.snapshot import read_snapshot
        out: List[object] = []
        covers = -1
        snap_path = os.path.join(self.directory, SNAPSHOT_NAME)
        if os.path.exists(snap_path):
            covers, msgs = read_snapshot(snap_path)
            out.extend(msgs)
        for idx, path in list_segments(self.directory):
            if idx <= covers:
                os.unlink(path)
                continue
            for payload in read_segment(path, truncate=True):
                out.append(decode_record(payload))
        if covers >= self._index:
            # every segment was covered: the next one must NOT reuse a
            # covered index, or a later open would skip its records
            self._index = covers + 1
        if self._retain:
            self._retained.extend(out)
        return out

    # -------------------------------------------------------------- append --
    @property
    def last_seq(self) -> int:
        return self._seq

    def queue_depth(self) -> int:
        """Group-commit backlog: records appended but not yet handed to the
        flush batch.  Read lock-free from the QoS pressure controller (loop
        thread) — `len` of a list is atomic under the GIL and an off-by-a-
        few stale read only nudges a normalized pressure contribution, so
        the flush thread's mutations need no coordination here."""
        return len(self._buffer)

    def append(self, request) -> int:
        """Journal one side-effecting request; returns its sequence number.
        Durable once `durable_seq` reaches it (immediately in sync mode)."""
        payload = encode_record(request)
        with self._lock:
            self._seq += 1
            seq = self._seq
            if self._retain:
                self._retained.append(request)
            if self.config.group_commit:
                self._buffer.append((seq, payload, time.monotonic()))
                self._work.notify()
                return seq
            self._write_batch([(seq, payload)])
            self._mark_durable(seq)
        self._fire_due_callbacks()
        return seq

    # sim/journal.Journal surface (Node._process, validate_node)
    def record(self, node_id: int, request) -> None:
        self.append(request)

    def for_node(self, node_id: int) -> List[object]:
        return list(self._retained)

    # ---------------------------------------------------------- durability --
    def wait_durable(self, seq: int, timeout_s: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout_s
        with self._durable_cv:
            while self.durable_seq < seq:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._durable_cv.wait(remaining)
        return True

    def on_durable(self, seq: int, fn) -> None:
        """Run `fn` once `seq` is durable (inline when it already is).
        Fired from the flush thread in group-commit mode."""
        with self._lock:
            if self.durable_seq < seq:
                self._tie += 1
                heapq.heappush(self._on_durable, (seq, self._tie, fn))
                return
        fn()

    def _mark_durable(self, seq: int) -> None:
        # lock held
        self.durable_seq = seq
        self._durable_cv.notify_all()

    def _pop_due_callbacks(self) -> List:
        # lock held
        due = []
        while self._on_durable and self._on_durable[0][0] <= self.durable_seq:
            due.append(heapq.heappop(self._on_durable)[2])
        return due

    def _fire_due_callbacks(self) -> None:
        with self._lock:
            due = self._pop_due_callbacks()
        for fn in due:
            fn()

    # --------------------------------------------------------------- write --
    def _write_batch(self, items) -> None:
        """Append `items` frames and certify them with one fsync (rotating
        first when the active segment is full).  Single-writer: the flush
        thread in group-commit mode, the appender (under the lock) in sync
        mode."""
        if self._writer is None:
            self._writer = SegmentWriter(
                os.path.join(self.directory, segment_name(self._index)))
        rotated = False
        nbytes = 0
        for seq, payload in items:
            if self._writer.size >= self.config.segment_bytes:
                self._rotate()
                rotated = True
            nbytes += self._writer.append(payload)
            if self.flight is not None:
                self.flight.record("journal_append", None, (seq, len(payload)))
        if self.config.fsync:
            self._writer.sync()
        else:
            self._writer.flush()
        self._c_fsync.inc()
        self._c_appends.inc(len(items))
        self._c_bytes.inc(nbytes)
        self._h_batch.observe(len(items))
        if rotated:
            self._maybe_compact()

    def _rotate(self) -> None:
        self._writer.close(sync=self.config.fsync)
        self._index += 1
        self._writer = SegmentWriter(
            os.path.join(self.directory, segment_name(self._index)))
        fsync_dir(self.directory)
        self._c_rotate.inc()
        if self.flight is not None:
            self.flight.record("journal_rotate", None, (self._index,))

    def _maybe_compact(self) -> None:
        if not self.config.snapshot_segments:
            return
        closed = [s for s in list_segments(self.directory)
                  if s[0] < self._index]
        if len(closed) < self.config.snapshot_segments:
            return
        from accord_tpu.journal.snapshot import compact
        stats = compact(self.directory, upto_index=self._index - 1,
                        verify=self.config.verify_compaction,
                        fsync=self.config.fsync)
        self._c_snapshots.inc()
        if self.flight is not None:
            self.flight.record("journal_snapshot", None,
                               (stats.records_in, stats.records_out,
                                stats.segments_retired))

    # ----------------------------------------------------------- flush loop --
    def _flush_loop(self) -> None:
        cfg = self.config
        window_s = cfg.fsync_window_us / 1e6
        while True:
            with self._work:
                while not self._buffer and not self._closing:
                    self._work.wait(0.1)
                if not self._buffer and self._closing:
                    return
                # group-commit window: anchored to the OLDEST buffered
                # append, closed early when the batch bound is hit OR when
                # a whole window slice passes with no new arrivals — with
                # durability-gated clients everyone who can append is then
                # blocked on this very fsync, so further waiting only adds
                # latency (the ingest pipeline's adaptive-deadline
                # discipline, pipeline/ingest.py)
                deadline = self._buffer[0][2] + window_s
                idle_slice = window_s / 8
                last_depth = len(self._buffer)
                while (len(self._buffer) < cfg.max_batch
                       and not self._closing):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._work.wait(min(idle_slice, remaining))
                    if len(self._buffer) == last_depth:
                        break  # a full slice brought nothing new
                    last_depth = len(self._buffer)
                batch, self._buffer = self._buffer, []
            if self._stall_pending and batch \
                    and batch[-1][0] >= cfg.stall_after:
                # injected fsync stall (config.stall_us): the flush thread
                # — not the coordinator door — wedges, so everything
                # durability-gated behind this window queues up exactly as
                # it would behind a stuck disk
                self._stall_pending = False
                self._c_stalls.inc()
                time.sleep(cfg.stall_us / 1e6)
            self._write_batch([(seq, payload) for seq, payload, _ in batch])
            with self._lock:
                self._mark_durable(batch[-1][0])
                due = self._pop_due_callbacks()
            for fn in due:
                fn()

    # ----------------------------------------------------------- lifecycle --
    def sync(self, timeout_s: float = 30.0) -> bool:
        """Barrier: everything appended so far is durable on return."""
        with self._lock:
            seq = self._seq
        if not self.config.group_commit:
            return True
        with self._work:
            self._work.notify()
        return self.wait_durable(seq, timeout_s)

    def sync_soon(self, fn) -> None:
        """Non-blocking persist-before-ack: run `fn` once everything
        appended so far is durable.  Unlike `sync()` this never parks the
        calling thread — safe on the event loop.  `fn` runs inline when
        already durable, else from the flush thread; callers that touch
        loop state must marshal back themselves (host `emit` paths do)."""
        with self._lock:
            seq = self._seq
        if self.config.group_commit:
            with self._work:
                self._work.notify()
        self.on_durable(seq, fn)

    def close(self) -> None:
        self.sync()
        with self._lock:
            self._closing = True
            self._work.notify_all()
            self._durable_cv.notify_all()
        if self._flusher is not None:
            self._flusher.join(timeout=5.0)
            self._flusher = None
        if self._writer is not None:
            self._writer.close(sync=self.config.fsync)

    def __repr__(self):
        return (f"WriteAheadLog(n{self.node_id} {self.directory!r} "
                f"seq={self._seq} durable={self.durable_seq})")


class DurableAckSink:
    """MessageSink wrapper gating outbound REPLIES on the fsync watermark:
    a reply acking work journaled in the current group-commit window leaves
    only once that window's fsync lands (requests pass through — only acks
    certify durable state).  The conservative watermark (the log's last
    appended seq at reply time) can hold a read-only reply for at most one
    fsync window; per-request tracking isn't worth threading through every
    handler."""

    def __init__(self, inner, wal: WriteAheadLog):
        self._inner = inner
        self._wal = wal

    def send(self, to: int, request) -> None:
        self._inner.send(to, request)

    def send_with_callback(self, to: int, request, callback,
                           executor=None) -> None:
        self._inner.send_with_callback(to, request, callback,
                                       executor=executor)

    def reply(self, to: int, reply_context, reply) -> None:
        wal = self._wal
        seq = wal.last_seq
        if seq <= wal.durable_seq:
            self._inner.reply(to, reply_context, reply)
        else:
            wal.on_durable(
                seq, lambda: self._inner.reply(to, reply_context, reply))

    def __getattr__(self, name):
        # deliver_reply / batch_begin / batch_flush / msg-id bookkeeping all
        # belong to the wrapped sink
        return getattr(self._inner, name)


class DurableJournalSet:
    """Per-node WALs under one base directory — the sim cluster's durable
    stand-in for sim/journal.Journal (same record/for_node surface, so
    validate_cluster folds the on-disk journal).  Runs the WALs in
    synchronous mode: deterministic (no flush threads) and exact on
    write-before-ack ordering; `fsync=False` because the sim simulates
    PROCESS death — OS buffers survive the kill, so the physical disk
    barrier would only slow the burn."""

    def __init__(self, base_dir: str, fsync: bool = False):
        self.base_dir = base_dir
        self.fsync = fsync
        self.wals: Dict[int, WriteAheadLog] = {}

    def node_dir(self, node_id: int) -> str:
        return os.path.join(self.base_dir, f"node-{node_id}")

    def open_node(self, node_id: int, registry=None, flight=None,
                  load: bool = False) -> WriteAheadLog:
        cfg = JournalConfig(self.node_dir(node_id), fsync_window_us=0,
                            segment_bytes=256 << 10, fsync=self.fsync)
        wal = WriteAheadLog(self.node_dir(node_id), node_id=node_id,
                            config=cfg, registry=registry, flight=flight,
                            retain=True)
        self.wals[node_id] = wal
        return wal

    def close_node(self, node_id: int) -> None:
        wal = self.wals.pop(node_id, None)
        if wal is not None:
            wal.close()

    def close(self) -> None:
        for node_id in list(self.wals):
            self.close_node(node_id)

    # sim/journal.Journal surface
    def record(self, node_id: int, request) -> None:
        self.wals[node_id].append(request)

    def for_node(self, node_id: int) -> List[object]:
        wal = self.wals.get(node_id)
        return wal.for_node(node_id) if wal is not None else []
