"""Append-only journal segments: length+CRC32-framed records on disk.

One segment is a sequence of frames:

    [4-byte big-endian payload length][4-byte CRC32 of payload][payload]

The payload is opaque bytes to this layer (wal.py stores wire-codec JSON).
A crashed writer can leave a torn tail — a partial header, a partial
payload, or a payload whose CRC does not match (the write raced the crash).
`read_segment` stops at the first such frame and, when asked, truncates the
file back to the last whole record, so an append-after-recovery never
splices new records onto garbage bytes.
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import List, Optional, Tuple

_HEADER = struct.Struct(">II")  # (payload_len, crc32)

# a frame longer than this is treated as corruption, not a record: a torn
# header can otherwise decode as a multi-GB length and stall recovery on a
# doomed read
MAX_RECORD_BYTES = 64 << 20


def frame(payload: bytes) -> bytes:
    return _HEADER.pack(len(payload), zlib.crc32(payload)) + payload


def scan_segment(path: str) -> Tuple[List[bytes], int, bool]:
    """Read whole records from `path`.  Returns (records, good_bytes,
    torn): `good_bytes` is the offset just past the last intact record and
    `torn` is True when trailing bytes past it had to be abandoned."""
    records: List[bytes] = []
    good = 0
    torn = False
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        return records, 0, False
    off = 0
    n = len(data)
    while off + _HEADER.size <= n:
        length, crc = _HEADER.unpack_from(data, off)
        end = off + _HEADER.size + length
        if length > MAX_RECORD_BYTES or end > n:
            torn = True
            break
        payload = data[off + _HEADER.size:end]
        if zlib.crc32(payload) != crc:
            torn = True
            break
        records.append(payload)
        off = end
        good = off
    if not torn and off != n:
        torn = True  # partial header at the tail
    return records, good, torn


def read_frame_at(path: str, offset: int) -> bytes:
    """Read exactly ONE frame starting at `offset` — the point-read a
    fault-index hit performs, so a refault costs one seek + one frame, not
    a segment scan.  Raises ValueError on a bad offset, torn frame, or CRC
    mismatch: the caller (the pager) treats that as spill-tier corruption,
    never as a missing command."""
    with open(path, "rb") as f:
        f.seek(offset)
        header = f.read(_HEADER.size)
        if len(header) != _HEADER.size:
            raise ValueError(f"truncated frame header at {path}:{offset}")
        length, crc = _HEADER.unpack(header)
        if length > MAX_RECORD_BYTES:
            raise ValueError(f"oversized frame at {path}:{offset}")
        payload = f.read(length)
    if len(payload) != length:
        raise ValueError(f"truncated frame payload at {path}:{offset}")
    if zlib.crc32(payload) != crc:
        raise ValueError(f"frame CRC mismatch at {path}:{offset}")
    return payload


def read_segment(path: str, truncate: bool = True) -> List[bytes]:
    """Records of one segment; with `truncate`, a torn tail is cut back to
    the last intact record on disk (fsynced) so later appends are safe."""
    records, good, torn = scan_segment(path)
    if torn and truncate:
        with open(path, "r+b") as f:
            f.truncate(good)
            f.flush()
            os.fsync(f.fileno())
    return records


def fsync_dir(directory: str) -> None:
    """Durably record directory-level changes (created/renamed/unlinked
    files).  Best-effort: not every filesystem supports opening a dir."""
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class SegmentWriter:
    """One open segment file being appended to.  `append` buffers into the
    OS (write); `sync` makes everything appended so far durable (flush +
    fsync).  Group commit lives a layer up (wal.py): many appends, one
    sync."""

    def __init__(self, path: str):
        self.path = path
        # append mode: reopening after a torn-tail truncation must continue
        # at the truncated offset, not clobber the surviving records
        self._f = open(path, "ab")
        self.size = self._f.tell()

    def append(self, payload: bytes) -> int:
        """Write one frame; returns the frame's size in bytes (not yet
        durable until `sync`)."""
        buf = frame(payload)
        self._f.write(buf)
        self.size += len(buf)
        return len(buf)

    def flush(self) -> None:
        self._f.flush()

    def sync(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self, sync: bool = True) -> None:
        if self._f.closed:
            return
        if sync:
            self.sync()
        self._f.close()


def segment_name(index: int) -> str:
    return f"segment-{index:08d}.wal"


def segment_index(name: str) -> Optional[int]:
    if name.startswith("segment-") and name.endswith(".wal"):
        try:
            return int(name[len("segment-"):-len(".wal")])
        except ValueError:
            return None
    return None


def list_segments(directory: str) -> List[Tuple[int, str]]:
    """(index, path) of every segment in `directory`, ascending."""
    out = []
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return out
    for name in names:
        idx = segment_index(name)
        if idx is not None:
            out.append((idx, os.path.join(directory, name)))
    out.sort()
    return out
