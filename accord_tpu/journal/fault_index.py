"""SpillStore: the paging tier's on-disk frame store + compaction-aware
fault index.

Reference: accord's Journal/CommandStore persistence seam — evicted command
state must be reloadable by identity without scanning history.  The node
WAL (wal.py) stays the crash-durability tier; this store is SCRATCH state
for one node incarnation: `local/paging.py` wipes it on attach and WAL
replay re-derives residency, so nothing here is ever the only copy of a
decided command.

Layout reuses the WAL's segment framing (segment.py): each eviction appends
one `SpillFrame` record and the in-memory fault index maps its TxnId to the
exact (segment, byte offset), so a refault is ONE point-read
(`read_frame_at`) — never a segment scan.  A fault or drop makes the frame
dead; when the dead fraction of the on-disk bytes crosses the compaction
threshold the live frames are rewritten into fresh segments and the index
is repointed (compaction-aware by construction).  `checkpoint()` appends a
`FaultIndexCheckpoint` so a clean-close reopen seeds the index from the
newest checkpoint and replays only the frames appended after it.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

from accord_tpu.journal.segment import (SegmentWriter, fsync_dir,
                                        list_segments, read_frame_at,
                                        scan_segment, segment_name,
                                        _HEADER)
from accord_tpu.journal.wal import decode_record, encode_record
from accord_tpu.messages.paging import FaultIndexCheckpoint, SpillFrame

# rotate the active spill segment at this size (small enough that a
# compaction rewrite touches bounded I/O per segment)
SPILL_SEGMENT_BYTES = 8 << 20
# rewrite live frames once dead bytes exceed this fraction of the total …
COMPACT_DEAD_FRACTION = 0.5
# … but never bother below this floor (compaction churn on tiny stores)
COMPACT_MIN_BYTES = 1 << 20
# append a FaultIndexCheckpoint every N spills (0 disables)
CHECKPOINT_EVERY = 4096


class SpillStore:
    """On-disk spill frames + in-memory fault index for ONE CommandStore.

    Single-threaded like its owner (command stores are logically
    single-threaded); durability is NOT required — spill segments are
    never fsynced, because the WAL already owns crash durability and a
    torn spill tail only ever loses frames the next incarnation would
    have wiped anyway."""

    def __init__(self, directory: str, fresh: bool = True,
                 flight=None,
                 segment_bytes: int = SPILL_SEGMENT_BYTES,
                 checkpoint_every: int = CHECKPOINT_EVERY):
        self.directory = directory
        self.segment_bytes = segment_bytes
        self.checkpoint_every = checkpoint_every
        self._flight = flight
        # txn_id -> (segment_index, byte_offset) of the LIVE frame
        self.index: Dict = {}
        # segment_index -> total frame bytes present in that segment
        self._seg_bytes: Dict[int, int] = {}
        self._live_bytes = 0
        self._total_bytes = 0
        self._spills_since_checkpoint = 0
        # lifetime counters the pager exports
        self.frames_written = 0
        self.frames_faulted = 0
        self.frames_dropped = 0
        self.compactions = 0
        os.makedirs(directory, exist_ok=True)
        if fresh:
            self._wipe()
            self._active_index = 0
        else:
            self._active_index = self._rebuild()
        self._writer = SegmentWriter(self._path(self._active_index))
        self._seg_bytes.setdefault(self._active_index, self._writer.size)

    # ------------------------------------------------------------ paths --
    def _path(self, index: int) -> str:
        return os.path.join(self.directory, segment_name(index))

    def _wipe(self) -> None:
        for _idx, path in list_segments(self.directory):
            os.unlink(path)
        fsync_dir(self.directory)

    # ------------------------------------------------------------- write --
    def spill(self, command) -> Tuple[int, int]:
        """Append one command's SpillFrame; returns its (segment, offset).
        A txn already spilled is superseded in place: the old frame goes
        dead and the index repoints to the new one."""
        record = SpillFrame.from_command(command)
        payload = encode_record(record)
        txn_id = record.txn_id
        old = self.index.get(txn_id)
        seg, off = self._append(payload)
        self.index[txn_id] = (seg, off)
        self.frames_written += 1
        if old is not None:
            # superseded frame: its bytes are dead but unknown exactly —
            # approximate with the new frame's size (same command, same
            # quiescent payload shape)
            self._live_bytes -= _HEADER.size + len(payload)
        if self._flight is not None:
            self._flight.record("page_spill", str(txn_id),
                                (seg, off, len(payload)))
        self._spills_since_checkpoint += 1
        if self.checkpoint_every and \
                self._spills_since_checkpoint >= self.checkpoint_every:
            self.checkpoint()
        self._maybe_compact()
        return seg, off

    def _append(self, payload: bytes) -> Tuple[int, int]:
        if self._writer.size >= self.segment_bytes:
            self._writer.close(sync=False)
            self._active_index += 1
            self._writer = SegmentWriter(self._path(self._active_index))
            self._seg_bytes[self._active_index] = 0
        off = self._writer.size
        n = self._writer.append(payload)
        self._writer.flush()
        self._seg_bytes[self._active_index] += n
        self._live_bytes += n
        self._total_bytes += n
        return self._active_index, off

    # -------------------------------------------------------------- read --
    def fault(self, txn_id):
        """Point-read one spilled command back; the frame becomes dead and
        the index entry is removed (the resident copy is now the only
        truth — re-eviction re-spills current state)."""
        seg, off = self.index.pop(txn_id)
        payload = read_frame_at(self._sync_path(seg), off)
        record = decode_record(payload)
        if not isinstance(record, SpillFrame) or record.txn_id != txn_id:
            raise ValueError(
                f"fault index corruption: {txn_id} -> {seg}:{off} holds "
                f"{record!r}")
        self.frames_faulted += 1
        self._live_bytes -= _HEADER.size + len(payload)
        self._maybe_compact()
        return record.to_command()

    def _sync_path(self, seg: int) -> str:
        # reading the active segment must see its buffered appends
        if seg == self._active_index:
            self._writer.flush()
        return self._path(seg)

    def drop(self, txn_id) -> bool:
        """Discard a spilled entry without reading it (it went redundant
        while cold).  Returns whether it was present."""
        entry = self.index.pop(txn_id, None)
        if entry is None:
            return False
        self.frames_dropped += 1
        # dead-byte size unknown without a read; fold it into the dead
        # fraction via live-byte average
        n = len(self.index)
        self._live_bytes -= self._live_bytes // (n + 1)
        self._maybe_compact()
        return True

    def __contains__(self, txn_id) -> bool:
        return txn_id in self.index

    def __len__(self) -> int:
        return len(self.index)

    # ------------------------------------------------------- checkpoint --
    def checkpoint(self) -> None:
        """Append a FaultIndexCheckpoint covering the current append
        position, so a clean-close reopen seeds from it."""
        self._spills_since_checkpoint = 0
        entries = tuple(tid.pack() + (seg, off)
                        for tid, (seg, off) in self.index.items())
        record = FaultIndexCheckpoint(entries, self._active_index,
                                      self._writer.size)
        self._append(encode_record(record))

    def _rebuild(self) -> int:
        """Reopen path: seed the index from the newest checkpoint, then
        replay only frames appended after its covered position; falls back
        to a full scan when no checkpoint exists.  Returns the active
        segment index to continue appending into."""
        from accord_tpu.primitives.timestamp import TxnId
        segments = list_segments(self.directory)
        if not segments:
            return 0
        # offset-tracked scan of every segment (spill stores are scratch,
        # so reopen is rare and bounded; the checkpoint trims the DECODE
        # cost, which dominates)
        frames = []  # (seg, off, payload)
        for seg, path in segments:
            off = 0
            records, good, _torn = scan_segment(path)
            for payload in records:
                frames.append((seg, off, payload))
                off += _HEADER.size + len(payload)
            self._seg_bytes[seg] = good
            self._total_bytes += good
        # newest checkpoint wins; tag-sniff the JSON head to avoid
        # decoding every spill frame just to find it
        ckpt = None
        ckpt_at = (-1, -1)
        for seg, off, payload in frames:
            if payload.startswith(b'{"$c":"FaultIndexCheckpoint"'):
                ckpt = decode_record(payload)
                ckpt_at = (ckpt.through_segment, ckpt.through_offset)
        if ckpt is not None:
            for msb, lsb, node, seg, off in ckpt.entries:
                self.index[TxnId.unpack(msb, lsb, node)] = (seg, off)
        for seg, off, payload in frames:
            if (seg, off) < ckpt_at:
                continue
            if payload.startswith(b'{"$c":"FaultIndexCheckpoint"'):
                continue
            record = decode_record(payload)
            if isinstance(record, SpillFrame):
                self.index[record.txn_id] = (seg, off)
        # live-byte estimate: index entries at average frame size
        if frames:
            avg = self._total_bytes // len(frames)
            self._live_bytes = min(self._total_bytes, avg * len(self.index))
        return segments[-1][0]

    # -------------------------------------------------------- compaction --
    def _maybe_compact(self) -> None:
        if self._total_bytes < COMPACT_MIN_BYTES:
            return
        dead = self._total_bytes - max(self._live_bytes, 0)
        if dead / self._total_bytes >= COMPACT_DEAD_FRACTION:
            self.compact()

    def compact(self) -> None:
        """Rewrite live frames into fresh segments and unlink the old
        ones; every index entry is repointed, so in-flight faults after
        compaction still read one frame."""
        self._writer.close(sync=False)
        old_paths = [path for _idx, path in list_segments(self.directory)]
        live = sorted(self.index.items(), key=lambda kv: kv[1])
        start = self._active_index + 1
        self._active_index = start
        self._writer = SegmentWriter(self._path(start))
        self._seg_bytes = {start: 0}
        self._live_bytes = 0
        self._total_bytes = 0
        for txn_id, (seg, off) in live:
            payload = read_frame_at(self._path(seg), off)
            self.index[txn_id] = self._append(payload)
        self._writer.flush()
        for path in old_paths:
            os.unlink(path)
        fsync_dir(self.directory)
        self.compactions += 1
        if self.checkpoint_every:
            self.checkpoint()

    # ------------------------------------------------------------- close --
    @property
    def disk_bytes(self) -> int:
        return self._total_bytes

    def close(self, final_checkpoint: bool = True) -> None:
        if final_checkpoint and self.checkpoint_every:
            self.checkpoint()
        self._writer.close(sync=False)
