"""Per-tenant QoS admission: token buckets, priority classes, typed nack.

The QoS tier is the host boundary's OUTER admission ring.  It runs before
any coordination state or journal append is spent on a submit: a rejected
transaction was never coordinated anywhere, so the nack is retriable by
construction (same guarantee as the pipeline's `Rejected`, which this
tier's nack subclasses — existing shed accounting in burn/bench clients
keeps working unchanged).

Three mechanisms, in decision order:

  1. pressure shed — the adaptive controller (qos/controller.py) folds the
     host's real bottleneck signals (loop-lag EWMA, loop saturation, WAL
     group-commit queue depth) into one normalized scalar, maxed with the
     tier's own admitted-but-unsettled backlog (`inflight/depth_target`,
     the signal that clamps admission to the concurrency the node
     sustains instead of oscillating on after-the-fact lag); a submit whose
     priority class's threshold is at/below the current pressure is shed.
     `best_effort` sheds first, `normal` at double the pressure, and
     `high` is NEVER pressure-shed — only the pipeline's bounded queue
     (the last-resort inner ring) can reject it.
  2. tenant throttle — a per-tenant token bucket with burst credit
     (`ACCORD_QOS_RATE` / `ACCORD_QOS_BURST`; rate 0 disables the
     bucket).  Keeps one chatty tenant from starving the rest even when
     the node itself is healthy.  `high` spends from the same bucket but
     by OVERDRAFT: it is never throttled, it drives the bucket negative
     (floored at -burst) and the debt is repaid out of the bulk tiers'
     refill.  That keeps the tenant's total admitted rate bounded by the
     bucket at every offered load — which is what preserves latency
     headroom for the high class at deep overload — while still giving
     high strict priority over its own tenant's bulk traffic.
  3. inner ring — the pipeline ingest queue's depth bound stays armed
     behind the tier; its sheds are tallied here too so the exported
     accounting covers every rejection path.

Every nack carries `retry_after_us` computed from bucket refill time plus
the measured loop lag, so clients back off proportionally to how far the
node actually is from keeping up.

Single-threaded by construction on the admission side: `admit()` runs on
the owning host's loop thread (TCP selector / Maelstrom stdio / sim
virtual-time queue), like the command stores and the ingest queue.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

from accord_tpu.pipeline.backpressure import Rejected
from accord_tpu.qos.controller import PressureController

PRIORITIES = ("high", "normal", "best_effort")

_RETRY_CAP_US = 2_000_000  # never tell a client to stay away longer than 2s


class QosRejected(Rejected):
    """QoS admission nack: the transaction was NEVER submitted to the
    protocol (no coordination state, no journal append — safe to retry).
    Carries the machine-readable hint clients use for jittered backoff."""

    def __init__(self, message: str = "", retry_after_us: int = 0,
                 tenant: str = "", priority: str = "normal",
                 reason: str = "shed"):
        super().__init__(message)
        self.retry_after_us = int(retry_after_us)
        self.tenant = tenant
        self.priority = priority
        self.reason = reason  # "shed" (pressure) | "throttle" (bucket)

    def wire_extra(self) -> Dict[str, object]:
        """Fields the wire codec re-attaches on decode (host/wire.py keeps
        only `str(exc)` for plain exceptions; the retry hint must survive
        the trip or remote clients cannot honor it)."""
        return {"retry_after_us": self.retry_after_us, "tenant": self.tenant,
                "priority": self.priority, "reason": self.reason}


class QosConfig:
    """Tunables for the QoS admission tier (env-overridable on hosts).

    Pressure is normalized so 1.0 means "the configured lag target is being
    missed" — `shed_pressure` is the `best_effort` threshold, `normal` sheds
    at `normal_pressure`, `high` has no pressure threshold at all."""

    def __init__(self, rate_per_s: float = 0.0, burst: float = 0.0,
                 shed_pressure: float = 1.0, normal_pressure: float = 2.0,
                 lag_target_us: float = 50_000.0, depth_target: float = 128.0,
                 wal_target: int = 256, ewma_half_life_s: float = 0.5,
                 retry_floor_us: int = 10_000, shard_factor: float = 2.0):
        self.rate_per_s = max(0.0, rate_per_s)
        self.burst = burst if burst > 0 else max(1.0, self.rate_per_s)
        # per-shard sub-quota slack under the worker runtime: each
        # (tenant, shard) bucket gets rate/n * shard_factor, so a skewed
        # tenant can lean on a hot shard up to factor× its fair share
        # while the node-level bucket stays the binding total cap
        self.shard_factor = max(1.0, shard_factor)
        self.shed_pressure = shed_pressure
        self.normal_pressure = max(normal_pressure, shed_pressure)
        self.lag_target_us = max(1.0, lag_target_us)
        # fractional targets are meaningful: inflight is an integer, so
        # e.g. 1.5 sheds best_effort at 2 in flight and normal at 3
        self.depth_target = max(0.25, float(depth_target))
        self.wal_target = max(1, wal_target)
        self.ewma_half_life_s = max(1e-3, ewma_half_life_s)
        self.retry_floor_us = max(0, retry_floor_us)

    @classmethod
    def from_env(cls) -> "QosConfig":
        def _f(name: str, default: float) -> float:
            try:
                return float(os.environ.get(name, default))
            except ValueError:
                return default

        return cls(
            rate_per_s=_f("ACCORD_QOS_RATE", 0.0),
            burst=_f("ACCORD_QOS_BURST", 0.0),
            shed_pressure=_f("ACCORD_QOS_SHED_PRESSURE", 1.0),
            normal_pressure=_f("ACCORD_QOS_NORMAL_PRESSURE", 2.0),
            lag_target_us=_f("ACCORD_QOS_LAG_TARGET_US", 50_000.0),
            depth_target=_f("ACCORD_QOS_DEPTH_TARGET", 128.0),
            wal_target=int(_f("ACCORD_QOS_WAL_TARGET", 256)),
            retry_floor_us=int(_f("ACCORD_QOS_RETRY_FLOOR_US", 10_000)),
            shard_factor=_f("ACCORD_QOS_SHARD_FACTOR", 2.0))

    def pressure_limit(self, priority: str) -> float:
        """Shed threshold for a priority class; inf means never
        pressure-shed (the burn's fairness invariant — high-priority ops
        are only rejectable by the bounded inner ring)."""
        if priority == "high":
            return float("inf")
        if priority == "normal":
            return self.normal_pressure
        return self.shed_pressure

    def __repr__(self):
        return (f"QosConfig(rate={self.rate_per_s} burst={self.burst} "
                f"shed={self.shed_pressure} normal={self.normal_pressure} "
                f"lag_target_us={self.lag_target_us})")


class TokenBucket:
    """Classic leaky token bucket with burst credit, lazily refilled on the
    caller's clock (injected, so the sim's virtual time keeps admission
    deterministic)."""

    __slots__ = ("rate", "burst", "tokens", "_last_us")

    def __init__(self, rate_per_s: float, burst: float, now_us: int):
        self.rate = rate_per_s
        self.burst = burst
        self.tokens = burst  # start full: a fresh tenant gets its burst
        self._last_us = now_us

    def _refill(self, now_us: int) -> None:
        elapsed_us = now_us - self._last_us
        if elapsed_us > 0:
            self.tokens = min(self.burst,
                              self.tokens + elapsed_us * 1e-6 * self.rate)
            self._last_us = now_us

    def try_take(self, now_us: int) -> float:
        """Take one token.  Returns 0.0 on success, else the refill delay
        in microseconds until one token will be available."""
        self._refill(now_us)
        # 1e-9 epsilon: refill arithmetic like 0.1s * 10/s lands at
        # 0.999...9 and must still count as a whole token
        if self.tokens >= 1.0 - 1e-9:
            self.tokens = max(0.0, self.tokens - 1.0)
            return 0.0
        return (1.0 - self.tokens) / self.rate * 1e6

    def refund(self) -> None:
        """Return one token (a later admission stage refused the op after
        this bucket had already charged it)."""
        self.tokens = min(self.burst, self.tokens + 1.0)

    def overdraw(self, now_us: int) -> None:
        """Unconditionally spend one token, allowing the bucket to go
        negative (floored at -burst so a surge can starve the bulk tiers
        for at most burst/rate seconds after it ends).  The high class
        uses this: never throttled itself, but its spend is repaid out of
        the same tenant's refill, so the tenant's TOTAL admitted rate
        stays bounded by the bucket."""
        self._refill(now_us)
        self.tokens = max(-self.burst, self.tokens - 1.0)


class QosTier:
    """One node's QoS admission tier.

    `admit(tenant, priority)` returns None (admitted) or a `QosRejected`
    ready to settle/ship as the nack.  Counters are per (tenant, priority)
    labeled `accord_qos_*_total` registry series, so the exported
    accounting identity

        admitted + shed + throttled == submitted   (per label pair)

    holds exactly — the burn and the slo-overload lane assert it."""

    def __init__(self, config: QosConfig, registry, flight, clock_us,
                 controller: Optional[PressureController] = None,
                 n_shards: int = 0):
        self.config = config
        self.registry = registry
        self.flight = flight
        self.clock_us = clock_us
        self.controller = controller if controller is not None else \
            PressureController(config, clock_us)
        self._buckets: Dict[str, TokenBucket] = {}
        # per-(tenant, shard) sub-buckets under the worker runtime
        # (ACCORD_SHARDS >= 2): a tenant hammering ONE worker's keyspace
        # slice is throttled at factor× its fair share of that shard
        # before it can queue the whole node quota onto one event loop.
        # The node-level bucket above stays the binding total cap — a
        # shard refusal refunds it, so the identity per (tenant,
        # priority) still balances and no token leaks.
        self.n_shards = n_shards if n_shards >= 2 else 0
        self._shard_buckets: Dict[Tuple[str, int], TokenBucket] = {}
        self._shard_ctrs: Dict[Tuple[str, int], object] = {}
        self._ctrs: Dict[Tuple[str, str, str], object] = {}
        self._g_pressure = registry.gauge("accord_qos_pressure_milli")
        self._g_inflight = registry.gauge("accord_qos_inflight")
        self._c_inner = registry.counter("accord_qos_inner_shed_total")
        self._admits_since_flight = 0
        # admitted-but-unsettled ops: the host calls op_done() when the
        # submit's reply ships.  inflight/depth_target is the tier's own
        # backlog signal — loop lag alone oscillates (it only rises after
        # the damage is queued), while inflight clamps admission to the
        # concurrency the node actually sustains
        self.inflight = 0

    # ------------------------------------------------------------ signals --
    def observe_lag(self, lag_s: float) -> None:
        """Scheduler lag-observer hook (chained after LoopHealth.timer_lag
        on the loop thread)."""
        self.controller.observe_lag(lag_s)

    # ----------------------------------------------------------- decision --
    def _counter(self, kind: str, tenant: str, priority: str):
        key = (kind, tenant, priority)
        c = self._ctrs.get(key)
        if c is None:
            c = self.registry.counter(f"accord_qos_{kind}_total",
                                      tenant=tenant, priority=priority)
            self._ctrs[key] = c
        return c

    def _retry_after_us(self, now_us: int, refill_us: float = 0.0,
                        pressure: float = 0.0) -> int:
        """Backoff hint: measured loop lag, floored by retry_floor scaled
        with pressure (an inflight-clamped node has LOW lag while turning
        work away — the hint must still grow with how overloaded it is)."""
        lag_us = self.controller.lag_us(now_us)
        floor = self.config.retry_floor_us * max(1.0, pressure)
        return int(min(_RETRY_CAP_US, max(floor, lag_us) + refill_us))

    def _shard_throttle(self, tenant: str, shard: int,
                        now: int) -> float:
        """Charge the (tenant, shard) sub-bucket; 0.0 admits, else the
        refill delay in microseconds.  Lazily built at rate/n × factor —
        slack for skew, but one shard can never drain the node quota."""
        key = (tenant, shard)
        bucket = self._shard_buckets.get(key)
        if bucket is None:
            scale = self.config.shard_factor / self.n_shards
            bucket = TokenBucket(self.config.rate_per_s * scale,
                                 max(1.0, self.config.burst * scale), now)
            self._shard_buckets[key] = bucket
        return bucket.try_take(now)

    def _shard_counter(self, tenant: str, shard: int):
        key = (tenant, shard)
        c = self._shard_ctrs.get(key)
        if c is None:
            c = self.registry.counter("accord_qos_shard_throttled_total",
                                      tenant=tenant, shard=shard)
            self._shard_ctrs[key] = c
        return c

    def admit(self, tenant: str, priority: str,
              shard: Optional[int] = None) -> Optional[QosRejected]:
        """One submit's admission decision, before any state is spent.
        `shard` (worker runtime only) keys the per-(tenant, shard)
        sub-bucket; None skips that stage."""
        now = self.clock_us()
        tenant = str(tenant) if tenant else "default"
        if priority not in PRIORITIES:
            priority = "normal"
        self._counter("submitted", tenant, priority).inc()
        pressure = max(self.controller.pressure(now),
                       self.inflight / self.config.depth_target)
        self._g_pressure.value = int(pressure * 1000)
        limit = self.config.pressure_limit(priority)
        if pressure >= limit:
            retry = self._retry_after_us(now, pressure=pressure)
            self._counter("shed", tenant, priority).inc()
            if self.flight is not None:
                self.flight.record("qos_shed", None,
                                   (tenant, priority, "pressure",
                                    int(pressure * 1000)))
            return QosRejected(
                f"qos shed: pressure {pressure:.2f} >= {limit:.2f} for "
                f"{priority}; retry after {retry}us",
                retry_after_us=retry, tenant=tenant, priority=priority,
                reason="shed")
        if self.config.rate_per_s > 0:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                bucket = TokenBucket(self.config.rate_per_s,
                                     self.config.burst, now)
                self._buckets[tenant] = bucket
            # strict priority WITHIN the tenant's quota: high is never
            # throttled — it overdraws the shared bucket and the debt is
            # repaid out of the bulk tiers' refill.  (A plain bypass
            # would let admitted load grow with the high arrival rate and
            # erase the latency headroom the quota exists to protect; a
            # plain shared take would let a tenant flooding best_effort
            # starve its own high ops, since tokens go in arrival order.)
            refill_us = (bucket.overdraw(now) or 0.0) if priority == "high" \
                else bucket.try_take(now)
            if refill_us > 0:
                retry = self._retry_after_us(now, refill_us,
                                             pressure=pressure)
                self._counter("throttled", tenant, priority).inc()
                if self.flight is not None:
                    self.flight.record("qos_throttle", None,
                                       (tenant, priority, retry))
                return QosRejected(
                    f"qos throttle: tenant {tenant} over "
                    f"{self.config.rate_per_s}/s; retry after {retry}us",
                    retry_after_us=retry, tenant=tenant, priority=priority,
                    reason="throttle")
            if self.n_shards and shard is not None and priority != "high":
                # shard sub-quota AFTER the node bucket (which stays the
                # binding cap); high rides its overdraft unthrottled here
                # too, for the same within-tenant strict-priority reason
                shard_refill = self._shard_throttle(tenant, shard, now)
                if shard_refill > 0:
                    bucket.refund()  # the node token was provisional
                    retry = self._retry_after_us(now, shard_refill,
                                                 pressure=pressure)
                    self._counter("throttled", tenant, priority).inc()
                    self._shard_counter(tenant, shard).inc()
                    if self.flight is not None:
                        self.flight.record("qos_throttle", None,
                                           (tenant, priority, retry, shard))
                    return QosRejected(
                        f"qos throttle: tenant {tenant} over shard {shard} "
                        f"sub-quota; retry after {retry}us",
                        retry_after_us=retry, tenant=tenant,
                        priority=priority, reason="throttle")
        self._counter("admitted", tenant, priority).inc()
        self.inflight += 1
        self._g_inflight.value = self.inflight
        self._admits_since_flight += 1
        if self.flight is not None and (self._admits_since_flight >= 64
                                        or self._admits_since_flight == 1):
            self.flight.record("qos_admit", None,
                               (tenant, priority, self._admits_since_flight))
            if self._admits_since_flight >= 64:
                self._admits_since_flight = 0
        return None

    # --------------------------------------------------------- inner ring --
    def note_inner_shed(self, depth: int) -> None:
        """The pipeline's bounded ingest queue (last-resort inner ring)
        shed a txn that this tier had admitted — tally it so the exported
        accounting covers every rejection path."""
        self._c_inner.inc()
        if self.flight is not None:
            self.flight.record("qos_shed", None,
                               ("", "", "inner", depth))

    def op_done(self) -> None:
        """An admitted submit settled (ack OR failure reply shipped) — the
        host calls this exactly once per admitted op, from the loop thread,
        so `inflight` tracks the true unsettled backlog."""
        if self.inflight > 0:
            self.inflight -= 1
        self._g_inflight.value = self.inflight

    # ------------------------------------------------------------ inspect --
    def pressure(self) -> float:
        return max(self.controller.pressure(self.clock_us()),
                   self.inflight / self.config.depth_target)
