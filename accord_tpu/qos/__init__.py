"""Host-boundary QoS admission tier (graceful overload, multi-tenant).

Past saturation, drop-tail mechanisms (pipeline `Rejected`, per-peer
in-flight bounds, drain shed) keep a node alive but are blind to WHO is
overloading it and WHAT the traffic is worth.  This package adds the
missing outer ring at the pluggable-host boundary (the reference protocol
leaves admission to `accord.api.*` hosts):

  * `admission.QosTier` — per-tenant token buckets with burst credit plus
    priority classes (`high` / `normal` / `best_effort`) carried on submit
    frames; admission runs BEFORE journal append and coordination state
    are spent, and every rejection is a typed retriable `QosRejected` nack
    with a `retry_after_us` hint;
  * `controller.PressureController` — adaptive shed threshold derived
    from the PR-9 loop-health lag/saturation gauges (plus WAL group-commit
    queue depth when journaling is on), so shedding tracks the real
    bottleneck rather than a static queue depth.

Hosts enable it with `ACCORD_QOS=1` (host/tcp.py, host/maelstrom.py);
the deterministic burn drives it via `SimCluster(qos=True)` /
`python -m accord_tpu.sim.burn --qos`.  Default off: with `ACCORD_QOS`
unset (or `0`) no tier is constructed and the submit path is byte-for-byte
today's, pinned by a differential burn in tests/test_qos.py.
"""

from __future__ import annotations

import os
from typing import Optional

from accord_tpu.qos.admission import (PRIORITIES, QosConfig, QosRejected,
                                      QosTier, TokenBucket)
from accord_tpu.qos.controller import PressureController


def qos_enabled() -> bool:
    """The host-side gate: ACCORD_QOS=1 (default off)."""
    return os.environ.get("ACCORD_QOS", "") == "1"


def qos_tier_from_env(registry, flight, clock_us, loop_health=None,
                      wal=None, sources=(),
                      n_shards: int = 0) -> Optional[QosTier]:
    """Construct one node's QoS tier from the environment, or None when the
    gate is off (hosts then keep today's submit path untouched).
    `n_shards >= 2` (the worker runtime) arms the per-(tenant, shard)
    sub-buckets."""
    if not qos_enabled():
        return None
    config = QosConfig.from_env()
    controller = PressureController(config, clock_us,
                                    loop_health=loop_health, wal=wal,
                                    sources=sources)
    return QosTier(config, registry, flight, clock_us, controller=controller,
                   n_shards=n_shards)
