"""Adaptive shed-pressure controller for the QoS admission tier.

Folds the host's REAL bottleneck signals into one normalized pressure
scalar (1.0 == "the lag target is being missed"), instead of a static
queue-depth bound:

  * loop-lag EWMA — fed by the scheduler's `lag_observer` hook (the same
    PR-9 signal behind `accord_loop_lag_us`), decayed toward zero with a
    configurable half-life so a recovered loop stops shedding without
    needing new timer fires to prove it;
  * loop saturation — `LoopHealth.saturated` (edge-triggered backlog
    alarm) floors pressure into the normal-shed band: a saturated loop
    sheds `normal` traffic too, not just `best_effort`;
  * WAL group-commit queue depth — when journaling is on, fsync is often
    the true bottleneck before the loop itself lags; depth/`wal_target`
    contributes linearly;
  * extra sources — arbitrary `() -> float` normalized-pressure callables.
    The sim wires the pipeline ingest depth here (its only deterministic
    backlog signal: virtual time never produces real loop lag).

Pressure is the MAX of the contributions — shedding tracks whichever
resource is the bottleneck right now.

Thread shape: `observe_lag` runs on the loop thread (scheduler hook);
`pressure()` runs on the loop thread too (from `QosTier.admit`).  The WAL
depth read crosses into the journal flush thread's territory — a lock-free
`len()` of the commit buffer, intentionally approximate (see
journal/wal.py `queue_depth`).
"""

from __future__ import annotations

from typing import Callable, Iterable, List, Optional


class PressureController:
    """Normalized admission pressure from live host signals."""

    __slots__ = ("config", "clock_us", "loop_health", "wal", "sources",
                 "_lag_ewma_us", "_lag_stamp_us")

    def __init__(self, config, clock_us, loop_health=None, wal=None,
                 sources: Iterable[Callable[[], float]] = ()):
        self.config = config
        self.clock_us = clock_us
        self.loop_health = loop_health
        self.wal = wal
        self.sources: List[Callable[[], float]] = list(sources)
        self._lag_ewma_us = 0.0
        self._lag_stamp_us = int(clock_us())

    # ------------------------------------------------------------ lag ewma --
    def _decayed(self, now_us: int) -> float:
        """Decay the lag EWMA by elapsed wall/virtual time (half-life from
        config) — recovery must not wait for the next late timer."""
        dt_s = (now_us - self._lag_stamp_us) * 1e-6
        if dt_s > 0:
            self._lag_ewma_us *= 0.5 ** (dt_s / self.config.ewma_half_life_s)
            self._lag_stamp_us = now_us
        return self._lag_ewma_us

    def observe_lag(self, lag_s: float) -> None:
        """One timer fired `lag_s` late (scheduler hook, loop thread)."""
        now = int(self.clock_us())
        current = self._decayed(now)
        lag_us = lag_s * 1e6
        if lag_us > current:
            # rise fast (half the gap per observation), decay on the clock
            self._lag_ewma_us = current + 0.5 * (lag_us - current)

    def lag_us(self, now_us: Optional[int] = None) -> float:
        """Current decayed loop-lag estimate, for retry_after hints."""
        if now_us is None:
            now_us = int(self.clock_us())
        return self._decayed(now_us)

    # ------------------------------------------------------------ pressure --
    def pressure(self, now_us: Optional[int] = None) -> float:
        if now_us is None:
            now_us = int(self.clock_us())
        cfg = self.config
        p = self._decayed(now_us) / cfg.lag_target_us
        lh = self.loop_health
        if lh is not None and lh.saturated:
            p = max(p, cfg.normal_pressure)
        wal = self.wal
        if wal is not None:
            p = max(p, wal.queue_depth() / cfg.wal_target)
        for src in self.sources:
            p = max(p, src())
        return p
