"""Writes: the applied effect set of a transaction (reference:
accord/primitives/Writes.java:32)."""

from __future__ import annotations

from typing import Optional

from accord_tpu.api.data import Write
from accord_tpu.primitives.keys import Keys, Ranges
from accord_tpu.primitives.timestamp import Timestamp, TxnId
from accord_tpu.utils.async_chains import AsyncResult, all_of, success


class Writes:
    __slots__ = ("txn_id", "execute_at", "keys", "write")

    def __init__(self, txn_id: TxnId, execute_at: Timestamp, keys: Keys,
                 write: Optional[Write]):
        self.txn_id = txn_id
        self.execute_at = execute_at
        self.keys = keys
        self.write = write

    @property
    def is_empty(self) -> bool:
        return self.write is None or not self.keys

    def apply(self, store, within: Ranges = None) -> AsyncResult[None]:
        """Apply per-key writes to the DataStore (chained async, Writes.apply)."""
        if self.is_empty:
            return success(None)
        keys = self.keys if within is None else self.keys.slice(within)
        pending = [self.write.apply(k, self.execute_at, store) for k in keys]
        if not pending:
            return success(None)
        return all_of(pending).map(lambda _: None)

    def slice(self, ranges: Ranges) -> "Writes":
        return Writes(self.txn_id, self.execute_at, self.keys.slice(ranges),
                      self.write)

    def merge(self, other: "Writes") -> "Writes":
        """Reunite per-shard slices (the `write` payload is the full effect
        object on every replica; only `keys` is sliced)."""
        if other is None or other.keys == self.keys:
            return self
        return Writes(self.txn_id, self.execute_at,
                      self.keys.with_(other.keys),
                      self.write if self.write is not None else other.write)

    def __repr__(self):
        return f"Writes({self.txn_id!r}@{self.execute_at!r}, {self.keys!r})"
