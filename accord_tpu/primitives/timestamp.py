"""Hybrid-logical-clock timestamps and transaction identity.

Reference: accord/primitives/Timestamp.java:27-140 (bit layout :36-44,80-90),
TxnId.java:32,124-157, Ballot.java:23, Txn.java:53-265 (kind conflict matrix
:220-260).

Bit layout follows the reference's 128-bit packing so timestamps round-trip
losslessly to a pair of int64 device lanes (accord_tpu.ops.timestamps):
    msb = epoch(48b) | hlc_high(16b)
    lsb = hlc_low(48b) | flags(16b)      flags: REJECTED=0x8000, domain(1b), kind(3b)
plus a 32-bit node id used as the final comparison tie-breaker.
"""

from __future__ import annotations

import enum
from typing import Optional, Tuple

from accord_tpu.utils import invariants

FLAG_REJECTED = 0x8000
_KIND_SHIFT = 1          # bits 1..3 of flags
_KIND_MASK = 0b111 << _KIND_SHIFT
_DOMAIN_MASK = 0b1       # bit 0 of flags
_HLC_LOW_BITS = 48
_HLC_LOW_MASK = (1 << _HLC_LOW_BITS) - 1
_EPOCH_BITS = 48
MAX_EPOCH = (1 << _EPOCH_BITS) - 1


class Domain(enum.IntEnum):
    KEY = 0
    RANGE = 1


class TxnKind(enum.IntEnum):
    """Transaction kinds (reference Txn.Kind, Txn.java:53).

    The conflict matrix (witnesses/witnessedBy, Txn.java:220-260) decides which
    prior transactions appear in a new transaction's dependency set.
    """

    READ = 1
    WRITE = 2
    EPHEMERAL_READ = 3
    SYNC_POINT = 4
    EXCLUSIVE_SYNC_POINT = 5
    LOCAL_ONLY = 6

    def witnesses(self) -> "KindSet":
        return _WITNESSES[self]

    def witnessed_by(self) -> "KindSet":
        return _WITNESSED_BY[self]

    @property
    def is_write(self) -> bool:
        return self is TxnKind.WRITE or self is TxnKind.EXCLUSIVE_SYNC_POINT

    @property
    def is_read(self) -> bool:
        return self in (TxnKind.READ, TxnKind.EPHEMERAL_READ)

    @property
    def is_sync_point(self) -> bool:
        return self in (TxnKind.SYNC_POINT, TxnKind.EXCLUSIVE_SYNC_POINT)

    @property
    def is_globally_visible(self) -> bool:
        """Can this txn appear in other txns' deps? (Txn.java AnyGloballyVisible)"""
        return self not in (TxnKind.EPHEMERAL_READ, TxnKind.LOCAL_ONLY)

    @property
    def awaits_only_deps(self) -> bool:
        """Sync points execute once deps apply; they have no data read/write."""
        return self.is_sync_point


class KindSet(frozenset):
    """A set of TxnKinds with a packed-int device encoding."""

    def test(self, kind: TxnKind) -> bool:
        return kind in self

    def mask(self) -> int:
        # cached per instance: the protocol's KindSets are the handful of
        # module constants below, and the hot per-key scans test kind
        # membership via this mask instead of a frozenset hash per entry
        try:
            return self._mask
        except AttributeError:
            m = 0
            for k in self:
                m |= 1 << int(k)
            self._mask = m
            return m


WRITES = KindSet({TxnKind.WRITE, TxnKind.EXCLUSIVE_SYNC_POINT})
READS_OR_WRITES = KindSet({TxnKind.READ, TxnKind.WRITE,
                           TxnKind.EXCLUSIVE_SYNC_POINT})
ANY_GLOBALLY_VISIBLE = KindSet({TxnKind.READ, TxnKind.WRITE, TxnKind.SYNC_POINT,
                                TxnKind.EXCLUSIVE_SYNC_POINT})
NONE_KINDS = KindSet()

# what each kind's deps must include (Txn.java:220-260: Reads witness Ws;
# Writes witness RsOrWs; ESP witnesses AnyGloballyVisible).
_WITNESSES = {
    TxnKind.READ: WRITES,
    TxnKind.WRITE: READS_OR_WRITES,
    TxnKind.EPHEMERAL_READ: WRITES,
    TxnKind.SYNC_POINT: READS_OR_WRITES,
    TxnKind.EXCLUSIVE_SYNC_POINT: ANY_GLOBALLY_VISIBLE,
    TxnKind.LOCAL_ONLY: NONE_KINDS,
}

_WITNESSED_BY = {
    k: KindSet({o for o in TxnKind if k in _WITNESSES[o]}) for k in TxnKind
}

# -- hot-path lookup tables (derived; the dicts above stay the single source
#    of truth).  Enum by-value construction costs ~µs per call and the
#    protocol engine resolves kind/domain/witnesses tens of millions of
#    times per burn — a tuple index is ~50ns.
_KIND_BY_INT = tuple(TxnKind(i) if any(int(k) == i for k in TxnKind) else None
                     for i in range(8))
_DOMAIN_BY_INT = (Domain.KEY, Domain.RANGE)
# _WITNESS_BITS[kind_int] bit j set <=> kind witnesses TxnKind(j)
_WITNESS_BITS = tuple(
    sum(1 << int(o) for o in _WITNESSES[_KIND_BY_INT[i]])
    if _KIND_BY_INT[i] is not None else 0
    for i in range(8))


class Timestamp:
    """Immutable 128-bit HLC timestamp + node id.

    Total order: (epoch, hlc, flags, node) lexicographic — identical to the
    reference's msb/lsb/node compare (Timestamp.java compareTo).
    """

    __slots__ = ("epoch", "hlc", "flags", "node", "_cmp", "_repr")

    def __init__(self, epoch: int, hlc: int, flags: int, node: int):
        # inline packing-range validation (one branch, no helper-call
        # frames): timestamps are constructed on every wire decode / HLC
        # advance, so the two check_argument calls here were measurable
        if (epoch | hlc | flags | node) < 0 or epoch > MAX_EPOCH \
                or hlc >> 64 or flags >> 16 or node >> 32:
            invariants.check_argument(0 <= epoch <= MAX_EPOCH,
                                      "epoch out of range")
            invariants.check_argument(
                False, "timestamp component out of packing range")
        self.epoch = epoch
        self.hlc = hlc
        self.flags = flags
        self.node = node
        # packed total-order key: one int comparison per <=> instead of a
        # tuple build (timestamp compares dominate the host engine — ~45%
        # of a deep apply-chain profile before this)
        self._cmp = ((((epoch << 80) | hlc) << 16) | flags) << 32 | node

    # -- construction --
    @classmethod
    def from_bits(cls, epoch: int, hlc: int, flags: int, node: int) -> "Timestamp":
        return cls(epoch, hlc, flags, node)

    @classmethod
    def none(cls) -> "Timestamp":
        return NONE

    @classmethod
    def max_value(cls) -> "Timestamp":
        return MAX

    def with_epoch_at_least(self, epoch: int) -> "Timestamp":
        return self if epoch <= self.epoch else type(self)(epoch, self.hlc, self.flags, self.node)

    def with_flags(self, flags: int) -> "Timestamp":
        return type(self)(self.epoch, self.hlc, flags, self.node)

    def as_rejected(self) -> "Timestamp":
        return self.with_flags(self.flags | FLAG_REJECTED)

    @property
    def is_rejected(self) -> bool:
        return bool(self.flags & FLAG_REJECTED)

    def next_hlc(self) -> "Timestamp":
        return Timestamp(self.epoch, self.hlc + 1, 0, self.node)

    # -- packing (device lanes; reference bit layout Timestamp.java:36-44) --
    def msb(self) -> int:
        return (self.epoch << 16) | ((self.hlc >> _HLC_LOW_BITS) & 0xFFFF)

    def lsb(self) -> int:
        return ((self.hlc & _HLC_LOW_MASK) << 16) | (self.flags & 0xFFFF)

    def pack(self) -> Tuple[int, int, int]:
        return (self.msb(), self.lsb(), self.node)

    @classmethod
    def unpack(cls, msb: int, lsb: int, node: int) -> "Timestamp":
        epoch = msb >> 16
        hlc = ((msb & 0xFFFF) << _HLC_LOW_BITS) | (lsb >> 16)
        return cls(epoch, hlc, lsb & 0xFFFF, node)

    # -- ordering (all via the packed key) --
    def __lt__(self, other): return self._cmp < other._cmp
    def __le__(self, other): return self._cmp <= other._cmp
    def __gt__(self, other): return self._cmp > other._cmp
    def __ge__(self, other): return self._cmp >= other._cmp

    def __eq__(self, other):
        return isinstance(other, Timestamp) and self._cmp == other._cmp

    def __hash__(self):
        return hash(self._cmp)

    def compare_to(self, other: "Timestamp") -> int:
        a, b = self._cmp, other._cmp
        return -1 if a < b else (1 if a > b else 0)

    @staticmethod
    def max(a: "Timestamp", b: "Timestamp") -> "Timestamp":
        return a if a >= b else b

    @staticmethod
    def min(a: "Timestamp", b: "Timestamp") -> "Timestamp":
        return a if a <= b else b

    @staticmethod
    def non_null_or_max(a: Optional["Timestamp"], b: Optional["Timestamp"]):
        if a is None:
            return b
        if b is None:
            return a
        return Timestamp.max(a, b)

    def merge_max(self, other: "Timestamp") -> "Timestamp":
        """Component-wise dominance merge used by HLC propagation."""
        return self if self >= other else other

    def __repr__(self):
        return f"[{self.epoch},{self.hlc},{self.flags:x},{self.node}]"


class TxnId(Timestamp):
    """Timestamp whose flags carry Txn kind (3b) + domain (1b).

    Reference: TxnId.java:32,124-157.
    """

    __slots__ = ()

    def __init__(self, epoch: int, hlc: int, flags: int, node: int):
        super().__init__(epoch, hlc, flags, node)
        # validate kind bits at the source (unpack/wire paths take flags
        # verbatim): a lookup-table miss would otherwise surface later as a
        # silently thinner deps set.  flags == 0 is the NONE sentinel.
        if flags and _KIND_BY_INT[(flags & _KIND_MASK) >> _KIND_SHIFT] is None:
            invariants.check_argument(
                False, "invalid TxnKind bits in flags %s", flags)

    @classmethod
    def create(cls, epoch: int, hlc: int, kind: TxnKind, domain: Domain,
               node: int) -> "TxnId":
        flags = (int(kind) << _KIND_SHIFT) | int(domain)
        return cls(epoch, hlc, flags, node)

    @classmethod
    def from_timestamp(cls, ts: Timestamp) -> "TxnId":
        return cls(ts.epoch, ts.hlc, ts.flags, ts.node)

    @property
    def kind(self) -> TxnKind:
        k = _KIND_BY_INT[(self.flags & _KIND_MASK) >> _KIND_SHIFT]
        if k is None:  # the NONE sentinel has no kind (matches TxnKind(0))
            raise ValueError(f"no TxnKind in flags {self.flags:#x}")
        return k

    @property
    def domain(self) -> Domain:
        return _DOMAIN_BY_INT[self.flags & _DOMAIN_MASK]

    @property
    def is_key_domain(self) -> bool:
        return not (self.flags & _DOMAIN_MASK)

    @property
    def is_range_domain(self) -> bool:
        return bool(self.flags & _DOMAIN_MASK)

    @property
    def is_write(self) -> bool:
        return self.kind.is_write

    @property
    def is_visible(self) -> bool:
        return self.kind.is_globally_visible

    def witnesses(self, other: "TxnId") -> bool:
        """Must `other` (an earlier txn) appear in this txn's deps?"""
        return bool(_WITNESS_BITS[(self.flags & _KIND_MASK) >> _KIND_SHIFT]
                    >> ((other.flags & _KIND_MASK) >> _KIND_SHIFT) & 1)

    def witnessed_by(self, other_kind: TxnKind) -> bool:
        return other_kind in self.kind.witnessed_by()

    def as_timestamp(self) -> Timestamp:
        return Timestamp(self.epoch, self.hlc, self.flags, self.node)

    def __repr__(self):
        # cached lazily (unset slot -> AttributeError): the repr IS the
        # trace/flight key, recomputed per status transition and span event
        # for the same long-lived TxnId instance
        try:
            return self._repr
        except AttributeError:
            pass
        if self._cmp == 0:
            s = "TxnId.NONE"
        else:
            s = (f"{self.kind.name[0]}{'R' if self.is_range_domain else ''}"
                 f"[{self.epoch},{self.hlc},{self.node}]")
        self._repr = s
        return s


class Ballot(Timestamp):
    """Paxos-style promise ballot (reference Ballot.java:23)."""

    __slots__ = ()

    ZERO: "Ballot"

    @classmethod
    def zero(cls) -> "Ballot":
        return BALLOT_ZERO

    def __repr__(self):
        return f"B[{self.epoch},{self.hlc},{self.node}]"


NONE = Timestamp(0, 0, 0, 0)
MAX = Timestamp(MAX_EPOCH, (1 << 63) - 1, 0xFFFF, (1 << 31) - 1)
BALLOT_ZERO = Ballot(0, 0, 0, 0)
Ballot.ZERO = BALLOT_ZERO
TXNID_NONE = TxnId(0, 0, 0, 0)
