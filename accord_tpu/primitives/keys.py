"""The Routables type family: keys, ranges, routes.

Reference: accord/primitives/Routables.java:35, Seekables.java, Unseekables.java,
Route.java:25, AbstractKeys.java, AbstractRanges.java, Range.java, and the api
key model (accord/api/Key.java:28, RoutingKey.java:26).

Two domains — KEY and RANGE — and two roles: *seekable* (data-addressing: Key,
Ranges used by the data plane) vs *unseekable* (position-only routing). Our
keys carry an integer token (the position); hosts may subclass Key to attach
richer identity, exactly as C* does with its partition keys.
"""

from __future__ import annotations

import bisect
from typing import Callable, Iterable, Iterator, List, Optional, Sequence, Tuple

from accord_tpu.utils import invariants
from accord_tpu.utils.sorted_arrays import (
    linear_intersection, linear_subtract, linear_union,
)


class RoutingKey:
    """Position-only key (unseekable): orders by token. Reference RoutingKey.java:26."""

    __slots__ = ("token",)

    def __init__(self, token: int):
        self.token = token

    def __lt__(self, other): return self.token < other.token
    def __le__(self, other): return self.token <= other.token
    def __gt__(self, other): return self.token > other.token
    def __ge__(self, other): return self.token >= other.token

    def __eq__(self, other):
        return isinstance(other, RoutingKey) and self.token == other.token

    def __hash__(self):
        return hash(self.token)

    def __repr__(self):
        return f"k{self.token}"

    def as_routing(self) -> "RoutingKey":
        return RoutingKey(self.token)


class Key(RoutingKey):
    """Data key (seekable). Hosts subclass to attach payload identity.
    Reference api/Key.java:28 (Key extends Seekable, RoutableKey)."""

    __slots__ = ()

    def __repr__(self):
        return f"K{self.token}"


class _SortedKeyList:
    """Base for Keys/RoutingKeys: immutable sorted unique key sequence."""

    __slots__ = ("_keys", "_tokens")
    _elem = RoutingKey

    def __init__(self, keys: Iterable[RoutingKey] = (), _presorted: bool = False):
        ks = list(keys)
        if not _presorted:
            ks = sorted(set(ks), key=lambda k: k.token)
        self._keys: Tuple[RoutingKey, ...] = tuple(ks)
        # parallel token tuple, built lazily: membership/slice queries then
        # bisect over plain ints (one C call) instead of rich-compared key
        # objects through the generic binary-search wrappers
        self._tokens: Optional[Tuple[int, ...]] = None

    def _tok(self) -> Tuple[int, ...]:
        # try/except rather than a None test: wire-decoded instances may
        # restore only the _keys slot, leaving this one unset
        try:
            t = self._tokens
        except AttributeError:
            t = None
        if t is None:
            t = self._tokens = tuple(k.token for k in self._keys)
        return t

    # -- sequence protocol --
    def __len__(self): return len(self._keys)
    def __iter__(self) -> Iterator[RoutingKey]: return iter(self._keys)
    def __getitem__(self, i): return self._keys[i]
    def __bool__(self): return bool(self._keys)

    def __eq__(self, other):
        return type(self) is type(other) and self._keys == other._keys

    def __hash__(self):
        return hash(self._keys)

    def __repr__(self):
        return f"{type(self).__name__}{list(self._keys)!r}"

    @property
    def is_empty(self) -> bool:
        return not self._keys

    def tokens(self) -> List[int]:
        return [k.token for k in self._keys]

    def contains(self, key: RoutingKey) -> bool:
        toks = self._tok()
        i = bisect.bisect_left(toks, key.token)
        return i < len(toks) and toks[i] == key.token

    def find(self, key: RoutingKey) -> int:
        """Index of key, or -(insertion)-1."""
        toks = self._tok()
        i = bisect.bisect_left(toks, key.token)
        if i < len(toks) and toks[i] == key.token:
            return i
        return -(i + 1)

    # -- set algebra (sorted merges) --
    def with_(self, other: "_SortedKeyList") -> "_SortedKeyList":
        return type(self)(linear_union(self._keys, other._keys), _presorted=True)

    def intersecting(self, other: "_SortedKeyList") -> "_SortedKeyList":
        return type(self)(linear_intersection(self._keys, other._keys), _presorted=True)

    def subtract(self, other: "_SortedKeyList") -> "_SortedKeyList":
        return type(self)(linear_subtract(self._keys, other._keys), _presorted=True)

    def slice(self, ranges: "Ranges") -> "_SortedKeyList":
        toks = self._tok()
        out: List[RoutingKey] = []
        for r in ranges:
            lo = bisect.bisect_left(toks, r.start)
            hi = bisect.bisect_left(toks, r.end, lo)
            if lo < hi:
                out.extend(self._keys[lo:hi])
        if len(out) == len(self._keys):
            return self  # fully covered: immutable, reuse
        if not out:
            cls = type(self)
            empty = cls.__dict__.get("_EMPTY")
            if empty is None:
                empty = cls()
                cls._EMPTY = empty
            return empty
        return type(self)(out, _presorted=True)

    def intersects_ranges(self, ranges: "Ranges") -> bool:
        toks = self._tok()
        for r in ranges:
            lo = bisect.bisect_left(toks, r.start)
            if lo < len(toks) and toks[lo] < r.end:
                return True
        return False

    def foldl(self, fn: Callable, acc):
        for k in self._keys:
            acc = fn(acc, k)
        return acc

    def to_ranges(self) -> "Ranges":
        """Minimal covering Ranges: one unit range per key, adjacent tokens
        merged inline (exactly what normalization would produce, without
        the per-key Range churn — this runs per destination per send via
        Route.covering)."""
        out: List[Range] = []
        start = prev = None
        for k in self._keys:
            t = k.token
            if prev is not None and t == prev + 1:
                prev = t
                continue
            if prev is not None:
                out.append(Range(start, prev + 1))
            start = prev = t
        if prev is not None:
            out.append(Range(start, prev + 1))
        return Ranges(out, _normalized=True)


class Keys(_SortedKeyList):
    """Sorted unique data keys (seekable). Reference primitives/Keys.java."""
    _elem = Key

    def __init__(self, keys: Iterable[Key] = (), _presorted: bool = False):
        super().__init__(keys, _presorted=_presorted)

    @classmethod
    def of(cls, *tokens: int) -> "Keys":
        return cls([Key(t) for t in tokens])

    def as_routing(self) -> "RoutingKeys":
        return RoutingKeys([RoutingKey(k.token) for k in self._keys], _presorted=True)


class RoutingKeys(_SortedKeyList):
    """Sorted unique routing keys (unseekable). Reference primitives/RoutingKeys.java."""

    @classmethod
    def of(cls, *tokens: int) -> "RoutingKeys":
        return cls([RoutingKey(t) for t in tokens])

    def as_routing(self) -> "RoutingKeys":
        return self


EMPTY_KEYS = Keys(())


class Range:
    """Half-open token range [start, end). Reference primitives/Range.java
    (the reference supports both end-inclusive/exclusive variants; we fix
    start-inclusive/end-exclusive, which is the variant its tests exercise)."""

    __slots__ = ("start", "end")

    def __init__(self, start: int, end: int):
        invariants.check_argument(start < end, "range start must precede end")
        self.start = start
        self.end = end

    def contains(self, key: RoutingKey) -> bool:
        return self.start <= key.token < self.end

    def contains_token(self, token: int) -> bool:
        return self.start <= token < self.end

    def intersects(self, other: "Range") -> bool:
        return self.start < other.end and other.start < self.end

    def contains_range(self, other: "Range") -> bool:
        return self.start <= other.start and other.end <= self.end

    def intersection(self, other: "Range") -> Optional["Range"]:
        s, e = max(self.start, other.start), min(self.end, other.end)
        return Range(s, e) if s < e else None

    def _key(self):
        return (self.start, self.end)

    def __lt__(self, other): return self._key() < other._key()
    def __le__(self, other): return self._key() <= other._key()

    def __eq__(self, other):
        return isinstance(other, Range) and self._key() == other._key()

    def __hash__(self):
        return hash(self._key())

    def __repr__(self):
        return f"[{self.start},{self.end})"


class Ranges:
    """Sorted, deoverlapped range set. Reference primitives/Ranges.java /
    AbstractRanges.java."""

    __slots__ = ("_ranges", "_starts")

    def __init__(self, ranges: Iterable[Range] = (), _normalized: bool = False):
        rs = list(ranges)
        if not _normalized:
            rs = self._normalize(rs)
        self._ranges: Tuple[Range, ...] = tuple(rs)
        # bisect index for _find_containing, built lazily: Ranges are
        # constructed ~200k times per hostile burn (slices/intersections)
        # and most are never point-probed
        self._starts: Optional[Tuple[int, ...]] = None

    @staticmethod
    def _normalize(rs: List[Range]) -> List[Range]:
        if not rs:
            return []
        rs = sorted(rs, key=lambda r: (r.start, r.end))
        out = [rs[0]]
        for r in rs[1:]:
            last = out[-1]
            if r.start <= last.end:  # overlap or adjacency -> merge
                if r.end > last.end:
                    out[-1] = Range(last.start, r.end)
            else:
                out.append(r)
        return out

    @classmethod
    def of(cls, *pairs: Tuple[int, int]) -> "Ranges":
        return cls([Range(s, e) for s, e in pairs])

    @classmethod
    def single(cls, start: int, end: int) -> "Ranges":
        return cls([Range(start, end)])

    EMPTY: "Ranges"

    def __len__(self): return len(self._ranges)
    def __iter__(self) -> Iterator[Range]: return iter(self._ranges)
    def __getitem__(self, i): return self._ranges[i]
    def __bool__(self): return bool(self._ranges)

    def __eq__(self, other):
        return isinstance(other, Ranges) and self._ranges == other._ranges

    def __hash__(self):
        return hash(self._ranges)

    def __repr__(self):
        return f"Ranges{list(self._ranges)!r}"

    @property
    def is_empty(self) -> bool:
        return not self._ranges

    def contains(self, key: RoutingKey) -> bool:
        return self._find_containing(key.token) is not None

    def contains_token(self, token: int) -> bool:
        return self._find_containing(token) is not None

    def _find_containing(self, token: int) -> Optional[Range]:
        starts = self._starts
        if starts is None:
            starts = self._starts = tuple(r.start for r in self._ranges)
        i = bisect.bisect_right(starts, token) - 1
        if i >= 0 and self._ranges[i].contains_token(token):
            return self._ranges[i]
        return None

    def intersects(self, other) -> bool:
        if isinstance(other, Ranges):
            i = j = 0
            while i < len(self._ranges) and j < len(other._ranges):
                a, b = self._ranges[i], other._ranges[j]
                if a.intersects(b):
                    return True
                if a.end <= b.start:
                    i += 1
                else:
                    j += 1
            return False
        if isinstance(other, _SortedKeyList):
            return other.intersects_ranges(self)
        if isinstance(other, Range):
            return any(r.intersects(other) for r in self._ranges)
        raise TypeError(type(other))

    def intersection(self, other: "Ranges") -> "Ranges":
        out: List[Range] = []
        i = j = 0
        while i < len(self._ranges) and j < len(other._ranges):
            a, b = self._ranges[i], other._ranges[j]
            x = a.intersection(b)
            if x is not None:
                out.append(x)
            if a.end <= b.end:
                i += 1
            else:
                j += 1
        return Ranges(out, _normalized=True)

    # intersection is slicing for ranges
    slice = intersection

    def union(self, other: "Ranges") -> "Ranges":
        return Ranges(list(self._ranges) + list(other._ranges))

    def subtract(self, other: "Ranges") -> "Ranges":
        out: List[Range] = []
        for a in self._ranges:
            pieces = [a]
            for b in other._ranges:
                nxt: List[Range] = []
                for p in pieces:
                    if not p.intersects(b):
                        nxt.append(p)
                        continue
                    if p.start < b.start:
                        nxt.append(Range(p.start, b.start))
                    if b.end < p.end:
                        nxt.append(Range(b.end, p.end))
                pieces = nxt
            out.extend(pieces)
        return Ranges(out)

    def contains_all_keys(self, keys: _SortedKeyList) -> bool:
        return all(self.contains(k) for k in keys)

    def contains_all_ranges(self, other: "Ranges") -> bool:
        return other.subtract(self).is_empty


Ranges.EMPTY = Ranges(())


class Route:
    """Routing cover for a transaction: participating routing keys + the home
    key (the shard that owns coordination/recovery responsibility).

    Reference: primitives/Route.java:25 (FullKeyRoute/PartialKeyRoute/
    FullRangeRoute/PartialRangeRoute). We model key- and range-domain routes
    with one class carrying either keys or ranges; `is_full` marks whether it
    covers the whole transaction (a Full route) or a shard slice (Partial).
    """

    __slots__ = ("home_key", "keys", "ranges", "is_full")

    def __init__(self, home_key: RoutingKey, keys: Optional[RoutingKeys] = None,
                 ranges: Optional[Ranges] = None, is_full: bool = True):
        invariants.check_argument((keys is None) != (ranges is None),
                                  "route holds keys xor ranges")
        self.home_key = home_key
        self.keys = keys
        self.ranges = ranges
        self.is_full = is_full

    @classmethod
    def of_keys(cls, home_key: RoutingKey, keys: RoutingKeys) -> "Route":
        return cls(home_key, keys=keys)

    @classmethod
    def of_ranges(cls, home_key: RoutingKey, ranges: Ranges) -> "Route":
        return cls(home_key, ranges=ranges)

    @classmethod
    def probe(cls, participants) -> "Route":
        """Partial route over bare participants (Keys/RoutingKeys/Ranges),
        for rounds that only need to reach the owning shards — route
        discovery (FindRoute's someUnseekables) and watermark queries. The
        nominal home key is the first participant."""
        if isinstance(participants, Ranges):
            return cls(RoutingKey(participants[0].start),
                       ranges=participants, is_full=False)
        routing = participants.as_routing()
        return cls(routing[0], keys=routing, is_full=False)

    @property
    def is_key_domain(self) -> bool:
        return self.keys is not None

    def participants(self):
        return self.keys if self.keys is not None else self.ranges

    def covering(self) -> Ranges:
        """Minimal Ranges covering the participants."""
        if self.ranges is not None:
            return self.ranges
        return self.keys.to_ranges()

    def participant_keys(self) -> "Keys":
        """Data-key view of a key-domain route (empty for range routes)."""
        if self.keys is None:
            return Keys(())
        return Keys([Key(k.token) for k in self.keys])

    def slice(self, ranges: Ranges) -> "Route":
        if self.keys is not None:
            return Route(self.home_key, keys=self.keys.slice(ranges), is_full=False)
        return Route(self.home_key, ranges=self.ranges.slice(ranges), is_full=False)

    def owned_participants(self, ranges: Ranges):
        """Participants falling within a store's owned `ranges`; the full
        participant set for an unbounded (empty-ranges) store. The shared
        idiom for 'what slice of this route does this store answer for'."""
        if ranges.is_empty:
            return self.participants()
        return self.slice(ranges).participants()

    def with_(self, other: "Route") -> "Route":
        invariants.check_argument(other.home_key == self.home_key, "home key mismatch")
        if self.keys is not None:
            return Route(self.home_key, keys=self.keys.with_(other.keys),
                         is_full=self.is_full or other.is_full)
        return Route(self.home_key, ranges=self.ranges.union(other.ranges),
                     is_full=self.is_full or other.is_full)

    def intersects(self, ranges: Ranges) -> bool:
        if self.keys is not None:
            return self.keys.intersects_ranges(ranges)
        return self.ranges.intersects(ranges)

    def contains(self, key: RoutingKey) -> bool:
        if self.keys is not None:
            return self.keys.contains(key)
        return self.ranges.contains(key)

    def __eq__(self, other):
        return (isinstance(other, Route) and self.home_key == other.home_key
                and self.keys == other.keys and self.ranges == other.ranges
                and self.is_full == other.is_full)

    def __hash__(self):
        return hash((self.home_key, self.keys, self.ranges, self.is_full))

    def __repr__(self):
        body = self.keys if self.keys is not None else self.ranges
        return f"Route(home={self.home_key}, {body!r}, full={self.is_full})"
