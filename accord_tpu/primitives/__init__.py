"""Protocol data model (reference: accord/primitives — SURVEY.md §2.2)."""

from accord_tpu.primitives.timestamp import (
    Timestamp, TxnId, Ballot, TxnKind, Domain, KindSet,
)
from accord_tpu.primitives.keys import (
    RoutingKey, Key, Keys, RoutingKeys, Range, Ranges, Route,
)
from accord_tpu.primitives.deps import KeyDeps, RangeDeps, Deps
from accord_tpu.primitives.txn import Txn, PartialTxn
from accord_tpu.primitives.writes import Writes
