"""Txn / PartialTxn: the transaction payload (reference: accord/primitives/Txn.java:267-411,
PartialTxn.java).

A Txn = kind + keys (or ranges) + data-plane ports (Read, Query, Update). The
protocol slices it to per-shard PartialTxns and drives read/execute through the
opaque ports.
"""

from __future__ import annotations

from typing import Optional

from accord_tpu.api.data import Data, Query, Read, Result, Update, Write
from accord_tpu.primitives.keys import Keys, Ranges, Route
from accord_tpu.primitives.timestamp import Timestamp, TxnId, TxnKind
from accord_tpu.utils import invariants
from accord_tpu.utils.async_chains import AsyncResult, all_of, success


class Txn:
    __slots__ = ("kind", "keys", "read", "query", "update")

    def __init__(self, kind: TxnKind, keys, read: Optional[Read] = None,
                 query: Optional[Query] = None, update: Optional[Update] = None):
        self.kind = kind
        self.keys = keys  # Keys (key-domain) or Ranges (range-domain)
        self.read = read
        self.query = query
        self.update = update

    # -- shape queries --
    @property
    def is_key_domain(self) -> bool:
        return isinstance(self.keys, Keys)

    @property
    def is_write(self) -> bool:
        return self.kind.is_write

    def covering(self) -> Ranges:
        if isinstance(self.keys, Ranges):
            return self.keys
        return self.keys.to_ranges()

    # -- slicing (per-shard partials; Txn.slice) --
    def slice(self, ranges: Ranges, include_query: bool) -> "PartialTxn":
        keys = self.keys.slice(ranges)
        if keys is self.keys and include_query \
                and type(self) is PartialTxn:
            # fully covered (Keys.slice returns the same object): the
            # read/update key sets are subsets, so their slices are full
            # too — reuse the immutable whole.  (A full Txn must still
            # downgrade to a PartialTxn: callers merge partials via with_.)
            return self
        return PartialTxn(
            self.kind, keys,
            read=self.read.slice(ranges) if self.read is not None else None,
            query=self.query if include_query else None,
            update=self.update.slice(ranges) if self.update is not None else None,
        )

    def intersects(self, ranges: Ranges) -> bool:
        if isinstance(self.keys, Ranges):
            return self.keys.intersects(ranges)
        return self.keys.intersects_ranges(ranges)

    # -- execution (Txn.java read()/execute()/result()) --
    def read_data(self, execute_at: Timestamp, store, on_keys: Keys = None
                  ) -> AsyncResult[Optional[Data]]:
        """Execute the read over `on_keys` (default: read.keys()) against the
        host DataStore; merges per-key Data fragments."""
        if self.read is None:
            return success(None)
        keys = on_keys if on_keys is not None else self.read.keys()
        reads = [self.read.read(k, execute_at, store) for k in keys]
        if not reads:
            return success(None)

        def merge_all(datas):
            acc = None
            for d in datas:
                if d is None:
                    continue
                acc = d if acc is None else acc.merge(d)
            return acc

        return all_of(reads).map(merge_all)

    def execute(self, txn_id: TxnId, execute_at: Timestamp,
                data: Optional[Data]) -> "Writes":
        """Compute Writes from read Data via Update (Txn.execute)."""
        from accord_tpu.primitives.writes import Writes
        if self.update is None:
            return Writes(txn_id, execute_at, Keys(()), None)
        write = self.update.apply(execute_at, data)
        return Writes(txn_id, execute_at, self.update.keys(), write)

    def result(self, txn_id: TxnId, execute_at: Timestamp,
               data: Optional[Data]) -> Result:
        invariants.non_null(self.query, "txn has no query")
        return self.query.compute(txn_id, execute_at, data, self.read, self.update)

    def __eq__(self, other):
        return (isinstance(other, Txn) and self.kind == other.kind
                and self.keys == other.keys and self.read == other.read
                and self.query == other.query and self.update == other.update)

    def __hash__(self):
        return hash((self.kind, self.keys))

    def __repr__(self):
        return f"Txn({self.kind.name}, {self.keys!r})"


class PartialTxn(Txn):
    """A Txn sliced to a shard's ranges (reference PartialTxn.java). Queries are
    retained only on the home shard's slice."""

    __slots__ = ()

    def covers(self, ranges: Ranges) -> bool:
        if isinstance(self.keys, Ranges):
            return self.keys.contains_all_ranges(ranges)
        # key-domain partial covers `ranges` iff it retains every key in them
        return True  # key slices retain exactly the keys in range; coverage checked at merge

    def with_(self, other: "PartialTxn") -> "PartialTxn":
        if self == other:
            return self
        keys = (self.keys.union(other.keys) if isinstance(self.keys, Ranges)
                else self.keys.with_(other.keys))
        return PartialTxn(
            self.kind, keys,
            read=(self.read.merge(other.read) if self.read and other.read
                  else self.read or other.read),
            query=self.query or other.query,
            update=(self.update.merge(other.update) if self.update and other.update
                    else self.update or other.update),
        )

    def reconstitute(self, route: Route) -> Txn:
        """Promote to a full Txn if this slice covers the whole route."""
        return Txn(self.kind, self.keys, self.read, self.query, self.update)
