"""Dependency sets in CSR form: KeyDeps, RangeDeps, Deps.

Reference: accord/primitives/KeyDeps.java:150-172 (CSR layout), :115-148
(merge), RangeDeps.java:63-120, Deps.java:36,98-124, and the shared helpers in
accord/utils/RelationMultiMap.java:58-80.

Layout (identical to the reference): sorted unique `keys`, sorted unique
`txn_ids`, and `keys_to_txn_ids` — the first len(keys) ints are *end offsets*
into the tail, the tail holds indices into txn_ids. This flat-int-array form is
deliberately the device format too: accord_tpu.ops consumes these arrays
zero-copy as int32 numpy buffers.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

from accord_tpu.primitives.keys import Key, Keys, Range, Ranges, RoutingKey, RoutingKeys
from accord_tpu.primitives.timestamp import TxnId
from accord_tpu.utils import invariants
from operator import attrgetter

from accord_tpu.utils.sorted_arrays import (find_ceil, linear_merge_n,
                                            linear_union)

# sort key for timestamp-like elements: C-level int compares on the packed
# total-order key instead of Python-level __lt__ dispatch per comparison
_CMP_KEY = attrgetter("_cmp")


def _build_csr(sorted_lhs: Sequence, lhs_to_sets: Dict, sorted_rhs: Sequence
               ) -> Tuple[int, ...]:
    """Build the [end-offsets..., value-indices...] CSR tail.

    Timestamps index by their packed `_cmp` int (hash/eq on plain ints take
    CPython's C fast path; the object forms dispatch to Python-level
    __hash__/__eq__ per probe, which made this dict build a top profile
    entry on the deps hot path).  Range lhs values have no `_cmp`; both
    element kinds sort by the same total order either way."""
    if sorted_rhs and hasattr(sorted_rhs[0], "_cmp"):
        rhs_index = {v._cmp: i for i, v in enumerate(sorted_rhs)}
        key_of = _CMP_KEY
    else:
        rhs_index = {v: i for i, v in enumerate(sorted_rhs)}
        key_of = None
    offsets: List[int] = []
    values: List[int] = []
    for lhs in sorted_lhs:
        ids = sorted(lhs_to_sets[lhs], key=key_of)
        if key_of is not None:
            values.extend(rhs_index[t._cmp] for t in ids)
        else:
            values.extend(rhs_index[t] for t in ids)
        offsets.append(len(sorted_lhs) + len(values))
    return tuple(offsets + values)


class KeyDeps:
    """key -> [TxnId] bidirectional multimap in CSR form (KeyDeps.java:150-172)."""

    __slots__ = ("keys", "txn_ids", "keys_to_txn_ids", "_inverse")

    def __init__(self, keys: Keys, txn_ids: Tuple[TxnId, ...],
                 keys_to_txn_ids: Tuple[int, ...]):
        self.keys = keys
        self.txn_ids = txn_ids
        self.keys_to_txn_ids = keys_to_txn_ids
        self._inverse: Optional[Tuple[Tuple[int, ...], ...]] = None  # lazy txn->keys

    # -- construction --
    NONE: "KeyDeps"

    class Builder:
        def __init__(self):
            self._map: Dict[Key, Set[TxnId]] = {}

        def add(self, key: Key, txn_id: TxnId) -> "KeyDeps.Builder":
            s = self._map.get(key)
            if s is None:
                s = self._map[key] = set()
            s.add(txn_id)
            return self

        def add_all(self, keys: Iterable[Key], txn_id: TxnId) -> "KeyDeps.Builder":
            for k in keys:
                self.add(k, txn_id)
            return self

        def is_empty(self) -> bool:
            return not self._map

        def build(self) -> "KeyDeps":
            if not self._map:
                return KeyDeps.NONE
            if len(self._map) == 1:
                # single-key deps (the common shape of a key txn's
                # calculate_deps): the CSR is the identity mapping
                (k, ids), = self._map.items()
                pool = tuple(sorted(ids, key=_CMP_KEY))
                n = len(pool)
                return KeyDeps(Keys((k,), _presorted=True), pool,
                               (1 + n,) + tuple(range(n)))
            keys = Keys(self._map.keys())
            all_ids = sorted(set().union(*self._map.values()), key=_CMP_KEY)
            csr = _build_csr(list(keys), self._map, all_ids)
            return KeyDeps(keys, tuple(all_ids), csr)

    @classmethod
    def builder(cls) -> "KeyDeps.Builder":
        return cls.Builder()

    @classmethod
    def of(cls, mapping: Dict[Key, Iterable[TxnId]]) -> "KeyDeps":
        b = cls.Builder()
        for k, ids in mapping.items():
            for t in ids:
                b.add(k, t)
        return b.build()

    # -- accessors --
    @property
    def is_empty(self) -> bool:
        return not self.keys

    def txn_id_count(self) -> int:
        return len(self.txn_ids)

    def key_count(self) -> int:
        return len(self.keys)

    def _span(self, key_idx: int) -> Tuple[int, int]:
        nk = len(self.keys)
        start = self.keys_to_txn_ids[key_idx - 1] if key_idx > 0 else nk
        end = self.keys_to_txn_ids[key_idx]
        return start, end

    def txn_ids_for_key(self, key) -> List[TxnId]:
        i = self.keys.find(key)
        if i < 0:
            return []
        s, e = self._span(i)
        return [self.txn_ids[self.keys_to_txn_ids[j]] for j in range(s, e)]

    def for_each(self, key, fn: Callable[[TxnId], None]) -> None:
        for t in self.txn_ids_for_key(key):
            fn(t)

    def for_each_unique_txn_id(self, fn: Callable[[TxnId], None]) -> None:
        for t in self.txn_ids:
            fn(t)

    def contains(self, txn_id: TxnId) -> bool:
        i = find_ceil(self.txn_ids, txn_id)
        return i < len(self.txn_ids) and self.txn_ids[i] == txn_id

    def _invert(self) -> Tuple[Tuple[int, ...], ...]:
        """txn-idx -> tuple of key indices (lazily computed; KeyDeps.java inverts
        the CSR the same way)."""
        if self._inverse is None:
            inv: List[List[int]] = [[] for _ in self.txn_ids]
            nk = len(self.keys)
            for ki in range(nk):
                s, e = self._span(ki)
                for j in range(s, e):
                    inv[self.keys_to_txn_ids[j]].append(ki)
            self._inverse = tuple(tuple(x) for x in inv)
        return self._inverse

    def participants(self, txn_id: TxnId) -> Keys:
        """Keys this txn participates in (reference participants(TxnId))."""
        i = find_ceil(self.txn_ids, txn_id)
        if i >= len(self.txn_ids) or self.txn_ids[i] != txn_id:
            return Keys(())
        return Keys([self.keys[ki] for ki in self._invert()[i]], _presorted=True)

    def participating_keys(self) -> Keys:
        return self.keys

    # -- algebra (linear CSR walks; reference RelationMultiMap.LinearMerger
    # merges the flat arrays the same way, no intermediate maps) --
    def _span_indices(self, ki: int) -> List[int]:
        s, e = self._span(ki)
        return [self.keys_to_txn_ids[j] for j in range(s, e)]

    def _remap_into(self, merged_ids: Sequence[TxnId]) -> List[int]:
        """positions of our (sorted) txn_ids within merged (sorted) ids."""
        remap: List[int] = []
        j = 0
        for t in self.txn_ids:
            while merged_ids[j] != t:
                j += 1
            remap.append(j)
        return remap

    @staticmethod
    def _from_spans(keys: List[Key], spans: List[List[int]],
                    id_pool: Sequence[TxnId]) -> "KeyDeps":
        """Assemble a CSR from per-key ascending id-index lists, compacting
        the id pool to the indices actually referenced."""
        if not keys:
            return KeyDeps.NONE
        used = sorted({i for span in spans for i in span})
        compact = {old: new for new, old in enumerate(used)}
        ids = tuple(id_pool[i] for i in used)
        nk = len(keys)
        ends: List[int] = []
        payload: List[int] = []
        off = nk
        for span in spans:
            payload.extend(compact[i] for i in span)
            off += len(span)
            ends.append(off)
        return KeyDeps(Keys(keys, _presorted=True), ids,
                       tuple(ends + payload))

    def with_(self, other: "KeyDeps") -> "KeyDeps":
        if self.is_empty:
            return other
        if other.is_empty:
            return self
        merged_ids = linear_union(self.txn_ids, other.txn_ids)
        remap_a = self._remap_into(merged_ids)
        remap_b = other._remap_into(merged_ids)
        keys_a, keys_b = list(self.keys), list(other.keys)
        out_keys: List[Key] = []
        out_spans: List[List[int]] = []
        ia = ib = 0
        while ia < len(keys_a) or ib < len(keys_b):
            if ib >= len(keys_b) or (ia < len(keys_a)
                                     and keys_a[ia] < keys_b[ib]):
                out_keys.append(keys_a[ia])
                out_spans.append([remap_a[i] for i in self._span_indices(ia)])
                ia += 1
            elif ia >= len(keys_a) or keys_b[ib] < keys_a[ia]:
                out_keys.append(keys_b[ib])
                out_spans.append([remap_b[i]
                                  for i in other._span_indices(ib)])
                ib += 1
            else:
                sa = [remap_a[i] for i in self._span_indices(ia)]
                sb = [remap_b[i] for i in other._span_indices(ib)]
                out_keys.append(keys_a[ia])
                out_spans.append(list(linear_union(sa, sb)))
                ia += 1
                ib += 1
        return KeyDeps._from_spans(out_keys, out_spans, merged_ids)

    def without(self, predicate: Callable[[TxnId], bool]) -> "KeyDeps":
        keep = [not predicate(t) for t in self.txn_ids]
        if all(keep):
            return self
        out_keys: List[Key] = []
        out_spans: List[List[int]] = []
        for ki, k in enumerate(self.keys):
            span = [i for i in self._span_indices(ki) if keep[i]]
            if span:
                out_keys.append(k)
                out_spans.append(span)
        return KeyDeps._from_spans(out_keys, out_spans, self.txn_ids)

    def without_ids(self, remove: Set[TxnId]) -> "KeyDeps":
        return self.without(lambda t: t in remove)

    def slice(self, ranges: Ranges) -> "KeyDeps":
        owned = self.keys.slice(ranges)
        if owned is self.keys or len(owned) == len(self.keys):
            return self  # fully covered: one bisect pass, no span rebuild
        out_keys: List[Key] = []
        out_spans: List[List[int]] = []
        for k in owned:
            ki = self.keys.find(k)
            out_keys.append(k)
            out_spans.append(self._span_indices(ki))
        return KeyDeps._from_spans(out_keys, out_spans, self.txn_ids)

    @staticmethod
    def merge(deps: Sequence["KeyDeps"]) -> "KeyDeps":
        """Single-pass k-way merge over the flat CSRs (the reference's
        LinearMerger): one id-pool union, one remap per input, one walk over
        the merged key space — no per-pair CSR rebuilds."""
        live = [d for d in deps if d is not None and not d.is_empty]
        if not live:
            return KeyDeps.NONE
        if len(live) == 1:
            return live[0]
        merged_ids: Sequence[TxnId] = linear_merge_n(
            [d.txn_ids for d in live])
        remaps = [d._remap_into(merged_ids) for d in live]
        idxs = [0] * len(live)
        out_keys: List[Key] = []
        out_spans: List[List[int]] = []
        while True:
            cur = None
            for src, d in enumerate(live):
                if idxs[src] < len(d.keys):
                    k = d.keys[idxs[src]]
                    if cur is None or k < cur:
                        cur = k
            if cur is None:
                break
            span: List[int] = []
            for src, d in enumerate(live):
                i = idxs[src]
                if i < len(d.keys) and d.keys[i] == cur:
                    s = [remaps[src][j] for j in d._span_indices(i)]
                    span = list(linear_union(span, s)) if span else s
                    idxs[src] += 1
            out_keys.append(cur)
            out_spans.append(span)
        return KeyDeps._from_spans(out_keys, out_spans, merged_ids)

    def __eq__(self, other):
        return (isinstance(other, KeyDeps) and self.keys == other.keys
                and self.txn_ids == other.txn_ids
                and self.keys_to_txn_ids == other.keys_to_txn_ids)

    def __hash__(self):
        return hash((self.keys, self.txn_ids))

    def __repr__(self):
        return f"KeyDeps({ {k: self.txn_ids_for_key(k) for k in self.keys} })"


KeyDeps.NONE = KeyDeps(Keys(()), (), ())


class RangeDeps:
    """Range -> [TxnId] CSR multimap; ranges may overlap (RangeDeps.java:63-120).

    Stabbing queries (which ranges cover key X) go through the CINTIA
    checkpoint-interval index (reference SearchableRangeList.java:79,
    CheckpointIntervalArray.java:28-84), built lazily on first query once the
    range count justifies it; small sets use a direct sorted scan.
    """

    __slots__ = ("ranges", "txn_ids", "ranges_to_txn_ids", "_index")

    INDEX_THRESHOLD = 16

    def __init__(self, ranges: Tuple[Range, ...], txn_ids: Tuple[TxnId, ...],
                 ranges_to_txn_ids: Tuple[int, ...]):
        self.ranges = ranges            # sorted by (start, end); may overlap
        self.txn_ids = txn_ids          # sorted unique
        self.ranges_to_txn_ids = ranges_to_txn_ids
        self._index = None              # lazy CheckpointIntervalIndex

    NONE: "RangeDeps"

    class Builder:
        def __init__(self):
            self._map: Dict[Range, Set[TxnId]] = {}

        def add(self, rng: Range, txn_id: TxnId) -> "RangeDeps.Builder":
            self._map.setdefault(rng, set()).add(txn_id)
            return self

        def is_empty(self) -> bool:
            return not self._map

        def build(self) -> "RangeDeps":
            if not self._map:
                return RangeDeps.NONE
            ranges = sorted(self._map.keys(), key=lambda r: (r.start, r.end))
            all_ids = sorted(set().union(*self._map.values()))
            csr = _build_csr(ranges, self._map, all_ids)
            return RangeDeps(tuple(ranges), tuple(all_ids), csr)

    @classmethod
    def builder(cls) -> "RangeDeps.Builder":
        return cls.Builder()

    @classmethod
    def of(cls, mapping: Dict[Range, Iterable[TxnId]]) -> "RangeDeps":
        b = cls.Builder()
        for r, ids in mapping.items():
            for t in ids:
                b.add(r, t)
        return b.build()

    @property
    def is_empty(self) -> bool:
        return not self.ranges

    def txn_id_count(self) -> int:
        return len(self.txn_ids)

    def _span(self, range_idx: int) -> Tuple[int, int]:
        nr = len(self.ranges)
        start = self.ranges_to_txn_ids[range_idx - 1] if range_idx > 0 else nr
        end = self.ranges_to_txn_ids[range_idx]
        return start, end

    def txn_ids_for_range_idx(self, i: int) -> List[TxnId]:
        s, e = self._span(i)
        return [self.txn_ids[self.ranges_to_txn_ids[j]] for j in range(s, e)]

    def _stab_index(self):
        if self._index is None and len(self.ranges) >= self.INDEX_THRESHOLD:
            from accord_tpu.utils.checkpoint_intervals import \
                CheckpointIntervalIndex
            self._index = CheckpointIntervalIndex(
                [r.start for r in self.ranges], [r.end for r in self.ranges])
        return self._index

    def _emit(self, i: int, seen: Set[TxnId], fn: Callable[[TxnId], None]
              ) -> None:
        for t in self.txn_ids_for_range_idx(i):
            if t not in seen:
                seen.add(t)
                fn(t)

    def for_each_covering(self, key: RoutingKey, fn: Callable[[TxnId], None],
                          dedup: Optional[Set[TxnId]] = None) -> None:
        """Visit txn ids of every range containing `key`, once each."""
        seen = dedup if dedup is not None else set()
        index = self._stab_index()
        if index is not None:
            index.find(key.token, lambda i: self._emit(i, seen, fn))
            return
        for i, r in enumerate(self.ranges):
            if r.start > key.token:
                break
            if r.contains(key):
                self._emit(i, seen, fn)

    def for_each_intersecting(self, rng: Range, fn: Callable[[TxnId], None],
                              dedup: Optional[Set[TxnId]] = None) -> None:
        seen = dedup if dedup is not None else set()
        index = self._stab_index()
        if index is not None:
            index.find_overlaps(rng.start, rng.end,
                                lambda i: self._emit(i, seen, fn))
            return
        for i, r in enumerate(self.ranges):
            if r.start >= rng.end:
                break
            if r.intersects(rng):
                self._emit(i, seen, fn)

    def for_each_unique_txn_id(self, fn: Callable[[TxnId], None]) -> None:
        for t in self.txn_ids:
            fn(t)

    def contains(self, txn_id: TxnId) -> bool:
        i = find_ceil(self.txn_ids, txn_id)
        return i < len(self.txn_ids) and self.txn_ids[i] == txn_id

    def participants(self, txn_id: TxnId) -> Ranges:
        out: List[Range] = []
        for i in range(len(self.ranges)):
            if txn_id in self.txn_ids_for_range_idx(i):
                out.append(self.ranges[i])
        return Ranges(out)

    def _as_map(self) -> Dict[Range, Set[TxnId]]:
        return {r: set(self.txn_ids_for_range_idx(i))
                for i, r in enumerate(self.ranges)}

    def with_(self, other: "RangeDeps") -> "RangeDeps":
        if self.is_empty:
            return other
        if other.is_empty:
            return self
        m = self._as_map()
        for r, ids in other._as_map().items():
            m.setdefault(r, set()).update(ids)
        return RangeDeps.of(m)

    def without(self, predicate: Callable[[TxnId], bool]) -> "RangeDeps":
        m = {r: {t for t in ids if not predicate(t)}
             for r, ids in self._as_map().items()}
        return RangeDeps.of({r: ids for r, ids in m.items() if ids})

    def slice(self, ranges: Ranges) -> "RangeDeps":
        m: Dict[Range, Set[TxnId]] = {}
        for i, r in enumerate(self.ranges):
            for s in ranges:
                x = r.intersection(s)
                if x is not None:
                    m.setdefault(x, set()).update(self.txn_ids_for_range_idx(i))
        return RangeDeps.of(m)

    @staticmethod
    def merge(deps: Sequence["RangeDeps"]) -> "RangeDeps":
        live = [d for d in deps if d is not None and not d.is_empty]
        if not live:
            return RangeDeps.NONE
        if len(live) == 1:
            return live[0]
        m = live[0]._as_map()
        for d in live[1:]:
            for r, ids in d._as_map().items():
                m.setdefault(r, set()).update(ids)
        return RangeDeps.of(m)

    def __eq__(self, other):
        return (isinstance(other, RangeDeps) and self.ranges == other.ranges
                and self.txn_ids == other.txn_ids
                and self.ranges_to_txn_ids == other.ranges_to_txn_ids)

    def __hash__(self):
        return hash((self.ranges, self.txn_ids))

    def __repr__(self):
        return f"RangeDeps({self._as_map()!r})"


RangeDeps.NONE = RangeDeps((), (), ())


class Deps:
    """The pair {keyDeps, rangeDeps} (Deps.java:36,98-124)."""

    __slots__ = ("key_deps", "range_deps")

    NONE: "Deps"

    def __init__(self, key_deps: KeyDeps = None, range_deps: RangeDeps = None):
        self.key_deps = key_deps if key_deps is not None else KeyDeps.NONE
        self.range_deps = range_deps if range_deps is not None else RangeDeps.NONE

    @property
    def is_empty(self) -> bool:
        return self.key_deps.is_empty and self.range_deps.is_empty

    def txn_id_count(self) -> int:
        return len(self.txn_id_set())

    def txn_id_set(self) -> Set[TxnId]:
        return set(self.key_deps.txn_ids) | set(self.range_deps.txn_ids)

    def sorted_txn_ids(self) -> List[TxnId]:
        if not self.range_deps.txn_ids:
            # key_deps.txn_ids is already the sorted unique pool
            return list(self.key_deps.txn_ids)
        return sorted(self.txn_id_set(), key=_CMP_KEY)

    def contains(self, txn_id: TxnId) -> bool:
        return self.key_deps.contains(txn_id) or self.range_deps.contains(txn_id)

    def for_each_unique_txn_id(self, fn: Callable[[TxnId], None]) -> None:
        for t in self.sorted_txn_ids():
            fn(t)

    def participants(self, txn_id: TxnId):
        """Keys/Ranges through which txn_id appears."""
        return (self.key_deps.participants(txn_id),
                self.range_deps.participants(txn_id))

    def with_(self, other: "Deps") -> "Deps":
        return Deps(self.key_deps.with_(other.key_deps),
                    self.range_deps.with_(other.range_deps))

    def without(self, predicate: Callable[[TxnId], bool]) -> "Deps":
        return Deps(self.key_deps.without(predicate),
                    self.range_deps.without(predicate))

    def slice(self, ranges: Ranges) -> "Deps":
        return Deps(self.key_deps.slice(ranges), self.range_deps.slice(ranges))

    def intersects(self, ranges: Ranges) -> bool:
        return (self.key_deps.keys.intersects_ranges(ranges)
                or any(any(r.intersects(s) for s in ranges)
                       for r in self.range_deps.ranges))

    @staticmethod
    def merge(deps: Sequence["Deps"]) -> "Deps":
        live = [d for d in deps if d is not None]
        return Deps(KeyDeps.merge([d.key_deps for d in live]),
                    RangeDeps.merge([d.range_deps for d in live]))

    def max_txn_id(self) -> Optional[TxnId]:
        ids = self.txn_id_set()
        return max(ids) if ids else None

    def __eq__(self, other):
        return (isinstance(other, Deps) and self.key_deps == other.key_deps
                and self.range_deps == other.range_deps)

    def __hash__(self):
        return hash((self.key_deps, self.range_deps))

    def __repr__(self):
        return f"Deps(keys={self.key_deps!r}, ranges={self.range_deps!r})"


Deps.NONE = Deps(KeyDeps.NONE, RangeDeps.NONE)
