"""LatestDeps: per-range, knowledge-level-aware dependency merging for
recovery.

Reference: accord/primitives/LatestDeps.java (429 LoC) — each BeginRecovery
reply describes, for every key range the replica covers, HOW WELL it knows the
txn's deps there (KnownDeps level), at what accepted ballot, with which
coordinator-proposed deps and which freshly-calculated local deps. Merging
replies range-by-range lets recovery survive mixed-status quorums: a range
where one replica holds committed deps wins outright; a range where two
replicas hold competing Accept-round proposals resolves by ballot; a range
nobody decided falls back to the union of local calculations.

Our layout: a ReducingIntervalMap over integer tokens holding immutable
LatestDepsEntry values. Entry deps are NOT pre-sliced to their interval —
extraction (`merge_proposal` / `merge_commit`) slices, which keeps merges
allocation-free (the reference's Merge buffer plays the same trick,
LatestDeps.java:246-251).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from accord_tpu.local.status import KnownDeps
from accord_tpu.primitives.deps import Deps
from accord_tpu.primitives.keys import Range, Ranges
from accord_tpu.primitives.timestamp import Ballot
from accord_tpu.utils.interval_map import ReducingIntervalMap


class LatestDepsEntry:
    """One range's deps knowledge (LatestDeps.LatestEntry).

    `local_list` is a merge-intention: the locals of every reply that lost
    the per-range reduction at or below PROPOSED, unioned only if extraction
    actually needs them."""

    __slots__ = ("known", "ballot", "coordinated", "local_list")

    def __init__(self, known: KnownDeps, ballot: Ballot,
                 coordinated: Optional[Deps],
                 local_list: Tuple[Deps, ...] = ()):
        self.known = known
        self.ballot = ballot
        self.coordinated = coordinated
        self.local_list = local_list

    @staticmethod
    def reduce(a: "LatestDepsEntry", b: "LatestDepsEntry"
               ) -> "LatestDepsEntry":
        """Higher knowledge wins; Accept-round proposals tie-break by ballot
        (only that phase re-proposes — LatestDeps.AbstractEntry.reduce).
        Local deps of both sides are retained while deps are undecided."""
        c = (a.known > b.known) - (a.known < b.known)
        if c == 0 and a.known == KnownDeps.PROPOSED:
            c = (a.ballot > b.ballot) - (a.ballot < b.ballot)
        if c < 0:
            a, b = b, a
        if a.known <= KnownDeps.PROPOSED:
            return LatestDepsEntry(a.known, a.ballot, a.coordinated,
                                   a.local_list + b.local_list)
        return a

    def __eq__(self, other):
        return (isinstance(other, LatestDepsEntry)
                and self.known == other.known and self.ballot == other.ballot
                and self.coordinated == other.coordinated
                and self.local_list == other.local_list)

    def __hash__(self):
        return hash((self.known, self.ballot))

    def __repr__(self):
        return (f"LatestDepsEntry({self.known.name}, b={self.ballot!r}, "
                f"locals={len(self.local_list)})")


class LatestDeps:
    __slots__ = ("map",)

    EMPTY: "LatestDeps"

    def __init__(self, map_: Optional[ReducingIntervalMap] = None):
        self.map = map_ if map_ is not None else ReducingIntervalMap.empty()

    @staticmethod
    def create(ranges: Ranges, known: KnownDeps, ballot: Ballot,
               coordinated: Optional[Deps], local: Optional[Deps]
               ) -> "LatestDeps":
        """One replica's contribution over the store ranges it covers
        (LatestDeps.create)."""
        m = ReducingIntervalMap.empty()
        entry = LatestDepsEntry(known, ballot, coordinated,
                                (local,) if local is not None else ())
        for r in ranges:
            m = m.update(r.start, r.end, entry, LatestDepsEntry.reduce)
        return LatestDeps(m)

    def merge(self, other: "LatestDeps") -> "LatestDeps":
        return LatestDeps(self.map.merge(other.map, LatestDepsEntry.reduce))

    def _spans(self) -> List[Tuple[int, int, LatestDepsEntry]]:
        return [(s, e, v) for s, e, v in self.map.spans() if v is not None]

    def merge_proposal(self) -> Deps:
        """Deps to re-propose (Recover's Accept payload): per range, the
        max-ballot accepted proposal if one exists, else the union of local
        calculations (LatestDeps.Merge.forProposal)."""
        parts: List[Deps] = []
        for s, e, v in self._spans():
            rng = Ranges([Range(s, e)])
            if v.known == KnownDeps.PROPOSED and v.coordinated is not None:
                parts.append(v.coordinated.slice(rng))
            else:
                parts.extend(d.slice(rng) for d in v.local_list)
        return Deps.merge(parts) if parts else Deps.NONE

    def merge_commit(self, use_local: bool) -> Tuple[Deps, Ranges]:
        """Deps for executing a decided txn, plus the ranges they are
        sufficient for; the remainder needs a CollectDeps round. `use_local`
        = executeAt == txnId: a fast-path commit's deps are exactly what the
        replicas calculate locally, so undecided ranges are still sufficient
        (LatestDeps.Merge.forCommit)."""
        parts: List[Deps] = []
        sufficient: List[Range] = []
        for s, e, v in self._spans():
            rng = Ranges([Range(s, e)])
            if v.known in (KnownDeps.COMMITTED, KnownDeps.STABLE):
                if v.coordinated is not None:
                    parts.append(v.coordinated.slice(rng))
                    sufficient.append(Range(s, e))
            elif use_local and (v.coordinated is not None or v.local_list):
                # sufficiency requires actual knowledge: an entry with
                # neither a proposal nor any local calculation (every replica
                # PRE_COMMITTED via depless Propagate) must NOT suppress the
                # CollectDeps round, or the txn commits with empty deps
                if v.coordinated is not None:
                    parts.append(v.coordinated.slice(rng))
                parts.extend(d.slice(rng) for d in v.local_list)
                sufficient.append(Range(s, e))
        merged = Deps.merge(parts) if parts else Deps.NONE
        return merged, Ranges(sufficient)

    def __eq__(self, other):
        return isinstance(other, LatestDeps) and self.map == other.map

    def __repr__(self):
        return f"LatestDeps({self._spans()!r})"


LatestDeps.EMPTY = LatestDeps()
