"""Length-prefixed frame I/O over the supervisor<->worker pipes.

Same 4-byte big-endian length prefix as the TCP transport's peer frames,
same structural codec (host/wire.py, native tier when present): a pipe
frame IS a wire frame, which is what lets test_wire_roundtrip.py pin the
shard frames on both codec tiers alongside peer traffic.

Threading contract: each end gives the pipe a dedicated READER thread that
only ever drains, so blocking writes (under `lock`) cannot deadlock — the
classic pipe-pair deadlock needs both ends blocked on write with both
buffers full, and a reader that always drains makes that state unreachable.
"""

from __future__ import annotations

import struct
from typing import Optional

from accord_tpu.host.wire import decode_message, pack_frame, unpack_frame_obj

_LEN = struct.Struct(">I")


def write_frame(fp, lock, obj) -> None:
    """Pack and write one frame under `lock` (any thread)."""
    data = pack_frame(obj)
    with lock:
        fp.write(_LEN.pack(len(data)))
        fp.write(data)
        fp.flush()


def read_frame(fp) -> Optional[object]:
    """Blocking read of one decoded frame object; None on EOF/short read."""
    header = fp.read(_LEN.size)
    if len(header) < _LEN.size:
        return None
    (n,) = _LEN.unpack(header)
    data = fp.read(n)
    if len(data) < n:
        return None
    obj = unpack_frame_obj(data)
    # python-tier codec returns the tree; the native tier already decoded
    return decode_message(obj) if type(obj) is dict else obj
