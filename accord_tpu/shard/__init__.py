"""Per-shard worker runtime: a true multi-core multi-store node.

The reference's core intra-node parallelism construct is range sharding:
`CommandStores` splits the owned keyspace over N single-threaded
`CommandStore` shards behind a `mapReduceConsume` fan-out that crosses a
per-store thread boundary (CommandStores.java:78,563).  Our logical shard
manager (local/store.py) has always existed, but every shard ran on one
event loop in one process — the GIL makes in-process threads a dead end,
so this package gives each shard its own PROCESS with its own event loop:

  * supervisor.py — ShardSupervisor spawns/monitors/respawns N workers and
    WorkerCommandStores routes the same map_reduce_request fan-out over
    framed duplex pipes (host/wire.py codec, native tier when available);
    store-affine callbacks are marshalled back to the owning worker
  * worker.py — the worker process: a full Node confined to its shard's
    EvenSplit slice (SlicedCommandStores), a pipe-backed sink, an HLC
    congruence stripe so same-id processes never mint colliding
    timestamps, and its own WAL band (journal-where-processed)
  * frames.py — the wire-registered pipe frames

In-loop mode (`ACCORD_SHARDS` unset, 0 or 1) is pinned bit-identical to
the pre-worker dispatch: hosts only swap in WorkerCommandStores when the
knob asks for 2+ workers.
"""

from __future__ import annotations

import os


def workers_from_env() -> int:
    """Number of shard worker processes the host should run, or 0 for the
    in-loop tier.  ACCORD_SHARDS=N with N >= 2 enables the worker runtime;
    unset/0/1 keeps every store on the host's own loop."""
    raw = os.environ.get("ACCORD_SHARDS", "")
    try:
        n = int(raw) if raw else 0
    except ValueError:
        return 0
    return n if n >= 2 else 0
