"""Worker-pipe frames for the per-shard runtime (shard/).

Every class here crosses the supervisor<->worker pipe through the
host/wire.py structural codec (the module is listed in wire._MODULES), so
frames ride the same binary framing — native tier when available — as
peer-to-peer traffic, and tests/test_wire_roundtrip.py synthesizers pin
their round trip on both codec tiers.

Parent -> worker:  ShardInit, ShardEpoch, ShardSubmit, ShardDeliver,
                   ShardStatsReq, ShardAudit, ShardRetire
Worker -> parent:  ShardHello, ShardReply, ShardSend, ShardStatsRsp,
                   ShardAuditRsp, ShardRetired

Two id spaces, one per direction: `seq` numbers parent-initiated RPCs
(submit/stats/audit/retire), `wmsg` numbers worker-initiated sends whose
replies the parent marshals back (the worker-side CallbackSink msg id).
Keep this module import-light: wire.py imports it while building the
registry, so it must not import wire (or anything host-tier) itself.
"""

from __future__ import annotations

from typing import Optional, Tuple


class ShardInit:
    """First frame on a fresh pipe: identity + slice arithmetic + the
    EpochInstall chain so far, so a (re)spawned worker rebuilds topology
    BEFORE replaying its WAL band.  `mod` is the HLC congruence modulus
    (workers + parent), `stripe` this worker's class (parent keeps 0)."""

    def __init__(self, node_id: int, shard: int, n_shards: int,
                 stripe: int, mod: int, generation: int,
                 installs: Tuple = ()):
        self.node_id = node_id
        self.shard = shard
        self.n_shards = n_shards
        self.stripe = stripe
        self.mod = mod
        self.generation = generation
        self.installs = tuple(installs)

    def __repr__(self):
        return (f"ShardInit(node={self.node_id} shard={self.shard}"
                f"/{self.n_shards} gen={self.generation})")


class ShardHello:
    """Worker is live (journal band replayed, stores initialized): the
    supervisor re-ships pending submits only after this lands."""

    def __init__(self, shard: int, pid: int, generation: int):
        self.shard = shard
        self.pid = pid
        self.generation = generation

    def __repr__(self):
        return f"ShardHello(shard={self.shard} pid={self.pid})"


class ShardEpoch:
    """One topology epoch for the worker's config service; `install` is the
    ordinary wire-registered EpochInstall spec (messages/admin.py)."""

    def __init__(self, install):
        self.install = install

    def __repr__(self):
        return f"ShardEpoch({self.install!r})"


class ShardSubmit:
    """Shard-affine fan-out: run `request` against the worker's stores
    (CommandStores.map_reduce_request) and answer with ShardReply(seq)."""

    def __init__(self, seq: int, request):
        self.seq = seq
        self.request = request

    def __repr__(self):
        return f"ShardSubmit(#{self.seq} {type(self.request).__name__})"


class ShardReply:
    """The worker-local reduce of one ShardSubmit: `value` is the shard's
    Reply (None for consume-only dispatches), `failure` a repr string."""

    def __init__(self, seq: int, value=None, failure: Optional[str] = None):
        self.seq = seq
        self.value = value
        self.failure = failure

    def __repr__(self):
        return (f"ShardReply(#{self.seq} "
                + (f"failure={self.failure!r}" if self.failure
                   else type(self.value).__name__) + ")")


class ShardSend:
    """Worker-initiated outbound request (recovery, progress log, audit
    fan-outs started inside a worker store): the parent forwards it through
    its own transport — self-addressed sends loop back through the parent's
    shard routing, so cross-shard coordination stays correct.  `wmsg` is
    the worker's callback id (None = fire-and-forget)."""

    def __init__(self, wmsg: Optional[int], to: int, request):
        self.wmsg = wmsg
        self.to = to
        self.request = request

    def __repr__(self):
        return (f"ShardSend(w#{self.wmsg} to=n{self.to} "
                f"{type(self.request).__name__})")


class ShardDeliver:
    """Reply delivery for a ShardSend: parent -> owning worker, which hands
    it to its CallbackSink under the original worker msg id."""

    def __init__(self, wmsg: int, from_id: int, reply):
        self.wmsg = wmsg
        self.from_id = from_id
        self.reply = reply

    def __repr__(self):
        return f"ShardDeliver(w#{self.wmsg} from=n{self.from_id})"


class ShardStatsReq:
    """Pull one obs snapshot from the worker (census, pager stats, flight
    tail) for the parent's merged node view."""

    def __init__(self, seq: int, flight_tail: int = 256):
        self.seq = seq
        self.flight_tail = flight_tail

    def __repr__(self):
        return f"ShardStatsReq(#{self.seq})"


class ShardStatsRsp:
    """One worker obs snapshot.  `census` is local/audit.census_node output
    (JSON-safe), `paging` the summed Pager.stats(), `flight` the ring tail
    as (at_us, seq, kind, trace_id, data) tuples."""

    def __init__(self, seq: int, shard: int, pid: int, generation: int,
                 census=None, paging=None, flight: Tuple = ()):
        self.seq = seq
        self.shard = shard
        self.pid = pid
        self.generation = generation
        self.census = census
        self.paging = paging
        self.flight = tuple(tuple(e) for e in flight)

    def __repr__(self):
        return f"ShardStatsRsp(#{self.seq} shard={self.shard})"


class ShardAudit:
    """One audit walk over the worker's stores: kind 'digest' answers with
    an AuditDigestOk, 'entries' with an AuditEntriesOk (messages/audit.py).
    The worker applies the min-token ownership filter so a cross-shard
    transaction contributes exactly one leaf node-wide."""

    def __init__(self, seq: int, kind: str, ranges, lo, hi,
                 limit: int = 0):
        self.seq = seq
        self.kind = kind
        self.ranges = ranges
        self.lo = lo
        self.hi = hi
        self.limit = limit

    def __repr__(self):
        return f"ShardAudit(#{self.seq} {self.kind} {self.ranges!r})"


class ShardAuditRsp:
    """The worker's audit answer; `reply` is the ordinary wire-registered
    AuditDigestOk / AuditEntriesOk the parent merges across workers."""

    def __init__(self, seq: int, reply):
        self.seq = seq
        self.reply = reply

    def __repr__(self):
        return f"ShardAuditRsp(#{self.seq} {self.reply!r})"


class ShardRetire:
    """Drain and exit: the worker flushes its WAL band, answers
    ShardRetired, and terminates."""

    def __init__(self, seq: int):
        self.seq = seq

    def __repr__(self):
        return f"ShardRetire(#{self.seq})"


class ShardRetired:
    def __init__(self, seq: int, shard: int, generation: int):
        self.seq = seq
        self.shard = shard
        self.generation = generation

    def __repr__(self):
        return f"ShardRetired(#{self.seq} shard={self.shard})"
