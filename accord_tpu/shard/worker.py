"""The shard worker process: one CommandStore slice on its own core.

Spawned by shard/supervisor.py as `python -m accord_tpu.shard.worker`,
speaking length-prefixed wire frames (shard/pipe.py) on stdin/stdout.
Holds a FULL Node under the parent's node id — coordination started inside
a worker store (recovery, progress-log escalation, bootstrap fetch) runs
the ordinary Node.send machinery — but with three worker-mode twists:

  * SlicedCommandStores: the node's owned ranges are cut down to this
    worker's EvenSplit slice, recomputed the same way on every epoch, so
    the worker and the parent's router always agree on who owns what
  * PipeSink: every outbound request becomes a ShardSend the parent
    forwards through its OWN transport (self-addressed sends loop back
    through the parent's shard routing — cross-shard coordination costs
    one extra pipe hop, not a special case); replies come back as
    ShardDeliver frames
  * HLC stripe: Node.set_hlc_stripe confines minted HLCs to this worker's
    congruence class, so N processes minting under one node id can never
    collide without any cross-process clock coordination

Durability is journal-where-processed: the worker appends every
side-effecting TxnRequest to its OWN WAL band (<journal>/node-<id>/
shard-<k>) before executing it, with group commit forced OFF — a
ShardReply must never precede its record's fsync, because the parent acks
clients off worker replies.  On respawn the band replays before ShardHello
and the supervisor re-ships whatever was pending, so a SIGKILL'd worker
loses zero acknowledged work.
"""

from __future__ import annotations

import json
import os
import queue
import sys
import threading
import time

from accord_tpu.api.spi import CallbackSink
from accord_tpu.local.store import CommandStores, EmptyFanout, EvenSplit
from accord_tpu.primitives.keys import Ranges
from accord_tpu.shard import frames
from accord_tpu.shard.pipe import read_frame, write_frame


class SlicedCommandStores(CommandStores):
    """CommandStores confined to one EvenSplit slice: the worker holds a
    single CommandStore whose id IS the shard index (census/flight labels
    line up node-wide), ranged at slice `shard` of the same N-way split
    the parent's router computes — both sides derive ownership from the
    node's owned ranges alone, so no range list ever crosses the pipe."""

    def __init__(self, node, shard: int, n_shards: int, store_factory=None):
        super().__init__(node, num_shards=1, store_factory=store_factory)
        self.shard = shard
        self.n_slices = n_shards
        self._slice_splitter = EvenSplit(n_shards)

    def _slice(self, ranges: Ranges) -> Ranges:
        return self._slice_splitter.split(ranges)[self.shard]

    def initialize(self, ranges: Ranges) -> None:
        sl = self._slice(ranges)
        self.stores = [self.store_factory(self.shard, self.node, sl)]

    def update_topology(self, ranges: Ranges) -> Ranges:
        sl = self._slice(ranges)
        if not self.stores:
            self.initialize(ranges)
            return sl
        store = self.stores[0]
        added = sl.subtract(store.ranges)
        store.update_ranges(sl, unsafe=added)
        return added

    def owned(self) -> Ranges:
        return self.stores[0].ranges if self.stores else Ranges.EMPTY


class PipeSink(CallbackSink):
    """MessageSink marshalling every outbound request to the parent as a
    ShardSend frame.  The CallbackSink msg-id space (`wmsg` on the wire)
    is this worker's own; the parent maps it to ITS transport callback and
    routes the reply back as ShardDeliver."""

    def __init__(self, host: "WorkerHost"):
        super().__init__()
        self.host = host

    def send(self, to: int, request) -> None:
        if self._capture(to, None, request):
            return
        self.host.out(frames.ShardSend(None, to, request))

    def send_with_callback(self, to: int, request, callback,
                           executor=None) -> None:
        wmsg = self._register(callback)
        if self._capture(to, wmsg, request):
            return
        self.host.out(frames.ShardSend(wmsg, to, request))

    def _send_prepared(self, to: int, reply_context, request) -> None:
        self.host.out(frames.ShardSend(reply_context, to, request))

    def reply(self, to: int, reply_context, reply) -> None:
        if reply_context is None:
            return
        # requests reach worker stores only through ShardSubmit (whose
        # reply path is the consume callback) — a transport reply context
        # inside a worker means a routing bug, not a silent drop
        raise RuntimeError(
            f"worker reply without a pipe path: to={to} {reply!r}")


class WorkerHost:
    """The worker's event loop: maelstrom-idiom single-threaded core — a
    reader thread only enqueues decoded frames; the node is touched
    exclusively on the loop thread.  Writes to the parent are blocking
    under a mutex (shard/pipe.py's deadlock-freedom contract: the
    supervisor gives this pipe a dedicated always-draining reader)."""

    def __init__(self):
        from accord_tpu.host.rt import RealTimeScheduler
        self.scheduler = RealTimeScheduler()
        self.sink = PipeSink(self)
        self.node = None
        self.shard = -1
        self.n_shards = 0
        self.generation = 0
        self.running = True
        self._inq: "queue.Queue" = queue.Queue()
        self._out_lock = threading.Lock()
        self._stdout = sys.stdout.buffer

    # ------------------------------------------------------------- egress --
    def out(self, frame) -> None:
        write_frame(self._stdout, self._out_lock, frame)

    # -------------------------------------------------------------- build --
    def _apply_init(self, init: "frames.ShardInit") -> None:
        from accord_tpu.host.maelstrom import HostAgent
        from accord_tpu.host.tcp import _env_store_factory
        from accord_tpu.impl.list_store import ListStore
        from accord_tpu.journal import attach_journal_from_env
        from accord_tpu.local.node import Node
        from accord_tpu.utils.random_source import RandomSource

        self.shard = init.shard
        self.n_shards = init.n_shards
        self.generation = init.generation
        agent = HostAgent()
        self.scheduler.on_error = agent.on_uncaught_exception
        node = Node(init.node_id, self.sink, agent, self.scheduler,
                    ListStore(init.node_id),
                    # distinct stream per (node, shard): same-id workers
                    # must not mirror each other's jitter/backoff draws
                    RandomSource(init.node_id * 8191 + init.shard + 1),
                    num_shards=1, store_factory=_env_store_factory(),
                    now_us=lambda: time.time_ns() // 1000)
        node.command_stores = SlicedCommandStores(
            node, init.shard, init.n_shards,
            store_factory=_env_store_factory())
        node.set_hlc_stripe(init.stripe, init.mod)
        self.node = node
        for install in init.installs:
            self._apply_install(install)
        # journal-where-processed: this worker's own WAL band, group commit
        # forced OFF — the parent acks clients off ShardReply, so a reply
        # must never precede its record's fsync (perf residual: per-append
        # fsync on the worker tier; see ROADMAP)
        os.environ["ACCORD_JOURNAL_FSYNC_US"] = "0"
        attach_journal_from_env(node, band=f"shard-{self.shard}")

    def _apply_install(self, install) -> None:
        """Adopt one EpochInstall directly (no config service in workers:
        the parent's service is the single epoch authority and streams the
        chain over the pipe in order).  start_sync=False — the PARENT owns
        epoch-sync negotiation with peers; the worker only re-ranges its
        slice and marks the added spans safe."""
        if self.node.topology.has_epoch(install.epoch):
            return
        self.node.on_topology_update(install.build_topology(),
                                     start_sync=False)

    # --------------------------------------------------------------- loop --
    def run(self) -> None:
        stdin = sys.stdin.buffer
        init = read_frame(stdin)
        if not isinstance(init, frames.ShardInit):
            print(f"shard worker: bad init frame {init!r}", file=sys.stderr,
                  flush=True)
            return
        self._apply_init(init)

        def reader():
            while True:
                fr = read_frame(stdin)
                self._inq.put(fr)
                if fr is None:  # EOF: the parent is gone
                    return

        threading.Thread(target=reader, daemon=True,
                         name=f"shard-{self.shard}-reader").start()
        # replay is done (attach_journal_from_env) — tell the supervisor
        # this generation is live so it re-ships pending submits
        self.out(frames.ShardHello(self.shard, os.getpid(), self.generation))
        while self.running:
            self.scheduler.run_due()
            deadline = self.scheduler.next_deadline()
            timeout = (max(0.0, deadline - time.monotonic())
                       if deadline is not None else 0.5)
            try:
                batch = [self._inq.get(timeout=min(timeout, 0.5))]
            except queue.Empty:
                continue
            while len(batch) < 64:
                try:
                    batch.append(self._inq.get_nowait())
                except queue.Empty:
                    break
            for fr in batch:
                if fr is None:
                    self.running = False
                    break
                try:
                    self._dispatch(fr)
                except Exception as e:  # noqa: BLE001
                    print(f"shard worker dispatch error: {e!r} on {fr!r}",
                          file=sys.stderr, flush=True)
            self.scheduler.run_due()

    # ----------------------------------------------------------- dispatch --
    def _dispatch(self, fr) -> None:
        node = self.node
        if isinstance(fr, frames.ShardSubmit):
            self._on_submit(fr)
        elif isinstance(fr, frames.ShardDeliver):
            self.sink.deliver_reply(fr.wmsg, fr.from_id, fr.reply)
        elif isinstance(fr, frames.ShardEpoch):
            self._apply_install(fr.install)
        elif isinstance(fr, frames.ShardStatsReq):
            self._on_stats(fr)
        elif isinstance(fr, frames.ShardAudit):
            self._on_audit(fr)
        elif isinstance(fr, frames.ShardRetire):
            if node is not None and node.journal is not None:
                node.journal.close()
            self.out(frames.ShardRetired(fr.seq, self.shard,
                                         self.generation))
            self.running = False
        else:
            print(f"shard worker: unknown frame {fr!r}", file=sys.stderr,
                  flush=True)

    def _on_submit(self, fr: "frames.ShardSubmit") -> None:
        node = self.node
        request = fr.request
        # mirror Node._process for a routed request: absorb witnessed
        # HLCs, record the hop, journal side effects BEFORE executing
        txn_id = getattr(request, "txn_id", None)
        if txn_id is not None:
            node.on_remote_timestamp(txn_id)
        execute_at = getattr(request, "execute_at", None)
        if execute_at is not None:
            node.on_remote_timestamp(execute_at)
        mt = request.type
        verb = mt.label if mt is not None else type(request).__name__
        node.obs.flight.record("rx", getattr(request, "trace_id", None),
                               (node.id, verb))
        if node.journal is not None and mt is not None \
                and mt.has_side_effects:
            node.journal.record(node.id, request)
        seq = fr.seq

        def consume(value, failure):
            if failure is not None and not isinstance(failure, EmptyFanout):
                self.out(frames.ShardReply(seq, None, repr(failure)))
            else:
                # EmptyFanout folds as a no-op leg: the parent's reduce
                # skips None values (epoch-skew tolerance)
                self.out(frames.ShardReply(seq, value, None))

        try:
            node.command_stores.map_reduce_request(request, consume)
        except BaseException as e:  # noqa: BLE001
            self.out(frames.ShardReply(seq, None, repr(e)))

    def _on_stats(self, fr: "frames.ShardStatsReq") -> None:
        from accord_tpu.local.audit import census_node
        node = self.node
        census = census_node(node)
        paging = census.get("paging")
        self.out(frames.ShardStatsRsp(
            fr.seq, self.shard, os.getpid(), self.generation,
            census=census, paging=paging,
            flight=node.obs.flight.tail(fr.flight_tail)))

    def _on_audit(self, fr: "frames.ShardAudit") -> None:
        from accord_tpu.local import audit as A
        from accord_tpu.messages.audit import AuditEntriesOk
        node = self.node
        owned = node.command_stores.owned()
        if fr.kind == "digest":
            reply = A.digest_reply(node, fr.ranges, fr.lo, fr.hi,
                                   owned=owned)
        else:
            entries = A.collect_entries(node, fr.ranges, fr.lo, fr.hi,
                                        owned=owned)
            limit = fr.limit or len(entries)
            reply = AuditEntriesOk(tuple(entries[:limit]),
                                   truncated=len(entries) > limit)
        self.out(frames.ShardAuditRsp(fr.seq, reply))


def main() -> None:
    # argv carries only a ps-visible identity tag; real configuration
    # arrives as the ShardInit frame (wire objects cannot ride argv)
    _tag = json.loads(sys.argv[1]) if len(sys.argv) > 1 else {}
    WorkerHost().run()
    # the reader daemon thread is parked in a blocking stdin read;
    # interpreter finalization would trip over its buffer lock — hard
    # exit instead (the WAL band is already closed/fsynced on retire)
    sys.stdout.buffer.flush()
    sys.stderr.flush()
    os._exit(0)


if __name__ == "__main__":
    main()
