"""Parent-side shard runtime: worker lifecycle + shard-affine routing.

ShardSupervisor owns the worker processes (spawn, monitor, respawn on
crash, retire on close) and the framed duplex pipes; WorkerCommandStores
is the CommandStores the parent node runs with — same fan-out API the
in-loop tier exposes, but `map_reduce_request` ships each shard's leg over
its worker pipe and reduces the ShardReplies in shard order, exactly like
the reference's mapReduceConsume across store threads.

Crash contract (zero lost acks): a submit stays in `pending` until its
ShardReply arrives; a SIGKILL'd worker is respawned with a bumped
generation, replays its own WAL band, answers ShardHello, and only then
gets the still-pending submits re-shipped.  Replay and re-execution are
idempotent for the same reason journal replay is — Accord message
application is state-merge.

Threading: every node-facing structure is touched ONLY on the host loop
thread; the per-worker reader threads decode frames and marshal them in
via host.call_soon, and pipe writes are blocking under a per-worker lock
(shard/pipe.py's contract: the worker's reader thread always drains).
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
from typing import Callable, Dict, List, Optional

from accord_tpu.local.store import CommandStores, EmptyFanout
from accord_tpu.messages.base import FunctionCallback
from accord_tpu.primitives.keys import Ranges, _SortedKeyList
from accord_tpu.shard import frames
from accord_tpu.shard.pipe import read_frame, write_frame

# parent-side TTL for forwarding a worker-initiated RPC's reply: the
# WORKER's own _SafeCallback timeout governs protocol behavior — this
# bound only stops a lost reply from pinning parent callback state
_FORWARD_TTL_S = 60.0


class _Worker:
    """One worker process and its pipe state."""

    __slots__ = ("shard", "proc", "generation", "live", "retired",
                 "write_lock", "pid")

    def __init__(self, shard: int, proc, generation: int):
        self.shard = shard
        self.proc = proc
        self.generation = generation
        self.live = False      # ShardHello received for this generation
        self.retired = False   # planned exit: do not respawn
        self.write_lock = threading.Lock()
        self.pid = proc.pid


class ShardSupervisor:
    """Spawns and supervises the N shard workers for one host node.

    `host` provides call_soon (cross-thread marshal onto the node's loop)
    and the node is used for its scheduler, flight ring, sink, and config
    service (the EpochInstall ledger workers are seeded from)."""

    def __init__(self, host, node, n_workers: int):
        self.host = host
        self.node = node
        self.n_workers = n_workers
        self.flight = node.obs.flight
        self.workers: List[Optional[_Worker]] = [None] * n_workers
        # seq -> (shard, request, on_reply(value, failure)) for submits;
        # control RPCs (stats/audit/retire) track their own continuations
        self.pending: Dict[int, tuple] = {}
        self._ctl: Dict[int, Callable] = {}
        self._seq = 0
        self._spawned = False
        self._closing = False
        self.stats_cache: Dict[int, frames.ShardStatsRsp] = {}
        self._stats_timer = None
        try:
            self._cpus = sorted(os.sched_getaffinity(0))
        except (AttributeError, OSError):
            self._cpus = []

    # ------------------------------------------------------------ spawning --
    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def _installs(self) -> tuple:
        """The EpochInstall chain a fresh worker needs, oldest first."""
        service = self.node.config_service
        if service is not None:
            out = []
            for epoch in range(1, self.node.topology.epoch + 1):
                spec = service.spec_for(epoch)
                if spec is not None:
                    out.append(spec)
            if out:
                return tuple(out)
        from accord_tpu.messages.admin import EpochInstall
        topo = self.node.topology.current()
        return (EpochInstall.from_topology(topo),) if topo.shards else ()

    def spawn_all(self) -> None:
        if self._spawned:
            return
        self._spawned = True
        for shard in range(self.n_workers):
            self._spawn(shard, generation=1)
        if self._stats_timer is None:
            self._stats_timer = self.node.scheduler.recurring(
                2.0, self._poll_stats)

    def _spawn(self, shard: int, generation: int) -> None:
        env = dict(os.environ)
        # the worker is a plain Node, not a host: no metrics port (would
        # collide), no auditor (the parent audits THROUGH the workers), no
        # QoS/pipeline tiers (admission happens before routing), and no
        # nested worker runtime
        for k in ("ACCORD_SHARDS", "ACCORD_METRICS_PORT", "ACCORD_QOS",
                  "ACCORD_PIPELINE", "ACCORD_TCP_PROFILE"):
            env.pop(k, None)
        env["ACCORD_AUDIT_S"] = "0"
        proc = subprocess.Popen(
            [sys.executable, "-m", "accord_tpu.shard.worker",
             f'{{"node": {self.node.id}, "shard": {shard}}}'],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env)
        w = _Worker(shard, proc, generation)
        self.workers[shard] = w
        if len(self._cpus) > self.n_workers:
            # enough cores that parent and workers need not share: pin
            # worker k off the parent's first core (best effort)
            try:
                os.sched_setaffinity(
                    proc.pid,
                    {self._cpus[1 + shard % (len(self._cpus) - 1)]})
            except OSError:
                pass
        self.flight.record("shard_spawn", None,
                           (shard, proc.pid, generation))
        write_frame(proc.stdin, w.write_lock, frames.ShardInit(
            self.node.id, shard, self.n_workers,
            stripe=shard + 1, mod=self.n_workers + 1,
            generation=generation, installs=self._installs()))
        threading.Thread(target=self._reader, args=(w,), daemon=True,
                         name=f"shard-{shard}-reader").start()

    def _reader(self, w: _Worker) -> None:
        while True:
            try:
                fr = read_frame(w.proc.stdout)
            except Exception:  # noqa: BLE001 — torn pipe == EOF
                fr = None
            if fr is None:
                self.host.call_soon(lambda: self._on_exit(w))
                return
            self.host.call_soon(lambda f=fr: self._on_frame(w, f))

    # ----------------------------------------------------- lifecycle (loop) --
    def _on_exit(self, w: _Worker) -> None:
        if self.workers[w.shard] is not w:
            return  # already replaced
        w.live = False
        try:
            w.proc.wait(timeout=1.0)
        except Exception:  # noqa: BLE001
            w.proc.kill()
        if self._closing or w.retired:
            return
        # crash: fail in-flight control RPCs (audit rounds turn
        # inconclusive), keep submits pending, respawn with a new
        # generation — ShardHello triggers the re-ship
        for seq in [s for s, cb in list(self._ctl.items())
                    if getattr(cb, "shard", None) == w.shard]:
            cb = self._ctl.pop(seq)
            cb(None)
        self._spawn(w.shard, w.generation + 1)

    def _on_frame(self, w: _Worker, fr) -> None:
        if self.workers[w.shard] is not w:
            return  # stale generation
        if isinstance(fr, frames.ShardReply):
            ent = self.pending.pop(fr.seq, None)
            if ent is not None:
                _shard, request, on_reply = ent
                failure = (RuntimeError(fr.failure)
                           if fr.failure is not None else None)
                on_reply(fr.value, failure)
        elif isinstance(fr, frames.ShardSend):
            self._forward(w, fr)
        elif isinstance(fr, frames.ShardHello):
            w.live = True
            w.pid = fr.pid
            for seq, ent in list(self.pending.items()):
                if ent[0] == w.shard:
                    self._write(w, frames.ShardSubmit(seq, ent[1]))
        elif isinstance(fr, (frames.ShardStatsRsp, frames.ShardAuditRsp,
                             frames.ShardRetired)):
            cb = self._ctl.pop(fr.seq, None)
            if cb is not None:
                cb(fr)
        else:
            self.node.agent.on_handled_exception(
                RuntimeError(f"unknown worker frame {fr!r}"))

    def _forward(self, w: _Worker, fr: frames.ShardSend) -> None:
        """Forward a worker-initiated send through the parent's OWN
        transport.  Self-addressed sends land back in the parent's local
        queue and re-enter WorkerCommandStores routing — cross-shard
        coordination needs no special case."""
        if fr.wmsg is None:
            self.node.sink.send(fr.to, fr.request)
            return
        shard, wmsg = w.shard, fr.wmsg

        def ok(from_id, reply):
            cur = self.workers[shard]
            if cur is not None and cur.live:
                self._write(cur, frames.ShardDeliver(wmsg, from_id, reply))

        # failure leg intentionally drops: the WORKER armed its own
        # _SafeCallback timeout when it sent — the parent-side TTL only
        # garbage-collects the forwarding state
        self.node.send(fr.to, fr.request, FunctionCallback(ok),
                       timeout_s=_FORWARD_TTL_S)

    # ------------------------------------------------------------- routing --
    def submit(self, shard: int, request, on_reply) -> None:
        seq = self._next_seq()
        self.pending[seq] = (shard, request, on_reply)
        mt = request.type
        verb = mt.label if mt is not None else type(request).__name__
        self.flight.record("shard_submit",
                           getattr(request, "trace_id", None), (shard, verb))
        w = self.workers[shard]
        if w is not None and w.live:
            self._write(w, frames.ShardSubmit(seq, request))
        # not live: ShardHello re-ships everything pending for the shard

    def _write(self, w: _Worker, frame) -> None:
        try:
            write_frame(w.proc.stdin, w.write_lock, frame)
        except (OSError, ValueError):
            pass  # torn pipe: the reader's EOF path owns recovery

    def control(self, shard: int, frame, done: Callable) -> bool:
        """Send one control RPC (stats/audit/retire); done(rsp|None)."""
        w = self.workers[shard]
        if w is None or not w.live:
            return False
        done.shard = shard  # let _on_exit fail RPCs of a dead worker
        self._ctl[frame.seq] = done
        self._write(w, frame)
        return True

    # --------------------------------------------------------------- stats --
    def _poll_stats(self) -> None:
        for shard in range(self.n_workers):
            seq = self._next_seq()

            def done(rsp, shard=shard):
                if rsp is not None:
                    self.stats_cache[shard] = rsp

            self.control(shard, frames.ShardStatsReq(seq), done)

    # --------------------------------------------------------------- audit --
    def audit_fan(self, kind: str, ranges, lo, hi, limit: int,
                  done: Callable) -> None:
        """Fan one audit walk over every worker and merge: XOR digests,
        sum counts, max lo floors / min hi floors (each worker already
        applied the min-token ownership filter, so the union is exactly
        one leaf per transaction node-wide).  done(reply|None)."""
        replies: Dict[int, object] = {}
        remaining = [0]
        failed = [False]

        def mk(shard):
            def on_rsp(rsp):
                remaining[0] -= 1
                if rsp is None:
                    failed[0] = True
                else:
                    replies[shard] = rsp.reply
                if remaining[0] == 0:
                    done(None) if failed[0] else done(
                        self._merge_audit(kind, replies))
            return on_rsp

        for shard in range(self.n_workers):
            seq = self._next_seq()
            cb = mk(shard)
            if self.control(shard,
                            frames.ShardAudit(seq, kind, ranges, lo, hi,
                                              limit), cb):
                remaining[0] += 1
            else:
                failed[0] = True
        if remaining[0] == 0:
            done(None)

    @staticmethod
    def _merge_audit(kind: str, replies: Dict[int, object]):
        from accord_tpu.messages.audit import AuditDigestOk, AuditEntriesOk
        vals = [replies[s] for s in sorted(replies)]
        if kind == "digest":
            acc = 0
            count = 0
            for r in vals:
                acc ^= int(r.digest, 16)
                count += r.count
            lo = max(r.lo_floor for r in vals)
            hi = min(r.hi_floor for r in vals)
            return AuditDigestOk(f"{acc:032x}", count, lo, hi)
        entries = sorted((e for r in vals for e in r.entries),
                         key=lambda e: e[0])
        return AuditEntriesOk(tuple(entries),
                              truncated=any(r.truncated for r in vals))

    # --------------------------------------------------------------- close --
    def close(self) -> None:
        self._closing = True
        if self._stats_timer is not None:
            self._stats_timer.cancel()
        for w in self.workers:
            if w is None:
                continue
            w.retired = True
            if w.live:
                self._write(w, frames.ShardRetire(self._next_seq()))
            try:
                w.proc.wait(timeout=2.0)
            except Exception:  # noqa: BLE001
                w.proc.kill()
            self.flight.record("shard_retire", None,
                               (w.shard, w.generation))

    def admin_view(self) -> List[dict]:
        """One row per worker for the host's "shards" admin frame."""
        return [{"shard": w.shard, "pid": w.pid,
                 "generation": w.generation, "live": w.live}
                if w is not None else {"shard": i, "live": False}
                for i, w in enumerate(self.workers)]


class WorkerCommandStores(CommandStores):
    """The parent node's CommandStores under the worker runtime: no local
    stores — the split snapshot routes every fan-out over the pipes."""

    remote = True

    def __init__(self, node, supervisor: ShardSupervisor):
        super().__init__(node, num_shards=supervisor.n_workers)
        self.supervisor = supervisor
        # per-shard cumulative ranges, mirroring each worker store's
        # only-grow update_ranges semantics so routing always reaches the
        # worker that still holds previously-owned state
        self.split: List[Ranges] = [Ranges.EMPTY] * supervisor.n_workers
        self._owned = Ranges.EMPTY

    # -- topology ----------------------------------------------------------
    def initialize(self, ranges: Ranges) -> None:
        self.update_topology(ranges)

    def update_topology(self, ranges: Ranges) -> Ranges:
        added = ranges.subtract(self._owned)
        self._owned = self._owned.union(ranges)
        slices = self._splitter.split(ranges)
        self.split = [old.union(sl)
                      for old, sl in zip(self.split, slices)]
        if not self.supervisor._spawned:
            self.supervisor.spawn_all()
        else:
            # stream the new epoch to every worker; each re-slices the
            # same owned ranges itself (no range list crosses the pipe)
            service = self.node.config_service
            spec = (service.spec_for(self.node.topology.epoch)
                    if service is not None else None)
            if spec is not None:
                for w in self.supervisor.workers:
                    if w is not None and w.live:
                        self.supervisor._write(w, frames.ShardEpoch(spec))
        return added

    # -- store access ------------------------------------------------------
    def all(self) -> List:
        return []

    def intersecting(self, participants) -> List:
        return []

    def _intersecting_shards(self, participants) -> List[int]:
        if participants is None:
            return list(range(self.num_shards))
        out = []
        for i, r in enumerate(self.split):
            if r.is_empty:
                continue
            if isinstance(participants, _SortedKeyList):
                if participants.intersects_ranges(r):
                    out.append(i)
            elif isinstance(participants, Ranges):
                if r.intersects(participants):
                    out.append(i)
            else:
                raise TypeError(type(participants))
        return out

    def shard_of(self, participants) -> int:
        idxs = self._intersecting_shards(participants)
        return idxs[0] if idxs else 0

    # -- fan-out -----------------------------------------------------------
    def map_reduce_request(self, request, consume) -> None:
        idxs = self._intersecting_shards(request.participants())
        if not idxs:
            consume(None, EmptyFanout("no intersecting shard"))
            return
        sup = self.supervisor
        mt = request.type
        verb = mt.label if mt is not None else type(request).__name__
        tid = getattr(request, "trace_id", None)
        vals: List = [None] * len(idxs)
        left = [len(idxs)]
        first_failure: List = [None]

        def mk(j):
            def on_reply(value, failure):
                if failure is not None and first_failure[0] is None:
                    first_failure[0] = failure
                vals[j] = value
                left[0] -= 1
                if left[0]:
                    return
                if first_failure[0] is not None:
                    consume(None, first_failure[0])
                    return
                sup.flight.record("shard_reduce", tid, (len(idxs), verb))
                acc = None
                for v in vals:  # shard order; None = EmptyFanout leg
                    if v is None:
                        continue
                    acc = v if acc is None else request.reduce(acc, v)
                consume(acc, None)
            return on_reply

        for j, shard in enumerate(idxs):
            sup.submit(shard, request, mk(j))

    # -- audit -------------------------------------------------------------
    def audit_local(self, req, done: Callable) -> None:
        """Serve a node-local audit walk by fanning over the workers."""
        kind = "digest" if type(req).__name__ == "AuditDigest" else "entries"
        limit = getattr(req, "limit", 0)
        self.supervisor.audit_fan(kind, req.ranges, req.lo, req.hi, limit,
                                  done)

    def audit_request(self, req, from_id: int, reply_context) -> None:
        """Serve a peer's AUDIT_* request (messages/audit.py remote
        branch); a dead worker leaves the peer to its RPC timeout, which
        audits as missing -> inconclusive."""

        def done(reply):
            if reply is not None:
                self.node.reply(from_id, reply_context, reply)

        self.audit_local(req, done)

    def merged_census(self) -> Optional[dict]:
        """Fold the cached per-worker censuses into one node view (the
        stats poll refreshes the cache every ~2s)."""
        rsps = [self.supervisor.stats_cache.get(s)
                for s in range(self.num_shards)]
        rsps = [r for r in rsps if r is not None]
        if not rsps:
            return None
        from accord_tpu.local.audit import merge_censuses
        return merge_censuses([r.census for r in rsps],
                              node_id=self.node.id,
                              at_us=self.node.obs.now_us())
