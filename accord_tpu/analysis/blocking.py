"""Event-loop blocking-call detector.

Call-graph reachability from the selector-loop roots (`TcpHost._run` /
`_dispatch`, `MaelstromHost.run`) and from `Node._process` to blocking
primitives: `time.sleep`, `Condition`/`Event.wait`, `Thread.join`,
`Queue.get/put`, blocking socket/file ops, `os.fsync`, subprocess.

Deferred edges (callbacks handed to `WriteAheadLog.on_durable`) are not
followed — those run on the flush thread, the canonical declared
off-loop context.  Specific (function, primitive) pairs that *are* the
loop's own idle wait live in ALLOWED with a one-line justification each.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

from .core import RepoIndex
from .findings import Finding

PASS_ID = "blocking"

# external dotted calls that block the calling thread
BLOCKING_EXTERNALS = {
    "time.sleep",
    "os.fsync", "os.fdatasync", "os.system", "os.wait", "os.waitpid",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
    "socket.create_connection", "socket.getaddrinfo",
    "threading.Condition.wait", "threading.Event.wait",
    "threading.Thread.join",
    "queue.Queue.get", "queue.Queue.put",
    "socket.socket.connect", "socket.socket.accept",
    "socket.socket.sendall", "socket.socket.recv",
    "socket.socket.makefile",
}

# default loop roots for the real repo
DEFAULT_ROOTS = (
    "accord_tpu.host.tcp::TcpHost._run",
    "accord_tpu.host.tcp::TcpHost._dispatch",
    "accord_tpu.host.maelstrom::MaelstromHost.run",
    "accord_tpu.local.node::Node._process",
    "accord_tpu.shard.worker::WorkerHost.run",
    "accord_tpu.shard.worker::WorkerHost._dispatch",
)

# (function qualname, primitive) pairs that are the loop's own idle wait
# or an otherwise-declared off-loop blocking point; each needs a reason.
ALLOWED: Dict[Tuple[str, str], str] = {
    ("accord_tpu.host.maelstrom::MaelstromHost.run", "queue.Queue.get"):
        "the Maelstrom loop's own poll: stdin lines arrive via the reader "
        "thread's queue, and this get(timeout=) IS the scheduler block",
    ("accord_tpu.shard.worker::WorkerHost.run", "queue.Queue.get"):
        "the shard worker loop's own poll: pipe frames arrive via the "
        "reader thread's queue, and this get(timeout=) IS the scheduler "
        "block",
}


def run(index: RepoIndex, roots: Sequence[str] = DEFAULT_ROOTS,
        allowed: Dict[Tuple[str, str], str] = None) -> List[Finding]:
    allowed = ALLOWED if allowed is None else allowed
    findings: List[Finding] = []
    paths = index.reachable(roots, skip_deferred=True)
    for qn, path in paths.items():
        fn = index.functions[qn]
        for ext in fn.externals:
            if ext.name not in BLOCKING_EXTERNALS:
                continue
            if (qn, ext.name) in allowed:
                continue
            via = " -> ".join(p.split("::")[-1] for p in path)
            findings.append(Finding(
                pass_id=PASS_ID,
                file=index.relpath(fn.path),
                line=ext.lineno,
                qualname=qn,
                code="blocking-call",
                message=f"{ext.name} reachable from loop root "
                        f"{path[0].split('::')[-1]} via {via}",
                detail=f"{ext.name}@root={path[0].split('::')[-1]}"))
    return findings
