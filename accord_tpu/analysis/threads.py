"""Cross-thread shared-state audit.

Thread entry points are enumerated from the index: every
``threading.Thread(target=...)`` site (the WAL flush thread, the TCP
host loop thread, the Maelstrom stdin reader, httpd, workload pacers),
plus configured main-thread loops (``MaelstromHost.run``) and, for every
class defined in a thread-creating module, a pseudo-root per public
method (the "any thread may call this" API surface — the client
`_send_lock` users enter here).

Contexts are propagated over the call graph with the marshalling idioms
rewritten en route: a callback handed to ``call_soon``/``scheduler.once``
recolors to the owner's loop context, a function opening with the
``get_ident() != self._loop_tid`` guard converts *any* caller context to
its loop, and ``on_durable`` callbacks recolor to the flush thread.

Two rules over attribute mutations (``self.x = ...``, ``+=``, item
writes; ``__init__``/ctor-only writes exempt — construction
happens-before publication):

- **inconsistent-lock**: the attribute is written under a recognized
  lock somewhere and without it elsewhere;
- **unlocked-write**: the attribute is written from ≥2 distinct thread
  contexts and this site holds no lock (sites that only the loop writes
  are reported on the foreign-context side).

A write counts as locked if a lock is held lexically *or* every call
site of the enclosing function holds a common lock (the
``_mark_durable`` caller-holds-the-lock idiom, one level deep).

Known blind spot (documented, not a guarantee): container mutations via
method call (``self.xs.append(...)``) and attributes shared across
modules that never construct a thread are not audited.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import FunctionInfo, RepoIndex
from .findings import Finding

PASS_ID = "threads"

# main-thread loops that are thread contexts but not Thread targets
DEFAULT_EXTRA_ROOTS = ("accord_tpu.host.maelstrom::MaelstromHost.run",)


def _loop_classes(index: RepoIndex) -> Set[str]:
    """Classes with a marshalling guard (or _loop_tid) own an event loop."""
    out: Set[str] = set()
    for fn in index.functions.values():
        if fn.has_marshal_guard and fn.cls:
            out.add(fn.cls)
    return out


def _color(index: RepoIndex, roots: Dict[str, str],
           loop_classes: Set[str]) -> Dict[str, Set[str]]:
    """Propagate thread-context colors over the call graph."""

    def loop_color(fn: FunctionInfo) -> Optional[str]:
        if fn.cls in loop_classes:
            return f"loop:{fn.cls}"
        return None

    colors: Dict[str, Set[str]] = {}
    queue: List[Tuple[str, str]] = []

    def add(qn: str, color: str) -> None:
        if qn not in index.functions:
            return
        fn = index.functions[qn]
        # a marshal guard converts any incoming context to the owner loop
        if fn.has_marshal_guard or fn.marshalled_to_loop:
            color = loop_color(fn) or color
        got = colors.setdefault(qn, set())
        if color not in got:
            got.add(color)
            queue.append((qn, color))

    for qn, color in roots.items():
        fn = index.functions.get(qn)
        if fn is None:
            continue
        # a loop class's thread target IS the loop: unify with loop color
        add(qn, (loop_color(fn) or color))

    while queue:
        cur, color = queue.pop(0)
        for edge in index.functions[cur].edges:
            nxt = color
            if edge.deferred:
                nxt = "thread:wal-flush"
            elif edge.marshalled:
                target = index.functions.get(edge.callee)
                if target is not None:
                    nxt = loop_color(target) or \
                        (loop_color(index.functions[cur]) or color)
            add(edge.callee, nxt)
    return colors


def _roots(index: RepoIndex,
           extra_roots: Sequence[str]) -> Dict[str, str]:
    """Real thread entry points only: Thread(target=...) sites plus the
    configured main-thread loops.  No speculative per-public-method
    contexts — a mutation is cross-thread when two *actual* entry points
    reach it, which keeps single-threaded drivers (host/runner.py's
    subprocess router, bench mains) out of the report."""
    roots: Dict[str, str] = {}
    for t in index.thread_targets:
        roots[t.target] = f"thread:{t.target.split('::')[-1]}"
    for qn in extra_roots:
        roots.setdefault(qn, f"main:{qn.split('::')[-1]}")
    return roots


def _caller_held_locks(index: RepoIndex, fn: FunctionInfo) -> Set[str]:
    """Common lock tokens held at EVERY call site of `fn` (one level)."""
    common: Optional[Set[str]] = None
    for other in index.functions.values():
        for edge in other.edges:
            if edge.callee != fn.qualname:
                continue
            held = set(edge.locks)
            common = held if common is None else (common & held)
            if not common:
                return set()
    return common or set()


def run(index: RepoIndex,
        extra_roots: Sequence[str] = DEFAULT_EXTRA_ROOTS) -> List[Finding]:
    # audited classes: defined in a module that constructs a thread (or
    # hosts a configured main-thread loop)
    threaded_modules = {
        index.functions[t.creator].module
        for t in index.thread_targets if t.creator in index.functions}
    for qn in extra_roots:
        if qn in index.functions:
            threaded_modules.add(index.functions[qn].module)
    audited = {qn for qn, cls in index.classes.items()
               if cls.module in threaded_modules}

    loop_classes = _loop_classes(index)
    # a class hosting a configured main-thread loop owns that loop too
    for qn in extra_roots:
        fn = index.functions.get(qn)
        if fn is not None and fn.cls:
            loop_classes.add(fn.cls)
    roots = _roots(index, extra_roots)
    colors = _color(index, roots, loop_classes)

    # ctor-only functions: every in-edge comes from the class's __init__
    in_edges: Dict[str, Set[str]] = {}
    for fn in index.functions.values():
        for e in fn.edges:
            in_edges.setdefault(e.callee, set()).add(fn.qualname)

    def ctor_only(fn: FunctionInfo) -> bool:
        if fn.name == "__init__":
            return True
        callers = in_edges.get(fn.qualname, set())
        return bool(callers) and all(
            c.endswith(".__init__") for c in callers)

    findings: List[Finding] = []
    for cls_qn in sorted(audited):
        cls = index.classes[cls_qn]
        # gather every mutation site per attribute across the class
        sites: Dict[str, List[Tuple[FunctionInfo, object, Set[str]]]] = {}
        for fq in cls.methods.values():
            for member in [fq] + [
                    f.qualname for f in index._children.get(fq, [])]:
                fn = index.functions[member]
                if ctor_only(fn):
                    continue
                held_by_callers = None
                for w in fn.self_writes:
                    locks = set(w.locks)
                    if not locks:
                        if held_by_callers is None:
                            held_by_callers = _caller_held_locks(index, fn)
                        locks |= held_by_callers
                    sites.setdefault(w.attr, []).append((fn, w, locks))
        for attr, writes in sorted(sites.items()):
            all_colors: Set[str] = set()
            for fn, w, _locks in writes:
                c = set(colors.get(fn.qualname, set()))
                if w.after_guard and fn.cls in loop_classes:
                    c = {f"loop:{fn.cls}"}
                all_colors |= c
            locked_somewhere = any(locks for _, _, locks in writes)
            for fn, w, locks in writes:
                c = colors.get(fn.qualname, set())
                if w.after_guard and fn.cls in loop_classes:
                    c = {f"loop:{fn.cls}"}
                if locks:
                    continue
                if locked_somewhere:
                    findings.append(Finding(
                        pass_id=PASS_ID, file=index.relpath(fn.path),
                        line=w.lineno, qualname=fn.qualname,
                        code="inconsistent-lock",
                        message=f"attribute {cls.name}.{attr} is written "
                                f"under a lock elsewhere but not here",
                        detail=attr))
                elif len(all_colors) >= 2 and c and \
                        not all(x.startswith("loop:") for x in all_colors):
                    others = sorted(all_colors - c) or sorted(all_colors)
                    findings.append(Finding(
                        pass_id=PASS_ID, file=index.relpath(fn.path),
                        line=w.lineno, qualname=fn.qualname,
                        code="unlocked-write",
                        message=f"attribute {cls.name}.{attr} written from "
                                f"{'/'.join(sorted(c))} without a lock; "
                                f"also written from {'/'.join(others)}",
                        detail=attr))
    return findings
