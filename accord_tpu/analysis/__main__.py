"""CLI runner: `python -m accord_tpu.analysis`.

Exit codes: 0 clean (possibly with suppressed/stale warnings), 2 when
unsuppressed findings exist, 3 on baseline-policy violations.
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from . import PASSES, run_repo
from .baseline import (DEFAULT_BASELINE, BaselineError, load_baseline,
                       write_baseline)
from .core import build_package_index


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m accord_tpu.analysis",
        description="accord-lint: protocol static analysis")
    ap.add_argument("--select", default=None,
                    help=f"comma-separated pass names "
                         f"(default: all of {','.join(PASSES)})")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--baseline", default=str(DEFAULT_BASELINE),
                    help="baseline file (use '' to disable)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write every current finding to the baseline file "
                         "with a TODO justification (must be edited before "
                         "it will load)")
    args = ap.parse_args(argv)

    select = args.select.split(",") if args.select else None
    baseline_path = Path(args.baseline) if args.baseline else None

    if args.write_baseline:
        index = build_package_index()
        report = run_repo(select=select, baseline_path=None, index=index)
        write_baseline(report.new, baseline_path or DEFAULT_BASELINE)
        print(f"wrote {len(report.new)} entries to "
              f"{baseline_path or DEFAULT_BASELINE} — justify each before "
              f"checking in")
        return 0

    try:
        report = run_repo(select=select, baseline_path=baseline_path)
    except BaselineError as e:
        print(f"baseline policy violation: {e}", file=sys.stderr)
        return 3
    except KeyError as e:
        ap.error(str(e.args[0] if e.args else e))

    if args.as_json:
        print(json.dumps({
            "ok": report.ok,
            "findings": [f.to_json() for f in report.new],
            "suppressed": [f.to_json() for f in report.suppressed],
            "stale_baseline_keys": report.stale,
            "timings_s": {k: round(v, 4) for k, v in report.timings.items()},
        }, indent=2))
    else:
        for f in report.new:
            print(f.render())
        total = sum(report.timings.values())
        print(f"accord-lint: {len(report.new)} finding(s), "
              f"{len(report.suppressed)} suppressed by baseline, "
              f"{len(report.stale)} stale baseline key(s) "
              f"[{total:.2f}s]")
        for k in report.stale:
            print(f"  stale baseline entry (construct gone — remove): {k}")
    return 0 if report.ok else 2


if __name__ == "__main__":
    sys.exit(main())
