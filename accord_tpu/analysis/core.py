"""Shared AST index for accord-lint (`accord_tpu.analysis`).

One parse of the package tree feeds every pass: a module index (imports
resolved, module-level types), a class table (base classes plus attribute
types inferred from ``self.x = Ctor(...)`` bindings), and an approximate
call graph keyed by qualnames (``pkg.mod::Class.method``).

Resolution policy is precision-over-recall: an edge is only created when
the callee can be pinned down — direct names, ``self.method`` through the
repo-local MRO, receivers whose type was inferred from a constructor
binding, or (last resort) a bare method name defined by at most
``AMBIG_CAP`` classes repo-wide.  Anything else gets *no* edge; passes
that care about specific primitives (``time.sleep``, ``os.fsync``,
``Condition.wait``) match them at the call site through the resolved
external-call list instead of chasing unresolvable dispatch.

Thread/marshalling idioms the index understands:

- ``threading.Thread(target=fn)`` records a thread entry point, not an
  edge (the target runs on its own thread, never the caller's);
- callbacks handed to ``call_soon`` / ``scheduler.once`` / ``.at`` are
  marked ``marshalled_to_loop`` (the wakeup-socketpair idiom);
- callbacks handed to ``on_durable`` are *deferred* edges (they fire on
  the WAL flush thread) and are skipped by loop reachability;
- the ``if threading.get_ident() != self._loop_tid: self.call_soon(...);
  return`` guard makes everything after it loop-context.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# receivers typed as one of these count as lock-like for `with` tracking
LOCK_TYPES = {"threading.Lock", "threading.RLock", "threading.Condition"}
# bare-name fallback resolution: give up beyond this many candidates
AMBIG_CAP = 4
# never bare-name-resolve these: they collide with builtin collection /
# socket / file APIs on untyped receivers and fabricate edges
AMBIG_EXCLUDED = {
    "append", "appendleft", "extend", "insert", "add", "remove", "discard",
    "pop", "popleft", "clear", "update", "get", "put", "setdefault", "sort",
    "join", "split", "strip", "read", "write", "close", "open", "send",
    "recv", "count", "index", "copy", "keys", "values", "items", "flush",
}
# method calls on self attributes that mutate the receiver in place
# (audited as writes by the threads pass)
MUTATOR_METHODS = {
    "append", "appendleft", "extend", "insert", "add", "remove", "discard",
    "pop", "popleft", "popitem", "clear", "update", "setdefault", "sort",
    "reverse",
}
# function-reference sinks that marshal the callback onto the event loop
MARSHAL_SINKS = {"call_soon", "once", "at"}
# function-reference sinks that defer the callback to another thread
DEFERRED_SINKS = {"on_durable"}
# external type prefixes worth remembering in attribute-type inference
_EXTERNAL_TYPE_PREFIXES = ("threading.", "queue.", "socket.", "selectors.",
                           "subprocess.", "collections.")


@dataclass
class CallEdge:
    caller: str
    callee: str                 # repo-local qualname
    lineno: int
    kind: str                   # direct | ctor | ambiguous | callback
    deferred: bool = False      # fires on another thread (on_durable)
    marshalled: bool = False    # fires on the owner's event loop (call_soon)
    locks: Tuple[str, ...] = () # lock tokens held lexically at the call site


@dataclass
class ExternalCall:
    name: str                   # dotted, e.g. "time.sleep", "threading.Condition.wait"
    lineno: int


@dataclass
class SelfWrite:
    attr: str
    lineno: int
    locks: Tuple[str, ...]      # lock tokens held lexically at the write
    kind: str                   # assign | augassign | item | del
    after_guard: bool           # past the get_ident()/call_soon marshal guard


@dataclass
class FunctionInfo:
    qualname: str
    module: str
    cls: Optional[str]          # owning class qualname, or None
    name: str
    node: ast.AST
    path: Path
    lineno: int
    parent: Optional[str] = None        # enclosing function (nested defs)
    edges: List[CallEdge] = field(default_factory=list)
    externals: List[ExternalCall] = field(default_factory=list)
    self_writes: List[SelfWrite] = field(default_factory=list)
    has_marshal_guard: bool = False
    marshalled_to_loop: bool = False    # passed to call_soon/scheduler


@dataclass
class ClassInfo:
    qualname: str
    module: str
    name: str
    node: ast.ClassDef
    lineno: int
    bases: List[str] = field(default_factory=list)      # resolved dotted/qualnames
    attr_types: Dict[str, str] = field(default_factory=dict)
    methods: Dict[str, str] = field(default_factory=dict)  # bare -> qualname


@dataclass
class ModuleInfo:
    name: str
    path: Path
    tree: ast.Module
    is_package: bool
    imports: Dict[str, str] = field(default_factory=dict)  # local name -> dotted
    global_types: Dict[str, str] = field(default_factory=dict)
    import_targets: Set[str] = field(default_factory=set)  # dotted modules imported


@dataclass
class ThreadTarget:
    creator: str                # function qualname containing Thread(...)
    target: str                 # resolved function qualname
    lineno: int


class RepoIndex:
    """Parsed view of one package tree; built once, shared by every pass."""

    def __init__(self, root: Path, package: str):
        self.root = Path(root)
        self.package = package
        self.modules: Dict[str, ModuleInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self.methods_by_name: Dict[str, List[str]] = {}
        self.thread_targets: List[ThreadTarget] = []
        self._children: Dict[str, List[FunctionInfo]] = {}

    # ------------------------------------------------------------ building --
    @classmethod
    def build(cls, root: Path, package: Optional[str] = None) -> "RepoIndex":
        root = Path(root)
        index = cls(root, package or root.name)
        for path in sorted(root.rglob("*.py")):
            if "__pycache__" in path.parts:
                continue
            rel = path.relative_to(root)
            parts = [index.package] + list(rel.parts[:-1])
            if rel.name != "__init__.py":
                parts.append(rel.stem)
            name = ".".join(parts)
            try:
                tree = ast.parse(path.read_text(), filename=str(path))
            except SyntaxError:
                continue
            index.modules[name] = ModuleInfo(
                name=name, path=path, tree=tree,
                is_package=(rel.name == "__init__.py"))
        for mod in index.modules.values():
            index._index_imports(mod)
            index._index_defs(mod)
        for mod in index.modules.values():
            index._index_types(mod)
        for f in index.functions.values():
            if f.parent is not None:
                index._children.setdefault(f.parent, []).append(f)
        for mod in index.modules.values():
            index._analyze_bodies(mod)
        return index

    def _index_imports(self, mod: ModuleInfo) -> None:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        mod.imports[a.asname] = a.name
                    else:
                        head = a.name.split(".")[0]
                        mod.imports[head] = head
                    mod.import_targets.add(a.name)
            elif isinstance(node, ast.ImportFrom):
                base = self._resolve_from(mod, node)
                if base is None:
                    continue
                mod.import_targets.add(base)
                for a in node.names:
                    if a.name == "*":
                        continue
                    mod.imports[a.asname or a.name] = f"{base}.{a.name}"

    def _resolve_from(self, mod: ModuleInfo, node: ast.ImportFrom) -> Optional[str]:
        if node.level == 0:
            return node.module
        parts = mod.name.split(".")
        if not mod.is_package:
            parts = parts[:-1]
        drop = node.level - 1
        if drop:
            if drop >= len(parts):
                return None
            parts = parts[:-drop]
        if node.module:
            parts = parts + node.module.split(".")
        return ".".join(parts) if parts else None

    def _index_defs(self, mod: ModuleInfo) -> None:
        def visit(body, cls_qn: Optional[str], parent_fn: Optional[str]):
            for node in body:
                if isinstance(node, ast.ClassDef) and parent_fn is None:
                    qn = f"{mod.name}::{node.name}"
                    self.classes[qn] = ClassInfo(
                        qualname=qn, module=mod.name, name=node.name,
                        node=node, lineno=node.lineno)
                    visit(node.body, qn, None)
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if parent_fn is not None:
                        fq = f"{parent_fn}.{node.name}"
                    elif cls_qn is not None:
                        fq = f"{cls_qn.split('::')[0]}::" \
                             f"{cls_qn.split('::')[1]}.{node.name}"
                    else:
                        fq = f"{mod.name}::{node.name}"
                    info = FunctionInfo(
                        qualname=fq, module=mod.name, cls=cls_qn,
                        name=node.name, node=node, path=mod.path,
                        lineno=node.lineno, parent=parent_fn)
                    self.functions[fq] = info
                    if cls_qn is not None and parent_fn is None:
                        self.classes[cls_qn].methods[node.name] = fq
                        self.methods_by_name.setdefault(node.name, []).append(fq)
                    visit(node.body, cls_qn, fq)

        visit(mod.tree.body, None, None)

    # ---------------------------------------------------------- resolution --
    def resolve_name(self, mod: ModuleInfo, name: str) -> Optional[str]:
        """Dotted target for a bare name in `mod`: local def, then import."""
        if f"{mod.name}::{name}" in self.classes:
            return f"{mod.name}.{name}"
        if f"{mod.name}::{name}" in self.functions:
            return f"{mod.name}.{name}"
        if name in mod.imports:
            return mod.imports[name]
        return None

    def dotted_of(self, mod: ModuleInfo, expr: ast.AST) -> Optional[str]:
        """Resolve Name / Attribute-chain expressions to a dotted path."""
        if isinstance(expr, ast.Name):
            return self.resolve_name(mod, expr.id)
        if isinstance(expr, ast.Attribute):
            base = self.dotted_of(mod, expr.value)
            if base is None:
                return None
            return f"{base}.{expr.attr}"
        return None

    def lookup(self, dotted: str) -> Optional[Tuple[str, str]]:
        """Map a dotted path to a repo entity: ('func'|'class', qualname)."""
        if "." not in dotted:
            return None
        mod_name, _, leaf = dotted.rpartition(".")
        # the binding may point one module deep (from pkg.mod import X)
        for candidate_mod, candidate_leaf in ((mod_name, leaf), (dotted, None)):
            if candidate_mod in self.modules and candidate_leaf:
                qn = f"{candidate_mod}::{candidate_leaf}"
                if qn in self.classes:
                    return ("class", qn)
                if qn in self.functions:
                    return ("func", qn)
        return None

    def mro_lookup(self, cls_qn: str, method: str,
                   _seen: Optional[Set[str]] = None) -> Optional[str]:
        """Find `method` on the class or its repo-local bases."""
        seen = _seen or set()
        if cls_qn in seen or cls_qn not in self.classes:
            return None
        seen.add(cls_qn)
        info = self.classes[cls_qn]
        if method in info.methods:
            return info.methods[method]
        for base in info.bases:
            ent = self.lookup(base)
            if ent and ent[0] == "class":
                found = self.mro_lookup(ent[1], method, seen)
                if found:
                    return found
        return None

    # -------------------------------------------------------------- typing --
    def _infer_type(self, mod: ModuleInfo, expr: ast.AST) -> Optional[str]:
        """Type of an expression, for constructor calls only."""
        if not isinstance(expr, ast.Call):
            return None
        dotted = self.dotted_of(mod, expr.func)
        if dotted is None:
            return None
        ent = self.lookup(dotted)
        if ent and ent[0] == "class":
            return ent[1]
        if dotted.startswith(_EXTERNAL_TYPE_PREFIXES):
            return dotted
        return None

    def _index_types(self, mod: ModuleInfo) -> None:
        # resolve class bases now that every module's defs are known
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
                t = self._infer_type(mod, stmt.value)
                if t:
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            mod.global_types[tgt.id] = t
        for cls in self.classes.values():
            if cls.module != mod.name:
                continue
            for b in cls.node.bases:
                dotted = self.dotted_of(mod, b)
                if dotted:
                    cls.bases.append(dotted)
            for fq in cls.methods.values():
                fn = self.functions[fq]
                for node in ast.walk(fn.node):
                    tgt = val = None
                    if isinstance(node, ast.Assign) and len(node.targets) == 1:
                        tgt, val = node.targets[0], node.value
                    elif isinstance(node, ast.AnnAssign) and node.value is not None:
                        tgt, val = node.target, node.value
                    if (tgt is not None and isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        t = self._infer_type(mod, val)
                        if t and tgt.attr not in cls.attr_types:
                            cls.attr_types[tgt.attr] = t

    # ---------------------------------------------------------- body walks --
    def _analyze_bodies(self, mod: ModuleInfo) -> None:
        for fn in self.functions.values():
            if fn.module == mod.name:
                _BodyWalker(self, mod, fn).run()

    # --------------------------------------------------------- reachability --
    def reachable(self, roots: Sequence[str], *,
                  skip_deferred: bool = True,
                  follow_marshalled: bool = True,
                  ) -> Dict[str, Tuple[str, ...]]:
        """BFS over call edges; returns qualname -> path-from-root."""
        paths: Dict[str, Tuple[str, ...]] = {}
        queue: List[str] = []
        for r in roots:
            if r in self.functions and r not in paths:
                paths[r] = (r,)
                queue.append(r)
        while queue:
            cur = queue.pop(0)
            for edge in self.functions[cur].edges:
                if skip_deferred and edge.deferred:
                    continue
                if not follow_marshalled and edge.marshalled:
                    continue
                nxt = edge.callee
                if nxt in self.functions and nxt not in paths:
                    paths[nxt] = paths[cur] + (nxt,)
                    queue.append(nxt)
        return paths

    def relpath(self, path: Path) -> str:
        try:
            return str(Path(path).relative_to(self.root.parent))
        except ValueError:
            return str(path)


class _BodyWalker:
    """Single walk of one function body: edges, externals, writes, guard."""

    def __init__(self, index: RepoIndex, mod: ModuleInfo, fn: FunctionInfo):
        self.index = index
        self.mod = mod
        self.fn = fn
        self.locals_types: Dict[str, Optional[str]] = {}
        self.lock_stack: List[str] = []
        self.guard_end: Optional[int] = None
        # nested defs visible by bare name: own children, then siblings and
        # the enclosing chain's children (closure scope, deepest wins)
        scopes = []
        anc: Optional[str] = fn.qualname
        while anc is not None:
            scopes.append(anc)
            anc = index.functions[anc].parent if anc in index.functions else None
        self.nested: Dict[str, str] = {}
        for scope in reversed(scopes):
            for f in index._children.get(scope, []):
                self.nested[f.name] = f.qualname

    def run(self) -> None:
        node = self.fn.node
        self.guard_end = self._find_marshal_guard(node)
        self.fn.has_marshal_guard = self.guard_end is not None
        self._infer_locals(node)
        for stmt in node.body:
            self._visit(stmt)

    # -- marshal guard: if get_ident() != self._loop_tid: call_soon(); return
    def _find_marshal_guard(self, node: ast.AST) -> Optional[int]:
        for stmt in getattr(node, "body", []):
            if not isinstance(stmt, ast.If):
                continue
            names = {n.id for n in ast.walk(stmt.test)
                     if isinstance(n, ast.Name)}
            attrs = {n.attr for n in ast.walk(stmt.test)
                     if isinstance(n, ast.Attribute)}
            if "get_ident" not in (names | attrs):
                continue
            has_marshal = any(
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in MARSHAL_SINKS
                for s in stmt.body for n in ast.walk(s))
            has_return = any(
                isinstance(n, ast.Return)
                for s in stmt.body for n in ast.walk(s))
            if has_marshal and has_return:
                return stmt.end_lineno or stmt.lineno
        return None

    def _infer_locals(self, node: ast.AST) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and sub is not node:
                continue
            tgt = val = None
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                tgt, val = sub.targets[0], sub.value
            elif isinstance(sub, ast.AnnAssign) and sub.value is not None:
                tgt, val = sub.target, sub.value
            if tgt is None or not isinstance(tgt, ast.Name):
                continue
            t = self.index._infer_type(self.mod, val)
            if tgt.id in self.locals_types and self.locals_types[tgt.id] != t:
                self.locals_types[tgt.id] = None     # conflicting rebind
            else:
                self.locals_types[tgt.id] = t

    # ------------------------------------------------------------- walking --
    def _visit(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return                       # nested defs walk themselves
        if isinstance(node, ast.With):
            tokens = [self._lock_token(item.context_expr)
                      for item in node.items]
            tokens = [t for t in tokens if t]
            self.lock_stack.extend(tokens)
            for stmt in node.body:
                self._visit(stmt)
            for _ in tokens:
                self.lock_stack.pop()
            for item in node.items:
                self._visit(item.context_expr)
            return
        if isinstance(node, ast.Call):
            self._visit_call(node)
        self._collect_write(node)
        for child in ast.iter_child_nodes(node):
            self._visit(child)

    def _lock_token(self, expr: ast.AST) -> Optional[str]:
        t = self._receiver_type(expr)
        if t in LOCK_TYPES:
            return ast.dump(expr) if not isinstance(expr, (ast.Name, ast.Attribute)) \
                else self._expr_token(expr)
        return None

    def _expr_token(self, expr: ast.AST) -> str:
        if isinstance(expr, ast.Name):
            return expr.id
        if isinstance(expr, ast.Attribute):
            return f"{self._expr_token(expr.value)}.{expr.attr}"
        return "<expr>"

    def _receiver_type(self, expr: ast.AST) -> Optional[str]:
        """Inferred type of a receiver expression, or None."""
        if isinstance(expr, ast.Name):
            if expr.id in self.locals_types:
                return self.locals_types[expr.id]
            if expr.id in self.mod.global_types:
                return self.mod.global_types[expr.id]
            return None
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self":
            if self.fn.cls and self.fn.cls in self.index.classes:
                return self.index.classes[self.fn.cls].attr_types.get(expr.attr)
        return None

    # --------------------------------------------------------------- calls --
    def _visit_call(self, call: ast.Call) -> None:
        fn, index, mod = self.fn, self.index, self.mod
        func = call.func
        callee_attr_name: Optional[str] = None
        callee_dotted: Optional[str] = None
        resolved = False

        if isinstance(func, ast.Name):
            name = func.id
            if name in self.nested:
                self._add_edge(self.nested[name], call.lineno, "direct")
                resolved = True
            else:
                dotted = index.resolve_name(mod, name)
                if dotted:
                    callee_dotted = dotted
                    ent = index.lookup(dotted)
                    if ent and ent[0] == "func":
                        self._add_edge(ent[1], call.lineno, "direct")
                        resolved = True
                    elif ent and ent[0] == "class":
                        init = index.mro_lookup(ent[1], "__init__")
                        if init:
                            self._add_edge(init, call.lineno, "ctor")
                        resolved = True
                    else:
                        fn.externals.append(ExternalCall(dotted, call.lineno))
                        resolved = True
                elif name == "id":
                    fn.externals.append(ExternalCall("builtins.id", call.lineno))
                    resolved = True
                elif name == "super":
                    resolved = True
        elif isinstance(func, ast.Attribute):
            callee_attr_name = func.attr
            recv = func.value
            # self.method(...) through the repo-local MRO
            if isinstance(recv, ast.Name) and recv.id == "self" and fn.cls:
                target = index.mro_lookup(fn.cls, func.attr)
                if target:
                    self._add_edge(target, call.lineno, "direct")
                    resolved = True
            # super().method(...)
            elif (isinstance(recv, ast.Call)
                    and isinstance(recv.func, ast.Name)
                    and recv.func.id == "super" and fn.cls):
                for base in self.index.classes[fn.cls].bases \
                        if fn.cls in self.index.classes else []:
                    ent = index.lookup(base)
                    if ent and ent[0] == "class":
                        target = index.mro_lookup(ent[1], func.attr)
                        if target:
                            self._add_edge(target, call.lineno, "direct")
                            resolved = True
                            break
            if not resolved:
                dotted = index.dotted_of(mod, recv)
                if dotted is not None:
                    full = f"{dotted}.{func.attr}"
                    callee_dotted = full
                    ent = index.lookup(full)
                    if ent and ent[0] == "func":
                        self._add_edge(ent[1], call.lineno, "direct")
                        resolved = True
                    elif ent and ent[0] == "class":
                        init = index.mro_lookup(ent[1], "__init__")
                        if init:
                            self._add_edge(init, call.lineno, "ctor")
                        resolved = True
                    elif dotted in mod.imports.values() or \
                            dotted.split(".")[0] in mod.imports.values():
                        fn.externals.append(ExternalCall(full, call.lineno))
                        resolved = True
            if not resolved:
                rtype = self._receiver_type(recv)
                if rtype is not None:
                    if rtype in index.classes:
                        target = index.mro_lookup(rtype, func.attr)
                        if target:
                            self._add_edge(target, call.lineno, "direct")
                        resolved = True
                    else:
                        fn.externals.append(
                            ExternalCall(f"{rtype}.{func.attr}", call.lineno))
                        resolved = True
            if not resolved and func.attr not in AMBIG_EXCLUDED:
                # bare-name fallback under the ambiguity cap
                cands = index.methods_by_name.get(func.attr, [])
                if 0 < len(cands) <= AMBIG_CAP:
                    for c in cands:
                        self._add_edge(c, call.lineno, "ambiguous")
                    resolved = True
            # self.<attr>.<mutator>(...) mutates the attribute in place
            if func.attr in MUTATOR_METHODS \
                    and isinstance(recv, ast.Attribute) \
                    and isinstance(recv.value, ast.Name) \
                    and recv.value.id == "self":
                after = self.guard_end is not None \
                    and call.lineno > self.guard_end
                self.fn.self_writes.append(SelfWrite(
                    attr=recv.attr, lineno=call.lineno,
                    locks=tuple(self.lock_stack), kind="method",
                    after_guard=after))

        self._visit_callback_args(call, callee_dotted, callee_attr_name)

    def _visit_callback_args(self, call: ast.Call,
                             callee_dotted: Optional[str],
                             callee_attr: Optional[str]) -> None:
        """Function references passed as arguments: thread targets,
        marshalled loop callbacks, deferred durability callbacks, or
        plain same-context continuations."""
        index, fn = self.index, self.fn
        is_thread = callee_dotted == "threading.Thread"
        refs: List[Tuple[Optional[str], str]] = []   # (kw, target qualname)
        for kw, arg in ([(None, a) for a in call.args]
                        + [(k.arg, k.value) for k in call.keywords]):
            target = self._func_ref(arg)
            if target:
                refs.append((kw, target))
        for kw, target in refs:
            if is_thread:
                if kw in (None, "target"):
                    index.thread_targets.append(
                        ThreadTarget(fn.qualname, target, call.lineno))
                continue
            deferred = callee_attr in DEFERRED_SINKS
            marshalled = callee_attr in MARSHAL_SINKS
            if marshalled and target in index.functions:
                index.functions[target].marshalled_to_loop = True
            fn.edges.append(CallEdge(
                caller=fn.qualname, callee=target, lineno=call.lineno,
                kind="callback", deferred=deferred, marshalled=marshalled,
                locks=tuple(self.lock_stack)))

    def _func_ref(self, expr: ast.AST) -> Optional[str]:
        """Resolve a non-called function reference to a qualname."""
        if isinstance(expr, ast.Name):
            if expr.id in self.nested:
                return self.nested[expr.id]
            dotted = self.index.resolve_name(self.mod, expr.id)
            if dotted:
                ent = self.index.lookup(dotted)
                if ent and ent[0] == "func":
                    return ent[1]
        elif isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and self.fn.cls:
            return self.index.mro_lookup(self.fn.cls, expr.attr)
        elif isinstance(expr, ast.Lambda):
            # lambdas are anonymous: approximate by linking the refs inside
            for sub in ast.walk(expr.body):
                t = None
                if isinstance(sub, ast.Call):
                    t = self._func_ref(sub.func)
                if t:
                    self.fn.edges.append(CallEdge(
                        caller=self.fn.qualname, callee=t,
                        lineno=expr.lineno, kind="callback",
                        locks=tuple(self.lock_stack)))
            return None
        return None

    def _add_edge(self, callee: str, lineno: int, kind: str) -> None:
        self.fn.edges.append(CallEdge(
            caller=self.fn.qualname, callee=callee, lineno=lineno, kind=kind,
            locks=tuple(self.lock_stack)))

    # -------------------------------------------------------------- writes --
    def _collect_write(self, node: ast.AST) -> None:
        targets: List[Tuple[ast.AST, str]] = []
        if isinstance(node, ast.Assign):
            targets = [(t, "assign") for t in node.targets]
        elif isinstance(node, ast.AugAssign):
            targets = [(node.target, "augassign")]
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [(node.target, "assign")]
        elif isinstance(node, ast.Delete):
            targets = [(t, "del") for t in node.targets]
        for tgt, kind in targets:
            attr = None
            if isinstance(tgt, ast.Attribute) and \
                    isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
                attr = tgt.attr
            elif isinstance(tgt, ast.Subscript):
                inner = tgt.value
                if isinstance(inner, ast.Attribute) and \
                        isinstance(inner.value, ast.Name) and \
                        inner.value.id == "self":
                    attr, kind = inner.attr, "item"
            if attr is None:
                continue
            after = self.guard_end is not None and node.lineno > self.guard_end
            self.fn.self_writes.append(SelfWrite(
                attr=attr, lineno=node.lineno,
                locks=tuple(self.lock_stack), kind=kind, after_guard=after))


def build_package_index() -> RepoIndex:
    """Index the installed accord_tpu package (the usual entry point)."""
    import accord_tpu
    root = Path(accord_tpu.__file__).parent
    return RepoIndex.build(root, "accord_tpu")
