"""Layering pass: import-boundary guards.

- ``accord_tpu.obs`` must stay off the device path: no ``jax`` /
  ``jaxlib`` / ``numpy`` imports, and its only intra-repo imports are
  ``accord_tpu.obs.*`` (anything else risks transitively pulling jax
  onto the always-on observability path).  This is the structural half
  of the determinism pass's obs carve-out: obs may read real clocks
  precisely because nothing in the protocol can import it back.
- ``accord_tpu.analysis`` itself obeys the same no-jax rule (the linter
  must run on a box with no device stack at all).
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

from .core import RepoIndex
from .findings import Finding

PASS_ID = "layering"

BANNED_ROOTS = ("jax", "jaxlib", "numpy")

# (package prefix, intra-repo import allowance or None for "any")
GUARDED: Tuple[Tuple[str, str], ...] = (
    ("obs", "obs"),
    ("analysis", None),
)


def run(index: RepoIndex) -> List[Finding]:
    pkg = index.package
    findings: List[Finding] = []
    for sub, allowance in GUARDED:
        prefix = f"{pkg}.{sub}"
        for mod in index.modules.values():
            if not (mod.name == prefix or mod.name.startswith(prefix + ".")):
                continue
            rel = index.relpath(mod.path)
            for target in sorted(mod.import_targets):
                root = target.split(".")[0]
                if root in BANNED_ROOTS:
                    findings.append(Finding(
                        pass_id=PASS_ID, file=rel, line=1,
                        qualname=mod.name, code="device-import",
                        message=f"{mod.name} imports {target}: {sub}/ must "
                                f"stay off the device path",
                        detail=target))
                elif root == pkg and allowance is not None:
                    allowed = f"{pkg}.{allowance}"
                    if not (target == allowed
                            or target.startswith(allowed + ".")):
                        findings.append(Finding(
                            pass_id=PASS_ID, file=rel, line=1,
                            qualname=mod.name, code="layer-import",
                            message=f"{mod.name} imports {target}: {sub}/ "
                                    f"may only import within {allowed} "
                                    f"(anything else risks pulling jax in)",
                            detail=target))
    return findings
