"""Finding model for accord-lint.

A finding's *baseline key* is deliberately line-number free — pass id,
file (relative to the package parent), qualname, code and a stable detail
string — so a baseline entry survives unrelated edits to the file and
goes stale only when the underlying construct moves or disappears.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List


@dataclass
class Finding:
    pass_id: str        # blocking | determinism | threads | surface | layering
    file: str           # path relative to the package parent
    line: int
    qualname: str       # function/class qualname, or module name
    code: str           # short machine code, e.g. "blocking-call"
    message: str        # human text, includes the reach path where useful
    detail: str = ""    # stable discriminator (primitive name, attr, ...)

    @property
    def key(self) -> str:
        return "::".join((self.pass_id, self.file, self.qualname,
                          self.code, self.detail))

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.pass_id}/{self.code}] " \
               f"{self.qualname}: {self.message}"

    def to_json(self) -> Dict[str, object]:
        return {"pass": self.pass_id, "file": self.file, "line": self.line,
                "qualname": self.qualname, "code": self.code,
                "message": self.message, "detail": self.detail,
                "key": self.key}
