"""Sim-determinism lint.

Burn bit-identity holds only if protocol code reachable from the sim
makes no decision from a wall clock, the module-global `random`, object
identity, set iteration order, or ad-hoc environment reads.  Scope is
the module import closure of `accord_tpu.sim` intersected with the
protocol packages (local, coordinate, messages, impl, primitives,
topology, utils, api, sim) — the code a burn actually executes.

Deliberate carve-outs (not baselined, excluded by design):

- `accord_tpu.obs.*`: observability measures real time by contract; the
  PR-2 invariant that obs never feeds protocol decisions is enforced
  structurally by the layering pass (obs imports nothing from the
  protocol), not by banning clocks inside it.
- `accord_tpu.utils.random_source`: the seeded RandomSource wrapper is
  the sanctioned owner of the stdlib `random` import.
- functions named `from_env` / `*_from_env` / `_env*` and module-level
  statements: config load is where env reads belong.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Sequence, Set

from .core import FunctionInfo, RepoIndex
from .findings import Finding

PASS_ID = "determinism"

WALL_CLOCKS = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.date.today",
}
RANDOM_DRAWS = {
    "random." + n for n in (
        "random", "randint", "randrange", "choice", "choices", "shuffle",
        "sample", "uniform", "gauss", "seed", "getrandbits", "expovariate",
        "betavariate", "triangular", "vonmisesvariate")
}
ENV_READS = {"os.getenv", "os.environ.get", "os.environ.setdefault"}

PROTOCOL_PACKAGES = ("sim", "local", "coordinate", "messages", "impl",
                     "primitives", "topology", "utils", "api")
EXCLUDE_PREFIXES = ("accord_tpu.obs", "accord_tpu.analysis",
                    "accord_tpu.utils.random_source")


def _sim_scope(index: RepoIndex) -> Set[str]:
    """Import closure of <pkg>.sim, restricted to protocol packages."""
    pkg = index.package
    allowed = {f"{pkg}.{p}" for p in PROTOCOL_PACKAGES}

    def in_protocol(name: str) -> bool:
        return name == pkg or any(
            name == a or name.startswith(a + ".") for a in allowed)

    roots = [m for m in index.modules if m.startswith(f"{pkg}.sim")]
    seen: Set[str] = set()
    queue = list(roots)
    while queue:
        cur = queue.pop()
        if cur in seen or cur not in index.modules:
            continue
        seen.add(cur)
        for target in index.modules[cur].import_targets:
            for name in (target, target.rpartition(".")[0]):
                if name and name not in seen and name in index.modules \
                        and in_protocol(name):
                    queue.append(name)
    return {m for m in seen if in_protocol(m)}


def _is_config_load(fn: FunctionInfo) -> bool:
    return (fn.name == "from_env" or fn.name.endswith("_from_env")
            or fn.name.startswith("_env"))


# consuming a set through these erases iteration order, so a
# comprehension fed straight into one is deterministic
ORDER_INSENSITIVE_SINKS = frozenset(
    {"sorted", "min", "max", "sum", "len", "any", "all",
     "set", "frozenset"})


def _set_iteration_sites(fn: FunctionInfo) -> List[int]:
    """`for x in {…}` / `for x in set(…)` — order-dependent iteration."""
    sites: List[int] = []
    set_locals: Set[str] = set()
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            if isinstance(node.value, ast.Set) or (
                    isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Name)
                    and node.value.func.id in ("set", "frozenset")):
                set_locals.add(node.targets[0].id)
            elif node.targets[0].id in set_locals:
                set_locals.discard(node.targets[0].id)
    # comprehensions handed directly to an order-insensitive consumer
    # (`tuple(sorted(t for t in dep_set))`) are fine
    laundered: Set[int] = set()
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id in ORDER_INSENSITIVE_SINKS:
            for arg in node.args:
                if isinstance(arg, (ast.ListComp, ast.SetComp,
                                    ast.GeneratorExp)):
                    laundered.add(id(arg))
    iters: List[ast.expr] = []
    for node in ast.walk(fn.node):
        if isinstance(node, ast.For):
            iters.append(node.iter)
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            if id(node) in laundered:
                continue
            iters.extend(g.iter for g in node.generators)
    for it in iters:
        if isinstance(it, ast.Set):
            sites.append(it.lineno)
        elif isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id in ("set", "frozenset"):
            sites.append(it.lineno)
        elif isinstance(it, ast.Name) and it.id in set_locals:
            sites.append(it.lineno)
    return sites


def _env_subscript_sites(index: RepoIndex, fn: FunctionInfo) -> List[int]:
    """`os.environ[...]` reads (not calls, so not in the externals list)."""
    mod = index.modules[fn.module]
    sites: List[int] = []
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load) \
                and index.dotted_of(mod, node.value) == "os.environ":
            sites.append(node.lineno)
    return sites


def run(index: RepoIndex, scope: Optional[Iterable[str]] = None,
        exclude_prefixes: Sequence[str] = EXCLUDE_PREFIXES) -> List[Finding]:
    if scope is None:
        scope_set = _sim_scope(index)
    else:
        scope_set = set(scope)
    findings: List[Finding] = []
    for fn in index.functions.values():
        if fn.module not in scope_set:
            continue
        if any(fn.module == p or fn.module.startswith(p + ".")
               for p in exclude_prefixes):
            continue
        rel = index.relpath(fn.path)

        def emit(line: int, code: str, msg: str, detail: str) -> None:
            findings.append(Finding(
                pass_id=PASS_ID, file=rel, line=line, qualname=fn.qualname,
                code=code, message=msg, detail=detail))

        config_load = _is_config_load(fn)
        for ext in fn.externals:
            if ext.name in WALL_CLOCKS:
                emit(ext.lineno, "wall-clock",
                     f"wall-clock read {ext.name} in sim-reachable code",
                     ext.name)
            elif ext.name in RANDOM_DRAWS:
                emit(ext.lineno, "global-random",
                     f"module-global {ext.name} — draw from a seeded "
                     f"RandomSource instead", ext.name)
            elif ext.name == "builtins.id":
                emit(ext.lineno, "id-keyed",
                     "id() in sim-reachable code — identity keys are "
                     "address-dependent across runs", "builtins.id")
            elif ext.name in ENV_READS and not config_load:
                emit(ext.lineno, "env-read",
                     f"{ext.name} outside config load", ext.name)
        if not config_load:
            for line in _env_subscript_sites(index, fn):
                emit(line, "env-read",
                     "os.environ[...] read outside config load",
                     "os.environ[]")
        for line in _set_iteration_sites(fn):
            emit(line, "set-iteration",
                 "iteration over a set — order is hash-seed dependent; "
                 "sort first if anything order-sensitive happens",
                 "set-iter")
    return findings
