"""Registry / exhaustiveness pass.

Folds the repo's coverage lints onto the shared index and adds the
native-tier parity check:

- every ``*_REQ``/``*_MSG`` verb in the MessageType registry is claimed
  by a message class under ``messages/`` (``COLLAPSED_VERBS`` allowlist
  for the deliberately-collapsed Propagate tiers, which must not rot);
- every flight-recorder kind recorded anywhere is documented in
  ``obs.flight.EVENT_KINDS`` and vice versa, with a real description;
- ``Node._process`` / ``Node.send`` keep the generic ``rx`` span +
  flight ``rx``/``tx`` instrumentation every claimed verb flows through;
- every module under ``messages/`` is listed in ``host.wire._MODULES``
  (a forgotten module means its payloads cannot cross the wire);
- native-vs-Python export parity: names exported by each C extension's
  ``PyMethodDef`` table match the attributes its Python callers actually
  use — a missing export breaks the native tier at runtime, a dead
  export is an unpinned code path.
"""
from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .core import RepoIndex
from .findings import Finding

PASS_ID = "surface"

# The port deliberately applies every Propagate tier through ONE local
# request class typed PROPAGATE_OTHER_MSG (messages/propagate.py); the
# per-tier verbs stay in the registry for reference parity but are never
# emitted.  Any OTHER unclaimed verb is a finding.
COLLAPSED_VERBS = frozenset({
    "PROPAGATE_PRE_ACCEPT_MSG", "PROPAGATE_STABLE_MSG",
    "PROPAGATE_APPLY_MSG",
})

# getter in native/__init__ -> C source whose PyMethodDef it loads
NATIVE_GETTERS = {
    "get": "_sorted_arrays.cpp",
    "get_wire": "_wire_codec.cpp",
    "get_cfk": "_cfk_core.cpp",
}


# ------------------------------------------------------------------ verbs --
def claimed_verbs(index: RepoIndex, enum_name: str = "MessageType",
                  messages_pkg: Optional[str] = None,
                  ) -> Dict[str, List[str]]:
    """{verb: [basenames]} for every assignment referencing
    `<enum_name>.X` under the messages package (excluding the registry
    module itself)."""
    messages_pkg = messages_pkg or f"{index.package}.messages"
    out: Dict[str, List[str]] = {}
    for mod in index.modules.values():
        if not mod.name.startswith(messages_pkg):
            continue
        if mod.path.name == "base.py":
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            for v in ([node.value] if node.value is not None else []):
                if isinstance(v, ast.Attribute) \
                        and isinstance(v.value, ast.Name) \
                        and v.value.id == enum_name:
                    out.setdefault(v.attr, []).append(mod.path.name)
    return out


def _enum_member_lines(index: RepoIndex, enum_name: str,
                       ) -> Tuple[Optional[str], Dict[str, int]]:
    """(relpath, {member: lineno}) of the AST class named `enum_name`."""
    for cls in index.classes.values():
        if cls.name != enum_name:
            continue
        lines: Dict[str, int] = {}
        for node in cls.node.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        lines[t.id] = node.lineno
        return index.relpath(index.modules[cls.module].path), lines
    return None, {}


def verb_findings(index: RepoIndex, verbs: Optional[Iterable[str]] = None,
                  collapsed: frozenset = COLLAPSED_VERBS,
                  enum_name: str = "MessageType",
                  messages_pkg: Optional[str] = None) -> List[Finding]:
    enum_file, member_lines = _enum_member_lines(index, enum_name)
    if verbs is None:
        verbs = list(member_lines)
    claimed = claimed_verbs(index, enum_name, messages_pkg)
    findings: List[Finding] = []
    file = enum_file or index.package
    for v in verbs:
        if not (v.endswith("_REQ") or v.endswith("_MSG")):
            continue     # replies correlate via msg ids, not dispatch
        if v in claimed or v in collapsed:
            continue
        findings.append(Finding(
            pass_id=PASS_ID, file=file, line=member_lines.get(v, 1),
            qualname=f"{enum_name}.{v}", code="verb-unclaimed",
            message=f"verb {v} registered in {enum_name} but claimed by no "
                    f"message class — it can never be processed or traced "
                    f"as rx:{v}", detail=v))
    known = set(verbs)
    for v, files in sorted(claimed.items()):
        if v not in known:
            findings.append(Finding(
                pass_id=PASS_ID, file=file, line=member_lines.get(v, 1),
                qualname=f"{enum_name}.{v}", code="verb-unknown",
                message=f"{files} claim verb {v} which {enum_name} does "
                        f"not register", detail=v))
    for v in sorted(collapsed):
        if v in claimed:
            findings.append(Finding(
                pass_id=PASS_ID, file=file, line=member_lines.get(v, 1),
                qualname=f"{enum_name}.{v}", code="verb-allowlist-stale",
                message=f"verb {v} is in COLLAPSED_VERBS but now claimed — "
                        f"drop it from the allowlist", detail=v))
    return findings


# ----------------------------------------------------------- flight kinds --
def recorded_flight_kinds(index: RepoIndex) -> Dict[str, List[str]]:
    """{kind: [paths relative to the package root]} for every literal
    kind passed to a `.record("<kind>", ...)` call."""
    kinds: Dict[str, List[str]] = {}
    for mod in index.modules.values():
        for n in ast.walk(mod.tree):
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "record" and n.args \
                    and isinstance(n.args[0], ast.Constant) \
                    and isinstance(n.args[0].value, str):
                kinds.setdefault(n.args[0].value, []).append(
                    str(mod.path.relative_to(index.root)))
    return kinds


def flight_findings(index: RepoIndex, event_kinds: Dict[str, str],
                    flight_file: str = "obs/flight.py") -> List[Finding]:
    recorded = recorded_flight_kinds(index)
    findings: List[Finding] = []
    file = str(Path(index.package) / flight_file)
    for kind, files in sorted(recorded.items()):
        if kind not in event_kinds:
            findings.append(Finding(
                pass_id=PASS_ID, file=file, line=1, qualname=kind,
                code="flight-undocumented",
                message=f"flight kind {kind!r} recorded in {files} but not "
                        f"documented in EVENT_KINDS", detail=kind))
    for kind, desc in event_kinds.items():
        if kind not in recorded:
            findings.append(Finding(
                pass_id=PASS_ID, file=file, line=1, qualname=kind,
                code="flight-dead",
                message=f"EVENT_KINDS documents {kind!r} which nothing "
                        f"records", detail=kind))
        if not (len(desc) > 20 and "/" in desc):
            findings.append(Finding(
                pass_id=PASS_ID, file=file, line=1, qualname=kind,
                code="flight-desc",
                message=f"EVENT_KINDS[{kind!r}] description must name its "
                        f"emitting layer (len>20 with a path)", detail=kind))
    return findings


# -------------------------------------------------- node instrumentation --
def instrumentation_findings(index: RepoIndex) -> List[Finding]:
    """Node._process keeps the generic rx span + flight rx record and
    Node.send the tx record — every claimed verb flows through these."""
    findings: List[Finding] = []
    node_mod = f"{index.package}.local.node"

    def check(fq: str, attr: str, literal: Optional[str], what: str) -> None:
        fn = index.functions.get(fq)
        if fn is None:
            findings.append(Finding(
                pass_id=PASS_ID, file=f"{index.package}/local/node.py",
                line=1, qualname=fq, code="node-instrumentation",
                message=f"{fq} missing", detail=what))
            return
        for n in ast.walk(fn.node):
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                    and n.func.attr == attr:
                if literal is None:
                    return
                if n.args and isinstance(n.args[0], ast.Constant) \
                        and n.args[0].value == literal:
                    return
        findings.append(Finding(
            pass_id=PASS_ID, file=index.relpath(fn.path), line=fn.lineno,
            qualname=fq, code="node-instrumentation",
            message=f"{fq.split('::')[-1]} lost the {what}", detail=what))

    check(f"{node_mod}::Node._process", "rx", None, "obs.rx span event")
    check(f"{node_mod}::Node._process", "record", "rx", "flight 'rx' record")
    check(f"{node_mod}::Node.send", "record", "tx", "flight 'tx' record")
    return findings


# -------------------------------------------------------- wire registry --
def wire_module_findings(index: RepoIndex, registered: Sequence[str],
                         ) -> List[Finding]:
    findings: List[Finding] = []
    prefix = f"{index.package}.messages."
    for mod in sorted(index.modules.values(), key=lambda m: m.name):
        if not mod.name.startswith(prefix) or mod.is_package:
            continue
        if mod.name not in registered:
            findings.append(Finding(
                pass_id=PASS_ID, file=index.relpath(mod.path), line=1,
                qualname=mod.name, code="wire-unregistered-module",
                message=f"{mod.name} is not in host.wire._MODULES — its "
                        f"classes cannot cross the wire", detail=mod.name))
    return findings


# --------------------------------------------------------- native parity --
_METHODDEF_RE = re.compile(r'\{\s*"(\w+)"\s*,')

def _cpp_exports(cpp_path: Path) -> Dict[str, int]:
    """{exported name: lineno} from the PyMethodDef table in a C source."""
    out: Dict[str, int] = {}
    in_table = False
    for i, line in enumerate(cpp_path.read_text().splitlines(), 1):
        if "PyMethodDef" in line:
            in_table = True
        if in_table:
            m = _METHODDEF_RE.search(line)
            if m:
                out[m.group(1)] = i
            if "};" in line.replace(" ", ""):
                in_table = False
    return out


def _native_handle_uses(index: RepoIndex) -> Dict[str, Dict[str, Tuple[str, int]]]:
    """getter -> {attr: (relpath, lineno)} for attributes accessed on
    variables bound from accord_tpu.native.get/get_wire/get_cfk()."""
    uses: Dict[str, Dict[str, Tuple[str, int]]] = {g: {} for g in NATIVE_GETTERS}
    native_mod = f"{index.package}.native"
    for mod in index.modules.values():
        if mod.name.startswith(native_mod):
            continue
        handles: Dict[str, str] = {}   # var name -> getter
        for n in ast.walk(mod.tree):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name) \
                    and isinstance(n.value, ast.Call):
                dotted = index.dotted_of(mod, n.value.func)
                if dotted and dotted.startswith(native_mod + "."):
                    getter = dotted.rsplit(".", 1)[1]
                    if getter in NATIVE_GETTERS:
                        handles[n.targets[0].id] = getter
        if not handles:
            continue
        rel = index.relpath(mod.path)
        for n in ast.walk(mod.tree):
            if isinstance(n, ast.Attribute) and isinstance(n.value, ast.Name) \
                    and n.value.id in handles:
                uses[handles[n.value.id]].setdefault(
                    n.attr, (rel, n.lineno))
    return uses


def native_parity_findings(index: RepoIndex) -> List[Finding]:
    findings: List[Finding] = []
    native_dir = index.root / "native"
    if not native_dir.exists():
        return findings
    uses = _native_handle_uses(index)
    for getter, cpp_name in NATIVE_GETTERS.items():
        cpp = native_dir / cpp_name
        if not cpp.exists():
            continue
        exports = _cpp_exports(cpp)
        cpp_rel = str(Path(index.package) / "native" / cpp_name)
        for attr, (rel, lineno) in sorted(uses[getter].items()):
            if attr not in exports:
                findings.append(Finding(
                    pass_id=PASS_ID, file=rel, line=lineno,
                    qualname=f"native.{getter}().{attr}",
                    code="native-missing-export",
                    message=f"{rel} calls {attr} on native.{getter}() but "
                            f"{cpp_name} exports no such method",
                    detail=f"{getter}.{attr}"))
        for name, lineno in sorted(exports.items()):
            if name not in uses[getter]:
                findings.append(Finding(
                    pass_id=PASS_ID, file=cpp_rel, line=lineno,
                    qualname=f"native.{getter}().{name}",
                    code="native-dead-export",
                    message=f"{cpp_name} exports {name} but no Python "
                            f"caller uses it — unpinned native path",
                    detail=f"{getter}.{name}"))
    return findings


# ----------------------------------------------------------------- runner --
def run(index: RepoIndex) -> List[Finding]:
    from accord_tpu.host.wire import _MODULES
    from accord_tpu.messages.base import MessageType
    from accord_tpu.obs.flight import EVENT_KINDS

    findings: List[Finding] = []
    findings += verb_findings(index, [m.name for m in MessageType])
    findings += flight_findings(index, EVENT_KINDS)
    findings += instrumentation_findings(index)
    findings += wire_module_findings(index, _MODULES)
    findings += native_parity_findings(index)
    return findings
