"""accord-lint: whole-repo protocol static analysis.

One shared AST/call-graph index (`core.RepoIndex`) feeds five passes:

===========  ==========================================================
blocking     event-loop blocking-call detector (reachability from the
             selector-loop roots and Node._process to time.sleep,
             Condition.wait, fsync, blocking sockets, subprocess)
determinism  sim-determinism lint (wall clocks, module-global random,
             id() keys, set iteration, env reads outside config load
             in the sim import closure)
threads      cross-thread shared-state audit (attributes mutated from
             ≥2 thread contexts without a recognized lock or the
             wakeup-socketpair marshalling idiom)
surface      registry/exhaustiveness (verb claims, EVENT_KINDS,
             Node rx/tx instrumentation, wire._MODULES coverage,
             native-vs-Python export parity)
layering     import boundaries (obs/ and analysis/ stay off jax)
===========  ==========================================================

Run `python -m accord_tpu.analysis` (see `--help`); the checked-in
baseline (`baseline.json`) suppresses accepted findings, each with a
one-line justification.  Tier-1 keeps the suite clean via
tests/test_analysis.py::test_repo_is_clean.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence

from . import blocking, determinism, layering, surface, threads
from .baseline import DEFAULT_BASELINE, apply_baseline, load_baseline
from .core import RepoIndex, build_package_index
from .findings import Finding

PASSES: Dict[str, Callable[[RepoIndex], List[Finding]]] = {
    "blocking": blocking.run,
    "determinism": determinism.run,
    "threads": threads.run,
    "surface": surface.run,
    "layering": layering.run,
}


@dataclass
class RunReport:
    new: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    stale: List[str] = field(default_factory=list)
    timings: Dict[str, float] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.new


def run_passes(index: RepoIndex,
               select: Optional[Sequence[str]] = None,
               ) -> Dict[str, List[Finding]]:
    out: Dict[str, List[Finding]] = {}
    for name in (select or PASSES):
        if name not in PASSES:
            raise KeyError(f"unknown pass {name!r}; have {sorted(PASSES)}")
        out[name] = PASSES[name](index)
    return out


def run_repo(select: Optional[Sequence[str]] = None,
             baseline_path: Optional[Path] = DEFAULT_BASELINE,
             index: Optional[RepoIndex] = None) -> RunReport:
    """Run the suite over the installed package against the baseline."""
    report = RunReport()
    t0 = time.perf_counter()
    if index is None:
        index = build_package_index()
    report.timings["index"] = time.perf_counter() - t0
    findings: List[Finding] = []
    for name in (select or PASSES):
        t0 = time.perf_counter()
        findings.extend(run_passes(index, [name])[name])
        report.timings[name] = time.perf_counter() - t0
    baseline = load_baseline(baseline_path) if baseline_path else {}
    report.new, report.suppressed, stale = apply_baseline(findings, baseline)
    # a baseline entry for a deselected pass is not stale — it just didn't run
    ran = set(select or PASSES)
    report.stale = [k for k in stale if k.split("::", 1)[0] in ran]
    return report
