"""Baseline handling for accord-lint.

The baseline file (`accord_tpu/analysis/baseline.json`) is the list of
findings the repo has consciously accepted.  Policy: **every entry must
carry a one-line justification** — an entry with a missing, empty or
"TODO"-prefixed justification fails loading, so `--write-baseline`
output (which stamps `TODO: justify`) cannot be checked in unedited.
"""
from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, Iterable, List, Tuple

from .findings import Finding

DEFAULT_BASELINE = Path(__file__).parent / "baseline.json"


class BaselineError(ValueError):
    pass


def load_baseline(path: Path = DEFAULT_BASELINE) -> Dict[str, str]:
    """Map of finding key -> justification; validates the policy."""
    if not Path(path).exists():
        return {}
    data = json.loads(Path(path).read_text())
    entries = data.get("entries", [])
    out: Dict[str, str] = {}
    for e in entries:
        key = e.get("key")
        just = (e.get("justification") or "").strip()
        if not key:
            raise BaselineError(f"baseline entry missing key: {e!r}")
        if not just or just.upper().startswith("TODO"):
            raise BaselineError(
                f"baseline entry for {key!r} has no justification — every "
                f"suppressed finding needs a one-line reason")
        if key in out:
            raise BaselineError(f"duplicate baseline key: {key!r}")
        out[key] = just
    return out


def apply_baseline(findings: List[Finding], baseline: Dict[str, str],
                   ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """Split findings into (new, suppressed) and report stale keys."""
    new: List[Finding] = []
    suppressed: List[Finding] = []
    seen: set = set()
    for f in findings:
        if f.key in baseline:
            suppressed.append(f)
            seen.add(f.key)
        else:
            new.append(f)
    stale = [k for k in baseline if k not in seen]
    return new, suppressed, stale


def write_baseline(findings: Iterable[Finding], path: Path,
                   justifications: Dict[str, str] = None) -> None:
    """Write a baseline template; unjustified entries get `TODO: justify`
    which the loader rejects, forcing a human-written reason per entry."""
    justifications = justifications or {}
    entries = []
    seen = set()
    for f in sorted(findings, key=lambda f: f.key):
        if f.key in seen:
            continue
        seen.add(f.key)
        entries.append({
            "key": f.key,
            "finding": f.render(),
            "justification": justifications.get(f.key, "TODO: justify"),
        })
    Path(path).write_text(json.dumps({"entries": entries}, indent=2) + "\n")
