"""Process-local metrics registry: counters, gauges, log-bucketed histograms.

Plain Python on the host path — no jax, no numpy, no allocation beyond the
metric objects themselves.  Metric identity is (name, labels); get-or-create
is the only locked operation (hosts mutate from their single loop thread;
reader threads only snapshot).

Naming convention (README "Observability"): `accord_<area>_<what>[_total]`
with snake_case label keys — `_total` suffix for monotonic counters,
`_us` suffix for microsecond-valued histograms.

Snapshot format (JSON-safe, mergeable across nodes/processes):

    {"counters":   {name: {label_key: value}},
     "gauges":     {name: {label_key: value}},
     "histograms": {name: {label_key: {"count": n, "sum": s,
                                       "buckets": {exp: n}}}}}

where `label_key` is the canonical "k=v,k2=v2" string ("" for no labels)
and a histogram bucket `exp` counts observations v with
2**(exp-1) < v <= 2**exp (exp "0" holds v <= 1).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple


def _label_key(labels: Dict[str, str]) -> str:
    return ",".join(f"{k}={labels[k]}" for k in sorted(labels))


def parse_labels(label_key: str) -> Dict[str, str]:
    """Inverse of the snapshot's canonical label string."""
    if not label_key:
        return {}
    out = {}
    for part in label_key.split(","):
        k, _, v = part.partition("=")
        out[k] = v
    return out


class Counter:
    """Monotonic-by-convention counter.  `value` is directly assignable so
    read-through views (obs/views.MetricView) can keep legacy `attr += 1` /
    `attr = max(...)` call sites working unchanged."""

    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = labels
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n

    def __repr__(self):
        return f"Counter({self.name}{self.labels or ''}={self.value})"


class Gauge(Counter):
    """Point-in-time value; same shape as Counter, different snapshot
    section (and different cross-node merge: max, not sum)."""

    __slots__ = ()

    def set(self, v) -> None:
        self.value = v


class Histogram:
    """Log2-bucketed histogram: observe(v) lands in bucket ceil(log2(v)),
    i.e. bucket e counts 2**(e-1) < v <= 2**e (e=0 holds v <= 1).  One dict
    op per observation; quantiles are bucket-upper-bound approximations,
    which is all a latency breakdown needs.

    ERROR BOUND (pinned by tests/test_obs.py): for any distribution and
    any q, the reported quantile r and the exact same-rank sample value v
    satisfy v <= r < 2*v (for v >= 1) — r is the upper bound of v's
    bucket.  Monitoring tolerates a [1x, 2x) one-sided bound; a tail-
    latency GATE does not, which is why every SLO lane and the profiler
    use raw-sample exact quantiles (obs/report.exact_quantiles_us)."""

    __slots__ = ("name", "labels", "count", "sum", "buckets")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = labels
        self.count = 0
        self.sum = 0
        self.buckets: Dict[int, int] = {}

    def observe(self, v) -> None:
        self.count += 1
        self.sum += v
        e = 0 if v <= 1 else (int(v) - 1).bit_length()
        self.buckets[e] = self.buckets.get(e, 0) + 1

    def quantile(self, q: float):
        """Upper bound of the bucket holding the q-quantile observation
        (None when empty)."""
        if self.count == 0:
            return None
        rank = max(1, int(q * self.count + 0.9999999))
        seen = 0
        for e in sorted(self.buckets):
            seen += self.buckets[e]
            if seen >= rank:
                return 1 << e if e else 1
        return 1 << max(self.buckets)

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def __repr__(self):
        return (f"Histogram({self.name}{self.labels or ''} "
                f"count={self.count} mean={self.mean:.1f})")


class Registry:
    """Get-or-create metric store.  Creation is locked (reader/writer
    threads on the TCP host); mutation of an existing metric is a plain
    attribute update on the single owning loop thread."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, str], Counter] = {}
        self._gauges: Dict[Tuple[str, str], Gauge] = {}
        self._histograms: Dict[Tuple[str, str], Histogram] = {}

    # ------------------------------------------------------ get-or-create --
    def counter(self, name: str, **labels) -> Counter:
        return self._get(self._counters, Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(self._gauges, Gauge, name, labels)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get(self._histograms, Histogram, name, labels)

    def _get(self, table, cls, name, labels):
        # canonical label string built without the per-call dict copy the
        # hot paths used to pay (str-izing happens in the f-format; values
        # are verbs/phases/ints, for which format == str); the full copy
        # only runs on the miss path when the metric is created
        if not labels:
            lk = ""
        elif len(labels) == 1:
            (k, v), = labels.items()
            lk = f"{k}={v}"
        else:
            lk = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
        key = (name, lk)
        m = table.get(key)
        if m is None:
            with self._lock:
                m = table.get(key)
                if m is None:
                    m = table[key] = cls(name, {k: str(v)
                                                for k, v in labels.items()})
        return m

    # -------------------------------------------------------------- query --
    def value(self, name: str, **labels) -> int:
        """Current value of one counter/gauge (0 when absent)."""
        key = (name, _label_key({k: str(v) for k, v in labels.items()}))
        m = self._counters.get(key) or self._gauges.get(key)
        return m.value if m is not None else 0

    def total(self, name: str) -> int:
        """Sum of a counter over every label set."""
        return sum(c.value for (n, _), c in self._counters.items()
                   if n == name)

    def find_histograms(self, name: str):
        return [h for (n, _), h in self._histograms.items() if n == name]

    # ----------------------------------------------------------- snapshot --
    def snapshot(self) -> dict:
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for (name, lk), c in list(self._counters.items()):
            out["counters"].setdefault(name, {})[lk] = c.value
        for (name, lk), g in list(self._gauges.items()):
            out["gauges"].setdefault(name, {})[lk] = g.value
        for (name, lk), h in list(self._histograms.items()):
            out["histograms"].setdefault(name, {})[lk] = {
                "count": h.count, "sum": h.sum,
                "buckets": {str(e): n for e, n in sorted(h.buckets.items())}}
        return out

    # --------------------------------------------------------- prometheus --
    def render_prometheus(self) -> str:
        """Prometheus text exposition (counters, gauges, histograms with
        cumulative `le` buckets in native units)."""
        lines = []

        def fmt(name, labels, value, extra=None):
            lab = dict(labels)
            if extra:
                lab.update(extra)
            if lab:
                body = ",".join(f'{k}="{v}"' for k, v in sorted(lab.items()))
                lines.append(f"{name}{{{body}}} {value}")
            else:
                lines.append(f"{name} {value}")

        for kind, table in (("counter", self._counters),
                            ("gauge", self._gauges)):
            seen = set()
            for (name, _), m in sorted(table.items()):
                if name not in seen:
                    seen.add(name)
                    lines.append(f"# TYPE {name} {kind}")
                fmt(name, m.labels, m.value)
        seen = set()
        for (name, _), h in sorted(self._histograms.items()):
            if name not in seen:
                seen.add(name)
                lines.append(f"# TYPE {name} histogram")
            acc = 0
            for e in sorted(h.buckets):
                acc += h.buckets[e]
                fmt(f"{name}_bucket", h.labels, acc,
                    {"le": str(1 << e if e else 1)})
            fmt(f"{name}_bucket", h.labels, h.count, {"le": "+Inf"})
            fmt(f"{name}_sum", h.labels, h.sum)
            fmt(f"{name}_count", h.labels, h.count)
        return "\n".join(lines) + "\n"


def merge_snapshots(snapshots) -> dict:
    """Merge registry snapshots across nodes/processes: counters and
    histogram buckets sum; gauges take the max (they are high-water marks
    or instantaneous depths — summing drifted instants is meaningless)."""
    out = {"counters": {}, "gauges": {}, "histograms": {}}
    for snap in snapshots:
        if not snap:
            continue
        for name, by_label in snap.get("counters", {}).items():
            dst = out["counters"].setdefault(name, {})
            for lk, v in by_label.items():
                dst[lk] = dst.get(lk, 0) + v
        for name, by_label in snap.get("gauges", {}).items():
            dst = out["gauges"].setdefault(name, {})
            for lk, v in by_label.items():
                dst[lk] = max(dst.get(lk, v), v)
        for name, by_label in snap.get("histograms", {}).items():
            dst = out["histograms"].setdefault(name, {})
            for lk, h in by_label.items():
                cur = dst.setdefault(lk, {"count": 0, "sum": 0,
                                          "buckets": {}})
                cur["count"] += h.get("count", 0)
                cur["sum"] += h.get("sum", 0)
                for e, n in h.get("buckets", {}).items():
                    cur["buckets"][e] = cur["buckets"].get(e, 0) + n
    return out


def snapshot_quantile(hist_snap: dict, q: float):
    """Quantile (bucket upper bound) from a snapshot-format histogram."""
    count = hist_snap.get("count", 0)
    if not count:
        return None
    rank = max(1, int(q * count + 0.9999999))
    seen = 0
    for e in sorted(hist_snap.get("buckets", {}), key=int):
        seen += hist_snap["buckets"][e]
        if seen >= rank:
            return 1 << int(e) if int(e) else 1
    return None
