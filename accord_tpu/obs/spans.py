"""Per-transaction trace spans.

A span is the ordered event list one node recorded for one trace id; the
trace id is the transaction id's canonical repr, so the id every replica
derives independently is identical — stitching a cross-replica trace is a
merge-sort of the participating nodes' span stores, no id exchange needed.

Senders additionally stamp the trace id onto outbound requests
(`Node.send` sets `request.trace_id`; `host/wire.py`'s structural codec
round-trips it as an ordinary instance field), so a replica records rx
events even for verbs it cannot attribute to a coordination of its own —
that is what makes recovery visible end-to-end: the recovering node's span
carries `begin(path=recovery)` while every contacted replica carries
`rx:BEGIN_RECOVER_REQ` under the SAME trace id.

Bounded: the store is an LRU of `capacity` traces; each span caps its
event list so a pathological retry loop cannot grow one span unboundedly.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

_MAX_EVENTS_PER_SPAN = 256

# protocol milestones in coordination order (the ephemeral-read path's two
# rounds slot in right after begin — a span carries either the eph_* pair
# or the witnessed-txn ladder, never both); the per-phase latency breakdown
# is the delta between consecutive *present* milestones
PHASE_ORDER = ("begin", "eph_deps", "eph_read", "preaccept",
               "preaccept_extend", "begin_recover", "accept", "commit",
               "stable", "apply", "end")


def phase_firsts(span) -> list:
    """[(phase, at_us)] — first occurrence of each PHASE_ORDER milestone
    present on the span, in coordination order.  The join key between the
    open-loop generator's intended-start ledger and a txn's trace."""
    if span is None:
        return []
    out = []
    for ph in PHASE_ORDER:
        ev = span.first(ph)
        if ev is not None:
            out.append((ph, ev[0]))
    return out


def phase_deltas(firsts) -> list:
    """[(phase, duration_us)] between consecutive present milestones of a
    `phase_firsts` list: the time attributed to each phase."""
    return [(ph, max(0, nat - at))
            for (ph, at), (_nph, nat) in zip(firsts, firsts[1:])]


def trace_key(txn_id) -> str:
    """Canonical trace id for a transaction (identical on every replica)."""
    return repr(txn_id)


class Span:
    """One trace id's events on ONE node: [(at_us, phase, tags-or-None)]."""

    __slots__ = ("trace_id", "node_id", "events", "path")

    def __init__(self, trace_id: str, node_id: int):
        self.trace_id = trace_id
        self.node_id = node_id
        self.events: List[Tuple[int, str, Optional[dict]]] = []
        self.path = None  # "fast" | "slow" | "recovery" | ... once known

    def first(self, phase: str):
        for at, ph, tags in self.events:
            if ph == phase:
                return (at, ph, tags)
        return None

    def phases(self):
        return [ph for _, ph, _ in self.events]

    def __repr__(self):
        return (f"Span({self.trace_id} n{self.node_id} "
                f"path={self.path} {self.phases()})")


class SpanStore:
    """Bounded per-node span collection (LRU on trace id)."""

    __slots__ = ("node_id", "capacity", "_spans")

    def __init__(self, node_id: int, capacity: int = 4096):
        self.node_id = node_id
        self.capacity = capacity
        self._spans: "OrderedDict[str, Span]" = OrderedDict()

    def event(self, trace_id: str, phase: str, at_us: int,
              tags: Optional[dict] = None) -> Span:
        span = self._spans.get(trace_id)
        if span is None:
            span = self._spans[trace_id] = Span(trace_id, self.node_id)
            if len(self._spans) > self.capacity:
                self._spans.popitem(last=False)
        if len(span.events) < _MAX_EVENTS_PER_SPAN:
            span.events.append((at_us, phase, tags))
        return span

    def get(self, trace_id: str) -> Optional[Span]:
        return self._spans.get(trace_id)

    def ids(self):
        return list(self._spans)

    def spans(self):
        return list(self._spans.values())

    def __len__(self):
        return len(self._spans)


def stitch(stores, trace_id: str):
    """Merge one trace id's events across span stores into a single
    time-ordered list of (at_us, node_id, phase, tags).  Per-node clocks
    may drift in sim; the order is best-effort, the per-node sublists are
    exact."""
    merged = []
    for store in stores:
        span = store.get(trace_id)
        if span is not None:
            merged.extend((at, span.node_id, ph, tags)
                          for at, ph, tags in span.events)
    merged.sort(key=lambda e: (e[0], e[1]))
    return merged


def find_trace_ids(stores, phase: Optional[str] = None, **tags):
    """Trace ids having at least one event matching `phase` (prefix match
    when it ends with '*') and every given tag, on ANY of the stores."""
    prefix = phase[:-1] if phase is not None and phase.endswith("*") else None
    ids = set()
    for store in stores:
        for span in store.spans():
            if span.trace_id in ids:
                continue
            for _, ph, tg in span.events:
                if phase is not None:
                    if prefix is not None:
                        if not ph.startswith(prefix):
                            continue
                    elif ph != phase:
                        continue
                if tags and not all((tg or {}).get(k) == v
                                    for k, v in tags.items()):
                    continue
                ids.add(span.trace_id)
                break
    return ids
