"""Kernel-level profiler: fenced wall timers, retrace ledger, flush waterfall.

Three blind spots this closes (ISSUE 3):

  * per-kernel wall time — every device-store precompute (deps, recovery,
    range-stab, wavefront, sharded) is split into encode / device / decode
    laps, each ended by a host pull or an injected fence so the timer
    measures the kernel, not the dispatch;
  * jit retraces — a ledger keyed by the compile-count hook's encoded-shape
    buckets (impl/device_store._note_compile_shape): the first sighting of
    a shape bucket per kernel is one XLA compile, counted ALWAYS (a set
    lookup), independent of sampling;
  * the flush-window waterfall — queue-wait -> encode -> device -> decode
    per drained window, so the latency tax of the batching tier is
    decomposable instead of one opaque number.

OFF BY DEFAULT on the hot path: `ACCORD_PROFILE=N` samples 1-in-N flush
windows (N=1 profiles every window; unset/0 disables timing entirely —
only the retrace ledger stays on).  When a window is not sampled, `begin`
returns None and every `lap` is a single early-returning call.

HARD CONSTRAINT (package docstring): no jax/numpy imports here.  Fencing
(`block_until_ready`) is the CALLER's job — device layers end each lap
with a host pull (np.asarray) or pass an explicit fence callable to
`lap`; this module only reads the clock.

`ACCORD_PROFILE_SCALE` (float, default 1) scales measured durations — a
test hook letting the bench's `--guard` regression gate be exercised with
a synthetic slowdown (tests/test_bench_guard.py).
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

# raw-sample cap per kernel: exact p50/p99 without unbounded growth (the
# registry histograms keep the full log2-bucketed stream regardless)
_MAX_SAMPLES = 512


class Profiler:
    """Per-store (or per-bench) profiler writing into a metrics registry.

    Registry metrics:
      accord_profile_kernel_us{kernel=...}   histogram — per-lap wall time
      accord_profile_window_us{stage=...}    histogram — waterfall stages
      accord_profile_retraces_total{kernel=...}  counter — shape-bucket
                                                 first-sightings (compiles)
      accord_profile_windows_sampled_total   counter — sampled windows
    """

    __slots__ = ("registry", "sample_n", "enabled", "_clock", "_scale",
                 "_tick", "_window_active", "_stage_acc", "_samples",
                 "_shapes")

    def __init__(self, registry, sample_n: int = 0, clock=None):
        self.registry = registry
        self.sample_n = sample_n
        self.enabled = sample_n > 0
        self._clock = clock if clock is not None else time.perf_counter
        try:
            self._scale = float(os.environ.get("ACCORD_PROFILE_SCALE", "1"))
        except ValueError:
            self._scale = 1.0
        self._tick = 0
        self._window_active = False
        self._stage_acc: Dict[str, float] = {}
        self._samples: Dict[str, List[float]] = {}
        self._shapes: Dict[str, set] = {}

    # ------------------------------------------------------ retrace ledger --
    def note_retrace(self, kernel: str, shapes) -> None:
        """First sighting of an encoded-shape bucket for `kernel` == one
        XLA compile (jit caches per shape tuple).  Always on — one set
        lookup per flush window."""
        seen = self._shapes.get(kernel)
        if seen is None:
            seen = self._shapes[kernel] = set()
        if shapes not in seen:
            seen.add(shapes)
            self.registry.counter("accord_profile_retraces_total",
                                  kernel=kernel).inc()

    # ------------------------------------------------------- window timing --
    def window_begin(self, opened_at: Optional[float]) -> bool:
        """Called at flush start with the wall time of the window's first
        _submit (or None).  Decides sampling for this window and records
        the queue-wait waterfall stage.  Returns whether sampling is on."""
        if not self.enabled:
            return False
        self._tick += 1
        if self._tick % self.sample_n:
            self._window_active = False
            return False
        self._window_active = True
        self._stage_acc = {}
        self.registry.counter("accord_profile_windows_sampled_total").inc()
        if opened_at is not None:
            self._observe_stage("queue_wait",
                                self._clock() - opened_at)
        return True

    def window_end(self) -> None:
        """Flush the sampled window's accumulated waterfall stages."""
        if not self._window_active:
            return
        for stage, dur in self._stage_acc.items():
            self._observe_stage(stage, dur)
        self._stage_acc = {}
        self._window_active = False

    def begin(self) -> Optional[float]:
        """Start a lap; None when this window is not sampled (making every
        subsequent `lap` a no-op)."""
        return self._clock() if self._window_active else None

    def lap(self, t: Optional[float], kernel: str,
            stage: Optional[str] = None, fence=None) -> Optional[float]:
        """End a lap started at `t`: record wall time for `kernel` (and
        accumulate into waterfall `stage`).  Returns the new lap start.
        `fence` (e.g. jax.block_until_ready on a result) runs INSIDE the
        lap — the caller injects synchronization, this module stays
        jax-free.  Callers whose lap already ends in a host pull pass no
        fence: the pull IS the fence."""
        if t is None:
            return None
        if fence is not None:
            fence()
        now = self._clock()
        dur = (now - t) * self._scale
        us = dur * 1e6
        self.registry.histogram("accord_profile_kernel_us",
                                kernel=kernel).observe(us)
        samples = self._samples.get(kernel)
        if samples is None:
            samples = self._samples[kernel] = []
        if len(samples) < _MAX_SAMPLES:
            samples.append(us)
        if stage is not None:
            self._stage_acc[stage] = self._stage_acc.get(stage, 0.0) + dur
        return now

    def _observe_stage(self, stage: str, dur_s: float) -> None:
        self.registry.histogram("accord_profile_window_us", stage=stage) \
            .observe(dur_s * self._scale * 1e6)

    # ------------------------------------------------------------- summary --
    def summary(self) -> dict:
        """The per-kernel p50/p99 + retrace summary the bench records into
        its emitted row and BENCH_HISTORY.json (`--guard` diffs these).
        Quantiles come from the raw-sample cap, not the log2 buckets, so a
        15% regression threshold is meaningful."""
        kernels = {}
        for kernel, samples in self._samples.items():
            if not samples:
                continue
            s = sorted(samples)
            kernels[kernel] = {
                "count": len(s),
                "p50": round(s[len(s) // 2], 1),
                "p99": round(s[min(len(s) - 1, int(len(s) * 0.99))], 1),
            }
        return {
            "kernels": kernels,
            "retraces": {k: len(v) for k, v in self._shapes.items() if v},
        }


def profiler_from_env(registry, env: str = "ACCORD_PROFILE") -> Profiler:
    """ACCORD_PROFILE=N -> sample 1-in-N flush windows; unset/0/garbage ->
    timing disabled (retrace ledger only)."""
    raw = os.environ.get(env, "")
    try:
        n = int(raw) if raw else 0
    except ValueError:
        n = 0
    if n > 0:
        return Profiler(registry, sample_n=n)
    return Profiler(registry, sample_n=0)
