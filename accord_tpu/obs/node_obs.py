"""NodeObs: the per-node observability facade the engine instruments on.

One registry + one span store per Node.  Coordinators call `txn_begin` /
`txn_phase` / `txn_path` / `txn_end` at protocol milestones; `Node._process`
calls `rx` for any inbound request carrying a trace id.  Everything is a
few dict ops — the <5% hot-loop budget is enforced by
tests/test_obs_budget.py.
"""

from __future__ import annotations

from typing import Callable, Optional

from accord_tpu.obs.cpuprof import cpu_profiler_from_env
from accord_tpu.obs.flight import FlightRecorder
from accord_tpu.obs.registry import Registry
from accord_tpu.obs.spans import (PHASE_ORDER, SpanStore, phase_deltas,
                                  phase_firsts, trace_key)

# milestones that each open one RPC round (fan-out + quorum wait): their
# per-txn count is the round-count histogram the ROADMAP Infer A/B
# harness prices against
ROUND_PHASES = frozenset({"preaccept", "preaccept_extend", "accept",
                          "commit", "stable", "apply", "begin_recover",
                          "get_deps", "await_commit", "invalidate",
                          "eph_deps", "eph_read"})


class NodeObs:
    """Per-node metrics registry + span store + instrumentation helpers."""

    __slots__ = ("node_id", "registry", "spans", "flight", "enabled",
                 "_clock_us", "audit_view", "cpuprof", "dc", "_dc_labels")

    def __init__(self, node_id: int = 0, registry: Optional[Registry] = None,
                 clock_us: Optional[Callable[[], int]] = None,
                 span_capacity: int = 4096, enabled: bool = True,
                 flight_capacity: int = 4096, dc: Optional[str] = None,
                 elect: Optional[str] = None):
        self.node_id = node_id
        # geo placement attribution: when this node is assigned to a DC
        # (topology/geo.GeoProfile), coordination counters/histograms carry
        # dc= (and elect= in|out, electorate membership) labels so the wan
        # report section can split fast/slow outcomes and phase latencies
        # by coordinator placement.  With dc unset (every pre-geo harness)
        # the label dicts are EMPTY and each metric row is byte-identical
        # to the pre-geo shape — the obs-budget and determinism pins hold.
        self.dc = dc
        self._dc_labels = ({"dc": dc, "elect": elect} if dc and elect
                           else {"dc": dc} if dc else {})
        self.registry = registry if registry is not None else Registry()
        self.spans = SpanStore(node_id, capacity=span_capacity)
        self.enabled = enabled
        self._clock_us = clock_us if clock_us is not None else (lambda: 0)
        # always-on bounded forensics ring (obs/flight.py) sharing the
        # node's clock — stitched across replicas on failure
        self.flight = FlightRecorder(node_id, capacity=flight_capacity,
                                     clock_us=self._clock_us)
        # live replica-state audit view: the node's Auditor (local/audit.py)
        # installs its `view` callable here so the metrics endpoint's
        # /audit route and host "audit" frames can serve it; None when no
        # auditor is attached
        self.audit_view: Optional[Callable[[], dict]] = None
        # protocol-tier CPU attribution (obs/cpuprof.py): sampled
        # per-dispatch decode/apply/cfk/reply-encode waterfall, labeled by
        # verb — off unless ACCORD_CPU_PROFILE=N is set
        self.cpuprof = cpu_profiler_from_env(self.registry)

    def now_us(self) -> int:
        return int(self._clock_us())

    def set_dc(self, dc: Optional[str], elect: Optional[str] = None) -> None:
        """(Re)bind this node's geo placement labels: the TCP host learns
        its DC only after construction (ACCORD_GEO env, or a geo profile
        riding an EpochInstall frame)."""
        self.dc = dc
        self._dc_labels = ({"dc": dc, "elect": elect} if dc and elect
                           else {"dc": dc} if dc else {})

    # -------------------------------------------------- coordination side --
    def txn_begin(self, txn_id, kind: Optional[str] = None,
                  path: str = "coordination") -> None:
        if not self.enabled:
            return
        self.registry.counter("accord_coordinate_started_total",
                              path=path).inc()
        span = self.spans.event(trace_key(txn_id), "begin", self.now_us(),
                                {"path": path, "kind": kind} if kind
                                else {"path": path})
        span.path = path

    def txn_phase(self, txn_id, phase: str, **tags) -> None:
        if not self.enabled:
            return
        self.spans.event(trace_key(txn_id), phase, self.now_us(),
                         tags or None)

    def txn_path(self, txn_id, which: str) -> None:
        """Record the decided commit path ("fast" | "slow").  Idempotent
        per trace: a coordination that re-decides after an epoch-extension
        round must not double-count its path."""
        if not self.enabled:
            return
        tid = trace_key(txn_id)
        span = self.spans.get(tid)
        if span is not None and span.first("path") is not None:
            return
        self.registry.counter("accord_path_total", path=which,
                              **self._dc_labels).inc()
        span = self.spans.event(tid, "path", self.now_us(), {"path": which})
        span.path = which

    def txn_end(self, txn_id, failure: Optional[BaseException] = None,
                path: str = "coordination") -> None:
        if not self.enabled:
            return
        outcome = "ok" if failure is None else type(failure).__name__
        self.registry.counter("accord_coordinate_outcomes_total",
                              outcome=outcome, path=path,
                              **self._dc_labels).inc()
        now = self.now_us()
        span = self.spans.event(trace_key(txn_id), "end", now,
                                {"outcome": outcome})
        begin = span.first("begin")
        if begin is not None:
            self.registry.histogram("accord_txn_latency_us",
                                    path=span.path or path,
                                    **self._dc_labels) \
                .observe(max(0, now - begin[0]))
        rounds = sum(1 for _, ph, _ in span.events if ph in ROUND_PHASES)
        if rounds:
            self.registry.histogram("accord_coordination_rounds",
                                    path=span.path or path).observe(rounds)
        self._observe_phase_latencies(span)

    def _observe_phase_latencies(self, span) -> None:
        """Delta between consecutive present milestones -> per-phase
        latency histograms (first occurrence of each milestone)."""
        for ph, dur in phase_deltas(phase_firsts(span)):
            self.registry.histogram("accord_phase_latency_us", phase=ph,
                                    **self._dc_labels).observe(dur)

    # -------------------------------------------------------- replica side --
    def rx(self, trace_id: str, verb: str, from_id: int) -> None:
        """Inbound traced request: stitch this replica into the span."""
        if not self.enabled:
            return
        self.spans.event(trace_id, f"rx:{verb}", self.now_us(),
                         {"from": from_id})

    # ------------------------------------------------------------ export --
    def snapshot(self) -> dict:
        """JSON-safe per-node snapshot (the wire/bench/burn interchange
        format; merge with obs.report.merge_node_snapshots).  When the
        protocol-CPU profiler has samples, they ride as the "cpu" key so
        the cross-node merge can compute exact-sample quantiles."""
        from accord_tpu.obs.report import summarize
        cpu = self.cpuprof.export()
        metrics = self.registry.snapshot()
        snap = {"node": self.node_id, "metrics": metrics,
                "summary": summarize(metrics, cpu=cpu)}
        if cpu is not None:
            snap["cpu"] = cpu
        return snap

    def cpu_view(self) -> dict:
        """The live protocol-CPU + loop-health view (httpd `GET /top`, the
        tcp host's "top" frame, `burn --cpu-top`): this node's per-verb
        waterfall and top-verbs table plus the event-loop health gauges."""
        from accord_tpu.obs.report import cpu_section, loop_section
        metrics = self.registry.snapshot()
        return {"node": self.node_id,
                "cpu": cpu_section(self.cpuprof.export()),
                "loop": loop_section(metrics)}
