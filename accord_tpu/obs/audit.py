"""Audit-side pure logic: entry-set classification and the leak detector.

The replica-state auditor (local/audit.py) exchanges range digests and
per-txn entry lists across replicas; THIS module holds the parts with no
engine dependencies — comparing entry sets into hard divergences vs
benign lag, and the census sweep's monotonic-growth leak alarm — so they
stay inside obs/'s import fence (intra-package only, no jax/numpy;
tests/test_obs_budget.py enforces it) and unit-testable on plain data.

Entry shape (produced by local/audit.py, opaque here):

    {node_id: {txn_key: (cls, at)}}   cls in ("committed", "invalidated",
                                      "unknown"); at = executeAt (opaque,
                                      compared via repr) or None

Classification rules (the soundness story lives with the digest window in
local/audit.py — everything compared here is below the negotiated
universal-durable bound, where every replica is certified to have applied
or invalidated every transaction):

  * two replicas committed with different executeAts  -> HARD divergence
  * one replica invalidated, another committed        -> HARD divergence
  * "unknown" (locally truncated, decision shed)      -> compatible with
    anything — the replica cannot represent the decision, it does not
    contradict it
  * absent on one replica, committed on another       -> lag candidate;
    below the universal bound this should be impossible at quiesce, so
    the auditor escalates it only after `lag_rounds` CONSECUTIVE rounds
    (a replica mid-bootstrap/replay must not trip a one-shot alarm)
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple


def classify_entry_sets(by_node: Dict[int, dict]
                        ) -> Tuple[List[tuple], List[tuple]]:
    """Compare per-replica entry maps for one digest window.

    Returns (hard, lag), each sorted by txn key so the FIRST element is the
    first divergent transaction in the window:

      hard: [(txn_key, kind, {node: ("cls", at) | None})]
            kind in ("execute_at", "invalidated_vs_committed")
      lag:  [(txn_key, (absent_node, ...))]
    """
    nodes = sorted(by_node)
    union = sorted({k for m in by_node.values() for k in m})
    hard: List[tuple] = []
    lag: List[tuple] = []
    for key in union:
        vals = {n: by_node[n].get(key) for n in nodes}
        present = {n: v for n, v in vals.items() if v is not None}
        committed = {n: v[1] for n, v in present.items()
                     if v[0] == "committed"}
        invalidated = [n for n, v in present.items() if v[0] == "invalidated"]
        if committed and len({repr(at) for at in committed.values()}) > 1:
            hard.append((key, "execute_at", vals))
            continue
        if committed and invalidated:
            hard.append((key, "invalidated_vs_committed", vals))
            continue
        if committed:
            absent = tuple(n for n in nodes if vals[n] is None)
            if absent:
                lag.append((key, absent))
    return hard, lag


class LeakDetector:
    """Alarm when quiescent-but-uncleaned state grows monotonically.

    The census sweep feeds it the per-node count of terminal commands the
    cleanup ladder should eventually purge (APPLIED / INVALIDATED, not yet
    truncated).  Healthy clusters saw-tooth: the count grows between
    durability rounds and drops at each cleanup sweep.  A broken ladder
    (durability rounds disabled, a watermark wedged, an erase bug) only
    grows — after `sweeps` consecutive non-decreasing observations with at
    least `min_growth` total growth, the detector latches one alarm and
    re-arms from the new baseline."""

    __slots__ = ("min_growth", "sweeps", "alarms", "_base", "_last",
                 "_streak")

    def __init__(self, min_growth: int = 64, sweeps: int = 20):
        self.min_growth = min_growth
        self.sweeps = sweeps
        self.alarms = 0
        self._base: Optional[int] = None
        self._last: Optional[int] = None
        self._streak = 0

    def observe(self, count: int) -> bool:
        """Feed one sweep's count; True when this observation trips the
        alarm."""
        if self._base is None or (self._last is not None
                                  and count < self._last):
            # any decrease proves cleanup is alive: re-arm from here
            self._base = count
            self._streak = 0
        else:
            self._streak += 1
        self._last = count
        if self._streak >= self.sweeps and count - self._base >= self.min_growth:
            self.alarms += 1
            self._base = count
            self._streak = 0
            return True
        return False
