"""Protocol-tier CPU attribution profiler + event-loop health telemetry.

PR 8 left protocol CPU (~2 ms per txn per node spent in message applies in
`local/`) as the binding constraint on this box; this module is the
measurement base the coming `local/` optimizations are judged against —
the protocol-tier sibling of the PR-3 device-kernel waterfall:

  * `CpuProfiler` — sampled 1-in-N (`ACCORD_CPU_PROFILE=N`, off by
    default) per-dispatch attribution: every inbound message a node
    processes is split into decode -> apply -> CFK/conflict-index work ->
    reply-encode stages, labeled by verb.  Fences live at the layer
    boundaries (hosts time the wire decode, `local/node.py` brackets the
    dispatch, `local/commands.py`/`local/store.py` fence the
    CommandsForKey work — PAPER.md's hot computational kernel —
    and `Node.reply` fences the reply encode).  Exact-sample p50/p99 per
    (verb, stage) come from bounded raw-sample buffers, never the log2
    buckets, for the same reason the PR-3 profiler keeps raw samples: a
    bucket quantile's [1x, 2x) error would false-trip a 15% gate.

  * `LoopHealth` — ALWAYS-ON event-loop health gauges for the wall-clock
    hosts (`host/tcp.py`, `host/maelstrom.py`): the loop-lag histogram
    (scheduled-vs-actual timer fire delta — the direct measurement of a
    saturated dispatch loop), tick busy duration, dispatch-burst length
    and leftover pending-queue depth, plus `loop_lag` /
    `queue_saturation` flight-recorder alarms when lag or backlog cross
    their thresholds — so saturation is visible BEFORE throughput
    collapses.

OFF-BY-DEFAULT CONTRACT: with `ACCORD_CPU_PROFILE` unset, the dispatch
hooks are one attribute check each (enforced <2% of the scalar hot loop
by tests/test_obs_budget.py).  When enabled, unsampled dispatches pay a
dict increment and a modulo.

`ACCORD_CPU_SCALE` (float, default 1) scales recorded durations — the
test hook that lets `bench.py --guard`'s per-verb regression gate be
exercised with a synthetic slowdown, mirroring `ACCORD_PROFILE_SCALE`
(tests/test_bench_guard.py).

HARD CONSTRAINT (package docstring): no jax/numpy imports; intra-package
accord_tpu imports only.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

# raw-sample cap per (verb, stage) AND per-verb total: exact p50/p99
# without unbounded growth.  The caps are EQUAL so a verb's stage sample
# lists are index-aligned prefixes of its total list — per-sample
# stage <= total then implies p50(stage) <= p50(total), an invariant the
# sampled-on burn test asserts.
_MAX_SAMPLES = 256

# the additive stage set every sampled dispatch decomposes into; "apply"
# is exclusive (dispatch wall minus the nested cfk/reply_encode fences)
STAGES = ("decode", "apply", "cfk", "reply_encode")

# stages measured via nested fences INSIDE the dispatch bracket; their
# time is subtracted from the enclosing "apply" so the waterfall is
# additive: decode + apply + cfk + reply_encode == total
_NESTED_STAGES = ("cfk", "reply_encode")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class CpuProfiler:
    """Per-node protocol-CPU profiler writing into a metrics registry.

    Registry metrics (always mirrored on export for /metrics):
      accord_cpu_stage_us{verb,stage}     histogram — per-stage wall time
      accord_cpu_dispatch_us{verb}        histogram — per-dispatch total
      accord_cpu_dispatches_total{verb}   counter — ALL dispatches while
                                          enabled (the sampling denominator
                                          and the verb census)
      accord_cpu_sampled_total            counter — sampled dispatches
    """

    __slots__ = ("registry", "sample_n", "enabled", "active", "_clock",
                 "_scale", "_tick", "_verb", "_t0", "_acc",
                 "_pending_decode", "_samples", "_totals", "_dispatches",
                 "_sampled", "_stage_hists", "_total_hists")

    def __init__(self, registry, sample_n: int = 0, clock=None):
        self.registry = registry
        self.sample_n = sample_n
        self.enabled = sample_n > 0
        self.active = False  # a sampled dispatch is open RIGHT NOW
        self._clock = clock if clock is not None else time.perf_counter
        self._scale = _env_float("ACCORD_CPU_SCALE", 1.0)
        self._tick = 0
        self._verb: Optional[str] = None
        self._t0 = 0.0
        self._acc: Dict[str, float] = {}
        self._pending_decode = 0.0
        self._samples: Dict[str, Dict[str, List[float]]] = {}  # verb->stage
        self._totals: Dict[str, List[float]] = {}              # verb->[us]
        self._dispatches: Dict[str, int] = {}                  # verb->count
        self._sampled = 0
        # histogram handles cached per (verb, stage) / verb: dispatch_end
        # runs per sampled dispatch and must not pay a labeled registry
        # lookup per stage (the profiler's own overhead lands inside the
        # very p50s it reports)
        self._stage_hists: Dict[tuple, object] = {}
        self._total_hists: Dict[str, object] = {}

    # -------------------------------------------------------- decode hook --
    def note_decode(self, dur_s: float) -> None:
        """Hosts time the per-message wire decode (which happens BEFORE the
        node dispatch exists) and park it here; the next dispatch_begin
        consumes it into the sample's "decode" stage.  Native-codec TCP
        ingress decodes whole frames in the loop's parser — that cost shows
        in LoopHealth's tick duration, not here."""
        self._pending_decode = dur_s

    # ---------------------------------------------------- dispatch bracket --
    def dispatch_begin(self, verb: str) -> bool:
        """Open the per-dispatch attribution bracket in Node._process.
        Counts every dispatch (the census --guard's top-verbs table scales
        by), decides 1-in-N sampling, and folds any parked decode lap.
        Returns whether this dispatch is sampled (the caller must then pair
        it with dispatch_end)."""
        self._dispatches[verb] = self._dispatches.get(verb, 0) + 1
        decode = self._pending_decode
        if decode:
            self._pending_decode = 0.0
        if self.active:
            # a nested local apply inside an open sample is absorbed into
            # the enclosing dispatch's stages, never double-counted
            return False
        self._tick += 1
        if self._tick % self.sample_n:
            return False
        self._sampled += 1
        self.active = True
        self._verb = verb
        self._acc = {"decode": decode} if decode else {}
        self._t0 = self._clock()
        return True

    def stage_begin(self) -> float:
        """Start a nested stage fence (call only when `active`)."""
        return self._clock()

    def stage_end(self, t: float, stage: str) -> None:
        """Close a nested stage fence, accumulating into `stage`."""
        self._acc[stage] = self._acc.get(stage, 0.0) + (self._clock() - t)

    def dispatch_end(self) -> None:
        """Close the sampled dispatch: apply = wall - nested fences, then
        record every stage + the per-verb total (histograms + raw
        samples)."""
        total = self._clock() - self._t0
        self.active = False
        verb = self._verb
        acc = self._acc
        nested = 0.0
        for s in _NESTED_STAGES:
            nested += acc.get(s, 0.0)
        acc["apply"] = max(0.0, total - nested)
        total += acc.get("decode", 0.0)
        scale = self._scale
        reg = self.registry
        by_stage = self._samples.get(verb)
        if by_stage is None:
            by_stage = self._samples[verb] = {}
        for stage, dur in acc.items():
            us = round(dur * scale * 1e6, 1)
            h = self._stage_hists.get((verb, stage))
            if h is None:
                h = self._stage_hists[(verb, stage)] = reg.histogram(
                    "accord_cpu_stage_us", verb=verb, stage=stage)
            h.observe(us)
            samples = by_stage.get(stage)
            if samples is None:
                samples = by_stage[stage] = []
            if len(samples) < _MAX_SAMPLES:
                samples.append(us)
        us_total = round(total * scale * 1e6, 1)
        h = self._total_hists.get(verb)
        if h is None:
            h = self._total_hists[verb] = reg.histogram(
                "accord_cpu_dispatch_us", verb=verb)
        h.observe(us_total)
        totals = self._totals.get(verb)
        if totals is None:
            totals = self._totals[verb] = []
        if len(totals) < _MAX_SAMPLES:
            totals.append(us_total)

    # -------------------------------------------------------------- export --
    def export(self) -> Optional[dict]:
        """Raw-sample export for the cross-node merge (rides NodeObs
        snapshots as the "cpu" key; obs/report.cpu_section summarizes).
        Mirrors the census counters into the registry so /metrics carries
        them.  None when nothing was recorded (profiling off)."""
        if not self._sampled and not self._dispatches:
            return None
        reg = self.registry
        for verb, n in self._dispatches.items():
            reg.counter("accord_cpu_dispatches_total", verb=verb).value = n
        reg.counter("accord_cpu_sampled_total").value = self._sampled
        return {
            "sampled": self._sampled,
            "dispatches": dict(self._dispatches),
            "totals": {v: list(s) for v, s in self._totals.items()},
            "stages": {v: {st: list(ss) for st, ss in by.items()}
                       for v, by in self._samples.items()},
        }


def merge_cpu_exports(exports) -> Optional[dict]:
    """Pool CpuProfiler.export() dicts from several nodes into one:
    dispatch counts sum, raw sample lists concatenate (every list is
    bounded per node, so the pool is bounded by node count)."""
    exports = [e for e in exports if e]
    if not exports:
        return None
    out = {"sampled": 0, "dispatches": {}, "totals": {}, "stages": {}}
    for e in exports:
        out["sampled"] += e.get("sampled", 0)
        for verb, n in e.get("dispatches", {}).items():
            out["dispatches"][verb] = out["dispatches"].get(verb, 0) + n
        for verb, s in e.get("totals", {}).items():
            out["totals"].setdefault(verb, []).extend(s)
        for verb, by in e.get("stages", {}).items():
            dst = out["stages"].setdefault(verb, {})
            for stage, ss in by.items():
                dst.setdefault(stage, []).extend(ss)
    return out


def cpu_profiler_from_env(registry,
                          env: str = "ACCORD_CPU_PROFILE") -> CpuProfiler:
    """ACCORD_CPU_PROFILE=N -> sample 1-in-N dispatches (N=1 samples every
    dispatch); unset/0/garbage -> disabled (the hot-path default)."""
    raw = os.environ.get(env, "")
    try:
        n = int(raw) if raw else 0
    except ValueError:
        n = 0
    return CpuProfiler(registry, sample_n=max(0, n))


# ---------------------------------------------------------- loop health ----

class LoopHealth:
    """Always-on event-loop health gauges for a wall-clock host loop.

    The selector/stdio loops are each node's ONLY protocol thread: when it
    saturates, timers fire late (RPC timeouts stretch, coalescing ticks
    slip) long before throughput visibly collapses.  These gauges make
    that stage observable:

      accord_loop_lag_us          histogram — scheduled-vs-actual timer
                                  fire delta (rt.RealTimeScheduler hook)
      accord_loop_tick_us         histogram — busy (non-blocking) portion
                                  of each loop pass that did work
      accord_loop_burst_msgs      histogram — dispatch-burst length per
                                  pass (inbound frames + loopback items)
      accord_loop_depth_max       gauge — high-water leftover queue depth
                                  measured AFTER a pass (work the pass
                                  could not drain)
      accord_loop_lag_alarms_total / accord_loop_queue_saturation_total
                                  counters — threshold crossings

    Alarms also land on the flight ring (`loop_lag`, rate-limited;
    `queue_saturation`, edge-triggered) so the cross-replica forensics
    timeline shows saturation next to the traffic that caused it.
    Thresholds: `ACCORD_LOOP_LAG_ALARM_US` (default 100000) and
    `ACCORD_LOOP_SATURATION_DEPTH` (default 512)."""

    __slots__ = ("flight", "_h_lag", "_h_tick", "_h_burst", "_g_depth",
                 "_c_lag_alarms", "_c_sat_alarms", "lag_alarm_us",
                 "saturation_depth", "_clock", "_last_lag_flight",
                 "_saturated")

    def __init__(self, registry, flight, clock=None):
        self.flight = flight
        self._h_lag = registry.histogram("accord_loop_lag_us")
        self._h_tick = registry.histogram("accord_loop_tick_us")
        self._h_burst = registry.histogram("accord_loop_burst_msgs")
        self._g_depth = registry.gauge("accord_loop_depth_max")
        self._c_lag_alarms = registry.counter("accord_loop_lag_alarms_total")
        self._c_sat_alarms = registry.counter(
            "accord_loop_queue_saturation_total")
        self.lag_alarm_us = int(_env_float("ACCORD_LOOP_LAG_ALARM_US",
                                           100_000))
        self.saturation_depth = int(_env_float("ACCORD_LOOP_SATURATION_DEPTH",
                                               512))
        self._clock = clock if clock is not None else time.monotonic
        # -inf so the FIRST alarm always reaches the ring regardless of
        # the clock's epoch
        self._last_lag_flight = float("-inf")
        self._saturated = False

    def timer_lag(self, lag_s: float) -> None:
        """One timer ran `lag_s` after its deadline (the scheduler hook:
        rt.RealTimeScheduler.lag_observer).  Zero-delay timers measure pure
        queue delay, which is exactly the loop-lag semantics wanted."""
        lag_us = int(lag_s * 1e6)
        self._h_lag.observe(lag_us)
        if lag_us > self.lag_alarm_us:
            self._c_lag_alarms.inc()
            now = self._clock()
            # rate-limit the forensics record: a saturated loop runs MANY
            # late timers per pass and must not wash out its own ring
            if now - self._last_lag_flight >= 0.25:
                self._last_lag_flight = now
                self.flight.record("loop_lag", None, (lag_us,))

    @property
    def saturated(self) -> bool:
        """Current (edge-triggered, hysteresis-cleared) saturation state —
        the QoS admission tier reads this as a pressure floor."""
        return self._saturated

    def tick(self, busy_s: float, burst: int, depth: int) -> None:
        """One loop pass that did work: `busy_s` excludes the blocking
        poll, `burst` is the dispatched item count, `depth` the backlog
        left undrained when the pass ended."""
        self._h_tick.observe(int(busy_s * 1e6))
        if burst:
            self._h_burst.observe(burst)
        if depth > self._g_depth.value:
            self._g_depth.value = depth
        if depth >= self.saturation_depth:
            if not self._saturated:
                self._saturated = True
                self._c_sat_alarms.inc()
                self.flight.record("queue_saturation", None, (depth,))
        elif self._saturated and depth < self.saturation_depth // 2:
            self._saturated = False
