"""Read-through views: registry-backed attributes with legacy call sites.

The three pre-registry stat surfaces (`Node.infer_stats`, the device
store's `device_*` ints, `PipelineStats`' counters) are mutated all over
the engine with plain `obj.attr += 1` / `stats[key] += 1`.  Migrating them
onto the registry must not churn those call sites — so the OLD attribute
names stay, as descriptors/dict-views whose storage IS a registry metric.
"""

from __future__ import annotations

from typing import Dict, Iterable


class MetricView:
    """Class-level descriptor making `obj.attr` an int view over a registry
    Counter/Gauge.  `bind_metric_views(obj, registry, **labels)` must run
    (normally first thing in __init__) before any access; after that,
    `obj.attr += 1` and `obj.attr = max(obj.attr, x)` work unchanged while
    the value lives in the registry."""

    __slots__ = ("metric", "kind", "_attr")

    def __init__(self, metric: str, kind: str = "counter"):
        self.metric = metric
        self.kind = kind
        self._attr = None

    def __set_name__(self, owner, name):
        self._attr = name

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        return obj._obs_metrics[self._attr].value

    def __set__(self, obj, value):
        obj._obs_metrics[self._attr].value = value


def bind_metric_views(obj, registry, **labels) -> None:
    """Create the per-instance metric objects behind every MetricView
    declared on `type(obj)` (registry get-or-create, so two instances with
    identical labels share one metric)."""
    metrics: Dict[str, object] = {}
    for klass in type(obj).__mro__:
        for name, desc in vars(klass).items():
            if isinstance(desc, MetricView) and name not in metrics:
                make = (registry.gauge if desc.kind == "gauge"
                        else registry.counter)
                metrics[name] = make(desc.metric, **labels)
    object.__setattr__(obj, "_obs_metrics", metrics)


class CounterDict:
    """Dict-shaped view over one labeled counter family: `d[key] += n`
    increments `name{<label>=key}`.  Fixed key set (the legacy dicts were
    fixed-shape); equality against plain dicts keeps test assertions
    working."""

    __slots__ = ("_metrics",)

    def __init__(self, registry, name: str, keys: Iterable[str],
                 label: str = "kind", **labels):
        self._metrics = {k: registry.counter(name, **{label: k}, **labels)
                         for k in keys}

    def __getitem__(self, key):
        return self._metrics[key].value

    def __setitem__(self, key, value):
        self._metrics[key].value = value

    def get(self, key, default=0):
        m = self._metrics.get(key)
        return m.value if m is not None else default

    def keys(self):
        return self._metrics.keys()

    def values(self):
        return [m.value for m in self._metrics.values()]

    def items(self):
        return [(k, m.value) for k, m in self._metrics.items()]

    def as_dict(self) -> dict:
        return dict(self.items())

    def __iter__(self):
        return iter(self._metrics)

    def __len__(self):
        return len(self._metrics)

    def __contains__(self, key):
        return key in self._metrics

    def __eq__(self, other):
        if isinstance(other, CounterDict):
            return self.as_dict() == other.as_dict()
        return self.as_dict() == other

    def __repr__(self):
        return repr(self.as_dict())
