"""Prometheus-style metrics endpoint for the wall-clock hosts.

`ACCORD_METRICS_PORT=<base>` on a host process serves:

    GET /metrics        Prometheus text exposition
    GET /metrics.json   the NodeObs snapshot (metrics + summary), JSON
    GET /flight         the node's flight-recorder tail (?limit=N), JSON
    GET /flight?txn=ID  one trace id's flight events on this node, JSON
    GET /audit          live replica-state auditor view (divergences,
                        last digest round, lifecycle census), JSON
    GET /top            protocol-CPU top-verbs waterfall + event-loop
                        health gauges (obs/cpuprof.py), JSON

Multi-process clusters on one machine offset the base port by the node id
(node N binds base + N - 1); base 0 binds an ephemeral port (recorded on
the returned server as `.port`).  The server runs on a daemon thread and
only READS the registry — snapshots tolerate concurrent mutation.
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional


class _Handler(BaseHTTPRequestHandler):
    server_version = "accord-obs/1"

    def log_message(self, fmt, *args):  # noqa: A003 — silence per-request
        pass

    def do_GET(self):  # noqa: N802 — http.server API
        obs = self.server.obs_provider()
        if self.path.startswith("/metrics.json"):
            body = json.dumps(obs.snapshot()).encode()
            ctype = "application/json"
        elif self.path.startswith("/flight"):
            from urllib.parse import parse_qs, urlparse
            qs = parse_qs(urlparse(self.path).query)
            txn = qs.get("txn", [None])[0]
            try:
                limit = int(qs.get("limit", ["200"])[0])
            except ValueError:
                limit = 200
            flight = obs.flight
            events = (flight.for_trace(txn) if txn
                      else flight.tail(limit))
            body = json.dumps({"node": obs.node_id, "txn": txn,
                               "recorded_total": flight.recorded_total,
                               "events": [list(e) for e in events]}).encode()
            ctype = "application/json"
        elif self.path.startswith("/top"):
            # protocol-CPU waterfall + loop health (obs/cpuprof.py): the
            # per-verb top table is live when ACCORD_CPU_PROFILE=N is set;
            # the loop gauges are always-on
            body = json.dumps(obs.cpu_view()).encode()
            ctype = "application/json"
        elif self.path.startswith("/audit"):
            # live replica-state auditor view (divergences, last digest
            # round, census) — {} when no Auditor is attached to this node
            view = obs.audit_view() if obs.audit_view is not None else {}
            body = json.dumps(view).encode()
            ctype = "application/json"
        elif self.path.startswith("/metrics"):
            body = obs.registry.render_prometheus().encode()
            ctype = "text/plain; version=0.0.4"
        else:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def start_metrics_server(obs_provider: Callable, port: int,
                         host: str = "127.0.0.1") -> ThreadingHTTPServer:
    """Serve `obs_provider()` (a NodeObs) on `port` (0 = ephemeral).  The
    realised port is on the returned server as `.port`."""
    server = ThreadingHTTPServer((host, port), _Handler)
    server.daemon_threads = True
    server.obs_provider = obs_provider
    server.port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server


def maybe_start_from_env(obs_provider: Callable, node_id: int = 1,
                         env: str = "ACCORD_METRICS_PORT"
                         ) -> Optional[ThreadingHTTPServer]:
    """Start the endpoint when the env var is set; None otherwise (or when
    the bind fails — metrics must never take a node down)."""
    raw = os.environ.get(env, "")
    if not raw:
        return None
    try:
        base = int(raw)
        port = 0 if base == 0 else base + max(0, node_id - 1)
        return start_metrics_server(obs_provider, port)
    except (ValueError, OSError):
        return None
