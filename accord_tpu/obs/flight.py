"""Always-on flight recorder: a bounded per-node ring of structured events.

Spans (obs/spans.py) answer "where did the time go" for transactions we
chose to follow; the flight recorder answers "what happened just before it
went wrong" for EVERYTHING, all the time.  Each node keeps one fixed-size
ring (a deque with maxlen — no allocation beyond the event slot itself)
recording command status transitions, message tx/rx/drop, progress-log
escalations and pipeline admission decisions, each stamped with the PR-2
trace id where one exists.  On a burn/verify/journal failure the rings are
stitched across replicas into one causally ordered timeline for the
offending transactions — the failure artifact (sim/burn.py), also exposed
live via `burn --flight-dump`, the tcp host's "flight" frame, and the
metrics endpoint's `/flight?txn=` route.

Event layout (one fixed tuple per slot, hot paths avoid dicts):

    (at_us, seq, kind, trace_id, data)

`kind` MUST appear in EVENT_KINDS below — tests/test_span_coverage.py
statically asserts every literal kind recorded anywhere in the tree is
documented here (and vice versa), so a new event class cannot silently
skip the forensics layer.  `data` is kind-specific (see the table).

HARD CONSTRAINT (package docstring): no jax/numpy imports, intra-package
accord_tpu imports only; always-on overhead is budgeted <2% of the scalar
hot loop by tests/test_obs_budget.py.
"""

from __future__ import annotations

import os
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

# Every event kind any call site may record, with its data layout.
# Documentation IS the registry: the span-coverage lint fails when a
# `flight.record("<kind>", ...)` literal is absent from this table.
EVENT_KINDS = {
    "status": "command status transition (local/command.py); "
              "data=(store_id, prev_status, new_status)",
    "tx": "outbound request (local/node.py Node.send); data=(to, verb)",
    "reply": "outbound reply (local/node.py Node.reply); data=(to, verb)",
    "rx": "inbound request dispatched (local/node.py Node._process); "
          "data=(from_id, verb)",
    "drop": "simulated network dropped a message (sim/network.py), "
            "recorded on the SENDER's ring; data=(from_id, to, verb)",
    "escalate": "progress-log escalation (impl/progress_log.py); "
                "data=(store_id, what, attempts)",
    "pipeline_admit": "ingest admission (pipeline/ingest.py); "
                      "data=(queue_depth,)",
    "pipeline_shed": "ingest admission shed -> Rejected "
                     "(pipeline/ingest.py); data=(queue_depth,)",
    "pipeline_batch": "ingest batch closed (pipeline/ingest.py); "
                      "data=(size, by_deadline)",
    "journal_append": "WAL record framed into the active segment "
                      "(journal/wal.py); data=(seq, payload_bytes)",
    "journal_rotate": "WAL segment rotated at the size threshold "
                      "(journal/wal.py); data=(new_segment_index,)",
    "journal_snapshot": "snapshot compaction folded + retired segments "
                        "(journal/wal.py); data=(records_in, records_out, "
                        "segments_retired)",
    "journal_replay_begin": "crash-restart journal replay started "
                            "(journal/replay.py); data=(records,)",
    "journal_replay_end": "crash-restart journal replay finished "
                          "(journal/replay.py); data=(records, txns)",
    "infer_evidence": "per-shard quorum of InvalidIf invalidation evidence "
                      "established by a CheckStatus round "
                      "(coordinate/fetch.py); data=(evidence_replies, "
                      "contacted)",
    "infer_invalidate": "invalidation committed with no extra round off "
                        "quorum evidence, or inferred locally by the "
                        "safe-to-clean sweep (coordinate/infer.py, "
                        "coordinate/recover.py, local/cleanup.py); "
                        "data=(site, merged_status)",
    "audit_digest": "cross-replica range-digest round settled "
                    "(local/audit.py); data=(range_start, range_end, "
                    "replicas, outcome)",
    "audit_divergence": "replica-state divergence confirmed by the audit "
                        "drill-down (local/audit.py), trace id = the "
                        "divergent txn; data=(kind, range_start, "
                        "range_end, disagreeing_nodes)",
    "census_sweep": "state-lifecycle census sweep completed "
                    "(local/audit.py); data=(resident, "
                    "quiescent_uncleaned, bytes_est)",
    "frame_coalesce": "message captured into a peer's transport egress "
                      "buffer (host/tcp.py), trace id = the bundled "
                      "message's; data=(peer, pending_in_buffer)",
    "frame_flush": "per-peer egress buffer left as ONE coalesced wire "
                   "frame (host/tcp.py); data=(peer, messages, bytes)",
    "loop_lag": "event-loop timer fired later than its deadline by more "
                "than the alarm threshold (obs/cpuprof.LoopHealth, wired "
                "by host/tcp.py and host/maelstrom.py; rate-limited); "
                "data=(lag_us,)",
    "queue_saturation": "event-loop backlog crossed the saturation "
                        "threshold (obs/cpuprof.LoopHealth, wired by "
                        "host/tcp.py and host/maelstrom.py; edge-"
                        "triggered); data=(depth,)",
    "epoch_install": "admin-plane epoch install accepted/journaled "
                     "(impl/config_service.py); data=(epoch, from_id)",
    "bootstrap_begin": "bootstrap attempt fenced + fetch started "
                       "(local/bootstrap.py); data=(epoch, attempt)",
    "bootstrap_checkpoint": "bootstrap progress checkpoint journaled — "
                            "crash resumes from here instead of "
                            "re-fetching (local/bootstrap.py); "
                            "data=(epoch, attempt, n_ranges)",
    "bootstrap_done": "bootstrap attempt chain settled ok/failed "
                      "(local/bootstrap.py); data=(epoch, attempt, "
                      "outcome)",
    "drain_begin": "scale-in drain fence raised on/about a retiring node "
                   "(messages/admin.py); data=(node_id, from_id)",
    "drain_done": "retiring node durably handed off + retired "
                  "(messages/admin.py); data=(node_id, from_id)",
    "cmd_evict": "quiescent command evicted from the resident tier to the "
                 "spill store (local/paging.py), trace id = the evicted "
                 "txn; data=(store_id, save_status)",
    "cmd_fault": "spilled command faulted back resident on access — one "
                 "fault-index point read (local/paging.py), trace id = "
                 "the faulted txn; data=(store_id, save_status)",
    "page_spill": "spill frame appended to the paging tier's on-disk "
                  "store (journal/fault_index.py); data=(segment, offset, "
                  "payload_bytes)",
    "qos_admit": "submit admitted by the QoS tier (qos/admission.py; "
                 "sampled 1-in-64 so a healthy host does not wash out its "
                 "own ring); data=(tenant, priority, admitted_since_last)",
    "qos_shed": "submit shed by the QoS tier — pressure above the class "
                "threshold, or the pipeline's last-resort inner ring "
                "(qos/admission.py); data=(tenant, priority, reason, "
                "millipressure)",
    "qos_throttle": "submit throttled by the QoS tier — tenant token "
                    "bucket empty (qos/admission.py); data=(tenant, "
                    "priority, retry_after_us)",
    "shard_spawn": "shard worker process spawned (or respawned after a "
                   "crash) by the supervisor (shard/supervisor.py); "
                   "data=(shard, pid, generation)",
    "shard_submit": "shard-affine request shipped over a worker pipe "
                    "(shard/supervisor.py), trace id = the request's; "
                    "data=(shard, verb)",
    "shard_reduce": "cross-worker fan-out reduced to one reply "
                    "(shard/supervisor.py), trace id = the request's; "
                    "data=(n_shards, verb)",
    "shard_retire": "shard worker drained and retired "
                    "(shard/supervisor.py); data=(shard, generation)",
    "geo_install": "geo placement profile installed on this node "
                   "(sim/cluster.py at build; host/tcp.py from ACCORD_GEO "
                   "or an EpochInstall frame); data=(profile_name, dc)",
    "dc_partition_begin": "a whole datacenter severed from the rest of "
                          "the cluster (sim/network.py DcPartitionNemesis; "
                          "recorded on every live node); data=(dc, "
                          "dc_node_ids)",
    "dc_partition_heal": "the DC partition healed (sim/network.py "
                         "DcPartitionNemesis); data=(dc, dc_node_ids)",
}


class FlightRecorder:
    """Bounded always-on event ring for one node.

    `record` is the only hot-path entry: one clock read, one tuple, one
    deque append.  The ring is a deque with maxlen, so capacity overflow
    evicts the oldest slot with no per-event allocation churn."""

    __slots__ = ("node_id", "capacity", "events", "enabled", "_clock_us",
                 "_seq", "recorded_total")

    def __init__(self, node_id: int = 0, capacity: int = 4096,
                 clock_us=None):
        self.node_id = node_id
        self.capacity = capacity
        self.events: deque = deque(maxlen=capacity)
        # always-on by design; ACCORD_FLIGHT=0 is the emergency kill
        # switch (and the overhead-A/B lever for the bench)
        self.enabled = os.environ.get("ACCORD_FLIGHT", "1") != "0"
        self._clock_us = clock_us if clock_us is not None else (lambda: 0)
        self._seq = 0
        self.recorded_total = 0  # lifetime count (ring wrap diagnostics)

    def record(self, kind: str, trace_id: Optional[str] = None,
               data=None) -> None:
        if not self.enabled:
            return
        seq = self._seq = self._seq + 1
        self.recorded_total += 1
        self.events.append((self._clock_us(), seq, kind, trace_id, data))

    # ------------------------------------------------------------- query --
    def tail(self, n: int = 200) -> List[tuple]:
        events = list(self.events)
        return events[-n:]

    def for_trace(self, trace_id: str) -> List[tuple]:
        return [e for e in self.events if e[3] == trace_id]

    def trace_ids(self) -> set:
        return {e[3] for e in self.events if e[3] is not None}

    def __len__(self):
        return len(self.events)


def stitch_flight(recorders: Iterable[FlightRecorder],
                  trace_ids=None, limit: Optional[int] = None
                  ) -> List[tuple]:
    """Merge rings across replicas into one causally ordered timeline:
    [(at_us, node_id, seq, kind, trace_id, data)].  `trace_ids` (a set)
    filters to the offending transactions; None merges everything.  Order
    is (at_us, node_id, seq) — per-node clocks may drift in sim, so the
    global order is best-effort while each node's subsequence is exact."""
    ids = set(trace_ids) if trace_ids is not None else None
    merged = []
    for rec in recorders:
        for at, seq, kind, tid, data in rec.events:
            if ids is None or tid in ids:
                merged.append((at, rec.node_id, seq, kind, tid, data))
    merged.sort(key=lambda e: (e[0], e[1], e[2]))
    if limit is not None and len(merged) > limit:
        merged = merged[-limit:]
    return merged


def trace_ids_in_text(recorders: Iterable[FlightRecorder],
                      text: str) -> set:
    """Trace ids present in any ring that also appear verbatim in `text`
    (failure messages embed TxnId reprs == trace ids; this recovers the
    offending transactions from an arbitrary assertion string)."""
    found = set()
    for rec in recorders:
        for tid in rec.trace_ids():
            if tid not in found and tid in text:
                found.add(tid)
    return found


def first_divergence(events: List[tuple]) -> Optional[tuple]:
    """First point where replicas' per-trace status histories disagree.

    Groups the stitched timeline's "status" events by node and walks the
    per-node transition sequences in lockstep: the first index at which
    the nodes that got that far do not all agree is the earliest observable
    divergence — the event a replay/verify failure should lead with.
    Returns (index, {node_id: transition-or-None}) or None when every
    node's recorded history is a prefix of the longest one."""
    by_node: Dict[int, List[Tuple]] = {}
    for _at, node_id, _seq, kind, _tid, data in events:
        if kind == "status":
            by_node.setdefault(node_id, []).append(data)
    if len(by_node) < 2:
        return None
    longest = max(len(v) for v in by_node.values())
    for i in range(longest):
        at_i = {n: (seqs[i] if i < len(seqs) else None)
                for n, seqs in by_node.items()}
        present = {v for v in at_i.values() if v is not None}
        if len(present) > 1:
            return (i, at_i)
    return None


def format_timeline(events: List[tuple], header: str = "") -> str:
    """Human-readable failure artifact for a stitched timeline."""
    lines = [header] if header else []
    if not events:
        lines.append("  (no flight events retained for these trace ids — "
                     "ring may have wrapped)")
        return "\n".join(lines)
    t0 = events[0][0]
    for at, node_id, _seq, kind, tid, data in events:
        body = f"  +{at - t0:>9}us n{node_id} {kind:<14}"
        if data is not None:
            body += f" {_fmt_data(kind, data)}"
        if tid is not None:
            body += f"  [{tid}]"
        lines.append(body)
    return "\n".join(lines)


def _fmt_data(kind: str, data) -> str:
    if kind == "status" and isinstance(data, tuple) and len(data) == 3:
        return f"s{data[0]} {data[1]}->{data[2]}"
    if kind in ("tx", "reply") and isinstance(data, tuple):
        return f"to=n{data[0]} {data[1]}"
    if kind == "rx" and isinstance(data, tuple):
        return f"from=n{data[0]} {data[1]}"
    if kind == "drop" and isinstance(data, tuple) and len(data) == 3:
        return f"n{data[0]}->n{data[1]} {data[2]}"
    if isinstance(data, tuple):
        return " ".join(str(d) for d in data)
    return str(data)
