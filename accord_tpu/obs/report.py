"""Cross-node snapshot merging + the human/bench summary + SLO reports.

`summarize` turns a (possibly merged) registry snapshot into the compact
report the bench records next to its BENCH_HISTORY row and the burn prints
at end of run: fast-path ratio, coordination outcomes, per-phase latency
quantiles, device flush-window counts, pipeline admission counters.

`slo_report` builds the open-loop workload harness's SLO row
(accord_tpu/workload/): exact-sample p50/p99/p99.9 — NEVER the registry's
log2-bucket quantiles, whose up-to-2x error would false-trip a 15% tail
gate (the PR-3 precedent that gave the profiler its raw-sample buffer) —
for open-loop (intended-start) and closed-loop (submit-start) latency,
per-phase attribution, and achieved-vs-offered rate.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from accord_tpu.obs.cpuprof import merge_cpu_exports
from accord_tpu.obs.registry import (merge_snapshots, parse_labels,
                                     snapshot_quantile)


def merge_node_snapshots(snaps: List[dict]) -> dict:
    """Merge NodeObs.snapshot() dicts from several nodes/processes into one
    cluster view: {"nodes": [...], "metrics": merged, "summary": ...}.
    Protocol-CPU raw samples ("cpu" key, obs/cpuprof.py) pool across nodes
    so the summary's exact-sample per-verb quantiles stay exact."""
    snaps = [s for s in snaps if s]
    metrics = merge_snapshots([s.get("metrics", {}) for s in snaps])
    cpu = merge_cpu_exports([s.get("cpu") for s in snaps])
    return {"nodes": [s.get("node") for s in snaps], "metrics": metrics,
            "summary": summarize(metrics, cpu=cpu)}


def _counter_by_label(metrics: dict, name: str, label: str) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for lk, v in metrics.get("counters", {}).get(name, {}).items():
        key = parse_labels(lk).get(label, "")
        out[key] = out.get(key, 0) + v
    return out


def _counter_total(metrics: dict, name: str) -> int:
    return sum(metrics.get("counters", {}).get(name, {}).values())


def _gauge_max(metrics: dict, name: str) -> int:
    vals = metrics.get("gauges", {}).get(name, {}).values()
    return max(vals) if vals else 0


def _gauge_sum_by_label(metrics: dict, name: str, label: str) -> Dict[str, int]:
    """Sum one gauge family across label sets grouped by `label` — census
    gauges carry a node label, so the cross-node merge (max per label set)
    keeps per-node values distinct and summing over them is the cluster
    total."""
    out: Dict[str, int] = {}
    for lk, v in metrics.get("gauges", {}).get(name, {}).items():
        key = parse_labels(lk).get(label, "")
        out[key] = out.get(key, 0) + v
    return out


def _gauge_total(metrics: dict, name: str) -> int:
    return sum(metrics.get("gauges", {}).get(name, {}).values())


def _per_shard_census(metrics: dict) -> Dict[str, dict]:
    """Cluster-wide per-shard command/pager table from the shard-labeled
    gauge series (emitted only under the worker runtime — local/audit.py
    census_once; empty dict when in-loop).  Series WITHOUT a shard label
    are the node rollups and are deliberately excluded: the rollup and
    the shard rows would double-count if folded together."""
    out: Dict[str, dict] = {}

    def row(shard: str) -> dict:
        return out.setdefault(shard, {"resident": 0, "spilled": 0,
                                      "pager": {}})

    for lk, v in metrics.get("gauges", {}).get("accord_census_commands",
                                               {}).items():
        labels = parse_labels(lk)
        shard = labels.get("shard", "")
        tier = labels.get("tier", "")
        if shard and tier in ("resident", "spilled"):
            row(shard)[tier] += v
    for name, series in metrics.get("gauges", {}).items():
        if not name.startswith("accord_pager_"):
            continue
        key = name[len("accord_pager_"):]
        for lk, v in series.items():
            shard = parse_labels(lk).get("shard", "")
            if shard:
                pg = row(shard)["pager"]
                pg[key] = pg.get(key, 0) + v
    return out


def _gauge_max_by_label(metrics: dict, name: str, label: str
                        ) -> Dict[str, int]:
    """Worst (max) value of one gauge family grouped by `label`."""
    out: Dict[str, int] = {}
    for lk, v in metrics.get("gauges", {}).get(name, {}).items():
        key = parse_labels(lk).get(label, "")
        out[key] = max(out.get(key, v), v)
    return out


def _hists_by_label(metrics: dict, name: str, label: str) -> Dict[str, dict]:
    """Merge one histogram family's snapshots grouped by a label value."""
    out: Dict[str, dict] = {}
    for lk, h in metrics.get("histograms", {}).get(name, {}).items():
        key = parse_labels(lk).get(label, "")
        cur = out.setdefault(key, {"count": 0, "sum": 0, "buckets": {}})
        cur["count"] += h.get("count", 0)
        cur["sum"] += h.get("sum", 0)
        for e, n in h.get("buckets", {}).items():
            cur["buckets"][e] = cur["buckets"].get(e, 0) + n
    return out


def _merged_hist(metrics: dict, name: str) -> dict:
    """One histogram family merged across every label set."""
    out = {"count": 0, "sum": 0, "buckets": {}}
    for h in metrics.get("histograms", {}).get(name, {}).values():
        out["count"] += h.get("count", 0)
        out["sum"] += h.get("sum", 0)
        for e, n in h.get("buckets", {}).items():
            out["buckets"][e] = out["buckets"].get(e, 0) + n
    return out


def _hist_report(h: dict) -> dict:
    count = h.get("count", 0)
    return {"count": count,
            "mean": round(h.get("sum", 0) / count, 1) if count else 0.0,
            "p50": snapshot_quantile(h, 0.50),
            "p95": snapshot_quantile(h, 0.95)}


def _infer_section(metrics: dict) -> dict:
    """The Infer-ladder A/B (coordinate/infer.py): every
    accord_infer_total kind, plus the no-round ratio — of the
    interrogations that established a per-shard evidence quorum
    (resolvable with zero extra rounds), how many the active
    configuration actually settled without a ballot round.  Comparing
    this section across ACCORD_INFER_FULL=0/1 snapshots of the same seed
    IS the pricing comparison (tests/test_infer.py)."""
    kinds = _counter_by_label(metrics, "accord_infer_total", "kind")
    quorum = kinds.get("quorum_evidence", 0)
    no_round = kinds.get("no_round_commits", 0)
    kinds["no_round_ratio"] = (round(no_round / quorum, 4)
                               if quorum else None)
    return kinds


# ------------------------------------------------------------- SLO rows ----

SLO_QUANTILES = ((0.50, "p50_us"), (0.99, "p99_us"), (0.999, "p999_us"))


def exact_quantiles_us(samples) -> dict:
    """Exact nearest-rank quantiles from raw microsecond samples.  The
    quantile path every SLO lane uses (quantile_source=exact-sample): the
    log2-bucket histograms stay for always-on monitoring, but a tail GATE
    needs sample-exact numbers (tests/test_obs.py pins the bucket path's
    error bound at [1x, 2x) — far above a 15% threshold)."""
    s = sorted(samples)
    n = len(s)
    if n == 0:
        return {"count": 0}
    out = {"count": n,
           "mean_us": round(sum(s) / n, 1),
           "max_us": int(s[-1])}
    for q, name in SLO_QUANTILES:
        rank = max(1, min(n, int(q * n + 0.9999999)))
        out[name] = int(s[rank - 1])
    return out


def slo_report(open_samples_us, closed_samples_us,
               phase_samples_us: Dict[str, list],
               counts: Dict[str, int], offered_per_s: float,
               duration_s: float, schedule: Optional[dict] = None,
               summary: Optional[dict] = None) -> dict:
    """The SLO row an open-loop lane records into BENCH_HISTORY (and
    `bench.py --guard` gates): open-loop latency is measured from each
    op's INTENDED start, so coordinator stalls are charged to the tail
    instead of silently pausing the load (coordinated omission);
    closed-loop is the same acked ops measured from actual submit — the
    two diverge exactly by the omitted time.

    phase_samples_us: per-phase exact samples from joining the intended-
    start ledger against the PR-2 trace spans (obs/spans.phase_deltas),
    plus the synthetic "admission" phase (begin - intended: scheduling +
    pipeline queueing + any stall ahead of the coordinator)."""
    submitted = sum(counts.get(k, 0)
                    for k in ("acked", "shed", "failed", "pending"))
    acked = counts.get("acked", 0)
    report = {
        "quantile_source": "exact-sample",
        "schedule": schedule or {},
        "offered_per_s": round(offered_per_s, 1),
        "achieved_per_s": (round(acked / duration_s, 1)
                           if duration_s > 0 else 0.0),
        "duration_s": round(duration_s, 3),
        "counts": dict(counts),
        "shed_rate": (round(counts.get("shed", 0) / submitted, 4)
                      if submitted else 0.0),
        "open_loop": exact_quantiles_us(open_samples_us),
        "closed_loop": exact_quantiles_us(closed_samples_us),
        "phases": {ph: exact_quantiles_us(samples)
                   for ph, samples in sorted(phase_samples_us.items())
                   if samples},
    }
    if summary is not None:
        report["fast_path_ratio"] = summary.get("fast_path_ratio")
        report["recoveries"] = summary.get("recoveries", 0)
    return report


# ------------------------------------------------------------ CPU rows ----

# how many verbs the "top verbs by total CPU" table keeps
_CPU_TOP_N = 10


def cpu_section(cpu: Optional[dict]) -> dict:
    """The protocol-CPU waterfall summary (tentpole of ISSUE 9): per-verb
    exact-sample p50/p99 of the per-dispatch total plus per-(verb, stage)
    quantiles, and the top-verbs-by-total-CPU table.  `cpu` is a (possibly
    cross-node pooled) CpuProfiler export; estimated totals scale each
    verb's sampled mean by its FULL dispatch census, so 1-in-N sampling
    does not skew the ranking.  Exact-sample quantiles only — the log2
    buckets stay for always-on monitoring, but a `--guard` gate needs
    sample-exact numbers (the PR-3 precedent)."""
    section = {"quantile_source": "exact-sample", "sampled": 0,
               "dispatches": 0, "verbs": {}, "top": []}
    if not cpu:
        return section
    section["sampled"] = cpu.get("sampled", 0)
    dispatches = cpu.get("dispatches", {})
    section["dispatches"] = sum(dispatches.values())
    verbs = {}
    grand_ms = 0.0
    for verb, samples in sorted(cpu.get("totals", {}).items()):
        if not samples:
            continue
        q = exact_quantiles_us(samples)
        n_disp = dispatches.get(verb, q["count"])
        est_ms = round(q["mean_us"] * n_disp / 1e3, 2)
        grand_ms += est_ms
        stages = {st: exact_quantiles_us(ss) for st, ss in
                  sorted(cpu.get("stages", {}).get(verb, {}).items()) if ss}
        verbs[verb] = dict(q, dispatches=n_disp, est_total_ms=est_ms,
                           stages=stages)
    section["verbs"] = verbs
    top = sorted(((v, d["est_total_ms"]) for v, d in verbs.items()),
                 key=lambda x: -x[1])[:_CPU_TOP_N]
    section["top"] = [[v, ms, round(ms / grand_ms, 4) if grand_ms else 0.0]
                      for v, ms in top]
    return section


def loop_section(metrics: dict) -> dict:
    """Event-loop health (obs/cpuprof.LoopHealth, always-on in the
    wall-clock hosts): timer lag, tick busy time, dispatch-burst shape,
    high-water backlog, and alarm counts."""
    return {
        "lag_us": _hist_report(_merged_hist(metrics, "accord_loop_lag_us")),
        "tick_us": _hist_report(_merged_hist(metrics,
                                             "accord_loop_tick_us")),
        "burst_msgs": _hist_report(_merged_hist(metrics,
                                                "accord_loop_burst_msgs")),
        "depth_max": _gauge_max(metrics, "accord_loop_depth_max"),
        "lag_alarms": _counter_total(metrics,
                                     "accord_loop_lag_alarms_total"),
        "saturation_alarms": _counter_total(
            metrics, "accord_loop_queue_saturation_total"),
    }


def _qos_section(metrics: dict) -> dict:
    """QoS admission-tier report (qos/): cluster totals, per-priority
    split (the fairness surface: shed{high} must stay 0 while best_effort
    is being admitted), and per-tenant admitted-goodput/shed-rate."""
    submitted = _counter_total(metrics, "accord_qos_submitted_total")
    inner = _counter_total(metrics, "accord_qos_inner_shed_total")
    if not submitted and not inner:
        return {"submitted": 0}
    by_tenant_sub = _counter_by_label(metrics, "accord_qos_submitted_total",
                                      "tenant")
    by_tenant_adm = _counter_by_label(metrics, "accord_qos_admitted_total",
                                      "tenant")
    tenants = {}
    for tenant, sub in sorted(by_tenant_sub.items()):
        adm = by_tenant_adm.get(tenant, 0)
        tenants[tenant] = {
            "submitted": sub, "admitted": adm,
            "shed_rate": round(1.0 - adm / sub, 4) if sub else 0.0}
    return {
        "submitted": submitted,
        "admitted": _counter_total(metrics, "accord_qos_admitted_total"),
        "shed": _counter_total(metrics, "accord_qos_shed_total"),
        "throttled": _counter_total(metrics, "accord_qos_throttled_total"),
        "inner_shed": inner,
        "admitted_by_priority": _counter_by_label(
            metrics, "accord_qos_admitted_total", "priority"),
        "shed_by_priority": _counter_by_label(
            metrics, "accord_qos_shed_total", "priority"),
        "throttled_by_priority": _counter_by_label(
            metrics, "accord_qos_throttled_total", "priority"),
        "tenants": tenants,
        "pressure_milli_max": _gauge_max(metrics,
                                         "accord_qos_pressure_milli"),
    }


def _wan_section(metrics: dict) -> dict:
    """Geo-placement attribution (topology/geo.GeoProfile): fast/slow
    split per coordinator DC and per electorate membership (the dc=/elect=
    labels NodeObs adds when a profile is installed), plus messages/txn
    per link class — the slo-wan lane's recorded surface, and the
    msgs_per_txn census doubles as the yardstick for the structural
    message-reduction roadmap item.  Empty dict when the run is geo-free
    (no dc-labeled counters, no link census)."""
    per_dc: Dict[str, dict] = {}
    per_elect: Dict[str, dict] = {}
    for lk, v in metrics.get("counters", {}).get(
            "accord_path_total", {}).items():
        labels = parse_labels(lk)
        dc = labels.get("dc")
        if not dc:
            continue
        path = labels.get("path", "")
        d = per_dc.setdefault(dc, {"fast": 0, "slow": 0})
        d[path] = d.get(path, 0) + v
        elect = labels.get("elect")
        if elect:
            e = per_elect.setdefault(elect, {"fast": 0, "slow": 0})
            e[path] = e.get(path, 0) + v
    for d in list(per_dc.values()) + list(per_elect.values()):
        done = d.get("fast", 0) + d.get("slow", 0)
        d["fast_path_ratio"] = (round(d.get("fast", 0) / done, 4)
                                if done else None)
    link_msgs = _counter_by_label(metrics, "accord_link_msgs_total", "cls")
    link_bytes = _counter_by_label(metrics, "accord_link_bytes_total", "cls")
    if not per_dc and not link_msgs and not link_bytes:
        return {}
    committed = sum(d.get("fast", 0) + d.get("slow", 0)
                    for d in per_dc.values())
    return {
        "dcs": {dc: per_dc[dc] for dc in sorted(per_dc)},
        "by_elect": {e: per_elect[e] for e in sorted(per_elect)},
        "link_msgs": link_msgs,
        "link_bytes": link_bytes,
        "msgs_per_txn": ({cls: round(n / committed, 2)
                          for cls, n in sorted(link_msgs.items())}
                         if committed else {}),
        "wan_crossings_per_txn": (round(link_msgs.get("wan", 0)
                                        / committed, 2)
                                  if committed else None),
        "wan_bytes_per_txn": (round(link_bytes.get("wan", 0) / committed, 1)
                              if committed and link_bytes else None),
    }


def summarize(metrics: dict, cpu: Optional[dict] = None) -> dict:
    paths = _counter_by_label(metrics, "accord_path_total", "path")
    fast = paths.get("fast", 0)
    slow = paths.get("slow", 0)
    outcomes = _counter_by_label(metrics,
                                 "accord_coordinate_outcomes_total",
                                 "outcome")
    started = _counter_by_label(metrics, "accord_coordinate_started_total",
                                "path")
    phase_hists = _hists_by_label(metrics, "accord_phase_latency_us",
                                  "phase")
    frames = _counter_total(metrics, "accord_tcp_frames_total")
    msgs = _counter_total(metrics, "accord_tcp_msgs_total")
    return {
        "fast_path": fast,
        "slow_path": slow,
        "fast_path_ratio": (round(fast / (fast + slow), 4)
                            if fast + slow else None),
        "started": started,
        "outcomes": outcomes,
        "recoveries": started.get("recovery", 0),
        "phase_latency_us": {ph: _hist_report(h)
                             for ph, h in sorted(phase_hists.items())},
        "txn_latency_us": {p: _hist_report(h) for p, h in sorted(
            _hists_by_label(metrics, "accord_txn_latency_us",
                            "path").items())},
        "device": {
            "flush_windows": _counter_total(
                metrics, "accord_device_flush_windows_total"),
            "cross_txn_windows": _counter_total(
                metrics, "accord_device_cross_txn_windows_total"),
            "window_txn_max": _gauge_max(metrics,
                                         "accord_device_window_txn_max"),
            "hits": _counter_total(metrics, "accord_device_hits_total"),
            "misses": _counter_total(metrics, "accord_device_misses_total"),
            "compile_shapes": _counter_total(
                metrics, "accord_device_compile_shapes_total"),
        },
        "pipeline": {
            "submitted": _counter_total(metrics,
                                        "accord_pipeline_submitted_total"),
            "shed": _counter_total(metrics, "accord_pipeline_shed_total"),
            "batches": _counter_total(metrics,
                                      "accord_pipeline_batches_total"),
            "dispatched": _counter_total(metrics,
                                         "accord_pipeline_dispatched_total"),
            "batch_size_max": _gauge_max(metrics,
                                         "accord_pipeline_batch_size_max"),
            # admission->dispatch wait (per-txn mean per batch): the
            # pipeline's contribution to the SLO lanes' "admission" phase
            "queue_wait_us": _hist_report(_merged_hist(
                metrics, "accord_pipeline_queue_wait_us")),
        },
        "qos": _qos_section(metrics),
        "transport": {
            # per-peer frame coalescing at the TCP egress buffer
            # (host/tcp.py): how many protocol messages each wire frame
            # amortised, and the frame-size shape — the coalescing-ratio
            # surface the tcp/multicore bench rows record
            "frames": frames,
            "msgs": msgs,
            "coalesce_ratio": (round(msgs / frames, 3) if frames else None),
            "frame_bytes": _hist_report(_merged_hist(
                metrics, "accord_tcp_frame_bytes")),
            "frame_msgs": _hist_report(_merged_hist(
                metrics, "accord_tcp_frame_msgs")),
            "shed": _counter_total(metrics, "accord_tcp_peer_shed_total"),
            "send_drops": _counter_total(
                metrics, "accord_tcp_peer_send_drops_total"),
            "retries": _counter_total(metrics,
                                      "accord_tcp_peer_retries_total"),
            # per-link-class census under a geo profile (topology/geo.py):
            # msgs counted at the sim delivery / tcp flush, bytes+frames
            # at the tcp flush with real frame sizes — WAN bytes/txn is
            # the first-class per-txn number in the "wan" section
            "link_msgs": _counter_by_label(metrics,
                                           "accord_link_msgs_total", "cls"),
            "link_bytes": _counter_by_label(metrics,
                                            "accord_link_bytes_total",
                                            "cls"),
            "link_frames": _counter_by_label(metrics,
                                             "accord_link_frames_total",
                                             "cls"),
        },
        "wan": _wan_section(metrics),
        "cpu": cpu_section(cpu),
        "loop": loop_section(metrics),
        "infer": _infer_section(metrics),
        "audit": {
            # replica-state auditor (local/audit.py): digest-round
            # outcomes, confirmed divergences by kind, drill-down volume
            "rounds": _counter_by_label(metrics,
                                        "accord_audit_rounds_total",
                                        "outcome"),
            "mismatches": _counter_total(metrics,
                                         "accord_audit_mismatch_total"),
            "divergences": _counter_by_label(
                metrics, "accord_audit_divergence_total", "kind"),
            "drill_requests": _counter_total(metrics,
                                             "accord_audit_drill_total"),
            "entries_checked": _counter_total(
                metrics, "accord_audit_entries_total"),
        },
        "census": {
            # state-lifecycle census (local/audit.py): cluster-wide
            # resident totals by class, cleanup-leak alarms, and the
            # worst per-node cleanup lag per watermark kind
            "sweeps": _counter_total(metrics, "accord_census_sweeps_total"),
            "resident": _gauge_total(metrics,
                                     "accord_census_resident_total"),
            "by_class": _gauge_sum_by_label(metrics,
                                            "accord_census_resident",
                                            "cls"),
            "quiescent_uncleaned": _gauge_total(
                metrics, "accord_census_quiescent_uncleaned"),
            "resident_bytes_est": _gauge_total(
                metrics, "accord_census_resident_bytes_est"),
            "leak_alarms": _counter_total(
                metrics, "accord_census_leak_alarms_total"),
            "watermark_lag_us": _gauge_max_by_label(
                metrics, "accord_watermark_lag_us", "kind"),
            # worker runtime only: per-shard resident/spilled/pager rows
            # (shard-labeled series; {} when every node runs in-loop)
            "per_shard": _per_shard_census(metrics),
        },
        "journal": {
            "appends": _counter_total(metrics,
                                      "accord_journal_appends_total"),
            "append_bytes": _counter_total(
                metrics, "accord_journal_append_bytes_total"),
            "fsyncs": _counter_total(metrics, "accord_journal_fsync_total"),
            "rotations": _counter_total(metrics,
                                        "accord_journal_rotations_total"),
            "snapshots": _counter_total(metrics,
                                        "accord_journal_snapshots_total"),
            "group_commit_batch": _hist_report(_merged_hist(
                metrics, "accord_journal_group_commit_batch")),
            "replay_records": _counter_total(
                metrics, "accord_journal_replay_records_total"),
            "replay_us": _hist_report(_merged_hist(
                metrics, "accord_journal_replay_duration_us")),
        },
    }
