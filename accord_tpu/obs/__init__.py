"""Unified observability: metrics registry + per-transaction trace spans.

The reference observes through slf4j logging plus ad-hoc burn counters;
this package replaces the port's scattered stat dicts (`Node.infer_stats`,
the pipeline's per-stage counters, the device store's flush-window tallies)
with one process-local layer:

  * `registry` — counters, gauges and log-bucketed histograms with labels,
    snapshot()-able to plain JSON and renderable as Prometheus text;
  * `spans` — lightweight per-transaction trace spans keyed by the txn id,
    following a transaction through PreAccept -> Accept -> Commit ->
    Execute/Apply and tagging fast-path / slow-path / recovery.  The trace
    id rides INSIDE the existing wire envelopes (`messages/base.py` sets an
    optional `trace_id` attribute that `host/wire.py`'s structural codec
    round-trips for free), so a span stitches across replicas in sim and
    over TCP alike;
  * `flight` — the always-on bounded flight recorder: one fixed-size ring
    per node of status transitions / message tx-rx / escalations /
    admission decisions, stitched across replicas into the failure
    artifact when a burn or verify check goes red;
  * `profiler` — kernel-level fenced wall timers, a jit-retrace ledger,
    and the flush-window waterfall (sampled via `ACCORD_PROFILE=N`, off
    by default; fences are injected by the device layer so this package
    stays jax-free);
  * `cpuprof` — the protocol-tier CPU attribution profiler (sampled
    per-dispatch decode/apply/cfk/reply-encode waterfall, labeled by
    verb, `ACCORD_CPU_PROFILE=N`, off by default) and the always-on
    event-loop health gauges (`LoopHealth`) the wall-clock hosts wire;
  * `node_obs.NodeObs` — the per-Node facade the engine instruments
    against (one registry + one span store + one flight ring per node);
  * `httpd` — the Prometheus-style text endpoint (`ACCORD_METRICS_PORT`)
    plus the live `/flight?txn=` forensics view;
  * `report` — cross-node snapshot merging and the human summary the
    bench and burn harnesses record.

HARD CONSTRAINT: nothing in this package may import jax (directly or
transitively) — the registry lives on the host path only, never inside
jitted code.  tests/test_obs_budget.py enforces this plus a <5% overhead
bound on the scalar hot loop.
"""

from accord_tpu.obs.cpuprof import (CpuProfiler, LoopHealth,
                                    cpu_profiler_from_env,
                                    merge_cpu_exports)
from accord_tpu.obs.flight import (EVENT_KINDS, FlightRecorder,
                                   first_divergence, format_timeline,
                                   stitch_flight, trace_ids_in_text)
from accord_tpu.obs.node_obs import NodeObs
from accord_tpu.obs.profiler import Profiler, profiler_from_env
from accord_tpu.obs.registry import (Counter, Gauge, Histogram, Registry,
                                     parse_labels)
from accord_tpu.obs.spans import (SpanStore, find_trace_ids, stitch,
                                  trace_key)
from accord_tpu.obs.views import CounterDict, MetricView, bind_metric_views

__all__ = [
    "Counter", "CounterDict", "CpuProfiler", "EVENT_KINDS",
    "FlightRecorder", "Gauge", "Histogram", "LoopHealth", "MetricView",
    "NodeObs", "Profiler", "Registry", "SpanStore", "bind_metric_views",
    "cpu_profiler_from_env", "find_trace_ids", "first_divergence",
    "format_timeline", "merge_cpu_exports", "parse_labels",
    "profiler_from_env", "stitch", "stitch_flight", "trace_ids_in_text",
    "trace_key",
]
