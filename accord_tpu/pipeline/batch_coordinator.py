"""Batch coordinator: run one micro-batch's coordinations as one window.

Takes the admission queue's closed batches (pipeline/ingest.py) and starts
every transaction's coordination inside ONE sink coalescing window, so the
whole batch's first-round fan-out leaves the process as one wire envelope
per replica (messages/multi.MultiPreAccept) instead of batch_size separate
frames — and the self-addressed slice of that fan-out arrives at the local
command stores as one dispatch, which the batched device tier resolves as
one fused probe window (impl/device_store.py hold_flush/release_flush).

The coordinations themselves are completely unchanged — each transaction
still runs coordinate/transaction.py's fast/slow-path state machine with
its own tracker, callbacks and timeouts; only the transport framing and the
device dispatch are amortized across the batch.  Coordinations are started
in admission order, so conflicting transactions admitted to the same batch
reach every replica in that order and witness each other accordingly
(batching coalesces delivery; it never reorders within a batch).
"""

from __future__ import annotations

from typing import List, Optional

from accord_tpu.pipeline.backpressure import PipelineStats
from accord_tpu.pipeline.ingest import Admitted


class BatchCoordinator:
    """Starts a batch of coordinations under one sink coalescing window."""

    def __init__(self, node, stats: Optional[PipelineStats] = None):
        self.node = node
        self.stats = stats if stats is not None else PipelineStats()

    def now_us(self) -> int:
        return int(self.node.scheduler.now_s() * 1e6)

    def coordinate_batch(self, items: List[Admitted]) -> None:
        sink = self.node.sink
        coalesce = hasattr(sink, "batch_begin")
        if coalesce:
            sink.batch_begin()
        try:
            for item in items:
                self._start_one(item)
        finally:
            if coalesce:
                # one MultiPreAccept per destination carries everything the
                # batch's coordinations sent during start (PreAccepts; plus
                # any Commits/Applies a same-tick reply burst produced when
                # the host loop holds a window open across dispatches)
                sink.batch_flush()

    def _start_one(self, item: Admitted) -> None:
        dispatched_us = self.now_us()

        def done(value, failure):
            self.stats.record_done(failure is None,
                                   self.now_us() - dispatched_us)
            if failure is not None:
                item.result.try_failure(failure)
            else:
                item.result.try_success(value)

        try:
            self.node.coordinate(item.txn).add_callback(done)
        except BaseException as e:  # noqa: BLE001 — one malformed txn must
            done(None, e)          # not poison the rest of the batch
