"""Backpressure and admission control for the ingest pipeline.

The continuous-batching ingest layer (pipeline/ingest.py) must never let an
unbounded client backlog grow inside the coordinator process: a bounded
admission queue sheds load the moment depth exceeds the configured bound,
replying with a typed `Rejected` failure the client can distinguish from a
protocol failure (retry-after semantics, like an HTTP 503, rather than a
Timeout that might mean the txn committed).  The same module carries the
pipeline's per-stage counters — queue depth, batch size, queue-wait and
service latency — surfaced through `utils.tracing.Trace` events and a
`snapshot()` dict for harness assertions and the bench.
"""

from __future__ import annotations

from typing import Dict, Optional

from accord_tpu.coordinate.errors import CoordinationFailed
from accord_tpu.obs.views import MetricView, bind_metric_views


class Rejected(CoordinationFailed):
    """Load-shed reply: the admission queue was full, the transaction was
    NEVER submitted to the protocol (safe to retry after backoff — unlike a
    Timeout, no partial coordination state exists anywhere)."""


class AdmissionController:
    """Bounded-queue admission decision (the shed policy, split from the
    queue mechanics so hosts can tune or replace it)."""

    def __init__(self, max_queue: int = 256):
        self.max_queue = max_queue

    def admit(self, depth: int) -> bool:
        return depth < self.max_queue


class PipelineStats:
    """Per-stage counters for the ingest pipeline.  Mutated only from the
    owning node's loop thread (the pipeline is single-threaded by
    construction, like the command stores).

    Registry-backed (obs/): the attribute names are read-through views over
    the node's metrics registry, so existing harness reads (`stats.shed`,
    `stats.batches`) and the snapshot() dict keep working while the same
    numbers flow to the Prometheus endpoint and bench/burn snapshots."""

    submitted = MetricView("accord_pipeline_submitted_total")
    admitted = MetricView("accord_pipeline_admitted_total")
    shed = MetricView("accord_pipeline_shed_total")
    batches = MetricView("accord_pipeline_batches_total")
    dispatched = MetricView("accord_pipeline_dispatched_total")
    completed = MetricView("accord_pipeline_completed_total")
    failed = MetricView("accord_pipeline_failed_total")
    deadline_closes = MetricView("accord_pipeline_deadline_closes_total")
    size_closes = MetricView("accord_pipeline_size_closes_total")
    depth_max = MetricView("accord_pipeline_depth_max", kind="gauge")
    batch_size_max = MetricView("accord_pipeline_batch_size_max",
                                kind="gauge")

    def __init__(self, registry=None, **labels):
        if registry is None:  # standalone (tests, bare queues)
            from accord_tpu.obs.registry import Registry
            registry = Registry()
        bind_metric_views(self, registry, **labels)
        self._g_depth = registry.gauge("accord_pipeline_queue_depth",
                                      **labels)
        self._h_batch_size = registry.histogram(
            "accord_pipeline_batch_size", **labels)
        # per-txn queue wait (admission -> dispatch): the pipeline's slice
        # of the open-loop SLO lanes' "admission" phase — surfaced as
        # pipeline.queue_wait_us in obs/report.summarize (burn --metrics,
        # bench rows)
        self._h_queue_wait = registry.histogram(
            "accord_pipeline_queue_wait_us", **labels)
        self._queue_wait_us_sum = 0   # admission -> dispatch
        self._service_us_sum = 0      # dispatch -> settle
        self._latency_n = 0

    # ------------------------------------------------------------- record --
    def record_admit(self, depth: int) -> None:
        self.submitted += 1
        self.admitted += 1
        self.depth_max = max(self.depth_max, depth)
        self._g_depth.value = depth

    def record_shed(self) -> None:
        self.submitted += 1
        self.shed += 1

    def record_batch(self, size: int, by_deadline: bool,
                     queue_wait_us_total: int) -> None:
        self.batches += 1
        self.dispatched += size
        self.batch_size_max = max(self.batch_size_max, size)
        self._h_batch_size.observe(size)
        self._h_queue_wait.observe(queue_wait_us_total // max(1, size))
        if by_deadline:
            self.deadline_closes += 1
        else:
            self.size_closes += 1
        self._queue_wait_us_sum += queue_wait_us_total

    def record_done(self, ok: bool, service_us: int) -> None:
        if ok:
            self.completed += 1
        else:
            self.failed += 1
        self._service_us_sum += max(0, service_us)
        self._latency_n += 1

    # ------------------------------------------------------------ inspect --
    @property
    def batch_size_mean(self) -> float:
        return self.dispatched / self.batches if self.batches else 0.0

    @property
    def queue_wait_us_mean(self) -> float:
        return (self._queue_wait_us_sum / self.dispatched
                if self.dispatched else 0.0)

    @property
    def service_us_mean(self) -> float:
        return (self._service_us_sum / self._latency_n
                if self._latency_n else 0.0)

    def snapshot(self) -> Dict[str, float]:
        return {
            "submitted": self.submitted,
            "admitted": self.admitted,
            "shed": self.shed,
            "batches": self.batches,
            "dispatched": self.dispatched,
            "completed": self.completed,
            "failed": self.failed,
            "deadline_closes": self.deadline_closes,
            "size_closes": self.size_closes,
            "depth_max": self.depth_max,
            "batch_size_max": self.batch_size_max,
            "batch_size_mean": round(self.batch_size_mean, 2),
            "queue_wait_us_mean": round(self.queue_wait_us_mean, 1),
            "service_us_mean": round(self.service_us_mean, 1),
        }

    def __repr__(self):
        return (f"PipelineStats(batches={self.batches} "
                f"dispatched={self.dispatched} shed={self.shed} "
                f"batch_max={self.batch_size_max})")


class SendBackoff:
    """Exponential backoff schedule for transport send retries (host/tcp.py
    peer writers): attempt -> seconds to wait before retrying, capped."""

    def __init__(self, base_s: float = 0.05, cap_s: float = 1.0,
                 max_attempts: int = 4):
        self.base_s = base_s
        self.cap_s = cap_s
        self.max_attempts = max_attempts

    def delay_s(self, attempt: int) -> Optional[float]:
        """Delay before retry `attempt` (1-based), or None when the frame
        should be dropped instead (RPC timeouts + the progress log heal,
        exactly like a lossy link)."""
        if attempt >= self.max_attempts:
            return None
        return min(self.cap_s, self.base_s * (2 ** (attempt - 1)))
