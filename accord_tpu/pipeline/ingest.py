"""Admission queue: coalesce client transactions into micro-batches.

The continuous-batching admission layer of the ingest pipeline: incoming
client transactions enqueue here and are released to the batch coordinator
as deadline-bounded micro-batches — the same amortization discipline an
inference server applies to model steps (admit continuously, close a batch
when it is full OR its deadline expires, never park a lone request longer
than `max_wait_us`).

A batch closes when either
  * depth reaches `max_batch` (closed immediately, no timer wait), or
  * the oldest admitted txn has waited its effective deadline.

The deadline is ADAPTIVE to queue depth: a deepening queue is evidence of
arrival pressure, so the effective wait shrinks linearly toward
`max_wait_us / 8` as depth approaches `max_batch` — light traffic pays the
full window (maximum coalescing per dispatch), heavy traffic closes early
(the batch will fill again immediately; waiting only adds latency).

Single-threaded by construction: owned by the node's loop thread (TCP/
Maelstrom hosts) or the virtual-time queue (sim), like the command stores.
"""

from __future__ import annotations

import os
from collections import deque
from typing import Callable, Deque, Optional

from accord_tpu.pipeline.backpressure import (AdmissionController,
                                              PipelineStats, Rejected)
from accord_tpu.utils.async_chains import AsyncResult


class PipelineConfig:
    """Tunables for the ingest pipeline (env-overridable on hosts)."""

    def __init__(self, max_batch: int = 8, max_wait_us: int = 2000,
                 max_queue: int = 256, adaptive: bool = True):
        self.max_batch = max(1, max_batch)
        self.max_wait_us = max(0, max_wait_us)
        self.max_queue = max(1, max_queue)
        self.adaptive = adaptive

    @classmethod
    def from_env(cls) -> "PipelineConfig":
        def _int(name: str, default: int) -> int:
            try:
                return int(os.environ.get(name, default))
            except ValueError:
                return default

        return cls(
            max_batch=_int("ACCORD_PIPELINE_MAX_BATCH", 8),
            max_wait_us=_int("ACCORD_PIPELINE_MAX_WAIT_US", 2000),
            max_queue=_int("ACCORD_PIPELINE_MAX_QUEUE", 256),
            adaptive=os.environ.get("ACCORD_PIPELINE_ADAPTIVE", "1") != "0")

    def __repr__(self):
        return (f"PipelineConfig(max_batch={self.max_batch} "
                f"max_wait_us={self.max_wait_us} max_queue={self.max_queue} "
                f"adaptive={self.adaptive})")


class Admitted:
    """One admitted transaction: the txn, its client-facing result, and the
    admission timestamp (for queue-wait accounting)."""

    __slots__ = ("txn", "result", "admitted_us")

    def __init__(self, txn, result: AsyncResult, admitted_us: int):
        self.txn = txn
        self.result = result
        self.admitted_us = admitted_us


class IngestQueue:
    """Deadline-bounded micro-batching admission queue.

    `dispatch(items)` is invoked with each closed batch (a list of Admitted,
    in admission order — the batch coordinator starts coordinations in this
    order, so conflicting txns admitted together witness each other in
    admission order on every replica that processes the batch envelope).
    """

    def __init__(self, scheduler, dispatch: Callable, config: PipelineConfig,
                 stats: Optional[PipelineStats] = None,
                 trace=None, flight=None, qos=None):
        from accord_tpu.utils.tracing import NO_TRACE
        self.scheduler = scheduler
        self.dispatch = dispatch
        self.config = config
        self.stats = stats if stats is not None else PipelineStats()
        self.admission = AdmissionController(config.max_queue)
        self.trace = trace if trace is not None else NO_TRACE
        # node's flight recorder (obs/flight.py); admission decisions land
        # on the forensics ring so a shedding node's timeline explains a
        # client's Rejected.  None on bare queues (unit tests).
        self.flight = flight
        # the host's QoS tier (qos/admission.py), when enabled: this queue
        # is its LAST-RESORT inner ring, so its sheds are tallied there too
        # and the exported accounting covers every rejection path
        self.qos = qos
        self._q: Deque[Admitted] = deque()
        self._timer = None
        self._deadline_us: Optional[int] = None

    # ------------------------------------------------------------- client --
    def now_us(self) -> int:
        return int(self.scheduler.now_s() * 1e6)

    def submit(self, txn) -> AsyncResult:
        """Admit (or shed) one client transaction; returns its result.

        Shedding settles the result immediately with `Rejected` — the txn
        was never coordinated, so the client may retry after backoff."""
        result: AsyncResult = AsyncResult()
        if not self.admission.admit(len(self._q)):
            self.stats.record_shed()
            if self.trace.enabled:
                self.trace.event("pipeline_shed", depth=len(self._q))
            if self.flight is not None:
                self.flight.record("pipeline_shed", None, (len(self._q),))
            if self.qos is not None:
                self.qos.note_inner_shed(len(self._q))
            result.try_failure(Rejected(
                f"ingest queue full ({self.config.max_queue}); retry later"))
            return result
        self._q.append(Admitted(txn, result, self.now_us()))
        self.stats.record_admit(len(self._q))
        if self.flight is not None:
            self.flight.record("pipeline_admit", None, (len(self._q),))
        if len(self._q) >= self.config.max_batch:
            self._close(by_deadline=False)
        else:
            self._arm()
        return result

    # -------------------------------------------------------------- close --
    def effective_wait_us(self, depth: int) -> int:
        """Deadline for the batch at the current depth: the full window when
        the queue is shallow, shrinking linearly to max_wait_us/8 as depth
        approaches max_batch (arrival pressure => close sooner)."""
        cfg = self.config
        if not cfg.adaptive or cfg.max_batch <= 1:
            return cfg.max_wait_us
        frac = 1.0 - (depth - 1) / cfg.max_batch
        return max(cfg.max_wait_us // 8, int(cfg.max_wait_us * frac))

    def _arm(self) -> None:
        """(Re)arm the deadline timer at the current depth's effective wait,
        anchored to the OLDEST admitted txn — adaptivity can only pull the
        deadline earlier, never push an already-waiting txn later."""
        if not self._q:
            return
        oldest = self._q[0].admitted_us
        deadline = oldest + self.effective_wait_us(len(self._q))
        if self._timer is not None:
            if self._deadline_us is not None and deadline >= self._deadline_us:
                return  # existing timer already fires at/before this
            self._timer.cancel()
        self._deadline_us = deadline
        delay_s = max(0.0, (deadline - self.now_us()) / 1e6)
        self._timer = self.scheduler.once(delay_s, self._on_deadline)

    def _on_deadline(self) -> None:
        self._timer = None
        self._deadline_us = None
        if self._q:
            self._close(by_deadline=True)

    def _close(self, by_deadline: bool) -> None:
        """Pop up to max_batch items and dispatch them; re-arm for any
        remainder (repeatedly, so a deep backlog drains as full batches)."""
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
            self._deadline_us = None
        while self._q:
            n = min(len(self._q), self.config.max_batch)
            if n < self.config.max_batch and not by_deadline:
                break  # partial batch: wait for its deadline
            batch = [self._q.popleft() for _ in range(n)]
            now = self.now_us()
            waited = sum(now - a.admitted_us for a in batch)
            self.stats.record_batch(n, by_deadline, waited)
            if self.trace.enabled:
                self.trace.event("pipeline_batch", size=n,
                                 depth=len(self._q),
                                 by_deadline=by_deadline,
                                 waited_us=waited)
            if self.flight is not None:
                self.flight.record("pipeline_batch", None,
                                   (n, by_deadline))
            self.dispatch(batch)
            by_deadline = False  # only the first pop is deadline-credited
        # the admission-queue depth gauge tracks drains as well as admits
        self.stats._g_depth.value = len(self._q)
        if self._q:
            self._arm()

    @property
    def depth(self) -> int:
        return len(self._q)
