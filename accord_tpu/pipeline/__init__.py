"""Continuous micro-batching ingest pipeline (coordinator side).

The device tier amortizes kernel dispatch across a flush window; this
package amortizes the COORDINATOR's costs the same way, at admission:

  * `ingest.IngestQueue` — coalesces incoming client transactions into
    deadline-bounded micro-batches (`max_batch` / `max_wait_us`, adaptive
    to queue depth);
  * `batch_coordinator.BatchCoordinator` — starts each batch's
    coordinations under one sink coalescing window, so the batch's fan-out
    leaves as one `MultiPreAccept` wire envelope per replica and its
    self-addressed slice resolves as one fused device probe window;
  * `backpressure` — bounded admission with a typed `Rejected` shed reply
    and per-stage depth/latency/batch-size counters.

Hosts enable it with `ACCORD_PIPELINE=1` (host/tcp.py, host/maelstrom.py);
the deterministic burn drives it via `SimCluster(pipeline=True)` /
`python -m accord_tpu.sim.burn --pipeline`.
"""

from __future__ import annotations

import os
from typing import Optional

from accord_tpu.pipeline.backpressure import (PipelineStats, Rejected,
                                              SendBackoff)
from accord_tpu.pipeline.batch_coordinator import BatchCoordinator
from accord_tpu.pipeline.ingest import IngestQueue, PipelineConfig


def pipeline_enabled() -> bool:
    """The host-side gate: ACCORD_PIPELINE=1 (default off)."""
    return os.environ.get("ACCORD_PIPELINE", "") == "1"


class Pipeline:
    """Facade wiring IngestQueue -> BatchCoordinator for one node."""

    def __init__(self, node, scheduler=None,
                 config: Optional[PipelineConfig] = None, qos=None):
        self.node = node
        self.config = config if config is not None else PipelineConfig()
        # per-stage counters live in the node's metrics registry (obs/)
        registry = getattr(getattr(node, "obs", None), "registry", None)
        self.stats = PipelineStats(registry=registry)
        self.batcher = BatchCoordinator(node, self.stats)
        self.ingest = IngestQueue(
            scheduler if scheduler is not None else node.scheduler,
            self.batcher.coordinate_batch, self.config, self.stats,
            trace=node.trace,
            flight=getattr(getattr(node, "obs", None), "flight", None),
            qos=qos)

    def submit(self, txn):
        """Admit one client transaction; returns its AsyncResult (settled
        with `Rejected` immediately when the admission queue sheds it)."""
        return self.ingest.submit(txn)
