"""External host tier: wire codec, real-time runtime, Maelstrom protocol.

Reference: accord-maelstrom (Main.java:145 stdin JSON-RPC node, Json.java
wire codec, Cluster.java in-process runner) — the black-box face of the
framework: real processes, a real serialization boundary, driven by an
external workload and checked by the same strict-serializability verifier
the burn test uses.
"""

from accord_tpu.host.wire import decode_message, encode_message
