"""Real-time single-threaded runtime for external hosts.

The simulator drives nodes from a virtual-time PendingQueue; a host process
drives the same Node from wall time: a monotonic timer heap polled by the
host's select loop. Single-threaded by construction, so the command stores
keep the simulator's logically-single-threaded execution model without
locks (the reference pins stores to executors for the same guarantee).
"""

from __future__ import annotations

import heapq
import itertools
import sys
import time
from typing import Callable, List, Optional, Tuple

from accord_tpu.api.spi import Scheduler


class TimerHandle:
    __slots__ = ("cancelled",)

    def __init__(self):
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True


class RealTimeScheduler(Scheduler):
    """Scheduler SPI over a wall-clock timer heap; the owning loop calls
    `run_due()` between IO waits and sleeps until `next_deadline()`."""

    def __init__(self, on_error: Optional[Callable] = None):
        self._heap: List[Tuple[float, int, TimerHandle, Callable]] = []
        self._seq = itertools.count()
        # a raising timer must not kill the loop (the simulator routes timer
        # failures to the drive loop the same way, sim/queue.py)
        self.on_error: Callable = on_error if on_error is not None else (
            lambda e: print(f"timer error: {e!r}", file=sys.stderr,
                            flush=True))
        # loop-health hook (obs/cpuprof.LoopHealth.timer_lag): called with
        # (now - deadline) seconds for every due timer run — the
        # scheduled-vs-actual fire delta that makes loop saturation
        # measurable.  None (the default) costs one attribute check.
        self.lag_observer: Optional[Callable[[float], None]] = None

    def once(self, delay_s: float, fn: Callable[[], None]) -> TimerHandle:
        h = TimerHandle()
        heapq.heappush(self._heap,
                       (time.monotonic() + max(0.0, delay_s),
                        next(self._seq), h, fn))
        return h

    def recurring(self, delay_s: float, fn: Callable[[], None]) -> TimerHandle:
        h = TimerHandle()

        def tick():
            if h.cancelled:
                return
            try:
                fn()
            finally:  # a raising tick must not disarm the recurrence
                heapq.heappush(self._heap,
                               (time.monotonic() + delay_s, next(self._seq),
                                h, tick))

        heapq.heappush(self._heap,
                       (time.monotonic() + delay_s, next(self._seq), h, tick))
        return h

    def now(self, fn: Callable[[], None]) -> None:
        self.once(0.0, fn)

    def now_s(self) -> float:
        return time.monotonic()

    # ---------------------------------------------------------- loop hooks --
    def next_deadline(self) -> Optional[float]:
        while self._heap and self._heap[0][2].cancelled:
            heapq.heappop(self._heap)
        return self._heap[0][0] if self._heap else None

    def run_due(self, limit: int = 1000) -> int:
        ran = 0
        now = time.monotonic()
        observer = self.lag_observer
        while self._heap and ran < limit:
            deadline, _, handle, fn = self._heap[0]
            if deadline > now:
                break
            heapq.heappop(self._heap)
            if handle.cancelled:
                continue
            if observer is not None:
                observer(now - deadline)
            try:
                fn()
            except Exception as e:  # noqa: BLE001
                self.on_error(e)
            ran += 1
        return ran
