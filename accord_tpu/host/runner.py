"""Black-box cluster runner over real Maelstrom host subprocesses.

Reference: accord-maelstrom's Cluster.java (the in-JVM runner driving nodes
through the same JSON wire format Maelstrom itself would use). Ours goes one
step further out of the box: each node is a separate OS process running
`python -m accord_tpu.host.maelstrom`, the runner routes envelopes between
their stdios, plays a randomized append/read workload as Maelstrom clients,
and feeds the observed results to the burn test's strict-serializability
verifier (sim/verify.py) with final states obtained through ordinary
linearizable read transactions — fully black-box.
"""

from __future__ import annotations

import json
import queue
import random
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from accord_tpu.host.maelstrom import key_token
from accord_tpu.sim.verify import Observation, StrictSerializabilityVerifier


class HostProcess:
    """One node subprocess; a reader thread enqueues its stdout lines."""

    def __init__(self, name: str, inbox: "queue.Queue",
                 extra_env: Optional[dict] = None):
        import os
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")  # hosts never need the chip
        if extra_env:
            env.update(extra_env)
        self.name = name
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "accord_tpu.host.maelstrom"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, bufsize=1, env=env)
        self.stderr_tail: List[str] = []

        def reader():
            for line in self.proc.stdout:
                inbox.put((name, line))

        def drain_stderr():
            # never let the child block on a full stderr pipe; keep a tail
            # for diagnostics
            for line in self.proc.stderr:
                self.stderr_tail.append(line.rstrip())
                del self.stderr_tail[:-50]

        threading.Thread(target=reader, daemon=True).start()
        threading.Thread(target=drain_stderr, daemon=True).start()

    def send(self, envelope: dict) -> None:
        self.proc.stdin.write(json.dumps(envelope) + "\n")
        self.proc.stdin.flush()

    def close(self) -> None:
        try:
            self.proc.stdin.close()
        except Exception:  # noqa: BLE001
            pass
        self.proc.terminate()
        try:
            self.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self.proc.kill()  # a wedged child must not abort teardown
            self.proc.wait(timeout=10)


class MaelstromRunner:
    """Drives N host processes; acts as all Maelstrom clients at once."""

    def __init__(self, n_nodes: int = 3, seed: int = 0,
                 pipeline: bool = False, journal_dir: Optional[str] = None):
        self.names = [f"n{i + 1}" for i in range(n_nodes)]
        self.inbox: "queue.Queue" = queue.Queue()
        # pipeline=True turns on the continuous micro-batching ingest layer
        # in every node process (accord_tpu/pipeline/, ACCORD_PIPELINE=1);
        # journal_dir points every node process at a durable write-ahead
        # journal (ACCORD_JOURNAL; accord_tpu/journal/), which also makes
        # restart_node a black-box crash test: kill -9 + respawn + replay
        self._extra_env: Dict[str, str] = {}
        if pipeline:
            self._extra_env["ACCORD_PIPELINE"] = "1"
        if journal_dir is not None:
            self._extra_env["ACCORD_JOURNAL"] = journal_dir
        extra_env = self._extra_env or None
        self.procs: Dict[str, HostProcess] = {
            name: HostProcess(name, self.inbox, extra_env=extra_env)
            for name in self.names}
        self.seed = seed
        self._msg_seq = 0
        self.pending: Dict[int, dict] = {}   # msg_id -> op record
        self.results: List[dict] = []
        self.init_acks: set = set()
        # QoS-nack honor (qos/): a code-11 error carrying retry_after_us is
        # resubmitted after the hinted backoff (with jitter) instead of
        # being finalized — up to qos_max_retries attempts per op
        self.qos_max_retries = 3
        self.qos_nacks = 0
        self.qos_retries = 0
        self._retryq: List[Tuple[int, dict]] = []  # (due_us, op record)
        self._retry_rng = random.Random(seed ^ 0x51C)
        # appended values must be unique across the runner's LIFETIME, not
        # per workload call: a crash-restart harness runs several phases
        # against the same cluster and verifies them together
        self._next_value = 0

    # ----------------------------------------------------------- plumbing --
    def _route(self, envelope: dict) -> None:
        dest = envelope.get("dest", "")
        body = envelope.get("body", {})
        if body.get("type") == "init_ok":
            self.init_acks.add(envelope.get("src"))
            return
        if dest in self.procs:
            self.procs[dest].send(envelope)
        elif dest.startswith("c"):
            rec = self.pending.pop(body.get("in_reply_to"), None)
            if rec is not None:
                if body.get("qos") and body.get("retry_after_us") is not None \
                        and rec.get("attempt", 0) < self.qos_max_retries:
                    self.qos_nacks += 1
                    attempt = rec.get("attempt", 0) + 1
                    rec["attempt"] = attempt
                    base = min(2_000_000, int(body["retry_after_us"])
                               * (2 ** (attempt - 1)))
                    delay = base + int(self._retry_rng.random() * 0.5 * base)
                    self._retryq.append(
                        (int(time.monotonic() * 1e6) + delay, rec))
                    return
                rec["reply"] = body
                rec["end_us"] = int(time.monotonic() * 1e6)
                self.results.append(rec)

    def _flush_retries(self) -> None:
        """Resubmit QoS-nacked ops whose (jittered) retry_after elapsed,
        under fresh msg_ids; `start_us` is kept from the FIRST attempt so
        latency accounting includes the honored backoff."""
        if not self._retryq:
            return
        now = int(time.monotonic() * 1e6)
        due = [item for item in self._retryq if item[0] <= now]
        if not due:
            return
        self._retryq = [item for item in self._retryq if item[0] > now]
        for _, rec in due:
            self.qos_retries += 1
            self._msg_seq += 1
            msg_id = self._msg_seq
            rec["msg_id"] = msg_id
            self.pending[msg_id] = rec
            dest = self.names[msg_id % len(self.names)]
            body = {"type": "txn", "msg_id": msg_id, "txn": rec["ops"]}
            if rec.get("tenant"):
                body["tenant"] = rec["tenant"]
            if rec.get("priority"):
                body["priority"] = rec["priority"]
            self.procs[dest].send({"src": rec["client"], "dest": dest,
                                   "body": body})

    def pump(self, timeout: float = 0.05) -> int:
        handled = 0
        self._flush_retries()
        try:
            name, line = self.inbox.get(timeout=timeout)
        except queue.Empty:
            return 0
        while True:
            try:
                self._route(json.loads(line))
                handled += 1
            except json.JSONDecodeError:
                print(f"bad json from {name}: {line[:200]}", file=sys.stderr)
            try:
                name, line = self.inbox.get_nowait()
            except queue.Empty:
                return handled

    def pump_until(self, predicate, deadline_s: float = 60.0) -> bool:
        end = time.monotonic() + deadline_s
        while time.monotonic() < end:
            if predicate():
                return True
            self.pump()
        return predicate()

    # ------------------------------------------------------- crash-restart --
    def restart_node(self, name: str, deadline_s: float = 60.0) -> None:
        """Black-box crash-restart: SIGKILL the node process (no shutdown
        hook runs — true process death), respawn it with the same identity
        and environment, and re-init it.  With a journal_dir the replica
        replays its on-disk WAL before serving; without one this is a
        data-loss crash (useful as the negative control)."""
        hp = self.procs[name]
        hp.proc.kill()
        try:
            hp.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        self.init_acks.discard(name)
        self.procs[name] = HostProcess(name, self.inbox,
                                       extra_env=self._extra_env or None)
        self._msg_seq += 1
        self.procs[name].send({"src": "c0", "dest": name,
                               "body": {"type": "init",
                                        "msg_id": self._msg_seq,
                                        "node_id": name,
                                        "node_ids": self.names}})
        ok = self.pump_until(lambda: name in self.init_acks, deadline_s)
        assert ok, f"restarted {name} never re-initialized"

    # --------------------------------------------------------- admin plane --
    def install_epoch(self, epoch: int, shards, to: Optional[str] = None,
                      deadline_s: float = 30.0) -> dict:
        """Admin-plane epoch proposal over the Maelstrom transport:
        `shards` is [[start, end, [node_num, ...]], ...].  One contacted
        node journals the install before acking and gossips it to the rest
        (admin_epoch_ok carries the contact's post-install epoch)."""
        self._msg_seq += 1
        msg_id = self._msg_seq
        dest = to if to is not None else self.names[0]
        acked: List[dict] = []
        self.pending[msg_id] = {"msg_id": msg_id, "client": "c0",
                                "ops": [], "start_us": 0, "reply": None}
        self.procs[dest].send({
            "src": "c0", "dest": dest,
            "body": {"type": "admin_epoch", "msg_id": msg_id,
                     "topology": {
                         "epoch": int(epoch),
                         "shards": [[int(s), int(e),
                                     [int(n) for n in nodes]]
                                    for s, e, nodes in shards]}}})

        def got_ack() -> bool:
            rec = next((r for r in self.results
                        if r["msg_id"] == msg_id), None)
            if rec is not None:
                acked.append(rec)
            return bool(acked)

        ok = self.pump_until(got_ack, deadline_s)
        assert ok, f"admin_epoch {epoch} never acked by {dest}"
        rec = acked[0]
        self.results.remove(rec)
        assert rec["reply"]["type"] == "admin_epoch_ok", rec["reply"]
        return rec["reply"]

    def drain_node(self, name: str, deadline_s: float = 60.0) -> dict:
        """Admin-plane scale-in over the Maelstrom transport: ask `name` to
        drain — fence new client work, hand off in-flight coordinations,
        raise the GLOBAL_SYNC durability barrier — and wait for its
        admin_drain_ok (whose `durable` flag reports the barrier verdict)."""
        self._msg_seq += 1
        msg_id = self._msg_seq
        self.pending[msg_id] = {"msg_id": msg_id, "client": "c0",
                                "ops": [], "start_us": 0, "reply": None}
        self.procs[name].send({"src": "c0", "dest": name,
                               "body": {"type": "admin_drain",
                                        "msg_id": msg_id}})
        ok = self.pump_until(
            lambda: any(r["msg_id"] == msg_id for r in self.results),
            deadline_s)
        assert ok, f"admin_drain never acked by {name}"
        rec = next(r for r in self.results if r["msg_id"] == msg_id)
        self.results.remove(rec)
        assert rec["reply"]["type"] == "admin_drain_ok", rec["reply"]
        return rec["reply"]

    # ------------------------------------------------------------- client --
    def init_all(self) -> None:
        for name, hp in self.procs.items():
            self._msg_seq += 1
            hp.send({"src": "c0", "dest": name,
                     "body": {"type": "init", "msg_id": self._msg_seq,
                              "node_id": name, "node_ids": self.names}})
        # cold-starting N python processes (each importing jax) contends for
        # CPU; the deadline scales with cluster size
        ok = self.pump_until(
            lambda: len(self.init_acks) == len(self.names),
            30.0 + 15.0 * len(self.names))
        assert ok, f"init timed out: {sorted(self.init_acks)}"

    def submit_txn(self, client: str, ops: list, to: Optional[str] = None,
                   tenant: str = "", priority: str = "") -> int:
        self._msg_seq += 1
        msg_id = self._msg_seq
        dest = to if to is not None else \
            self.names[msg_id % len(self.names)]
        self.pending[msg_id] = {
            "msg_id": msg_id, "client": client, "ops": ops,
            "tenant": tenant, "priority": priority,
            "start_us": int(time.monotonic() * 1e6), "reply": None}
        body = {"type": "txn", "msg_id": msg_id, "txn": ops}
        if tenant:
            body["tenant"] = tenant
        if priority:
            body["priority"] = priority
        self.procs[dest].send({"src": client, "dest": dest, "body": body})
        return msg_id

    # ------------------------------------------------------------ workload --
    def run_workload(self, n_ops: int = 40, n_keys: int = 8,
                     pipeline: int = 4, deadline_s: float = 120.0,
                     single_key: bool = False) -> dict:
        """Randomized append/read mix; returns counters. Appended values are
        globally unique so the verifier can track per-key sequences.
        `single_key` restricts every txn to one key (the lin-kv shape);
        the default mixes multi-key RMWs (txn-rw-register)."""
        import random
        rng = random.Random(self.seed + self._next_value)
        submitted = [0]
        base = len(self.results)  # completions are counted per phase

        def submit_one():
            client = f"c{1 + rng.randrange(4)}"
            k = rng.randrange(n_keys)
            ops = [["r", k, None]]
            if rng.random() < 0.7:
                self._next_value += 1
                ops.append(["append", k, self._next_value])
            if not single_key and rng.random() < 0.3:
                k2 = rng.randrange(n_keys)
                if not any(o == "append" and ok == k2 for o, ok, _ in ops):
                    self._next_value += 1
                    ops.append(["append", k2, self._next_value])
            self.submit_txn(client, ops)
            submitted[0] += 1

        def completed() -> int:
            return len(self.results) - base

        for _ in range(min(pipeline, n_ops)):
            submit_one()
        end = time.monotonic() + deadline_s
        while completed() < n_ops and time.monotonic() < end:
            self.pump()
            while submitted[0] < n_ops \
                    and submitted[0] - completed() < pipeline:
                submit_one()
        ok = sum(1 for r in self.results[base:]
                 if r["reply"] and r["reply"].get("type") == "txn_ok")
        return {"submitted": submitted[0], "completed": completed(),
                "acked": ok}

    # -------------------------------------------------------------- verify --
    def final_histories(self, n_keys: int) -> Dict[int, tuple]:
        """Read every key through an ordinary linearizable read txn."""
        # drain in-flight txns first: a straggler acked after the final-read
        # snapshot would be verified against a state that predates it
        self.pump_until(lambda: not self.pending and not self._retryq, 30.0)
        for msg_id in list(self.pending):
            del self.pending[msg_id]  # never acked; late replies are ignored
        self._retryq.clear()  # a queued retry must not land past the snapshot
        ops = [["r", k, None] for k in range(n_keys)]
        msg_id = self.submit_txn("c9", ops, to=self.names[0])
        assert self.pump_until(
            lambda: any(r["msg_id"] == msg_id for r in self.results), 60.0), \
            "final read timed out"
        rec = next(r for r in self.results if r["msg_id"] == msg_id)
        assert rec["reply"]["type"] == "txn_ok", rec["reply"]
        self.results.remove(rec)
        return {key_token(k): tuple(v)
                for _, k, v in rec["reply"]["txn"]}

    def check_strict_serializability(self, n_keys: int) -> int:
        final = self.final_histories(n_keys)
        from accord_tpu.sim.verify_replay import full_verifier
        verifier = full_verifier(witness_replay=False)
        checked = 0
        for rec in self.results:
            reply = rec["reply"]
            if not reply or reply.get("type") != "txn_ok":
                continue
            reads = {}
            appends = {}
            applied_so_far: Dict[int, int] = {}
            for op, k, v in reply["txn"]:
                token = key_token(k)
                if op == "r":
                    # the wire reply includes this txn's own earlier appends
                    # (Maelstrom txn-list-append semantics); the verifier's
                    # Observation wants the PRE-state read, so strip them
                    own = applied_so_far.get(token, 0)
                    reads[token] = tuple(v[:len(v) - own] if own else v)
                else:
                    appends[token] = v
                    applied_so_far[token] = applied_so_far.get(token, 0) + 1
            verifier.observe(Observation(
                f"txn{rec['msg_id']}", reads, appends,
                rec["start_us"], rec["end_us"]))
            checked += 1
        verifier.verify(final)
        return checked

    def close(self) -> None:
        for hp in self.procs.values():
            hp.close()


def main():
    import argparse
    ap = argparse.ArgumentParser(description="black-box maelstrom run")
    ap.add_argument("-n", "--nodes", type=int, default=3)
    ap.add_argument("-o", "--ops", type=int, default=40)
    ap.add_argument("-k", "--keys", type=int, default=8)
    ap.add_argument("-s", "--seed", type=int, default=0)
    ap.add_argument("--pipeline", action="store_true",
                    help="continuous micro-batching ingest in every node "
                         "process (ACCORD_PIPELINE=1)")
    ns = ap.parse_args()
    runner = MaelstromRunner(ns.nodes, ns.seed, pipeline=ns.pipeline)
    try:
        t0 = time.monotonic()
        runner.init_all()
        stats = runner.run_workload(ns.ops, ns.keys)
        checked = runner.check_strict_serializability(ns.keys)
        dt = time.monotonic() - t0
        print(json.dumps({**stats, "verified_txns": checked,
                          "wall_s": round(dt, 2),
                          "txns_per_sec": round(stats["acked"] / dt, 1),
                          "ok": True}))
    finally:
        runner.close()


if __name__ == "__main__":
    main()
