"""Maelstrom protocol host: one Accord node as a stdin/stdout JSON process.

Reference: accord-maelstrom/Main.java:145 — reads newline-delimited JSON
envelopes {"src","dest","body"} from stdin, writes them to stdout. Supports:
  * init            — builds the Node; topology derives deterministically
                      from the init node list so every process agrees
  * txn             — Maelstrom txn-list-append workload: micro-ops
                      [["r", k, null], ["append", k, v], ...] become one
                      Accord transaction over the list-register data plane
  * accord          — inter-node Accord traffic, wire.py-encoded; request
                      callbacks ride msg_id/in_reply_to like the reference's
                      Packet/MaelstromReplyContext

Run: python -m accord_tpu.host.maelstrom
"""

from __future__ import annotations

import json
import sys
import time
import zlib
from typing import Dict, Optional

from accord_tpu.api.spi import Agent, CallbackSink
from accord_tpu.host.rt import RealTimeScheduler
from accord_tpu.host.wire import decode_message, encode_message
from accord_tpu.impl.list_store import (ListQuery, ListRead, ListResult,
                                        ListStore, ListUpdate)
from accord_tpu.messages.base import Reply, Request
from accord_tpu.primitives.keys import Key, Keys, Range, Ranges
from accord_tpu.primitives.timestamp import TxnKind
from accord_tpu.primitives.txn import Txn
from accord_tpu.topology.shard import Shard
from accord_tpu.topology.topology import Topology
from accord_tpu.utils.random_source import RandomSource

TOKEN_SPAN = 1 << 31


def node_num(name: str) -> int:
    """'n3' -> 3; anything else hashes stably."""
    if name.startswith("n") and name[1:].isdigit():
        return int(name[1:])
    return (zlib.crc32(name.encode()) % 1_000_000) + 1_000


def build_topology(ids, rf: int, n_shards: int) -> Topology:
    """Static epoch-1 topology over the token span: `n_shards` even ranges,
    replicas rotated over the sorted node ids (shared by every host
    transport so deployments cannot diverge on shard boundaries)."""
    ids = sorted(ids)
    width = TOKEN_SPAN // n_shards
    shards = []
    for i in range(n_shards):
        start = i * width
        end = TOKEN_SPAN if i == n_shards - 1 else (i + 1) * width
        replicas = [ids[(i + j) % len(ids)] for j in range(rf)]
        shards.append(Shard(Range(start, end), replicas))
    return Topology(1, shards)


def key_token(k) -> int:
    if isinstance(k, bool) or not isinstance(k, int):
        return zlib.crc32(str(k).encode()) % TOKEN_SPAN
    return k % TOKEN_SPAN


class HostAgent(Agent):
    def __init__(self):
        # env-tunable RPC timeout, parsed once (constant for the process):
        # a device-store host (ACCORD_TCP_DEVICE_STORE) whose first flush
        # jit-compiles inside the dispatch loop needs rounds to survive
        # multi-second peer stalls
        import os
        try:
            self._rpc_timeout_s = float(
                os.environ.get("ACCORD_HOST_RPC_TIMEOUT_S", "1.0"))
        except ValueError:
            self._rpc_timeout_s = 1.0

    def on_uncaught_exception(self, failure: BaseException) -> None:
        print(f"uncaught: {failure!r}", file=sys.stderr, flush=True)

    def on_handled_exception(self, failure: BaseException) -> None:
        # recovered-from incidents (e.g. the device tier degrading to
        # scalar on a mid-run backend death) must still be operator-visible
        print(f"handled: {failure!r}", file=sys.stderr, flush=True)

    def pre_accept_timeout(self) -> float:
        return self._rpc_timeout_s

    def empty_txn(self, kind: TxnKind, keys_or_ranges) -> Txn:
        return Txn(kind, keys_or_ranges)


class MaelstromSink(CallbackSink):
    """MessageSink writing Maelstrom envelopes (reference Wrapper/Packet).

    A single-slot encode memo (identity-keyed) covers the fan-out pattern:
    Node.send encodes the SAME request object once per recipient — the
    PreAccept/Commit/Apply rounds each pay one structural walk instead of
    rf of them.  Requests are never mutated between their fan-out sends
    (the trace id is stamped before the first), so identity implies an
    identical tree."""

    def __init__(self, host: "MaelstromHost"):
        super().__init__()
        self.host = host
        self._memo_req = None
        self._memo_tree = None

    def _enc(self, request):
        if self._memo_req is request:
            return self._memo_tree
        tree = encode_message(request)
        self._memo_req = request
        self._memo_tree = tree
        return tree

    def send(self, to: int, request: Request) -> None:
        if self._capture(to, None, request):
            return
        self.host.emit_node(to, {"type": "accord",
                                 "payload": self._enc(request)})

    def send_with_callback(self, to: int, request: Request, callback,
                           executor=None) -> None:
        msg_id = self._register(callback)
        if self._capture(to, msg_id, request):
            return
        self.host.emit_node(to, {"type": "accord", "msg_id": msg_id,
                                 "payload": self._enc(request)})

    def _send_prepared(self, to: int, reply_context, request) -> None:
        body = {"type": "accord", "payload": self._enc(request)}
        if reply_context is not None:
            body["msg_id"] = reply_context
        self.host.emit_node(to, body)

    def reply(self, to: int, reply_context, reply: Reply) -> None:
        if reply_context is None:
            return
        self.host.emit_node(to, {"type": "accord",
                                 "in_reply_to": reply_context,
                                 "payload": self._enc(reply)})


class MaelstromHost:
    def __init__(self, stdin=None, stdout=None, rf: Optional[int] = None):
        self.stdin = stdin if stdin is not None else sys.stdin
        self.stdout = stdout if stdout is not None else sys.stdout
        self.rf = rf
        self.node = None
        self.pipeline = None  # built with the node when ACCORD_PIPELINE=1
        self.metrics_server = None  # built with the node (obs/httpd)
        self.auditor = None         # built with the node (local/audit.py)
        self.loop_health = None     # built with the node (obs/cpuprof.py)
        self.config_service = None  # built with the node (admin epoch plane)
        self.node_name = ""
        self.names: Dict[int, str] = {}
        self.scheduler = RealTimeScheduler()
        self.sink = MaelstromSink(self)
        self._msg_seq = 0
        self.running = True
        self._pre_init: list = []
        self.wal = None  # ACCORD_JOURNAL: attached in _build_node
        # stdout is shared by the loop thread and (with a journal in
        # group-commit mode) the WAL flush thread releasing durability-
        # gated replies: envelope writes must not interleave
        import threading
        self._emit_lock = threading.Lock()

    # ------------------------------------------------------------- output --
    def _emit(self, dest: str, body: dict) -> None:
        with self._emit_lock:
            print(json.dumps({"src": self.node_name, "dest": dest,
                              "body": body}),
                  file=self.stdout, flush=True)

    def emit_node(self, to: int, body: dict) -> None:
        self._emit(self.names.get(to, f"n{to}"), body)

    # -------------------------------------------------------------- wiring --
    def _build_node(self, name: str, node_names) -> None:
        from accord_tpu.local.node import Node
        self.node_name = name
        my_id = node_num(name)
        ids = sorted(node_num(n) for n in node_names)
        self.names = {node_num(n): n for n in node_names}
        rf = self.rf if self.rf is not None else min(3, len(ids))
        topology = build_topology(ids, rf, n_shards=len(ids))
        agent = HostAgent()
        self.scheduler.on_error = agent.on_uncaught_exception
        self.node = Node(my_id, self.sink, agent, self.scheduler,
                         ListStore(my_id), RandomSource(my_id),
                         num_shards=1,
                         now_us=lambda: int(time.time() * 1e6))
        # always-on event-loop health telemetry, same layer the TCP loop
        # wires (obs/cpuprof.LoopHealth): the PR-8 due-timer fix gave this
        # loop correct timer scheduling but no way to OBSERVE timer
        # lateness — the lag histogram closes that
        from accord_tpu.obs.cpuprof import LoopHealth
        self.loop_health = LoopHealth(self.node.obs.registry,
                                      self.node.obs.flight)
        self.scheduler.lag_observer = self.loop_health.timer_lag
        # topology flows through a real ConfigurationService (same layer
        # the TCP host wires): admin_epoch installs gossip over ordinary
        # "accord" envelopes and gaps heal via TOPOLOGY_FETCH
        from accord_tpu.impl.config_service import LedgerConfigService
        from accord_tpu.messages.admin import EpochInstall
        self.config_service = LedgerConfigService(my_id)
        self.config_service.attach_node(self.node)
        self.config_service.remember_spec(EpochInstall.from_topology(topology))
        self.config_service.report_topology(topology)
        # ACCORD_JOURNAL=<dir>: replay surviving state from
        # <dir>/node-<id>, then journal every side-effecting request before
        # it is acked (group-commit fsync windows; see journal/wal.py)
        from accord_tpu.journal import attach_journal_from_env
        self.wal = attach_journal_from_env(self.node)
        # ACCORD_QOS=1: per-tenant QoS admission tier (same layer the TCP
        # host wires; see accord_tpu/qos/).  Default off — with the gate
        # unset the lag observer and txn path are the pre-QoS wiring.
        from accord_tpu.qos import qos_tier_from_env
        self.qos = qos_tier_from_env(
            self.node.obs.registry, self.node.obs.flight,
            clock_us=lambda: int(time.time() * 1e6),
            loop_health=self.loop_health, wal=self.wal)
        if self.qos is not None:
            lh_hook, qos_hook = self.loop_health.timer_lag, self.qos.observe_lag

            def _lag_chain(lag_s, _lh=lh_hook, _qos=qos_hook):
                _lh(lag_s)
                _qos(lag_s)
            self.scheduler.lag_observer = _lag_chain
        # ACCORD_PIPELINE=1: continuous micro-batching ingest (same layer
        # the TCP host wires; see accord_tpu/pipeline/).  Default off.
        from accord_tpu.pipeline import (Pipeline, PipelineConfig,
                                         pipeline_enabled)
        self.pipeline = Pipeline(self.node, self.scheduler,
                                 PipelineConfig.from_env(), qos=self.qos) \
            if pipeline_enabled() else None
        # ACCORD_METRICS_PORT=<base>: per-process Prometheus/JSON metrics
        # endpoint (base + node_id - 1), same layer the TCP host exposes
        from accord_tpu.obs.httpd import maybe_start_from_env
        self.metrics_server = maybe_start_from_env(lambda: self.node.obs,
                                                   node_id=my_id)
        # ACCORD_AUDIT_S=<s>: periodic replica-state audit + census over
        # the AUDIT_* verbs (local/audit.py; default on at 5 s, 0 off) —
        # the audit traffic rides ordinary "accord" envelopes, the live
        # view rides the metrics endpoint's /audit route
        from accord_tpu.local.audit import auditor_from_env
        self.auditor = auditor_from_env(self.node)

    # ------------------------------------------------------------ handlers --
    def handle(self, envelope: dict) -> None:
        body = envelope.get("body", {})
        typ = body.get("type")
        src = envelope.get("src", "")
        if typ == "init":
            self._build_node(body["node_id"], body["node_ids"])
            self._emit(src, {"type": "init_ok",
                             "in_reply_to": body.get("msg_id")})
            replay, self._pre_init = self._pre_init, []
            for env in replay:
                self.handle(env)
        elif self.node is None:
            # a faster peer's traffic raced our init: hold it
            self._pre_init.append(envelope)
        elif typ == "txn":
            self._handle_txn(src, body)
        elif typ == "accord":
            self._handle_accord(src, body)
        elif typ == "final_read":
            # harness-only: linearizable read of a key set via a READ txn
            self._handle_txn(src, {
                "msg_id": body.get("msg_id"),
                "type": "txn",
                "txn": [["r", k, None] for k in body["keys"]]})
        elif typ == "admin_epoch":
            # admin plane: propose a topology epoch over the Maelstrom
            # transport — journaled before the ack, gossiped so one
            # contacted node converges the whole membership
            self._handle_admin_epoch(src, body)
        elif typ == "admin_drain":
            # admin plane: scale-in — fence, hand off in-flight work, wait
            # the durability barrier, retire without losing an ack
            self._handle_admin_drain(src, body)

    def _handle_admin_epoch(self, client: str, body: dict) -> None:
        from accord_tpu.messages.admin import EpochInstall
        spec = body.get("topology", {})
        install = EpochInstall(
            int(spec["epoch"]),
            [(s[0], s[1], tuple(s[2])) for s in spec["shards"]])
        self.node.receive(install, 0, None)

        def ack():
            # _emit serializes under _emit_lock, so firing from the WAL
            # flush thread is safe
            self._emit(client, {"type": "admin_epoch_ok",
                                "in_reply_to": body.get("msg_id"),
                                "epoch": self.node.epoch})

        if self.wal is not None:
            # persist-before-ack without parking the scheduler loop
            self.wal.sync_soon(ack)
        else:
            ack()

    def _handle_admin_drain(self, client: str, body: dict) -> None:
        """`{"type":"admin_drain"}`: scale-in this node (the TCP host's
        drain ladder, host/tcp.py:_admin_drain, over Maelstrom envelopes).
        DrainBegin fences new client coordination (journaled: a crashed
        drainer comes back fenced) and tells peers to deprioritize us as a
        fetch source; then we wait for in-flight coordinations to settle,
        raise a GLOBAL_SYNC durability barrier over our ranges, and only
        then ack + DrainDone."""
        from accord_tpu.messages.admin import DrainBegin, DrainDone
        node = self.node
        msg_id = body.get("msg_id")
        topology = node.topology.current()
        members = sorted(n for n in topology.nodes() if n != node.id)
        node.receive(DrainBegin(node.id), 0, None)
        for to in members:
            node.send(to, DrainBegin(node.id))
        deadline = time.monotonic() + float(body.get("timeout_s", 60.0))

        def finish(_v=None, failure=None):
            node.receive(DrainDone(node.id), 0, None)
            for to in members:
                node.send(to, DrainDone(node.id))

            def ack():
                # every acked write is on disk before we go; _emit holds
                # _emit_lock so the flush thread may fire this directly
                self._emit(client, {"type": "admin_drain_ok",
                                    "in_reply_to": msg_id, "node": node.id,
                                    "durable": failure is None})

            if self.wal is not None:
                self.wal.sync_soon(ack)
            else:
                ack()

        def durability_barrier():
            owned = topology.ranges_for_node(node.id)
            if owned.is_empty:
                # the current epoch already moved everything away; older
                # in-flight work still needs the watermark — barrier all
                owned = Ranges([s.range for s in topology.shards])
            from accord_tpu.coordinate.syncpoint import BarrierType, barrier
            barrier(node, owned, BarrierType.GLOBAL_SYNC) \
                .add_callback(finish)

        def wait_idle():
            # hand off in-flight work: poll until nothing this node is
            # coordinating remains (new client work is already fenced)
            if not node.coordinating or time.monotonic() >= deadline:
                durability_barrier()
                return
            self.scheduler.once(0.05, wait_idle)

        wait_idle()

    def _handle_txn(self, client: str, body: dict) -> None:
        ops = body["txn"]
        msg_id = body.get("msg_id")
        if self.node.draining:
            # drain fence: never coordinated — Maelstrom code 11 is
            # temporarily-unavailable (retriable), so the workload remaps
            # to another coordinator instead of losing the op
            self._emit(client, {"type": "error", "in_reply_to": msg_id,
                                "code": 11, "text": "draining",
                                "drained": True})
            return
        if self.qos is not None:
            # QoS outer ring: admission before any coordination/journal
            # state is spent.  Maelstrom code 11 is temporarily-unavailable
            # (retriable); the tenant defaults to the client name so every
            # Maelstrom client gets its own token bucket
            nack = self.qos.admit(str(body.get("tenant") or client),
                                  str(body.get("priority") or "normal"))
            if nack is not None:
                self._emit(client, {"type": "error", "in_reply_to": msg_id,
                                    "code": 11, "text": repr(nack),
                                    "qos": True, "reason": nack.reason,
                                    "retry_after_us": nack.retry_after_us})
                return
        reads = []
        appends: Dict[Key, int] = {}
        for op, k, v in ops:
            token = key_token(k)
            if op == "r":
                reads.append(Key(token))
            elif op == "append":
                if Key(token) in appends:
                    # the list-register data plane carries one append per
                    # key per txn; acking a collapsed second append would be
                    # a lost acknowledged write
                    if self.qos is not None:
                        self.qos.op_done()  # admitted but never coordinated
                    self._emit(client, {"type": "error",
                                        "in_reply_to": msg_id, "code": 10,
                                        "text": f"duplicate append to {k}"})
                    return
                appends[Key(token)] = v
            else:
                if self.qos is not None:
                    self.qos.op_done()  # admitted but never coordinated
                self._emit(client, {"type": "error", "in_reply_to": msg_id,
                                    "code": 10,
                                    "text": f"unsupported op {op}"})
                return
        keys = Keys(set(reads) | set(appends))
        txn = Txn(TxnKind.WRITE if appends else TxnKind.READ, keys,
                  read=ListRead(Keys(reads)) if reads else None,
                  query=ListQuery(),
                  update=ListUpdate(appends) if appends else None)

        def done(result, failure):
            if self.qos is not None:
                # admitted op settled (either way): shrink the tier's
                # inflight backlog signal
                self.qos.op_done()
            if failure is not None:
                self._emit(client, {"type": "error", "in_reply_to": msg_id,
                                    "code": 11, "text": repr(failure)})
                return
            out = []
            values = (result.read_values
                      if isinstance(result, ListResult) else {})
            applied: Dict[Key, list] = {}  # own appends, in micro-op order
            for op, k, v in ops:
                kk = Key(key_token(k))
                if op == "r":
                    # txn-list-append semantics: a read observes the
                    # pre-state PLUS this txn's earlier appends to the key
                    pre = values.get(kk)
                    got = list(pre) if pre is not None else []
                    out.append([op, k, got + applied.get(kk, [])])
                else:
                    applied.setdefault(kk, []).append(v)
                    out.append([op, k, v])
            self._emit(client, {"type": "txn_ok", "in_reply_to": msg_id,
                                "txn": out})

        if self.pipeline is not None:
            self.pipeline.submit(txn).add_callback(done)
        else:
            self.node.coordinate(txn).add_callback(done)

    def _handle_accord(self, src: str, body: dict) -> None:
        prof = self.node.obs.cpuprof
        if prof.enabled:
            # decode lap for the CPU waterfall (obs/cpuprof.py): parked on
            # the profiler, consumed by the dispatch it precedes
            t0 = time.perf_counter()
            payload = decode_message(body["payload"])
            prof.note_decode(time.perf_counter() - t0)
        else:
            payload = decode_message(body["payload"])
        from_id = node_num(src)
        if "in_reply_to" in body:
            self.sink.deliver_reply(body["in_reply_to"], from_id, payload)
        else:
            reply_context = body.get("msg_id")
            self.node.receive(payload, from_id, reply_context)

    # ---------------------------------------------------------------- loop --
    def run(self) -> None:
        """Single-threaded core: a reader thread only enqueues stdin lines
        (select+readline over buffered pipes loses lines parked in the
        Python-side buffer); the node is touched exclusively here."""
        import queue
        import threading
        lines: "queue.Queue[Optional[str]]" = queue.Queue()

        def reader():
            for line in self.stdin:
                lines.put(line)
            lines.put(None)

        threading.Thread(target=reader, daemon=True).start()
        eof = False
        while self.running and not eof:
            # due timers run BEFORE blocking: `min(timeout, 0.5) or 0.01`
            # used to turn a due-now deadline (timeout == 0.0) into a 10ms
            # sleep — the host/tcp.py event loop fixed the same bug
            self.scheduler.run_due()
            deadline = self.scheduler.next_deadline()
            timeout = (max(0.0, deadline - time.monotonic())
                       if deadline is not None else 0.5)
            try:
                batch = [lines.get(timeout=min(timeout, 0.5))]
            except queue.Empty:
                batch = []
            # pipeline mode: drain the stdin burst and process it under one
            # sink coalescing window (same-destination messages the burst
            # produces leave as one envelope per peer per tick)
            while self.pipeline is not None and len(batch) < 64:
                try:
                    batch.append(lines.get_nowait())
                except queue.Empty:
                    break
            coalesce = self.pipeline is not None and len(batch) > 1
            if coalesce:
                self.sink.batch_begin()
            t_busy = time.perf_counter()
            try:
                for line in batch:
                    if line is None:
                        eof = True
                        break
                    if line and line.strip():
                        try:
                            self.handle(json.loads(line))
                        except Exception as e:  # noqa: BLE001
                            print(f"handle error: {e!r} on {line[:200]}",
                                  file=sys.stderr, flush=True)
            finally:
                if coalesce:
                    self.sink.batch_flush()
            self.scheduler.run_due()
            if batch and self.loop_health is not None:
                # loop-health parity with the TCP event loop
                # (obs/cpuprof.LoopHealth): busy time of this pass (the
                # blocking stdin get excluded), burst length, and the
                # stdin backlog left unread — the saturation signal
                self.loop_health.tick(time.perf_counter() - t_busy,
                                      len(batch), lines.qsize())
        if self.wal is not None:
            self.wal.close()  # final fsync on clean shutdown


def main():
    MaelstromHost().run()


if __name__ == "__main__":
    main()
