"""JSON wire codec for every Accord message and primitive.

Reference: accord-maelstrom/Json.java — gson adapters per type. Our
counterpart is a registry-driven structural codec: every class defined in
the framework's message/primitive/data-plane modules is encodable by
walking its slots/dict, with enums by value and exceptions by name. The
encoding is plain JSON (Maelstrom requires it), self-describing via `$c`
class tags, and round-trip-exact for every verb in the MessageType registry
(tests/test_host.py proves it).

Decode reconstructs via `__new__` + setattr — constructor revalidation is
the sender's job; the wire is trusted only as far as the registry (unknown
class tags are rejected, so a peer cannot instantiate arbitrary types).
"""

from __future__ import annotations

import enum
import importlib
from typing import Any, Dict, Type

_MODULES = [
    "accord_tpu.primitives.timestamp",
    "accord_tpu.primitives.keys",
    "accord_tpu.primitives.deps",
    "accord_tpu.primitives.latest_deps",
    "accord_tpu.primitives.txn",
    "accord_tpu.primitives.writes",
    "accord_tpu.local.status",
    "accord_tpu.local.command",
    # only AcceptOutcome/ApplyOutcome enums: AcceptNack carries its reason
    # (found by tests/test_wire_roundtrip.py — the nack encoded but could
    # not decode, so a slow-path reject crashed the receiving host)
    "accord_tpu.local.commands",
    "accord_tpu.messages.base",
    "accord_tpu.messages.preaccept",
    "accord_tpu.messages.accept",
    "accord_tpu.messages.commit",
    "accord_tpu.messages.apply_msg",
    "accord_tpu.messages.read",
    "accord_tpu.messages.recover",
    "accord_tpu.messages.invalidate_msg",
    "accord_tpu.messages.getdeps",
    "accord_tpu.messages.ephemeral",
    "accord_tpu.messages.wait",
    "accord_tpu.messages.checkstatus",
    "accord_tpu.messages.propagate",
    "accord_tpu.messages.durability",
    "accord_tpu.messages.epoch",
    "accord_tpu.messages.maxconflict",
    "accord_tpu.messages.multi",
    "accord_tpu.messages.audit",
    "accord_tpu.messages.admin",
    "accord_tpu.messages.paging",
    "accord_tpu.impl.list_store",
    "accord_tpu.coordinate.errors",
    "accord_tpu.pipeline.backpressure",
    # QosRejected: the admission tier's retriable nack must survive the
    # wire (retry_after_us/tenant/priority re-attached via wire_extra)
    "accord_tpu.qos.admission",
    "accord_tpu.utils.interval_map",
    # worker-pipe frames for the per-shard runtime (shard/): the
    # supervisor<->worker duplex pipe speaks the same codec as the network
    "accord_tpu.shard.frames",
]

_CLASSES: Dict[str, Type] = {}
_ENUMS: Dict[str, Type] = {}
import threading as _threading

_REGISTRY_LOCK = _threading.Lock()

# compact fast paths for the primitives that dominate every frame (a deps
# list is hundreds of TxnIds; the structural walk also serialises cached
# comparison slots).  Exact-type dispatch: subclasses fall through to the
# structural codec.
from accord_tpu.primitives.keys import (Key as _Key, Keys as _Keys,
                                        RoutingKey as _RoutingKey,
                                        RoutingKeys as _RoutingKeys)
from accord_tpu.primitives.timestamp import (Ballot as _Ballot,
                                             Timestamp as _Timestamp,
                                             TxnId as _TxnId)

_TS_TAGS = {_Timestamp: "$T", _TxnId: "$I", _Ballot: "$B"}
_TS_DECODE = {"$T": _Timestamp, "$I": _TxnId, "$B": _Ballot}
_SLOTS_CACHE: Dict[Type, list] = {}


def _registry() -> Dict[str, Type]:
    if _CLASSES:
        return _CLASSES
    # build-then-publish under a lock: encoders run concurrently (node loop
    # thread + the WAL's group-commit flush thread releasing gated replies,
    # or many bench appenders), and a reader racing a partial in-place
    # population would reject registered types as unknown
    with _REGISTRY_LOCK:
        if _CLASSES:
            return _CLASSES
        classes: Dict[str, Type] = {}
        enums: Dict[str, Type] = {}
        for mod_name in _MODULES:
            mod = importlib.import_module(mod_name)
            for name, obj in vars(mod).items():
                if not isinstance(obj, type) or obj.__module__ != mod_name:
                    continue
                if issubclass(obj, enum.Enum):
                    enums[name] = obj
                else:
                    classes[name] = obj
        _ENUMS.update(enums)
        _CLASSES.update(classes)
    return _CLASSES


def _slots_of(cls: Type):
    out = _SLOTS_CACHE.get(cls)
    if out is None:
        out = []
        for klass in cls.__mro__:
            out.extend(getattr(klass, "__slots__", ()))
        _SLOTS_CACHE[cls] = out
    return out


# hot-path dispatch: one dict lookup on the exact type replaces the old
# isinstance chain (half a million isinstance calls per 400-txn TCP run).
# Types absent from the table (enums, exceptions, registered classes,
# subclasses of the fast-path primitives) take _encode_slow, which keeps
# the original ordering semantics exactly.

def _enc_self(obj):
    return obj


def _enc_ts(obj):
    msb, lsb, node = obj.pack()
    return {_TS_TAGS[type(obj)]: [msb, lsb, node]}


def _enc_key(obj):
    return {"$K": obj.token}


def _enc_rkey(obj):
    return {"$RK": obj.token}


def _enc_keys(obj):
    # hosts may subclass Key for richer identity — those fall through
    # to the structural codec (loud if unregistered) instead of being
    # silently flattened to plain tokens
    if all(type(k) is _Key for k in obj):
        return {"$Ks": [k.token for k in obj]}
    return _encode_slow(obj)


def _enc_rkeys(obj):
    if all(type(k) is _RoutingKey for k in obj):
        return {"$RKs": [k.token for k in obj]}
    return _encode_slow(obj)


def _enc_list(obj):
    return [encode(x) for x in obj]


def _enc_tuple(obj):
    # deps CSR offsets/ids are long int tuples: skip per-element calls
    if all(type(x) is int for x in obj):
        return {"$t": list(obj)}
    return {"$t": [encode(x) for x in obj]}


def _enc_set(obj):
    return {"$s": [encode(x) for x in obj]}


def _enc_dict(obj):
    return {"$d": [[encode(k), encode(v)] for k, v in obj.items()]}


_ENC = {
    type(None): _enc_self, bool: _enc_self, int: _enc_self,
    float: _enc_self, str: _enc_self,
    _Timestamp: _enc_ts, _TxnId: _enc_ts, _Ballot: _enc_ts,
    _Key: _enc_key, _RoutingKey: _enc_rkey,
    _Keys: _enc_keys, _RoutingKeys: _enc_rkeys,
    list: _enc_list, tuple: _enc_tuple,
    set: _enc_set, frozenset: _enc_set,
    dict: _enc_dict,
}


def encode(obj: Any) -> Any:
    f = _ENC.get(type(obj))
    if f is not None:
        return f(obj)
    return _encode_slow(obj)


def _encode_slow(obj: Any) -> Any:
    if isinstance(obj, enum.Enum):  # before int: IntEnum is an int
        return {"$e": type(obj).__name__, "v": encode(obj.value)}
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, list):
        return [encode(x) for x in obj]
    if isinstance(obj, tuple):
        return _enc_tuple(obj)
    if isinstance(obj, (set, frozenset)):
        return _enc_set(obj)
    if isinstance(obj, dict):
        return _enc_dict(obj)
    if isinstance(obj, BaseException):
        out = {"$x": type(obj).__name__, "msg": str(obj)}
        # exceptions encode by name+message only; ones carrying
        # machine-readable payload (QosRejected's retry_after_us hint)
        # declare it via wire_extra() and get it re-attached on decode
        extra = getattr(obj, "wire_extra", None)
        if extra is not None:
            out["f"] = {k: encode(v) for k, v in extra().items()}
        return out
    _registry()
    cls = type(obj)
    name = cls.__name__
    if name not in _CLASSES:
        raise TypeError(f"unregistered wire type: {cls.__module__}.{name}")
    fields: Dict[str, Any] = {}
    for slot in _slots_of(cls):
        if hasattr(obj, slot):
            fields[slot] = encode(getattr(obj, slot))
    for key, val in getattr(obj, "__dict__", {}).items():
        fields[key] = encode(val)
    return {"$c": name, "f": fields}


def _dec_ts(cls, v):
    return cls.unpack(v[0], v[1], v[2])


def _dec_keys(v):
    # verify the remote peer's ordering before trusting it: an
    # unsorted list silently corrupts bisect-based set operations
    ok = all(v[i] < v[i + 1] for i in range(len(v) - 1))
    return _Keys([_Key(t) for t in v], _presorted=ok)


def _dec_rkeys(v):
    ok = all(v[i] < v[i + 1] for i in range(len(v) - 1))
    return _RoutingKeys([_RoutingKey(t) for t in v], _presorted=ok)


def _dec_tuple(t):
    if all(type(x) is int for x in t):
        return tuple(t)
    return tuple(decode(x) for x in t)


_DEC1 = {
    "$T": lambda v: _dec_ts(_Timestamp, v),
    "$I": lambda v: _dec_ts(_TxnId, v),
    "$B": lambda v: _dec_ts(_Ballot, v),
    "$K": _Key,
    "$RK": _RoutingKey,
    "$Ks": _dec_keys,
    "$RKs": _dec_rkeys,
    "$t": _dec_tuple,
    "$s": lambda v: frozenset(decode(x) for x in v),
    "$d": lambda v: {decode(k): decode(val) for k, val in v},
}


def decode(data: Any) -> Any:
    t = type(data)
    if t is dict:
        if len(data) == 1:
            ((k, v),) = data.items()
            h = _DEC1.get(k)
            if h is not None:
                return h(v)
        return _decode_tagged(data)
    if t is list:
        return [decode(x) for x in data]
    return data  # scalars: None / bool / int / float / str


def _decode_tagged(data: dict) -> Any:
    if "$t" in data:
        return _dec_tuple(data["$t"])
    if "$s" in data:
        return frozenset(decode(x) for x in data["$s"])
    if "$d" in data:
        return {decode(k): decode(v) for k, v in data["$d"]}
    if "$e" in data:
        _registry()
        return _ENUMS[data["$e"]](decode(data["v"]))
    if "$x" in data:
        _registry()
        cls = _CLASSES.get(data["$x"])
        if cls is not None and issubclass(cls, BaseException):
            exc = cls(data["msg"])
            for key, val in (data.get("f") or {}).items():
                setattr(exc, key, decode(val))
            return exc
        return RuntimeError(f"{data['$x']}: {data['msg']}")
    name = data["$c"]
    cls = _registry().get(name)
    if cls is None:
        raise TypeError(f"unregistered wire type: {name}")
    obj = cls.__new__(cls)
    setattr_ = object.__setattr__
    dec = decode
    for key, val in data["f"].items():
        setattr_(obj, key, dec(val))
    return obj


def encode_message(msg) -> Any:
    """Top-level entry for Request/Reply payloads."""
    return encode(msg)


def decode_message(data) -> Any:
    return decode(data)


# ---------------------------------------------------- binary frame codec ----
#
# The TCP host's frames used to travel as JSON: every frame paid a full
# json.dumps/json.loads over the structural tree.  The binary codec below
# serialises the SAME tree (the output of `encode`, the input of `decode`)
# into a compact tagged format — one byte of tag per value, varints for
# ints, fast paths for the timestamp/key dicts that dominate deps-heavy
# payloads.  Two behaviourally identical implementations exist:
#
#   * this pure-Python tier (always available, the fallback), and
#   * native/_wire_codec.cpp (built lazily like _sorted_arrays.cpp),
#
# and they are BYTE-IDENTICAL by contract: tests/test_wire_roundtrip.py
# cross-checks pack outputs and unpack round-trips between the two over
# every registered verb, so a host running the native tier interoperates
# bit-for-bit with one running the fallback.  `unpack_frame` auto-detects
# legacy JSON frames (they start with "{"), so mixed-version peers and
# hand-written harness clients keep working.
#
# ACCORD_WIRE=json forces JSON frames (debugging); ACCORD_WIRE=py pins the
# Python tier (the codec A/B lever the bench and tests use).

import json as _json
import os as _os
import struct as _struct

WIRE_MAGIC = 0xAC    # cannot begin a JSON document
WIRE_VERSION = 0x01

_F64 = _struct.Struct(">d")
_I64_MIN = -(1 << 63)
_I64_MAX = (1 << 63) - 1

_T_NONE, _T_FALSE, _T_TRUE, _T_INT, _T_FLOAT = 0x00, 0x01, 0x02, 0x03, 0x04
_T_STR, _T_LIST, _T_DICT = 0x05, 0x06, 0x07
_T_TS, _T_TXNID, _T_BALLOT = 0x08, 0x09, 0x0A   # {"$T"/"$I"/"$B": [a,b,c]}
_T_KEY, _T_RKEY, _T_KEYS, _T_RKEYS = 0x0B, 0x0C, 0x0D, 0x0E  # token dicts
_T_ITUPLE = 0x0F                                 # {"$t": [int, ...]}
_T_BIGINT = 0x10                                 # decimal string (> int64)

_TAG1 = {"$T": _T_TS, "$I": _T_TXNID, "$B": _T_BALLOT,
         "$K": _T_KEY, "$RK": _T_RKEY,
         "$Ks": _T_KEYS, "$RKs": _T_RKEYS, "$t": _T_ITUPLE}
_KEY1 = {tag: key for key, tag in _TAG1.items()}


def _w_varint(out: bytearray, v: int) -> None:
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)


def _w_zigzag(out: bytearray, n: int) -> None:
    _w_varint(out, ((n << 1) ^ (n >> 63)) & 0xFFFFFFFFFFFFFFFF)


def _all_i64(xs) -> bool:
    for x in xs:
        if type(x) is not int or not (_I64_MIN <= x <= _I64_MAX):
            return False
    return True


_U64_MAX = (1 << 64) - 1


def _all_u64(xs) -> bool:
    # timestamp packs (msb/lsb/node) are non-negative bit-packs that can
    # exceed int64 (lsb carries hlc_low << 16): they travel as UNSIGNED
    # varints, where zigzag would overflow
    for x in xs:
        if type(x) is not int or not (0 <= x <= _U64_MAX):
            return False
    return True


def _py_pack_value(obj: Any, out: bytearray) -> None:
    t = type(obj)
    if obj is None:
        out.append(_T_NONE)
    elif t is bool:
        out.append(_T_TRUE if obj else _T_FALSE)
    elif t is int:
        if _I64_MIN <= obj <= _I64_MAX:
            out.append(_T_INT)
            _w_zigzag(out, obj)
        else:
            raw = str(obj).encode()
            out.append(_T_BIGINT)
            _w_varint(out, len(raw))
            out += raw
    elif t is float:
        out.append(_T_FLOAT)
        out += _F64.pack(obj)
    elif t is str:
        raw = obj.encode()
        out.append(_T_STR)
        _w_varint(out, len(raw))
        out += raw
    elif t is list or t is tuple:  # tuples flatten to lists, like JSON
        out.append(_T_LIST)
        _w_varint(out, len(obj))
        for x in obj:
            _py_pack_value(x, out)
    elif t is dict:
        if len(obj) == 1:
            ((k, v),) = obj.items()
            tag = _TAG1.get(k)
            # fast paths apply only to the exact shapes `encode` mints;
            # anything else (a host body reusing the key name) falls
            # through to the generic dict so nothing is misrepresented
            if tag is not None:
                if tag in (_T_TS, _T_TXNID, _T_BALLOT):
                    if type(v) is list and len(v) == 3 and _all_u64(v):
                        out.append(tag)
                        for x in v:
                            _w_varint(out, x)
                        return
                elif tag in (_T_KEY, _T_RKEY):
                    if type(v) is int and _I64_MIN <= v <= _I64_MAX:
                        out.append(tag)
                        _w_zigzag(out, v)
                        return
                elif type(v) is list and _all_i64(v):
                    out.append(tag)              # $Ks / $RKs / $t
                    _w_varint(out, len(v))
                    for x in v:
                        _w_zigzag(out, x)
                    return
        out.append(_T_DICT)
        _w_varint(out, len(obj))
        for k, v in obj.items():
            _py_pack_value(k, out)
            _py_pack_value(v, out)
    else:
        # a raw protocol object at the payload boundary: the structural
        # walk (encode) yields its tree, packed with tree semantics —
        # the byte-identical Python mirror of the native one-pass object
        # packer (unregistered types raise from encode, as ever)
        _py_pack_value(encode(obj), out)


class _Reader:
    __slots__ = ("data", "pos")

    def __init__(self, data, pos: int = 0):
        self.data = data
        self.pos = pos

    def byte(self) -> int:
        b = self.data[self.pos]
        self.pos += 1
        return b

    def take(self, n: int) -> bytes:
        end = self.pos + n
        if end > len(self.data):
            raise ValueError("truncated binary frame")
        out = self.data[self.pos:end]
        self.pos = end
        return out

    def varint(self) -> int:
        shift = 0
        v = 0
        while True:
            b = self.byte()
            v |= (b & 0x7F) << shift
            if not b & 0x80:
                return v
            shift += 7
            if shift > 70:
                raise ValueError("varint too long")

    def zigzag(self) -> int:
        u = self.varint()
        return (u >> 1) ^ -(u & 1)


def _py_unpack_value(r: _Reader) -> Any:
    tag = r.byte()
    if tag == _T_NONE:
        return None
    if tag == _T_FALSE:
        return False
    if tag == _T_TRUE:
        return True
    if tag == _T_INT:
        return r.zigzag()
    if tag == _T_FLOAT:
        return _F64.unpack(r.take(8))[0]
    if tag == _T_STR:
        return r.take(r.varint()).decode()
    if tag == _T_LIST:
        return [_py_unpack_value(r) for _ in range(r.varint())]
    if tag == _T_DICT:
        out = {}
        for _ in range(r.varint()):
            k = _py_unpack_value(r)
            out[k] = _py_unpack_value(r)
        return out
    if tag in (_T_TS, _T_TXNID, _T_BALLOT):
        return {_KEY1[tag]: [r.varint(), r.varint(), r.varint()]}
    if tag in (_T_KEY, _T_RKEY):
        return {_KEY1[tag]: r.zigzag()}
    if tag in (_T_KEYS, _T_RKEYS, _T_ITUPLE):
        return {_KEY1[tag]: [r.zigzag() for _ in range(r.varint())]}
    if tag == _T_BIGINT:
        return int(r.take(r.varint()).decode())
    raise ValueError(f"unknown binary wire tag 0x{tag:02x}")


def py_pack(obj: Any) -> bytes:
    """Pure-Python pack of one encoded tree (no frame header)."""
    out = bytearray()
    _py_pack_value(obj, out)
    return bytes(out)


def py_unpack(data: bytes) -> Any:
    """Pure-Python unpack of one packed tree (no frame header)."""
    r = _Reader(data)
    out = _py_unpack_value(r)
    if r.pos != len(data):
        raise ValueError("trailing bytes after binary frame")
    return out


def _native_codec():
    """(pack, unpack) from the native tier, or None (build failure, no
    toolchain, ACCORD_NO_NATIVE=1).  Binding arms the native raw-object
    packer: the primitive classes, enum base, the (lazy) verb registry and
    slots helper, and the Python `encode` as its semantics-of-last-resort
    fallback."""
    from accord_tpu import native
    mod = native.get_wire()
    if mod is None:
        return None
    def _provider():
        _registry()
        return _CLASSES, _ENUMS

    mod.wire_bind(_Timestamp, _TxnId, _Ballot, _Key, _RoutingKey, _Keys,
                  _RoutingKeys, enum.Enum, _provider, _slots_of, encode)
    return mod.wire_pack, mod.wire_unpack, mod.wire_unpack_obj


_WIRE_MODE = _os.environ.get("ACCORD_WIRE", "")
if _WIRE_MODE == "py":
    _NATIVE = None
else:
    try:
        _NATIVE = _native_codec()
    except Exception:  # noqa: BLE001 — any native failure means Python tier
        _NATIVE = None

_HEADER = bytes((WIRE_MAGIC, WIRE_VERSION))


def codec_tier() -> str:
    """Which frame codec this process runs: native / python / json."""
    if _WIRE_MODE == "json":
        return "json"
    return "native" if _NATIVE is not None else "python"


def packs_objects() -> bool:
    """Both binary tiers serialise RAW protocol objects at the payload
    boundary in one pass (tree-free); only the legacy JSON mode needs the
    sender to pre-encode payload trees."""
    return _WIRE_MODE != "json"


def pack_frame(obj: Any) -> bytes:
    """One wire frame body: binary (native tier when available) unless
    ACCORD_WIRE=json pins the legacy JSON framing."""
    if _WIRE_MODE == "json":
        return _json.dumps(obj).encode()
    if _NATIVE is not None:
        return _HEADER + _NATIVE[0](obj)
    return _HEADER + py_pack(obj)


def unpack_frame(data: bytes) -> Any:
    """Decode one frame body to its TREE, auto-detecting the format:
    binary frames start with the magic byte, JSON frames with '{' (legacy
    peers, hand-written harness clients)."""
    if data[:1] == _HEADER[:1]:
        if data[1] != WIRE_VERSION:
            raise ValueError(f"unknown binary wire version {data[1]}")
        if _NATIVE is not None:
            return _NATIVE[1](bytes(data[2:]))
        return py_unpack(data[2:])
    return _json.loads(data.decode())


def unpack_frame_obj(data: bytes) -> Any:
    """Decode one frame body with payloads as DECODED MESSAGE OBJECTS —
    the native fusion of unpack_frame + decode_message (one pass, no
    intermediate tree).  Falls back to the tree form when the native tier
    is absent: callers must decode dict-typed payloads themselves (the
    `decode_message(p) if type(p) is dict else p` pattern)."""
    if data[:1] == _HEADER[:1] and _NATIVE is not None:
        if data[1] != WIRE_VERSION:
            raise ValueError(f"unknown binary wire version {data[1]}")
        return _NATIVE[2](bytes(data[2:]))
    return unpack_frame(data)
