"""JSON wire codec for every Accord message and primitive.

Reference: accord-maelstrom/Json.java — gson adapters per type. Our
counterpart is a registry-driven structural codec: every class defined in
the framework's message/primitive/data-plane modules is encodable by
walking its slots/dict, with enums by value and exceptions by name. The
encoding is plain JSON (Maelstrom requires it), self-describing via `$c`
class tags, and round-trip-exact for every verb in the MessageType registry
(tests/test_host.py proves it).

Decode reconstructs via `__new__` + setattr — constructor revalidation is
the sender's job; the wire is trusted only as far as the registry (unknown
class tags are rejected, so a peer cannot instantiate arbitrary types).
"""

from __future__ import annotations

import enum
import importlib
from typing import Any, Dict, Type

_MODULES = [
    "accord_tpu.primitives.timestamp",
    "accord_tpu.primitives.keys",
    "accord_tpu.primitives.deps",
    "accord_tpu.primitives.latest_deps",
    "accord_tpu.primitives.txn",
    "accord_tpu.primitives.writes",
    "accord_tpu.local.status",
    "accord_tpu.local.command",
    # only AcceptOutcome/ApplyOutcome enums: AcceptNack carries its reason
    # (found by tests/test_wire_roundtrip.py — the nack encoded but could
    # not decode, so a slow-path reject crashed the receiving host)
    "accord_tpu.local.commands",
    "accord_tpu.messages.base",
    "accord_tpu.messages.preaccept",
    "accord_tpu.messages.accept",
    "accord_tpu.messages.commit",
    "accord_tpu.messages.apply_msg",
    "accord_tpu.messages.read",
    "accord_tpu.messages.recover",
    "accord_tpu.messages.invalidate_msg",
    "accord_tpu.messages.getdeps",
    "accord_tpu.messages.ephemeral",
    "accord_tpu.messages.wait",
    "accord_tpu.messages.checkstatus",
    "accord_tpu.messages.propagate",
    "accord_tpu.messages.durability",
    "accord_tpu.messages.epoch",
    "accord_tpu.messages.maxconflict",
    "accord_tpu.messages.multi",
    "accord_tpu.messages.audit",
    "accord_tpu.impl.list_store",
    "accord_tpu.coordinate.errors",
    "accord_tpu.pipeline.backpressure",
    "accord_tpu.utils.interval_map",
]

_CLASSES: Dict[str, Type] = {}
_ENUMS: Dict[str, Type] = {}
import threading as _threading

_REGISTRY_LOCK = _threading.Lock()

# compact fast paths for the primitives that dominate every frame (a deps
# list is hundreds of TxnIds; the structural walk also serialises cached
# comparison slots).  Exact-type dispatch: subclasses fall through to the
# structural codec.
from accord_tpu.primitives.keys import (Key as _Key, Keys as _Keys,
                                        RoutingKey as _RoutingKey,
                                        RoutingKeys as _RoutingKeys)
from accord_tpu.primitives.timestamp import (Ballot as _Ballot,
                                             Timestamp as _Timestamp,
                                             TxnId as _TxnId)

_TS_TAGS = {_Timestamp: "$T", _TxnId: "$I", _Ballot: "$B"}
_TS_DECODE = {"$T": _Timestamp, "$I": _TxnId, "$B": _Ballot}
_SLOTS_CACHE: Dict[Type, list] = {}


def _registry() -> Dict[str, Type]:
    if _CLASSES:
        return _CLASSES
    # build-then-publish under a lock: encoders run concurrently (node loop
    # thread + the WAL's group-commit flush thread releasing gated replies,
    # or many bench appenders), and a reader racing a partial in-place
    # population would reject registered types as unknown
    with _REGISTRY_LOCK:
        if _CLASSES:
            return _CLASSES
        classes: Dict[str, Type] = {}
        enums: Dict[str, Type] = {}
        for mod_name in _MODULES:
            mod = importlib.import_module(mod_name)
            for name, obj in vars(mod).items():
                if not isinstance(obj, type) or obj.__module__ != mod_name:
                    continue
                if issubclass(obj, enum.Enum):
                    enums[name] = obj
                else:
                    classes[name] = obj
        _ENUMS.update(enums)
        _CLASSES.update(classes)
    return _CLASSES


def _slots_of(cls: Type):
    out = _SLOTS_CACHE.get(cls)
    if out is None:
        out = []
        for klass in cls.__mro__:
            out.extend(getattr(klass, "__slots__", ()))
        _SLOTS_CACHE[cls] = out
    return out


def encode(obj: Any) -> Any:
    tag = _TS_TAGS.get(type(obj))
    if tag is not None:
        msb, lsb, node = obj.pack()
        return {tag: [msb, lsb, node]}
    if type(obj) is _Key:
        return {"$K": obj.token}
    if type(obj) is _RoutingKey:
        return {"$RK": obj.token}
    if type(obj) is _Keys and all(type(k) is _Key for k in obj):
        # hosts may subclass Key for richer identity — those fall through
        # to the structural codec (loud if unregistered) instead of being
        # silently flattened to plain tokens
        return {"$Ks": [k.token for k in obj]}
    if type(obj) is _RoutingKeys \
            and all(type(k) is _RoutingKey for k in obj):
        return {"$RKs": [k.token for k in obj]}
    if isinstance(obj, enum.Enum):  # before int: IntEnum is an int
        return {"$e": type(obj).__name__, "v": encode(obj.value)}
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, list):
        return [encode(x) for x in obj]
    if isinstance(obj, tuple):
        # deps CSR offsets/ids are long int tuples: skip per-element calls
        if all(type(x) is int for x in obj):
            return {"$t": list(obj)}
        return {"$t": [encode(x) for x in obj]}
    if isinstance(obj, (set, frozenset)):
        return {"$s": [encode(x) for x in obj]}
    if isinstance(obj, dict):
        return {"$d": [[encode(k), encode(v)] for k, v in obj.items()]}
    if isinstance(obj, BaseException):
        return {"$x": type(obj).__name__, "msg": str(obj)}
    _registry()
    cls = type(obj)
    name = cls.__name__
    if name not in _CLASSES:
        raise TypeError(f"unregistered wire type: {cls.__module__}.{name}")
    fields: Dict[str, Any] = {}
    for slot in _slots_of(cls):
        if hasattr(obj, slot):
            fields[slot] = encode(getattr(obj, slot))
    for key, val in getattr(obj, "__dict__", {}).items():
        fields[key] = encode(val)
    return {"$c": name, "f": fields}


def decode(data: Any) -> Any:
    if data is None or isinstance(data, (bool, int, float, str)):
        return data
    if isinstance(data, list):
        return [decode(x) for x in data]
    assert isinstance(data, dict), data
    if len(data) == 1:
        ((k, v),) = data.items()
        cls = _TS_DECODE.get(k)
        if cls is not None:
            return cls.unpack(v[0], v[1], v[2])
        if k == "$K":
            return _Key(v)
        if k == "$RK":
            return _RoutingKey(v)
        if k == "$Ks":
            # verify the remote peer's ordering before trusting it: an
            # unsorted list silently corrupts bisect-based set operations
            ok = all(v[i] < v[i + 1] for i in range(len(v) - 1))
            return _Keys([_Key(t) for t in v], _presorted=ok)
        if k == "$RKs":
            ok = all(v[i] < v[i + 1] for i in range(len(v) - 1))
            return _RoutingKeys([_RoutingKey(t) for t in v],
                                _presorted=ok)
    if "$t" in data:
        t = data["$t"]
        if all(type(x) is int for x in t):
            return tuple(t)
        return tuple(decode(x) for x in t)
    if "$s" in data:
        return frozenset(decode(x) for x in data["$s"])
    if "$d" in data:
        return {decode(k): decode(v) for k, v in data["$d"]}
    if "$e" in data:
        _registry()
        return _ENUMS[data["$e"]](decode(data["v"]))
    if "$x" in data:
        _registry()
        cls = _CLASSES.get(data["$x"])
        if cls is not None and issubclass(cls, BaseException):
            return cls(data["msg"])
        return RuntimeError(f"{data['$x']}: {data['msg']}")
    name = data["$c"]
    cls = _registry().get(name)
    if cls is None:
        raise TypeError(f"unregistered wire type: {name}")
    obj = cls.__new__(cls)
    for key, val in data["f"].items():
        object.__setattr__(obj, key, decode(val))
    return obj


def encode_message(msg) -> Any:
    """Top-level entry for Request/Reply payloads."""
    return encode(msg)


def decode_message(data) -> Any:
    return decode(data)
