"""TCP transport host: Accord nodes over real sockets.

Reference context: the MessageSink SPI (api/MessageSink.java) is the
distributed communication backend; the reference ships a simulated sink, a
mock, and Maelstrom's stdio JSON sink, with real transports host-provided
(SURVEY §5.8).  This module is that real transport: each node listens on a
TCP socket; inter-node Accord traffic travels as length-prefixed JSON frames
using the same registry-driven wire codec as the Maelstrom host
(host/wire.py), with CallbackSink msg-id bookkeeping for replies.

Threading model mirrors the stdio host: socket reader threads only enqueue
decoded frames; ONE loop thread owns the Node (dispatch + RealTimeScheduler
timers).  Client transactions enter through `submit()`, which enqueues onto
the same loop and hands back a thread-safe future.
"""

from __future__ import annotations

import json
import queue
import socket
import struct
import threading
import time
from typing import Dict, Optional, Tuple

from accord_tpu.host.maelstrom import (HostAgent, MaelstromSink,
                                       build_topology)
from accord_tpu.host.rt import RealTimeScheduler
from accord_tpu.host.wire import decode_message, encode_message
from accord_tpu.impl.list_store import ListQuery, ListRead, ListStore, ListUpdate
from accord_tpu.primitives.keys import Key, Keys
from accord_tpu.primitives.timestamp import TxnKind
from accord_tpu.primitives.txn import Txn
from accord_tpu.utils.random_source import RandomSource

_LEN = struct.Struct(">I")


def _send_frame(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_frame(sock: socket.socket) -> Optional[dict]:
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (n,) = _LEN.unpack(header)
    data = _recv_exact(sock, n)
    return None if data is None else json.loads(data.decode())


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


# TcpSink IS MaelstromSink: both write {"type": "accord", ...} bodies to a
# host exposing emit_node(to, body); only the transport underneath differs.
# One implementation keeps the framing (and the None-reply_context guard)
# from ever diverging between transports.
TcpSink = MaelstromSink


class SubmitResult:
    """Thread-safe completion handle for a submitted transaction."""

    def __init__(self):
        self._event = threading.Event()
        self.value = None
        self.failure: Optional[BaseException] = None

    def _complete(self, value, failure) -> None:
        self.value = value
        self.failure = failure
        self._event.set()

    def wait(self, timeout_s: float = 30.0) -> "SubmitResult":
        if not self._event.wait(timeout_s):
            self.failure = TimeoutError("txn did not complete")
        return self


class _PeerWriter:
    """Owns the outbound connection to one peer: a dedicated thread drains a
    bounded queue, (re)connecting as needed, so slow/blackholed peers only
    back up their own lane. Frames to a dead peer are dropped — RPC
    timeouts and the progress log heal, exactly like a lossy link."""

    def __init__(self, host: "TcpHost", to: int):
        self.host = host
        self.to = to
        self.queue: "queue.Queue" = queue.Queue(maxsize=10_000)
        self.sock: Optional[socket.socket] = None
        threading.Thread(target=self._drain, daemon=True).start()

    def enqueue(self, frame: dict) -> None:
        try:
            self.queue.put_nowait(frame)
        except queue.Full:
            pass  # backpressure: shed like a drop-tail link

    def _drain(self) -> None:
        while self.host.running:
            try:
                frame = self.queue.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                if self.sock is None:
                    self.sock = socket.create_connection(
                        self.host.peers[self.to], timeout=5.0)
                _send_frame(self.sock, frame)
            except OSError:
                if self.sock is not None:
                    try:
                        self.sock.close()
                    except OSError:
                        pass
                self.sock = None  # drop the frame; reconnect on the next

    def close(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None


class TcpHost:
    """One Accord node bound to a TCP port, peered with `peers`
    (node_id -> (host, port), including itself)."""

    def __init__(self, my_id: int, peers: Dict[int, Tuple[str, int]],
                 rf: Optional[int] = None, n_shards: int = 4):
        self.my_id = my_id
        self.peers = dict(peers)
        self.inbox: "queue.Queue" = queue.Queue()
        self.scheduler = RealTimeScheduler()
        self.sink = TcpSink(self)
        self._out: Dict[int, _PeerWriter] = {}
        self._out_lock = threading.Lock()
        self.running = True

        self.server = socket.create_server(self.peers[my_id],
                                           reuse_port=False)
        # the OS may have assigned the port (port 0): record reality
        self.peers[my_id] = self.server.getsockname()

        ids = sorted(self.peers)
        rf = rf if rf is not None else min(3, len(ids))
        topology = build_topology(ids, rf, n_shards)

        from accord_tpu.local.node import Node
        agent = HostAgent()
        self.scheduler.on_error = agent.on_uncaught_exception
        self.node = Node(my_id, self.sink, agent, self.scheduler,
                         ListStore(my_id), RandomSource(my_id), num_shards=1,
                         now_us=lambda: int(time.time() * 1e6))
        self.node.on_topology_update(topology)

        threading.Thread(target=self._accept_loop, daemon=True).start()
        self.loop_thread = threading.Thread(target=self._run, daemon=True)
        self.loop_thread.start()

    # ------------------------------------------------------------- sockets --
    def _accept_loop(self) -> None:
        while self.running:
            try:
                conn, _addr = self.server.accept()
            except OSError:
                return
            threading.Thread(target=self._reader, args=(conn,),
                             daemon=True).start()

    def _reader(self, conn: socket.socket) -> None:
        try:
            while self.running:
                frame = _recv_frame(conn)  # raises on corrupt bytes
                if frame is None:
                    return  # clean EOF
                self.inbox.put(("frame", frame))
        except (OSError, ValueError, UnicodeDecodeError):
            return  # corrupt stream / peer reset: drop the connection
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def emit(self, to: int, body: dict) -> None:
        """Enqueue onto the peer's writer thread — the loop thread must
        never block on connect/send (a blackholed peer would stall every
        timer and dispatch for the connect timeout). Self-addressed frames
        skip the loopback round trip entirely."""
        frame = {"src": self.my_id, "body": body}
        if to == self.my_id:
            self.inbox.put(("frame", frame))
            return
        with self._out_lock:
            writer = self._out.get(to)
            if writer is None:
                writer = self._out[to] = _PeerWriter(self, to)
        writer.enqueue(frame)

    # MaelstromSink's transport hook (shared sink implementation)
    def emit_node(self, to: int, body: dict) -> None:
        self.emit(to, body)

    # ---------------------------------------------------------------- loop --
    def _run(self) -> None:
        while self.running:
            deadline = self.scheduler.next_deadline()
            timeout = (max(0.0, deadline - time.monotonic())
                       if deadline is not None else 0.2)
            try:
                kind, item = self.inbox.get(timeout=min(timeout, 0.2) or 0.01)
            except queue.Empty:
                kind, item = "", None
            try:
                if kind == "frame":
                    self._dispatch(item)
                elif kind == "call":
                    item()
            except Exception as e:  # noqa: BLE001 — one bad frame/callback
                # must never kill the node's only loop thread
                print(f"tcp host n{self.my_id} dispatch error: {e!r}",
                      flush=True)
            self.scheduler.run_due()

    def _dispatch(self, frame: dict) -> None:
        body = frame["body"]
        from_id = frame["src"]
        payload = decode_message(body["payload"])
        if "in_reply_to" in body:
            self.sink.deliver_reply(body["in_reply_to"], from_id, payload)
        else:
            self.node.receive(payload, from_id, body.get("msg_id"))

    # -------------------------------------------------------------- client --
    def submit(self, read_tokens, appends: Dict[int, int]) -> SubmitResult:
        """Client entry from ANY thread: list-register read/append txn."""
        result = SubmitResult()

        def run():
            try:
                keys = Keys.of(*(set(read_tokens) | set(appends)))
                txn = Txn(
                    TxnKind.WRITE if appends else TxnKind.READ, keys,
                    read=ListRead(Keys.of(*read_tokens))
                    if read_tokens else None,
                    query=ListQuery(),
                    update=ListUpdate({Key(t): v
                                       for t, v in appends.items()})
                    if appends else None)
                self.node.coordinate(txn).add_callback(result._complete)
            except BaseException as e:  # noqa: BLE001 — the client must see
                result._complete(None, e)  # the real error, not a timeout

        self.inbox.put(("call", run))
        return result

    def close(self) -> None:
        self.running = False
        try:
            self.server.close()
        except OSError:
            pass
        with self._out_lock:
            for writer in self._out.values():
                writer.close()
            self._out.clear()
