"""TCP transport host: Accord nodes over real sockets.

Reference context: the MessageSink SPI (api/MessageSink.java) is the
distributed communication backend; the reference ships a simulated sink, a
mock, and Maelstrom's stdio JSON sink, with real transports host-provided
(SURVEY §5.8).  This module is that real transport: each node listens on a
TCP socket; inter-node Accord traffic travels as length-prefixed JSON frames
using the same registry-driven wire codec as the Maelstrom host
(host/wire.py), with CallbackSink msg-id bookkeeping for replies.

Threading model mirrors the stdio host: socket reader threads only enqueue
decoded frames; ONE loop thread owns the Node (dispatch + RealTimeScheduler
timers).  Client transactions enter through `submit()`, which enqueues onto
the same loop and hands back a thread-safe future.
"""

from __future__ import annotations

import json
import os
import queue
import socket
import struct
import threading
import time
from typing import Dict, Optional, Tuple

from accord_tpu.host.maelstrom import (HostAgent, MaelstromSink,
                                       build_topology)
from accord_tpu.host.rt import RealTimeScheduler
from accord_tpu.host.wire import decode_message, encode_message
from accord_tpu.impl.list_store import ListQuery, ListRead, ListStore, ListUpdate
from accord_tpu.obs.views import MetricView, bind_metric_views
from accord_tpu.primitives.keys import Key, Keys
from accord_tpu.primitives.timestamp import TxnKind
from accord_tpu.primitives.txn import Txn
from accord_tpu.utils.random_source import RandomSource

_LEN = struct.Struct(">I")


def _build_list_txn(read_tokens, appends: Dict[int, int],
                    ephemeral: bool = False) -> Txn:
    """List-register read/append txn (shared by the in-process and wire
    client paths).  `ephemeral` routes a pure read down the single-round
    invisible EPHEMERAL_READ path (coordinate/ephemeral.py) — the
    workload harness's read-heavy SLO lane."""
    if ephemeral:
        assert read_tokens and not appends, \
            "ephemeral txns are pure reads"
        keys = Keys.of(*read_tokens)
        return Txn(TxnKind.EPHEMERAL_READ, keys, read=ListRead(keys),
                   query=ListQuery())
    keys = Keys.of(*(set(read_tokens) | set(appends)))
    return Txn(
        TxnKind.WRITE if appends else TxnKind.READ, keys,
        read=ListRead(Keys.of(*read_tokens)) if read_tokens else None,
        query=ListQuery(),
        update=ListUpdate({Key(t): v for t, v in appends.items()})
        if appends else None)


def _send_frame(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_frame(sock: socket.socket) -> Optional[dict]:
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (n,) = _LEN.unpack(header)
    data = _recv_exact(sock, n)
    return None if data is None else json.loads(data.decode())


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


# TcpSink IS MaelstromSink: both write {"type": "accord", ...} bodies to a
# host exposing emit_node(to, body); only the transport underneath differs.
# One implementation keeps the framing (and the None-reply_context guard)
# from ever diverging between transports.
TcpSink = MaelstromSink


class SubmitResult:
    """Thread-safe completion handle for a submitted transaction."""

    def __init__(self):
        self._event = threading.Event()
        self.value = None
        self.failure: Optional[BaseException] = None

    def _complete(self, value, failure) -> None:
        self.value = value
        self.failure = failure
        self._event.set()

    def wait(self, timeout_s: float = 30.0) -> "SubmitResult":
        if not self._event.wait(timeout_s):
            self.failure = TimeoutError("txn did not complete")
        return self


class _PeerWriter:
    """Owns the outbound connection to one peer: a dedicated thread drains a
    bounded queue, (re)connecting as needed, so slow/blackholed peers only
    back up their own lane.

    In-flight fan-out is bounded by a per-peer semaphore (default 512
    frames, ACCORD_TCP_PEER_INFLIGHT): with pipeline coalescing one frame
    can carry a whole batch's requests, so the old 10k-frame queue bound
    alone would let a burst overrun a slow replica by megabytes.  A failed
    send is retried with exponential backoff (reconnecting between
    attempts) before the frame is finally dropped — transient stalls no
    longer cost a frame, while a genuinely dead peer still degrades to the
    lossy-link model (RPC timeouts and the progress log heal).

    shed/send_drops/retries are registry-backed views (obs/) labeled per
    peer; the in-flight depth is a gauge the metrics endpoint exposes."""

    shed = MetricView("accord_tcp_peer_shed_total")
    send_drops = MetricView("accord_tcp_peer_send_drops_total")
    retries = MetricView("accord_tcp_peer_retries_total")

    def __init__(self, host: "TcpHost", to: int):
        from accord_tpu.pipeline.backpressure import SendBackoff
        self.host = host
        self.to = to
        max_inflight = _env_int("ACCORD_TCP_PEER_INFLIGHT", 512)
        self.queue: "queue.Queue" = queue.Queue(maxsize=max_inflight)
        self.inflight = threading.BoundedSemaphore(max_inflight)
        self.backoff = SendBackoff()
        registry = host.node.obs.registry
        bind_metric_views(self, registry, peer=to)
        self._g_inflight = registry.gauge("accord_tcp_peer_inflight",
                                          peer=to)
        self.sock: Optional[socket.socket] = None
        threading.Thread(target=self._drain, daemon=True).start()

    def enqueue(self, frame: dict) -> None:
        if not self.inflight.acquire(blocking=False):
            self.shed += 1  # backpressure: shed like a drop-tail link
            return
        try:
            self.queue.put_nowait(frame)
            self._g_inflight.value = self.queue.qsize()
        except queue.Full:  # unreachable (semaphore == queue bound); belt
            self.inflight.release()
            self.shed += 1

    def _drain(self) -> None:
        while self.host.running:
            try:
                frame = self.queue.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self._send_with_retry(frame)
            finally:
                self.inflight.release()
                self._g_inflight.value = self.queue.qsize()

    def _send_with_retry(self, frame: dict) -> None:
        attempt = 0
        while self.host.running:
            try:
                if self.sock is None:
                    self.sock = socket.create_connection(
                        self.host.peers[self.to], timeout=5.0)
                    # consensus rounds are small request/reply frames:
                    # Nagle + delayed-ACK otherwise stalls each ~40ms
                    self.sock.setsockopt(socket.IPPROTO_TCP,
                                         socket.TCP_NODELAY, 1)
                _send_frame(self.sock, frame)
                return
            except OSError:
                if self.sock is not None:
                    try:
                        self.sock.close()
                    except OSError:
                        pass
                self.sock = None
                attempt += 1
                delay = self.backoff.delay_s(attempt)
                if delay is None:
                    self.send_drops += 1  # dead peer: drop, timeouts heal
                    return
                self.retries += 1
                time.sleep(delay)  # only this peer's lane stalls

    def close(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_store_factory():
    """Optional batched-device command stores for the real-socket host:
    ACCORD_TCP_DEVICE_STORE=1 puts DeviceCommandStore behind every node
    (flush window ACCORD_TCP_FLUSH_US wall-clock µs, default 1000; inline
    scalar verification with ACCORD_TCP_DEVICE_VERIFY=1).  The same tier
    the burn exercises, demonstrated on the black-box transport."""
    if os.environ.get("ACCORD_TCP_DEVICE_STORE", "") != "1":
        return None
    from accord_tpu.utils.backend import resolve_platform
    resolve_platform()  # pin CPU if the tunneled device backend is dead
    # multi-process mode: every node process would otherwise pay the full
    # first-jit cost inside its dispatch loop (stalling peers' RPC rounds);
    # a persistent compilation cache amortizes it across processes and runs
    import jax
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("ACCORD_JAX_CACHE", "/tmp/accord_jax_cache"))
    except Exception:  # noqa: BLE001 — cache is an optimization only
        pass
    from accord_tpu.impl.device_store import DeviceCommandStore
    return DeviceCommandStore.factory(
        flush_window_us=int(os.environ.get("ACCORD_TCP_FLUSH_US", "1000")),
        verify=os.environ.get("ACCORD_TCP_DEVICE_VERIFY", "") == "1")


class TcpHost:
    """One Accord node bound to a TCP port, peered with `peers`
    (node_id -> (host, port), including itself)."""

    def __init__(self, my_id: int, peers: Dict[int, Tuple[str, int]],
                 rf: Optional[int] = None, n_shards: int = 4):
        self.my_id = my_id
        self.peers = dict(peers)
        self.inbox: "queue.Queue" = queue.Queue()
        self.scheduler = RealTimeScheduler()
        self.sink = TcpSink(self)
        self._out: Dict[int, _PeerWriter] = {}
        self._out_lock = threading.Lock()
        self.running = True

        self.server = socket.create_server(self.peers[my_id],
                                           reuse_port=False)
        # the OS may have assigned the port (port 0): record reality
        self.peers[my_id] = self.server.getsockname()

        # non-positive ids are CLIENT endpoints: they share the frame
        # transport (their replies travel as ordinary frames to their own
        # listening socket) but are not cluster members
        ids = sorted(i for i in self.peers if i > 0)
        rf = rf if rf is not None else min(3, len(ids))
        topology = build_topology(ids, rf, n_shards)

        from accord_tpu.local.node import Node
        agent = HostAgent()
        self.scheduler.on_error = agent.on_uncaught_exception
        self.node = Node(my_id, self.sink, agent, self.scheduler,
                         ListStore(my_id), RandomSource(my_id), num_shards=1,
                         store_factory=_env_store_factory(),
                         now_us=lambda: int(time.time() * 1e6))
        self.node.on_topology_update(topology)

        # ACCORD_JOURNAL=<dir>: durable write-ahead journal under
        # <dir>/node-<id> — existing state replays into the node BEFORE any
        # peer traffic is accepted, every side-effecting request is
        # journaled before its ack, and (group-commit mode) acks are gated
        # on the covering fsync by DurableAckSink.  Default off.
        from accord_tpu.journal import attach_journal_from_env
        self.wal = attach_journal_from_env(self.node)

        # ACCORD_PIPELINE=1: continuous micro-batching ingest — client
        # submissions coalesce into deadline-bounded batches whose fan-out
        # leaves as one MultiPreAccept envelope per replica (and whose
        # self-addressed slice the device store resolves as one fused
        # probe window).  Default off.
        from accord_tpu.pipeline import (Pipeline, PipelineConfig,
                                         pipeline_enabled)
        self.pipeline = Pipeline(self.node, self.scheduler,
                                 PipelineConfig.from_env()) \
            if pipeline_enabled() else None

        # ACCORD_METRICS_PORT=<base>: Prometheus text + JSON snapshot on
        # base + node_id - 1 (per-process port offset); 0 = ephemeral
        from accord_tpu.obs.httpd import maybe_start_from_env
        self.metrics_server = maybe_start_from_env(lambda: self.node.obs,
                                                   node_id=my_id)

        # ACCORD_AUDIT_S=<s>: periodic replica-state audit + lifecycle
        # census (local/audit.py) — cross-replica range digests over the
        # AUDIT_* verbs every <s> seconds, divergences and census served
        # at the "audit" frame and the metrics endpoint's /audit route.
        # Default on at 5 s; 0 disables.
        from accord_tpu.local.audit import auditor_from_env
        self.auditor = auditor_from_env(self.node)

        threading.Thread(target=self._accept_loop, daemon=True).start()
        self.loop_thread = threading.Thread(target=self._run, daemon=True)
        self.loop_thread.start()

    # ------------------------------------------------------------- sockets --
    def _accept_loop(self) -> None:
        while self.running:
            try:
                conn, _addr = self.server.accept()
            except OSError:
                return
            threading.Thread(target=self._reader, args=(conn,),
                             daemon=True).start()

    def _reader(self, conn: socket.socket) -> None:
        try:
            while self.running:
                frame = _recv_frame(conn)  # raises on corrupt bytes
                if frame is None:
                    return  # clean EOF
                self.inbox.put(("frame", frame))
        except (OSError, ValueError, UnicodeDecodeError):
            return  # corrupt stream / peer reset: drop the connection
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def emit(self, to: int, body: dict) -> None:
        """Enqueue onto the peer's writer thread — the loop thread must
        never block on connect/send (a blackholed peer would stall every
        timer and dispatch for the connect timeout). Self-addressed frames
        skip the loopback round trip entirely."""
        frame = {"src": self.my_id, "body": body}
        if to == self.my_id:
            self.inbox.put(("frame", frame))
            return
        with self._out_lock:
            writer = self._out.get(to)
            if writer is None:
                writer = self._out[to] = _PeerWriter(self, to)
        writer.enqueue(frame)

    # MaelstromSink's transport hook (shared sink implementation)
    def emit_node(self, to: int, body: dict) -> None:
        self.emit(to, body)

    # ---------------------------------------------------------------- loop --
    def _run(self) -> None:
        import os as _os
        prof_path = _os.environ.get("ACCORD_TCP_PROFILE")
        if not prof_path:
            return self._run_loop()
        # profile the node's single dispatch thread (where all protocol
        # work happens; reader/writer threads only move bytes) — the
        # BASELINE host-tier binding-constraint analysis reads these dumps
        import cProfile
        pr = cProfile.Profile()
        try:
            pr.runcall(self._run_loop)
        finally:
            pr.dump_stats(f"{prof_path}.{self.my_id}")

    def _run_loop(self) -> None:
        # pipeline mode drains the inbox in bursts under one sink
        # coalescing window: every same-destination message a burst
        # produces (Commits fanned out by a batch of PreAccept replies,
        # reads, applies) leaves as one envelope per replica per tick
        burst = 64 if self.pipeline is not None else 1
        while self.running:
            deadline = self.scheduler.next_deadline()
            timeout = (max(0.0, deadline - time.monotonic())
                       if deadline is not None else 0.2)
            try:
                items = [self.inbox.get(timeout=min(timeout, 0.2) or 0.01)]
            except queue.Empty:
                items = []
            while len(items) < burst:
                try:
                    items.append(self.inbox.get_nowait())
                except queue.Empty:
                    break
            coalesce = self.pipeline is not None and len(items) > 1
            if coalesce:
                self.sink.batch_begin()
            try:
                for kind, item in items:
                    try:
                        if kind == "frame":
                            self._dispatch(item)
                        elif kind == "call":
                            item()
                    except Exception as e:  # noqa: BLE001 — one bad frame/
                        # callback must never kill the node's only loop
                        # thread.  stderr: the parent reads stdout exactly
                        # once (the ready line) — a full stdout pipe would
                        # block this, the node's ONLY thread
                        import sys as _sys
                        print(f"tcp host n{self.my_id} dispatch error: "
                              f"{e!r}", file=_sys.stderr, flush=True)
            finally:
                if coalesce:
                    self.sink.batch_flush()
            self.scheduler.run_due()

    def _dispatch(self, frame: dict) -> None:
        body = frame["body"]
        from_id = frame["src"]
        kind = body.get("type")
        if kind == "submit":
            # client txn over the wire (multi-process bench/harness path)
            self._client_submit(from_id, body)
            return
        if kind == "metrics":
            # harness/client JSON snapshot fetch (bench.py records these
            # alongside its BENCH_HISTORY rows); client-endpoint src only
            if from_id <= 0:
                self.emit(from_id, {"type": "metrics_reply",
                                    "req": body.get("req"),
                                    "snapshot": self.node.obs.snapshot()})
            return
        if kind == "flight":
            # live forensics view over the frame transport: the node's
            # flight-recorder tail, or one trace id's events (the same
            # data the metrics endpoint serves at /flight?txn=)
            if from_id <= 0:
                flight = self.node.obs.flight
                txn = body.get("txn")
                events = (flight.for_trace(txn) if txn
                          else flight.tail(int(body.get("limit", 200))))
                self.emit(from_id, {
                    "type": "flight_reply", "req": body.get("req"),
                    "node": self.my_id,
                    "recorded_total": flight.recorded_total,
                    "events": [list(e) for e in events]})
            return
        if kind == "audit":
            # live replica-state audit view over the frame transport:
            # divergences, the last digest-round report, and the census
            # (same data the metrics endpoint serves at /audit)
            if from_id <= 0:
                view = (self.auditor.view() if self.auditor is not None
                        else {})
                self.emit(from_id, {"type": "audit_reply",
                                    "req": body.get("req"),
                                    "node": self.my_id, "audit": view})
            return
        if kind == "stop":
            # accept stop only from harness/client frames (non-positive
            # declared src).  NOTE: src is self-declared — this guards
            # against misdirected frames from well-behaved nodes, not
            # against a hostile peer (which could claim src 0).  This
            # transport is a localhost bench harness; real deployments
            # need authenticated connections before trusting any frame.
            if from_id <= 0:
                self.running = False
            return
        payload = decode_message(body["payload"])
        if "in_reply_to" in body:
            self.sink.deliver_reply(body["in_reply_to"], from_id, payload)
        else:
            self.node.receive(payload, from_id, body.get("msg_id"))

    def _client_submit(self, from_id: int, body: dict) -> None:
        req = body.get("req")
        want_phases = bool(body.get("phases"))

        def done(value, failure):
            from accord_tpu.pipeline.backpressure import Rejected
            reads = {}
            if failure is None and value is not None:
                reads = {k.token: list(v)
                         for k, v in value.read_values.items()}
            reply = {"type": "submit_reply", "req": req,
                     "ok": failure is None,
                     "error": repr(failure) if failure else None,
                     "reads": reads}
            if isinstance(failure, Rejected):
                # typed load-shed: never coordinated, safe to retry
                reply["shed"] = True
            if want_phases and failure is None and value is not None \
                    and getattr(value, "txn_id", None) is not None:
                # per-phase SLO attribution for the open-loop harness
                # (workload/openloop.py): the coordinator's span milestone
                # firsts ride back on the reply — timestamps are this
                # node's clock (time.time()-us, same machine as the
                # harness), so the client can join them against its
                # intended-start ledger without a second round trip
                from accord_tpu.obs.spans import phase_firsts, trace_key
                span = self.node.obs.spans.get(trace_key(value.txn_id))
                reply["phases"] = [[ph, at]
                                   for ph, at in phase_firsts(span)]
            self.emit(from_id, reply)

        try:
            read_tokens = body.get("reads", [])
            appends = {int(t): v for t, v in body.get("appends", {}).items()}
            txn = _build_list_txn(read_tokens, appends,
                                  ephemeral=body.get("kind") == "ephemeral")
            self._coordinate(txn).add_callback(done)
        except BaseException as e:  # noqa: BLE001
            done(None, e)

    def _coordinate(self, txn: Txn):
        """Client txn entry: through the ingest pipeline when enabled."""
        if self.pipeline is not None:
            return self.pipeline.submit(txn)
        return self.node.coordinate(txn)

    # -------------------------------------------------------------- client --
    def submit(self, read_tokens, appends: Dict[int, int]) -> SubmitResult:
        """Client entry from ANY thread: list-register read/append txn."""
        result = SubmitResult()

        def run():
            try:
                txn = _build_list_txn(read_tokens, appends)
                self._coordinate(txn).add_callback(result._complete)
            except BaseException as e:  # noqa: BLE001 — the client must see
                result._complete(None, e)  # the real error, not a timeout

        self.inbox.put(("call", run))
        return result

    def close(self) -> None:
        self.running = False
        if self.auditor is not None:
            self.auditor.stop()
        if self.wal is not None:
            try:
                self.wal.close()  # final fsync: nothing acked is lost
            except Exception:  # noqa: BLE001 — teardown must not raise
                pass
        if self.metrics_server is not None:
            try:
                self.metrics_server.shutdown()
            except Exception:  # noqa: BLE001 — teardown must not raise
                pass
        try:
            self.server.close()
        except OSError:
            pass
        with self._out_lock:
            for writer in self._out.values():
                writer.close()
            self._out.clear()


# --------------------------------------------------- multi-process cluster --

def _free_ports(n: int):
    """Pre-select n distinct free localhost ports (bind-then-close; the
    tiny reuse race is acceptable for local harnesses)."""
    socks = [socket.create_server(("127.0.0.1", 0)) for _ in range(n)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


class TcpClusterClient:
    """Client endpoint (pseudo-node 0) for a cluster of OS-process TcpHost
    nodes: spawns the workers, speaks the same length-prefixed frame codec,
    and collects submit replies — SURVEY §5.8's comm backend driven
    end-to-end over real sockets with one GIL per node."""

    def __init__(self, n_nodes: int = 3, n_shards: int = 4):
        import subprocess
        import sys as _sys
        ports = _free_ports(n_nodes + 1)
        self.peers = {i: ("127.0.0.1", ports[i]) for i in range(n_nodes + 1)}
        self.server = socket.create_server(self.peers[0], reuse_port=False)
        self.inbox: "queue.Queue" = queue.Queue()
        self.running = True
        threading.Thread(target=self._accept_loop, daemon=True).start()
        self.procs = []
        spec_peers = {str(i): list(p) for i, p in self.peers.items()}
        try:
            for i in range(1, n_nodes + 1):
                spec = json.dumps({"id": i, "peers": spec_peers,
                                   "n_shards": n_shards})
                self.procs.append(subprocess.Popen(
                    [_sys.executable, "-m", "accord_tpu.host.tcp", spec],
                    stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                    text=True))
            for p in self.procs:
                line = p.stdout.readline()  # ready marker
                assert line.strip(), "tcp worker failed to start"
        except BaseException:
            for p in self.procs:  # a failed spawn must not orphan the rest
                p.kill()
            raise
        self._out: Dict[int, socket.socket] = {}

    def _accept_loop(self) -> None:
        while self.running:
            try:
                conn, _ = self.server.accept()
            except OSError:
                return
            threading.Thread(target=self._reader, args=(conn,),
                             daemon=True).start()

    def _reader(self, conn: socket.socket) -> None:
        try:
            while self.running:
                frame = _recv_frame(conn)
                if frame is None:
                    return
                self.inbox.put(frame)
        except (OSError, ValueError):
            return

    def _send(self, to: int, body: dict) -> None:
        sock = self._out.get(to)
        if sock is None:
            sock = self._out[to] = socket.create_connection(self.peers[to],
                                                            timeout=10.0)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        _send_frame(sock, {"src": 0, "body": body})

    def submit(self, to: int, reads, appends: Dict[int, int], req,
               ephemeral: bool = False, want_phases: bool = False) -> None:
        body = {"type": "submit", "req": req, "reads": list(reads),
                "appends": {str(k): v for k, v in appends.items()}}
        if ephemeral:
            body["kind"] = "ephemeral"
        if want_phases:
            body["phases"] = True
        self._send(to, body)

    def recv(self, timeout_s: float = 30.0) -> Optional[dict]:
        try:
            return self.inbox.get(timeout=timeout_s)
        except queue.Empty:
            return None

    def fetch_metrics(self, to: int, timeout_s: float = 15.0
                      ) -> Optional[dict]:
        """Pull node `to`'s obs snapshot over the frame transport (use only
        when no submit replies are outstanding — stray frames between the
        request and its reply are consumed and dropped)."""
        req = f"metrics-{to}"
        try:
            self._send(to, {"type": "metrics", "req": req})
        except OSError:
            return None
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            frame = self.recv(min(1.0, timeout_s))
            if frame is None:
                continue
            body = frame.get("body", {})
            if body.get("type") == "metrics_reply" and body.get("req") == req:
                return body.get("snapshot")
        return None

    def fetch_flight(self, to: int, txn=None, limit: int = 200,
                     timeout_s: float = 15.0) -> Optional[dict]:
        """Pull node `to`'s flight-recorder view over the frame transport
        (same quiet-channel caveat as fetch_metrics)."""
        req = f"flight-{to}"
        frame = {"type": "flight", "req": req, "limit": limit}
        if txn is not None:
            frame["txn"] = txn
        try:
            self._send(to, frame)
        except OSError:
            return None
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            got = self.recv(min(1.0, timeout_s))
            if got is None:
                continue
            body = got.get("body", {})
            if body.get("type") == "flight_reply" and body.get("req") == req:
                return body
        return None

    def fetch_audit(self, to: int, timeout_s: float = 15.0
                    ) -> Optional[dict]:
        """Pull node `to`'s replica-state audit view over the frame
        transport (same quiet-channel caveat as fetch_metrics)."""
        req = f"audit-{to}"
        try:
            self._send(to, {"type": "audit", "req": req})
        except OSError:
            return None
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            got = self.recv(min(1.0, timeout_s))
            if got is None:
                continue
            body = got.get("body", {})
            if body.get("type") == "audit_reply" and body.get("req") == req:
                return body.get("audit")
        return None

    def close(self) -> None:
        for i in range(1, len(self.procs) + 1):
            try:
                self._send(i, {"type": "stop"})
            except OSError:
                pass
        self.running = False
        try:
            self.server.close()
        except OSError:
            pass
        for s in self._out.values():
            try:
                s.close()
            except OSError:
                pass
        for p in self.procs:
            try:
                p.wait(timeout=5.0)
            except Exception:
                p.kill()


def main() -> None:
    """Worker-process entry: python -m accord_tpu.host.tcp '<spec json>'
    with spec = {"id": N, "peers": {"0": [host, port], ...}, "n_shards": S}.
    Prints one ready line (its realised port), serves until a stop frame."""
    import sys as _sys
    spec = json.loads(_sys.argv[1])
    peers = {int(k): tuple(v) for k, v in spec["peers"].items()}
    host = TcpHost(spec["id"], peers, n_shards=spec.get("n_shards", 4))
    print(json.dumps({"id": spec["id"],
                      "port": host.peers[spec["id"]][1]}), flush=True)

    def parent_watch():
        # the spawner holds our stdin pipe: EOF means it is gone — exit
        # rather than serve forever as an orphan
        _sys.stdin.read()
        host.running = False

    threading.Thread(target=parent_watch, daemon=True).start()
    try:
        while host.running:
            time.sleep(0.05)
    finally:
        host.close()
        # the loop is a daemon thread: give it a moment to finish its
        # last dispatch (and flush the profiler dump when enabled)
        host.loop_thread.join(timeout=5.0)


if __name__ == "__main__":
    main()
