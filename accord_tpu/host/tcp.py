"""TCP transport host: Accord nodes over real sockets.

Reference context: the MessageSink SPI (api/MessageSink.java) is the
distributed communication backend; the reference ships a simulated sink, a
mock, and Maelstrom's stdio JSON sink, with real transports host-provided
(SURVEY §5.8).  This module is that real transport, rearchitected for raw
per-node speed (the BASELINE r5 profile showed the host tier — not the
protocol — as the binding constraint: ~14 small frames/txn of cross-thread
and cross-process scheduling):

  * ONE selector-driven event loop thread owns everything: the Node,
    RealTimeScheduler timers (deadlines are the poll timeout — due timers
    run before every block, never floored into a sleep), all sockets
    (non-blocking), and all framing.  No per-frame thread handoffs: the
    old architecture paid a queue.Queue hop per inbound frame plus a
    dedicated writer thread per peer.
  * Universal per-peer frame coalescing: every message a flush tick
    produces for a given peer leaves as ONE multi-message frame (the
    transport-level generalisation of the pipeline's MultiPreAccept
    envelope — amortising syscalls the way the pipeline amortises device
    dispatch), decoded back into individual dispatches on the far side.
    `ACCORD_TCP_FLUSH_TICK_US` bounds how long a frame may wait for
    company (0 = flush at the end of every loop pass, the default: a pass
    already coalesces everything a burst of input produced).
  * Binary frame codec (host/wire.py pack_frame/unpack_frame): the native
    tier when the toolchain is present, the byte-identical pure-Python
    tier otherwise; legacy JSON frames are auto-detected on decode.

Client transactions enter through `submit()` (any thread), which enqueues
onto the loop and hands back a thread-safe future.
"""

from __future__ import annotations

import os
import selectors
import socket
import struct
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from accord_tpu.host.maelstrom import (HostAgent, MaelstromSink,
                                       build_topology)
from accord_tpu.host.rt import RealTimeScheduler
from accord_tpu.host.wire import (decode_message, pack_frame, unpack_frame,
                                  unpack_frame_obj)
from accord_tpu.impl.list_store import ListQuery, ListRead, ListStore, ListUpdate
from accord_tpu.obs.views import MetricView, bind_metric_views
from accord_tpu.primitives.keys import Key, Keys
from accord_tpu.primitives.timestamp import TxnKind
from accord_tpu.primitives.txn import Txn
from accord_tpu.utils.random_source import RandomSource

_LEN = struct.Struct(">I")
_MAX_FRAME = 256 << 20  # corrupt-length guard: drop the connection instead
_RECV_CHUNK = 1 << 18
# max bulk-tier client submits dispatched per loop pass under QoS: bounds
# pass length during an overload flood so high submits, protocol messages
# and timers keep a few-ms service cadence (see _run_loop's lane comment)
_BULK_PER_PASS = 32


def _build_list_txn(read_tokens, appends: Dict[int, int],
                    ephemeral: bool = False) -> Txn:
    """List-register read/append txn (shared by the in-process and wire
    client paths).  `ephemeral` routes a pure read down the single-round
    invisible EPHEMERAL_READ path (coordinate/ephemeral.py) — the
    workload harness's read-heavy SLO lane."""
    if ephemeral:
        assert read_tokens and not appends, \
            "ephemeral txns are pure reads"
        keys = Keys.of(*read_tokens)
        return Txn(TxnKind.EPHEMERAL_READ, keys, read=ListRead(keys),
                   query=ListQuery())
    keys = Keys.of(*(set(read_tokens) | set(appends)))
    return Txn(
        TxnKind.WRITE if appends else TxnKind.READ, keys,
        read=ListRead(Keys.of(*read_tokens)) if read_tokens else None,
        query=ListQuery(),
        update=ListUpdate({Key(t): v for t, v in appends.items()})
        if appends else None)


def _send_frame(sock: socket.socket, obj: dict) -> None:
    data = pack_frame(obj)
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_frame(sock: socket.socket) -> Optional[dict]:
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (n,) = _LEN.unpack(header)
    data = _recv_exact(sock, n)
    return None if data is None else unpack_frame(data)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class TcpSink(MaelstromSink):
    """MaelstromSink over the socket transport, plus two codec shortcuts:

    * object-identity loopback: self-addressed traffic (the coordinator is
      a replica of everything it coordinates at rf=n) skips the
      encode->decode round trip entirely and is delivered as the original
      object on the next loop pass — exactly the sim sink's delivery
      semantics, worth ~1/3 of all codec work on a 3-node cluster;
    * raw payloads: in the binary wire modes, bodies carry the protocol
      message OBJECT and the frame codec serialises it in one native pass
      at flush time (host/wire.py pack_frame) — no intermediate structural
      tree at all.  Legacy JSON framing pre-encodes as before.

    The envelope framing for real peers stays MaelstromSink's, so the two
    transports cannot diverge."""

    _packs_objects = None  # resolved once per process (env-dependent)

    def _enc(self, request):
        packs = TcpSink._packs_objects
        if packs is None:
            from accord_tpu.host.wire import packs_objects
            packs = TcpSink._packs_objects = packs_objects()
        if packs:
            return request
        return super()._enc(request)

    def send(self, to, request) -> None:
        if to == self.host.my_id:
            if self._capture(to, None, request):
                return
            self.host.deliver_local(request, None)
            return
        super().send(to, request)

    def send_with_callback(self, to, request, callback,
                           executor=None) -> None:
        if to == self.host.my_id:
            msg_id = self._register(callback)
            if self._capture(to, msg_id, request):
                return
            self.host.deliver_local(request, msg_id)
            return
        super().send_with_callback(to, request, callback, executor)

    def _send_prepared(self, to, reply_context, request) -> None:
        if to == self.host.my_id:
            self.host.deliver_local(request, reply_context)
            return
        super()._send_prepared(to, reply_context, request)

    def reply(self, to, reply_context, reply) -> None:
        if to == self.host.my_id:
            if reply_context is not None:
                self.host.deliver_local_reply(reply_context, reply)
            return
        super().reply(to, reply_context, reply)


class SubmitResult:
    """Thread-safe completion handle for a submitted transaction."""

    def __init__(self):
        self._event = threading.Event()
        self.value = None
        self.failure: Optional[BaseException] = None

    def _complete(self, value, failure) -> None:
        self.value = value
        self.failure = failure
        self._event.set()

    def wait(self, timeout_s: float = 30.0) -> "SubmitResult":
        if not self._event.wait(timeout_s):
            self.failure = TimeoutError("txn did not complete")
        return self


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _trace_of(body: dict) -> Optional[str]:
    """The PR-2 trace id riding an accord payload — raw message object
    (binary modes) or encoded tree (JSON mode); the frame_coalesce flight
    events stamp the bundled messages' ids at the egress buffer."""
    payload = body.get("payload")
    if payload is None:
        return None
    if type(payload) is dict:
        fields = payload.get("f")
        if type(fields) is dict:
            tid = fields.get("trace_id")
            return tid if type(tid) is str else None
    tid = getattr(payload, "trace_id", None)
    return tid if type(tid) is str else None


class _PeerLane:
    """The outbound lane to one peer, owned by the event loop thread: a
    coalescing egress buffer (bodies awaiting the next flush tick), a FIFO
    of packed frames awaiting socket writability, and the non-blocking
    connection itself with backoff reconnect.

    Ordering contract: frames leave in enqueue order, always.  On a broken
    connection the partially-written head frame is resent IN FULL on the
    fresh connection (the peer's reader discarded the torn tail at EOF),
    so reconnection can duplicate a frame but never reorder or lose one
    silently — duplicates are idempotent at the protocol layer, exactly as
    with the old per-frame retry loop.  Only admission (buffer bound
    exceeded -> `shed`) and a peer that outlives the whole backoff
    schedule (`send_drops`, frames dropped whole) lose frames, degrading
    to the lossy-link model that RPC timeouts and the progress log heal.

    Obs: shed/send_drops/retries keep their PR-1 names; frames/msgs
    counters and the frame-size histograms are the coalescing-ratio
    surface the bench rows record."""

    shed = MetricView("accord_tcp_peer_shed_total")
    send_drops = MetricView("accord_tcp_peer_send_drops_total")
    retries = MetricView("accord_tcp_peer_retries_total")
    frames = MetricView("accord_tcp_frames_total")
    msgs = MetricView("accord_tcp_msgs_total")

    def __init__(self, host: "TcpHost", to: int):
        from accord_tpu.pipeline.backpressure import SendBackoff
        self.host = host
        self.to = to
        self.pending: List[dict] = []   # bodies awaiting the flush tick
        self.flush_at: Optional[float] = None
        self.frames_q: deque = deque()  # packed frames awaiting the socket
        self.head_off = 0               # bytes of frames_q[0] already sent
        self.buffered_bytes = 0
        # bytes held by the geo egress shim (frames waiting out their
        # injected one-way delay on the scheduler before joining frames_q);
        # counted against the admission bound so a WAN lane under load
        # still backpressures
        self.delayed_bytes = 0
        self.max_buffered = _env_int("ACCORD_TCP_PEER_BUF_BYTES", 8 << 20)
        self.max_pending = _env_int("ACCORD_TCP_PEER_INFLIGHT", 4096)
        self.sock: Optional[socket.socket] = None
        self.connecting = False
        self.backoff = SendBackoff()
        self.attempt = 0
        self._retry_timer = None
        registry = host.node.obs.registry
        bind_metric_views(self, registry, peer=to)
        self._g_buffered = registry.gauge("accord_tcp_peer_buffered_bytes",
                                          peer=to)
        self._h_frame_bytes = registry.histogram("accord_tcp_frame_bytes",
                                                 peer=to)
        self._h_frame_msgs = registry.histogram("accord_tcp_frame_msgs",
                                                peer=to)

    # ----------------------------------------------------------- egress --
    def enqueue(self, body: dict) -> None:
        if len(self.pending) >= self.max_pending \
                or self.buffered_bytes + self.delayed_bytes \
                > self.max_buffered:
            self.shed += 1  # backpressure: shed like a drop-tail link
            return
        self.pending.append(body)
        self.host.flight.record("frame_coalesce", _trace_of(body),
                                (self.to, len(self.pending)))
        if self.flush_at is None:
            tick = self.host.flush_tick_us
            # tick 0 flushes as soon as the producing dispatch returns; a
            # positive tick lets the frame wait for company so a burst
            # amortises into one syscall per peer.  Either way the
            # deadline is enforced DURING long dispatch passes (the loop
            # checks after every body), never only at pass end — an
            # egress buffer must add bounded latency, not pass-length
            # latency.
            self.flush_at = time.monotonic() + tick / 1e6 if tick else 0.0
            self.host.mark_dirty(self)

    def flush(self) -> None:
        """Close the coalescing window: everything pending leaves as ONE
        frame (single-body frames skip the multi-envelope key)."""
        bodies, self.pending = self.pending, []
        self.flush_at = None
        if not bodies:
            return
        if len(bodies) == 1:
            frame = {"src": self.host.my_id, "body": bodies[0]}
        else:
            frame = {"src": self.host.my_id, "m": bodies}
        data = pack_frame(frame)
        packed = _LEN.pack(len(data)) + data
        self.frames += 1
        self.msgs += len(bodies)
        self._h_frame_bytes.observe(len(data))
        self._h_frame_msgs.observe(len(bodies))
        self.host.flight.record("frame_flush", None,
                                (self.to, len(bodies), len(data)))
        # getattr: unit tests drive lanes with a minimal host stub that
        # predates the geo field
        geo = getattr(self.host, "geo", None)
        if geo is not None:
            cls = geo.link_class(self.host.my_id, self.to)
            if cls is not None:
                # per-link-class census with REAL frame bytes (the wan
                # report's WAN bytes/txn numerator)
                reg = self.host.node.obs.registry
                reg.counter("accord_link_msgs_total",
                            cls=cls).inc(len(bodies))
                reg.counter("accord_link_frames_total", cls=cls).inc()
                reg.counter("accord_link_bytes_total",
                            cls=cls).inc(len(packed))
                d = geo.one_way_nominal_us(self.host.my_id, self.to)
                if d:
                    # tc-free egress delay shim: hold the packed frame on
                    # the loop's own timer heap for the nominal one-way
                    # delay.  The delay is CONSTANT per pair and the heap
                    # is FIFO-stable on ties, so per-lane frame order is
                    # preserved.
                    self.delayed_bytes += len(packed)
                    self.host.scheduler.once(
                        d / 1e6, lambda p=packed: self._release(p))
                    return
        self.frames_q.append(packed)
        self.buffered_bytes += len(packed)
        self._g_buffered.value = self.buffered_bytes
        if self.sock is None and not self.connecting:
            self._connect()
        elif self.sock is not None and not self.connecting:
            self.drain()

    def _release(self, packed: bytes) -> None:
        """A geo-delayed frame served its injected one-way latency: move
        it onto the socket FIFO (loop thread — scheduler timers run in
        run_due)."""
        self.delayed_bytes -= len(packed)
        self.frames_q.append(packed)
        self.buffered_bytes += len(packed)
        self._g_buffered.value = self.buffered_bytes
        if self.sock is None and not self.connecting:
            self._connect()
        elif self.sock is not None and not self.connecting:
            self.drain()

    # ------------------------------------------------------- connection --
    def _connect(self) -> None:
        addr = self.host.peers.get(self.to)
        if addr is None:
            # peer address not (yet) known — an epoch install's `peers`
            # spec teaches it; until then back off like a dead link
            self._fail()
            return
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setblocking(False)
        self.sock = sock
        self.connecting = True
        try:
            rc = sock.connect_ex(addr)
        except OSError:
            self._fail()
            return
        if rc == 0:
            self._connected()
        else:
            # completion (or refusal) arrives as writability
            self.host.register(sock, selectors.EVENT_WRITE, self)

    def _connected(self) -> None:
        self.connecting = False
        self.attempt = 0
        # consensus rounds are small request/reply frames: Nagle +
        # delayed-ACK otherwise stalls each ~40ms
        try:
            self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self.host.register(self.sock, selectors.EVENT_READ, self)
        self.drain()

    def on_io(self, mask: int) -> None:
        """Selector event on this lane's socket."""
        if self.connecting:
            err = self.sock.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
            self.host.unregister(self.sock)
            if err != 0:
                self._fail()
                return
            self._connected()
            return
        if mask & selectors.EVENT_READ:
            # peers never send on our outbound connection: readability is
            # EOF/reset (recv b"" or an error) — tear down and reconnect
            try:
                if self.sock.recv(4096) == b"":
                    self._fail()
                    return
            except BlockingIOError:
                pass
            except OSError:
                self._fail()
                return
        if mask & selectors.EVENT_WRITE:
            self.drain()

    def drain(self) -> None:
        """Write as much of the frame FIFO as the socket accepts; keep
        EVENT_WRITE armed exactly while bytes remain."""
        sock = self.sock
        if sock is None or self.connecting:
            return
        try:
            while self.frames_q:
                head = self.frames_q[0]
                n = sock.send(head[self.head_off:] if self.head_off
                              else head)
                self.head_off += n
                self.buffered_bytes -= n
                if self.head_off >= len(head):
                    self.frames_q.popleft()
                    self.head_off = 0
        except BlockingIOError:
            pass
        except OSError:
            self._fail()
            return
        self._g_buffered.value = self.buffered_bytes
        want = selectors.EVENT_READ | (
            selectors.EVENT_WRITE if self.frames_q else 0)
        self.host.register(sock, want, self)

    def _fail(self) -> None:
        """Connection failed or broke: resend the head frame whole on the
        next connection (never a torn tail), back off, and after the
        whole schedule drop the buffered frames (lossy-link model)."""
        self._teardown()
        # the peer saw a torn (discarded) tail: restore head-frame bytes
        self.buffered_bytes += self.head_off
        self.head_off = 0
        self.attempt += 1
        delay = self.backoff.delay_s(self.attempt)
        if delay is None:
            dropped = len(self.frames_q)
            if dropped:
                self.send_drops += dropped  # dead peer: timeouts heal
                self.frames_q.clear()
                self.buffered_bytes = 0
                self._g_buffered.value = 0
            # keep probing a dead peer at the backoff cap so a restarted
            # process is rediscovered without a fresh frame having to pay
            # the whole schedule again
            self.attempt = self.backoff.max_attempts - 1
            delay = self.backoff.cap_s
        self.retries += 1
        self._retry_timer = self.host.scheduler.once(delay, self._retry)

    def _retry(self) -> None:
        self._retry_timer = None
        if self.sock is None and not self.connecting \
                and (self.frames_q or self.pending):
            self._connect()

    def _teardown(self) -> None:
        if self.sock is not None:
            self.host.unregister(self.sock)
            try:
                self.sock.close()
            except OSError:
                pass
        self.sock = None
        self.connecting = False

    def close(self) -> None:
        if self._retry_timer is not None:
            self._retry_timer.cancel()
        self._teardown()


class _InConn:
    """One accepted inbound connection: a read buffer and its incremental
    length-prefix frame parser (all on the loop thread)."""

    __slots__ = ("sock", "rbuf")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.rbuf = bytearray()

    def read_frames(self) -> Optional[List[dict]]:
        """Drain readable bytes and parse complete frames; None = close
        this connection (EOF, reset, or corrupt stream)."""
        try:
            while True:
                chunk = self.sock.recv(_RECV_CHUNK)
                if chunk == b"":
                    return None
                self.rbuf += chunk
                if len(chunk) < _RECV_CHUNK:
                    break
        except BlockingIOError:
            pass
        except OSError:
            return None
        frames = []
        buf = self.rbuf
        pos = 0
        try:
            while len(buf) - pos >= _LEN.size:
                (n,) = _LEN.unpack_from(buf, pos)
                if n > _MAX_FRAME:
                    return None
                if len(buf) - pos - _LEN.size < n:
                    break
                start = pos + _LEN.size
                frames.append(unpack_frame_obj(bytes(buf[start:start + n])))
                pos = start + n
        except (ValueError, UnicodeDecodeError):
            return None  # corrupt stream: drop the connection
        if pos:
            del buf[:pos]
        return frames


def _env_store_factory():
    """Optional batched-device command stores for the real-socket host:
    ACCORD_TCP_DEVICE_STORE=1 puts DeviceCommandStore behind every node
    (flush window ACCORD_TCP_FLUSH_US wall-clock µs, default 1000; inline
    scalar verification with ACCORD_TCP_DEVICE_VERIFY=1).  The same tier
    the burn exercises, demonstrated on the black-box transport."""
    if os.environ.get("ACCORD_TCP_DEVICE_STORE", "") != "1":
        return None
    from accord_tpu.utils.backend import resolve_platform
    resolve_platform()  # pin CPU if the tunneled device backend is dead
    # multi-process mode: every node process would otherwise pay the full
    # first-jit cost inside its dispatch loop (stalling peers' RPC rounds);
    # a persistent compilation cache amortizes it across processes and runs
    import jax
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("ACCORD_JAX_CACHE", "/tmp/accord_jax_cache"))
    except Exception:  # noqa: BLE001 — cache is an optimization only
        pass
    from accord_tpu.impl.device_store import DeviceCommandStore
    return DeviceCommandStore.factory(
        flush_window_us=int(os.environ.get("ACCORD_TCP_FLUSH_US", "1000")),
        verify=os.environ.get("ACCORD_TCP_DEVICE_VERIFY", "") == "1")


class TcpHost:
    """One Accord node bound to a TCP port, peered with `peers`
    (node_id -> (host, port), including itself)."""

    def __init__(self, my_id: int, peers: Dict[int, Tuple[str, int]],
                 rf: Optional[int] = None, n_shards: int = 4,
                 topology_ids: Optional[List[int]] = None):
        self.my_id = my_id
        self.peers = dict(peers)
        self._loop_tid: Optional[int] = None  # set once the loop starts:
        # everything emitted before then (journal replay, topology
        # install) marshals through call_soon and drains on the first tick
        self.scheduler = RealTimeScheduler()
        self.sink = TcpSink(self)
        # coalescing default-on: up to 1ms of company-waiting per frame
        # WHILE A BURST IS IN PROGRESS (the loop flushes everything the
        # moment it would otherwise go idle, so an unloaded request never
        # pays the tick); 0 flushes after every dispatched item
        # coalescing window default raised 1000 -> 2500us (ISSUE 10): on a
        # core-starved box every frame syscall is also a likely preemption
        # point for the peer processes, so deeper coalescing cuts protocol
        # CPU twice over (measured: ~1/3 fewer frames, +6% tcp lane, lower
        # per-verb dispatch p50s).  Unloaded latency is unaffected — the
        # loop still flushes immediately on idle.
        self.flush_tick_us = _env_int("ACCORD_TCP_FLUSH_TICK_US", 2500)
        self._out: Dict[int, _PeerLane] = {}
        self.running = True

        self.selector = selectors.DefaultSelector()
        self._calls: deque = deque()     # cross-thread entry (thread-safe)
        self._local_q: deque = deque()   # self-addressed bodies (loop only)
        self._bulk_backlog: deque = deque()  # deferred bulk-tier submits
        self._dirty: List[_PeerLane] = []  # lanes with an open flush tick
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self.selector.register(self._wake_r, selectors.EVENT_READ,
                               ("wake", None))

        self.server = socket.create_server(self.peers[my_id],
                                           reuse_port=False)
        # the OS may have assigned the port (port 0): record reality
        self.peers[my_id] = self.server.getsockname()
        self.server.setblocking(False)
        self.selector.register(self.server, selectors.EVENT_READ,
                               ("accept", None))

        # non-positive ids are CLIENT endpoints: they share the frame
        # transport (their replies travel as ordinary frames to their own
        # listening socket) but are not cluster members.  `topology_ids`
        # pins which members form EPOCH 1: a node joining an established
        # cluster mid-run (scale-out) must build the same genesis topology
        # the founders did — one that does NOT include it — and acquire
        # its ranges only through the epoch that assigns them.
        ids = (sorted(topology_ids) if topology_ids
               else sorted(i for i in self.peers if i > 0))
        rf = rf if rf is not None else min(3, len(ids))
        topology = build_topology(ids, rf, n_shards)

        from accord_tpu.local.node import Node
        agent = HostAgent()
        self.scheduler.on_error = agent.on_uncaught_exception
        self.node = Node(my_id, self.sink, agent, self.scheduler,
                         ListStore(my_id), RandomSource(my_id), num_shards=1,
                         store_factory=_env_store_factory(),
                         # time_ns // 1000: no float round-trip — this
                         # clock runs per flight/span event, not just per
                         # HLC mint
                         now_us=lambda: time.time_ns() // 1000)
        self.flight = self.node.obs.flight
        # always-on event-loop health telemetry (obs/cpuprof.LoopHealth):
        # timer-lag histogram via the scheduler hook, tick/burst/backlog
        # gauges from _tick, loop_lag/queue_saturation flight alarms
        from accord_tpu.obs.cpuprof import LoopHealth
        self.loop_health = LoopHealth(self.node.obs.registry, self.flight)
        self.scheduler.lag_observer = self.loop_health.timer_lag
        # ACCORD_SHARDS=<n> (n >= 2): per-shard worker runtime (shard/) —
        # the node's command stores live in n forked worker processes, one
        # event loop + one store + one WAL band each, and this host's
        # command_stores becomes the supervisor-side router.  Unset or 1:
        # the in-loop CommandStores built by Node above is untouched —
        # bit-identical to the pre-shard wiring.  The swap happens BEFORE
        # report_topology below so the genesis install drives spawn_all().
        from accord_tpu import shard as _shard
        self.shard_supervisor = None
        _n_workers = _shard.workers_from_env()
        if _n_workers:
            from accord_tpu.shard.supervisor import (ShardSupervisor,
                                                     WorkerCommandStores)
            self.shard_supervisor = ShardSupervisor(self, self.node,
                                                    _n_workers)
            self.node.command_stores = WorkerCommandStores(
                self.node, self.shard_supervisor)
            # HLC striping: parent mints stripe 0, worker k stripe k+1,
            # all mod n+1 — timestamps stay unique across the processes
            # sharing this node id without coordination
            self.node.set_hlc_stripe(0, _n_workers + 1)
        # topology flows through a real ConfigurationService (the admin
        # plane's epoch ledger): installs gossip peer-to-peer, gaps heal
        # via TOPOLOGY_FETCH, and `peers` specs riding an install teach
        # this transport new nodes' addresses (scale-out)
        from accord_tpu.impl.config_service import LedgerConfigService
        from accord_tpu.messages.admin import EpochInstall
        self.config_service = LedgerConfigService(
            my_id, peers_hook=self._merge_peers,
            geo_hook=self._install_geo_wire)
        self.config_service.attach_node(self.node)
        self.config_service.remember_spec(EpochInstall.from_topology(topology))
        self.config_service.report_topology(topology)

        # ACCORD_GEO=<json spec>: geo placement profile (topology/geo.py)
        # — DC labels on this node's obs and a tc-free egress delay shim
        # injecting the nominal one-way latency per peer lane.  A profile
        # riding a later EpochInstall frame replaces it cluster-wide.
        self.geo = None
        from accord_tpu.topology.geo import GeoProfile
        geo_env = GeoProfile.from_env(os.environ.get("ACCORD_GEO"))
        if geo_env is not None:
            self.install_geo_profile(geo_env)

        # ACCORD_JOURNAL=<dir>: durable write-ahead journal under
        # <dir>/node-<id> — existing state replays into the node BEFORE any
        # peer traffic is accepted, every side-effecting request is
        # journaled before its ack, and (group-commit mode) acks are gated
        # on the covering fsync by DurableAckSink (whose flush thread
        # re-enters emit(): cross-thread sends marshal onto the loop).
        from accord_tpu.journal import attach_journal_from_env
        self.wal = attach_journal_from_env(self.node)

        # ACCORD_QOS=1: per-tenant QoS admission tier (qos/) — pressure-
        # adaptive shed before any journal/coordination state is spent,
        # fed by the loop-health lag signal (the lag observer chains: both
        # callbacks run on the loop thread) and the WAL's group-commit
        # backlog.  Default off: with the gate unset the lag observer and
        # submit path are byte-for-byte the pre-QoS wiring.
        from accord_tpu.qos import qos_tier_from_env
        self.qos = qos_tier_from_env(
            self.node.obs.registry, self.flight,
            clock_us=lambda: time.time_ns() // 1000,
            loop_health=self.loop_health, wal=self.wal,
            n_shards=_n_workers)
        if self.qos is not None:
            lh_hook, qos_hook = self.loop_health.timer_lag, self.qos.observe_lag

            def _lag_chain(lag_s, _lh=lh_hook, _qos=qos_hook):
                _lh(lag_s)
                _qos(lag_s)
            self.scheduler.lag_observer = _lag_chain

        # ACCORD_PIPELINE=1: continuous micro-batching ingest — client
        # submissions coalesce into deadline-bounded batches whose fan-out
        # leaves as one MultiPreAccept envelope per replica (and whose
        # self-addressed slice the device store resolves as one fused
        # probe window).  Default off.
        from accord_tpu.pipeline import (Pipeline, PipelineConfig,
                                         pipeline_enabled)
        self.pipeline = Pipeline(self.node, self.scheduler,
                                 PipelineConfig.from_env(), qos=self.qos) \
            if pipeline_enabled() else None

        # ACCORD_METRICS_PORT=<base>: Prometheus text + JSON snapshot on
        # base + node_id - 1 (per-process port offset); 0 = ephemeral
        from accord_tpu.obs.httpd import maybe_start_from_env
        self.metrics_server = maybe_start_from_env(lambda: self.node.obs,
                                                   node_id=my_id)

        # ACCORD_AUDIT_S=<s>: periodic replica-state audit + lifecycle
        # census (local/audit.py) — cross-replica range digests over the
        # AUDIT_* verbs every <s> seconds, divergences and census served
        # at the "audit" frame and the metrics endpoint's /audit route.
        # Default on at 5 s; 0 disables.
        from accord_tpu.local.audit import auditor_from_env
        self.auditor = auditor_from_env(self.node)

        self.loop_thread = threading.Thread(target=self._run, daemon=True)
        self.loop_thread.start()

    # ------------------------------------------------------ selector glue --
    def register(self, sock: socket.socket, events: int,
                 lane: "_PeerLane") -> None:
        """Register-or-modify a lane socket (loop thread only)."""
        try:
            key = self.selector.get_key(sock)
        except KeyError:
            self.selector.register(sock, events, ("peer", lane))
            return
        if key.events != events:
            self.selector.modify(sock, events, ("peer", lane))

    def unregister(self, sock: socket.socket) -> None:
        try:
            self.selector.unregister(sock)
        except (KeyError, ValueError):
            pass

    def _wakeup(self) -> None:
        try:
            self._wake_w.send(b"\x01")
        except (BlockingIOError, OSError):
            pass  # a wakeup is already pending (or we are shutting down)

    def call_soon(self, fn) -> None:
        """Run `fn` on the event loop thread (any thread may call)."""
        self._calls.append(fn)
        self._wakeup()

    # ------------------------------------------------------------- egress --
    def emit(self, to: int, body: dict) -> None:
        """Queue one message body for `to`.  On the loop thread this lands
        directly in the peer's coalescing buffer (self-addressed bodies
        skip the loopback round trip entirely); other threads (the WAL's
        group-commit flush thread releasing durability-gated replies)
        marshal onto the loop first — sockets and lanes have exactly one
        owning thread."""
        if threading.get_ident() != self._loop_tid:
            self.call_soon(lambda: self.emit(to, body))
            return
        if to == self.my_id:
            self._local_q.append(
                lambda: self._dispatch(self.my_id, body))
            return
        lane = self._out.get(to)
        if lane is None:
            lane = self._out[to] = _PeerLane(self, to)
        lane.enqueue(body)

    # MaelstromSink's transport hook (shared sink implementation)
    def emit_node(self, to: int, body: dict) -> None:
        self.emit(to, body)

    # object-identity loopback (TcpSink): self-addressed protocol traffic
    # is delivered as the ORIGINAL message object on the next loop pass —
    # deferred, never reentrant into whatever is currently dispatching
    def deliver_local(self, request, msg_id) -> None:
        if threading.get_ident() != self._loop_tid:
            self.call_soon(lambda: self.deliver_local(request, msg_id))
            return
        self._local_q.append(
            lambda: self.node.receive(request, self.my_id, msg_id))

    def deliver_local_reply(self, reply_context, reply) -> None:
        if threading.get_ident() != self._loop_tid:
            self.call_soon(
                lambda: self.deliver_local_reply(reply_context, reply))
            return
        self._local_q.append(
            lambda: self.sink.deliver_reply(reply_context, self.my_id,
                                            reply))

    # ---------------------------------------------------------------- loop --
    def _run(self) -> None:
        prof_path = os.environ.get("ACCORD_TCP_PROFILE")
        if not prof_path:
            return self._run_loop()
        # profile the node's single dispatch thread (where all protocol
        # work happens) — the BASELINE host-tier binding-constraint
        # analysis reads these dumps
        import cProfile
        pr = cProfile.Profile()
        try:
            pr.runcall(self._run_loop)
        finally:
            pr.dump_stats(f"{prof_path}.{self.my_id}")

    def _run_loop(self) -> None:
        self._loop_tid = threading.get_ident()
        try:
            while self.running:
                self._tick()
        finally:
            self._shutdown_sockets()

    def mark_dirty(self, lane: _PeerLane) -> None:
        self._dirty.append(lane)

    def _flush_due(self, now: Optional[float] = None) -> None:
        """Flush every lane whose coalescing tick has elapsed.  Called
        after EVERY dispatched body (not just at pass end): a long burst
        must not stretch the egress hold beyond the configured tick — the
        buffer's latency contribution is bounded by the knob, period."""
        dirty = self._dirty
        if not dirty:
            return
        if not self.flush_tick_us:
            self._dirty = []
            for lane in dirty:
                lane.flush()
            return
        if now is None:
            now = time.monotonic()
        keep = []
        for lane in dirty:
            if lane.flush_at is None:
                continue
            if lane.flush_at <= now:
                lane.flush()
            else:
                keep.append(lane)
        self._dirty = keep

    def _tick(self) -> None:
        t_start = time.perf_counter()
        # 1. due timers run BEFORE blocking: a due-now deadline must never
        #    be floored into a sleep (the old loop's `or 0.01` cost 10ms
        #    of timer latency exactly when a deadline was already due).
        #    Timers emit too (RPC timeouts, pipeline batch dispatch):
        #    flush what they produced.
        ran_timers = self.scheduler.run_due()
        if ran_timers:
            self._flush_due()

        # 2. cross-thread calls (submits, WAL-released replies)
        work = False
        while self._calls:
            work = True
            self._safe(self._calls.popleft())
        if work:
            self._flush_due()

        # 3. poll: the nearest timer deadline is the timeout; pending
        #    local work polls without blocking.  About to go IDLE with
        #    frames still held open? Nothing else is coming that could
        #    join them — flush now, so the coalescing tick only ever
        #    delays frames while a burst is actually in progress.
        timeout = self._poll_timeout(work)
        if timeout > 0.0 and self._dirty:
            self._flush_all()
        busy_pre = time.perf_counter() - t_start
        try:
            events = self.selector.select(timeout)
        except OSError:
            return  # selector torn down under us during shutdown
        t_resume = time.perf_counter()

        # 4. IO: collect every complete inbound frame this pass produced
        #    (plus deferred loopback deliveries), then dispatch the burst
        #    under one sink coalescing window (pipeline mode) so
        #    same-destination fan-out amortises
        items: List = []
        # QoS priority lane: within one select pass's burst, bulk-tier
        # client submits are dispatched AFTER everything else — protocol
        # messages (they advance already-admitted txns, including the
        # high class's rounds) and high-class submits must not queue
        # behind an overload flood's decode+nack work.  Order within
        # each lane is preserved; with QoS off the single FIFO is
        # untouched.
        bulk: List = []

        def _enqueue(src: int, body: dict) -> None:
            if (self.qos is not None and body.get("type") == "submit"
                    and body.get("priority") != "high"):
                bulk.append(lambda s=src, b=body: self._dispatch(s, b))
            else:
                items.append(lambda s=src, b=body: self._dispatch(s, b))

        for key, mask in events:
            kind, payload = key.data
            if kind == "wake":
                try:
                    self._wake_r.recv(4096)
                except (BlockingIOError, OSError):
                    pass
            elif kind == "accept":
                self._accept()
            elif kind == "peer":
                payload.on_io(mask)
            elif kind == "conn":
                frames = payload.read_frames()
                if frames is None:
                    self._drop_conn(payload)
                else:
                    for frame in frames:
                        src = frame.get("src", 0)
                        if "m" in frame:
                            for body in frame["m"]:
                                _enqueue(src, body)
                        else:
                            _enqueue(src, frame.get("body", {}))
        while self._local_q:
            items.append(self._local_q.popleft())
        # bounded bulk drain: at most _BULK_PER_PASS bulk submits join
        # this pass; the rest wait in the loop-owned backlog.  Keeps
        # every pass short under an overload flood so the selector (and
        # with it protocol messages, high submits, timers) is serviced
        # every few milliseconds — a deferred bulk submit is simply
        # admitted-or-nacked a pass or two later, which its retry_after
        # already accounts for.  The backlog feeds loop-health's
        # saturation signal below, so deferral itself raises pressure.
        if bulk:
            self._bulk_backlog.extend(bulk)
        if self._bulk_backlog:
            take = min(len(self._bulk_backlog), _BULK_PER_PASS)
            for _ in range(take):
                items.append(self._bulk_backlog.popleft())

        coalesce = self.pipeline is not None and len(items) > 1
        if coalesce:
            self.sink.batch_begin()
        try:
            for item in items:
                self._safe(item)
                # bounded egress hold: a reply produced by item #1 of a
                # 50-item burst leaves now, not after item #50
                self._flush_due()
        finally:
            if coalesce:
                self.sink.batch_flush()
        self._flush_due()
        if items or ran_timers or work:
            # loop health: busy time (blocking poll excluded), burst
            # length, and the backlog this pass left undrained — the
            # saturation signal (obs/cpuprof.LoopHealth); idle passes
            # record nothing
            self.loop_health.tick(
                busy_pre + (time.perf_counter() - t_resume), len(items),
                len(self._calls) + len(self._local_q)
                + len(self._bulk_backlog))

    def _flush_all(self) -> None:
        dirty, self._dirty = self._dirty, []
        for lane in dirty:
            lane.flush()

    def _poll_timeout(self, have_work: bool) -> float:
        if have_work or self._local_q or self._calls or self._bulk_backlog:
            return 0.0
        deadline = self.scheduler.next_deadline()
        return 0.2 if deadline is None \
            else min(max(0.0, deadline - time.monotonic()), 0.2)

    def _safe(self, fn) -> None:
        try:
            fn()
        except Exception as e:  # noqa: BLE001 — one bad frame/callback
            # must never kill the node's only loop thread.  stderr: the
            # parent reads stdout exactly once (the ready line) — a full
            # stdout pipe would block this, the node's ONLY thread
            import sys as _sys
            print(f"tcp host n{self.my_id} dispatch error: {e!r}",
                  file=_sys.stderr, flush=True)

    def _accept(self) -> None:
        while True:
            try:
                sock, _addr = self.server.accept()
            except (BlockingIOError, OSError):
                return
            sock.setblocking(False)
            conn = _InConn(sock)
            try:
                self.selector.register(sock, selectors.EVENT_READ,
                                       ("conn", conn))
            except (KeyError, ValueError):
                pass

    def _drop_conn(self, conn: _InConn) -> None:
        self.unregister(conn.sock)
        try:
            conn.sock.close()
        except OSError:
            pass

    def _shutdown_sockets(self) -> None:
        for key in list(self.selector.get_map().values()):
            kind, payload = key.data
            if kind == "conn":
                self._drop_conn(payload)
        for lane in self._out.values():
            lane.close()
        self._out.clear()
        try:
            self.selector.close()
        except OSError:
            pass

    # ----------------------------------------------------------- dispatch --
    def _dispatch(self, from_id: int, body: dict) -> None:
        kind = body.get("type")
        if kind == "submit":
            # client txn over the wire (multi-process bench/harness path)
            self._client_submit(from_id, body)
            return
        if kind == "metrics":
            # harness/client JSON snapshot fetch (bench.py records these
            # alongside its BENCH_HISTORY rows); client-endpoint src only
            if from_id <= 0:
                self.emit(from_id, {"type": "metrics_reply",
                                    "req": body.get("req"),
                                    "snapshot": self.node.obs.snapshot()})
            return
        if kind == "flight":
            # live forensics view over the frame transport: the node's
            # flight-recorder tail, or one trace id's events (the same
            # data the metrics endpoint serves at /flight?txn=)
            if from_id <= 0:
                flight = self.node.obs.flight
                txn = body.get("txn")
                events = (flight.for_trace(txn) if txn
                          else flight.tail(int(body.get("limit", 200))))
                self.emit(from_id, {
                    "type": "flight_reply", "req": body.get("req"),
                    "node": self.my_id,
                    "recorded_total": flight.recorded_total,
                    "events": [list(e) for e in events]})
            return
        if kind == "audit":
            # live replica-state audit view over the frame transport:
            # divergences, the last digest-round report, and the census
            # (same data the metrics endpoint serves at /audit)
            if from_id <= 0:
                view = (self.auditor.view() if self.auditor is not None
                        else {})
                self.emit(from_id, {"type": "audit_reply",
                                    "req": body.get("req"),
                                    "node": self.my_id, "audit": view})
            return
        if kind == "top":
            # live protocol-CPU waterfall + loop health over the frame
            # transport (obs/cpuprof.py; the same data the metrics
            # endpoint serves at /top); client-endpoint src only
            if from_id <= 0:
                self.emit(from_id, {"type": "top_reply",
                                    "req": body.get("req"),
                                    "node": self.my_id,
                                    "top": self.node.obs.cpu_view()})
            return
        if kind == "stop":
            # accept stop only from harness/client frames (non-positive
            # declared src).  NOTE: src is self-declared — this guards
            # against misdirected frames from well-behaved nodes, not
            # against a hostile peer (which could claim src 0).  This
            # transport is a localhost bench harness; real deployments
            # need authenticated connections before trusting any frame.
            if from_id <= 0:
                self.running = False
            return
        if kind == "epoch":
            # admin plane: propose a topology epoch (journaled before the
            # ack; gossips to every member, so ONE admin contact suffices)
            if from_id <= 0:
                self._admin_epoch(from_id, body)
            return
        if kind == "topology":
            # routing refresh for clients: the current topology spec
            if from_id <= 0:
                self.emit(from_id, {"type": "topology_reply",
                                    "req": body.get("req"),
                                    "node": self.my_id,
                                    "topology": self._topology_spec()})
            return
        if kind == "shards":
            # shard-worker runtime view: per-worker pid/generation/live
            # rows from the supervisor (empty when in-loop); the crash
            # nemesis uses the pids to aim its SIGKILL
            if from_id <= 0:
                sup = self.shard_supervisor
                self.emit(from_id, {"type": "shards_reply",
                                    "req": body.get("req"),
                                    "node": self.my_id,
                                    "shards": (sup.admin_view()
                                               if sup is not None else [])})
            return
        if kind == "drain":
            # admin plane: scale-in — fence, hand off, wait durability,
            # retire without losing an ack
            if from_id <= 0:
                self._admin_drain(from_id, body)
            return
        payload = body["payload"]
        if type(payload) is dict:
            # tree payload (JSON frame or Python-tier unpack): decode here;
            # the native ingress already delivered the message object.
            # Under ACCORD_CPU_PROFILE the decode lap is parked on the
            # profiler so the next dispatch attributes it (the native
            # tier's frame-level unpack shows in the loop tick gauge)
            prof = self.node.obs.cpuprof
            if prof.enabled:
                t0 = time.perf_counter()
                payload = decode_message(payload)
                prof.note_decode(time.perf_counter() - t0)
            else:
                payload = decode_message(payload)
        if "in_reply_to" in body:
            self.sink.deliver_reply(body["in_reply_to"], from_id, payload)
        else:
            self.node.receive(payload, from_id, body.get("msg_id"))

    # -------------------------------------------------------- admin plane --
    def _merge_peers(self, peers) -> None:
        """An epoch install's `peers` spec taught us addresses (a node
        joining in that epoch): merge them so lazily-created lanes can
        connect.  Specs may carry a 4th element (the peer's DC under a geo
        profile) — placement itself comes from the profile, so the tag is
        informational here.  Loop thread (installs arrive via dispatch)."""
        for spec in peers:
            nid, host, port = int(spec[0]), spec[1], int(spec[2])
            if nid != self.my_id:
                self.peers[nid] = (host, port)

    def _install_geo_wire(self, geo) -> None:
        """Config-service hook: a geo profile arrived on an EpochInstall
        frame (GeoProfile.to_wire form)."""
        from accord_tpu.topology.geo import GeoProfile
        self.install_geo_profile(GeoProfile.from_wire(geo))

    def install_geo_profile(self, profile) -> None:
        """Install/replace the geo placement profile: per-peer egress
        delay shim (see _PeerLane.flush — frames wait out the nominal
        one-way delay on the loop's own timer heap, no `tc`, no root) and
        dc= labels on this node's coordination obs."""
        self.geo = profile
        dc = profile.dc_of(self.my_id)
        self.node.obs.set_dc(dc)
        self.flight.record("geo_install", None, (profile.name, dc))

    def _topology_spec(self) -> dict:
        topo = self.node.topology.current()
        return {"epoch": topo.epoch,
                "shards": [[s.range.start, s.range.end,
                            list(s.sorted_nodes)] for s in topo.shards]}

    def _admin_epoch(self, from_id: int, body: dict) -> None:
        """`{"type":"epoch","topology":{...}}`: build the EpochInstall and
        feed it through normal dispatch — journaled (has_side_effects)
        BEFORE the ack below, applied via the config service's immutable
        topology swap, then gossiped to every member."""
        from accord_tpu.messages.admin import EpochInstall
        spec = body.get("topology", {})
        peers = spec.get("peers")
        geo = spec.get("geo")
        if geo:
            # JSON spec dict -> canonical wire tuples
            from accord_tpu.topology.geo import GeoProfile
            geo = GeoProfile.from_spec(geo)
        install = EpochInstall(
            int(spec["epoch"]),
            [(s[0], s[1], tuple(s[2])) for s in spec["shards"]],
            peers=[tuple(p) for p in peers] if peers else None,
            geo=geo or None)
        self.node.receive(install, 0, None)

        def ack():
            # emit marshals back to the loop, so firing from the WAL
            # flush thread is safe
            self.emit(from_id, {"type": "epoch_ok", "req": body.get("req"),
                                "node": self.my_id,
                                "epoch": self.node.epoch})

        if self.wal is not None:
            # persist-before-ack: the install survives us.  sync_soon
            # keeps the loop thread free while the flush thread works —
            # a blocking wal.sync() here stalls every peer connection.
            self.wal.sync_soon(ack)
        else:
            ack()

    def _admin_drain(self, from_id: int, body: dict) -> None:
        """`{"type":"drain"}`: scale-in this node.  DrainBegin fences new
        client coordination (journaled: a crashed drainer comes back
        fenced) and tells peers to deprioritize us as a fetch source; then
        we wait for in-flight coordinations to settle, raise a GLOBAL_SYNC
        durability barrier over our ranges, and only then ack + DrainDone."""
        from accord_tpu.messages.admin import DrainBegin, DrainDone
        node = self.node
        req = body.get("req")
        topology = node.topology.current()
        members = sorted(n for n in topology.nodes() if n != self.my_id)
        node.receive(DrainBegin(self.my_id), 0, None)
        for to in members:
            node.send(to, DrainBegin(self.my_id))
        deadline = time.monotonic() + float(body.get("timeout_s", 60.0))

        def finish(_v=None, failure=None):
            node.receive(DrainDone(self.my_id), 0, None)
            for to in members:
                node.send(to, DrainDone(self.my_id))

            def ack():
                # every acked write is on disk before we go; emit
                # marshals to the loop so the flush thread may fire this
                self.emit(from_id, {"type": "drain_ok", "req": req,
                                    "node": self.my_id,
                                    "durable": failure is None})

            if self.wal is not None:
                self.wal.sync_soon(ack)
            else:
                ack()

        def durability_barrier():
            owned = topology.ranges_for_node(self.my_id)
            if owned.is_empty:
                # the current epoch already moved everything away; older
                # in-flight work still needs the watermark — barrier all
                from accord_tpu.primitives.keys import Ranges
                owned = Ranges([s.range for s in topology.shards])
            from accord_tpu.coordinate.syncpoint import BarrierType, barrier
            barrier(node, owned, BarrierType.GLOBAL_SYNC) \
                .add_callback(finish)

        self._drain_wait_idle(durability_barrier, deadline)

    def _drain_wait_idle(self, then, deadline: float) -> None:
        """Hand off in-flight work: poll until nothing this node is
        coordinating remains (new client work is already fenced)."""
        if not self.node.coordinating or time.monotonic() >= deadline:
            then()
            return
        self.scheduler.once(0.05,
                            lambda: self._drain_wait_idle(then, deadline))

    def _client_submit(self, from_id: int, body: dict) -> None:
        req = body.get("req")
        if self.node.draining:
            # drain fence: never coordinated, safe for the client to remap
            # to another coordinator (openloop counts these as shed)
            self.emit(from_id, {"type": "submit_reply", "req": req,
                                "ok": False, "error": "draining",
                                "shed": True, "drained": True})
            return
        if self.qos is not None:
            # QoS outer ring: admission BEFORE journal append/coordination
            # state is spent — the nack is retriable by construction and
            # carries the backoff hint the client honors.  Under the
            # worker runtime the submit is also charged against its home
            # shard's (tenant, shard) sub-bucket — the shard the router
            # would dispatch it to, derived from the same key set
            shard = None
            if self.qos.n_shards:
                toks = (set(body.get("reads", []))
                        | {int(t) for t in body.get("appends", {})})
                if toks:
                    shard = self.node.command_stores.shard_of(Keys.of(*toks))
            nack = self.qos.admit(str(body.get("tenant") or ""),
                                  str(body.get("priority") or "normal"),
                                  shard=shard)
            if nack is not None:
                self.emit(from_id, {"type": "submit_reply", "req": req,
                                    "ok": False, "error": repr(nack),
                                    "shed": True, "qos": True,
                                    "reason": nack.reason,
                                    "retry_after_us": nack.retry_after_us})
                return
        want_phases = bool(body.get("phases"))

        def done(value, failure):
            from accord_tpu.pipeline.backpressure import Rejected
            if self.qos is not None:
                # admitted op settled (either way): shrink the tier's
                # inflight backlog signal
                self.qos.op_done()
            reads = {}
            if failure is None and value is not None:
                reads = {k.token: list(v)
                         for k, v in value.read_values.items()}
            reply = {"type": "submit_reply", "req": req,
                     "ok": failure is None,
                     "error": repr(failure) if failure else None,
                     "reads": reads}
            if isinstance(failure, Rejected):
                # typed load-shed: never coordinated, safe to retry
                reply["shed"] = True
            if want_phases and failure is None and value is not None \
                    and getattr(value, "txn_id", None) is not None:
                # per-phase SLO attribution for the open-loop harness
                # (workload/openloop.py): the coordinator's span milestone
                # firsts ride back on the reply — timestamps are this
                # node's clock (time.time()-us, same machine as the
                # harness), so the client can join them against its
                # intended-start ledger without a second round trip
                from accord_tpu.obs.spans import phase_firsts, trace_key
                span = self.node.obs.spans.get(trace_key(value.txn_id))
                reply["phases"] = [[ph, at]
                                   for ph, at in phase_firsts(span)]
            self.emit(from_id, reply)

        try:
            read_tokens = body.get("reads", [])
            appends = {int(t): v for t, v in body.get("appends", {}).items()}
            txn = _build_list_txn(read_tokens, appends,
                                  ephemeral=body.get("kind") == "ephemeral")
            self._coordinate(txn).add_callback(done)
        except BaseException as e:  # noqa: BLE001
            done(None, e)

    def _coordinate(self, txn: Txn):
        """Client txn entry: through the ingest pipeline when enabled."""
        if self.pipeline is not None:
            return self.pipeline.submit(txn)
        return self.node.coordinate(txn)

    # -------------------------------------------------------------- client --
    def submit(self, read_tokens, appends: Dict[int, int]) -> SubmitResult:
        """Client entry from ANY thread: list-register read/append txn."""
        result = SubmitResult()

        def run():
            try:
                txn = _build_list_txn(read_tokens, appends)
                self._coordinate(txn).add_callback(result._complete)
            except BaseException as e:  # noqa: BLE001 — the client must see
                result._complete(None, e)  # the real error, not a timeout

        self.call_soon(run)
        return result

    def close(self) -> None:
        self.running = False
        self._wakeup()
        if self.auditor is not None:
            self.auditor.stop()
        if self.shard_supervisor is not None:
            try:
                self.shard_supervisor.close()  # retire workers: final
            except Exception:  # noqa: BLE001 — teardown must not raise
                pass           # fsync per band rides ShardRetire
        if self.wal is not None:
            try:
                self.wal.close()  # final fsync: nothing acked is lost
            except Exception:  # noqa: BLE001 — teardown must not raise
                pass
        if self.metrics_server is not None:
            try:
                self.metrics_server.shutdown()
            except Exception:  # noqa: BLE001 — teardown must not raise
                pass
        try:
            self.server.close()
        except OSError:
            pass
        self.loop_thread.join(timeout=5.0)


# --------------------------------------------------- multi-process cluster --

def _free_ports(n: int):
    """Pre-select n distinct free localhost ports (bind-then-close; the
    tiny reuse race is acceptable for local harnesses)."""
    socks = [socket.create_server(("127.0.0.1", 0)) for _ in range(n)]
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


class TcpClusterClient:
    """Client endpoint (pseudo-node 0) for a cluster of OS-process TcpHost
    nodes: spawns the workers, speaks the same length-prefixed frame codec,
    and collects submit replies — SURVEY §5.8's comm backend driven
    end-to-end over real sockets with one GIL per node.

    `pin_cpus` maps node id -> cpu index: each worker process pins itself
    with sched_setaffinity before serving (the multicore bench lane's
    one-core-per-node discipline)."""

    def __init__(self, n_nodes: int = 3, n_shards: int = 4,
                 pin_cpus: Optional[Dict[int, int]] = None):
        import json as _json
        import queue
        import subprocess
        import sys as _sys
        ports = _free_ports(n_nodes + 1)
        self.peers = {i: ("127.0.0.1", ports[i]) for i in range(n_nodes + 1)}
        self.n_shards = n_shards
        # the founding membership: nodes added later (add_node) must build
        # the founders' epoch-1 topology, not one that includes themselves
        self._seed_ids = list(range(1, n_nodes + 1))
        # routing spec cache for owner_of (refresh_topology updates it)
        self.topology_spec: Optional[dict] = None
        self.server = socket.create_server(self.peers[0], reuse_port=False)
        self.inbox: "queue.Queue" = queue.Queue()
        self.running = True
        threading.Thread(target=self._accept_loop, daemon=True).start()
        self.procs = []
        spec_peers = {str(i): list(p) for i, p in self.peers.items()}
        try:
            for i in range(1, n_nodes + 1):
                spec = {"id": i, "peers": spec_peers, "n_shards": n_shards}
                if pin_cpus and i in pin_cpus:
                    spec["cpu"] = pin_cpus[i]
                self.procs.append(subprocess.Popen(
                    [_sys.executable, "-m", "accord_tpu.host.tcp",
                     _json.dumps(spec)],
                    stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                    text=True))
            for p in self.procs:
                line = p.stdout.readline()  # ready marker
                assert line.strip(), "tcp worker failed to start"
        except BaseException:
            for p in self.procs:  # a failed spawn must not orphan the rest
                p.kill()
            raise
        self._out: Dict[int, socket.socket] = {}
        # one client endpoint may be driven from two threads (the open-loop
        # pacer and the reshard admin driver): serialize socket writes so
        # frames never interleave mid-write
        self._send_lock = threading.Lock()

    def _accept_loop(self) -> None:
        while self.running:
            try:
                conn, _ = self.server.accept()
            except OSError:
                return
            threading.Thread(target=self._reader, args=(conn,),
                             daemon=True).start()

    def _reader(self, conn: socket.socket) -> None:
        try:
            while self.running:
                frame = _recv_frame(conn)
                if frame is None:
                    return
                if "m" in frame:
                    # the node coalesces replies to this client endpoint
                    # exactly as it does to peers: unwrap per body
                    for body in frame["m"]:
                        self.inbox.put({"src": frame.get("src"),
                                        "body": body})
                else:
                    self.inbox.put(frame)
        except (OSError, ValueError):
            return

    def _send(self, to: int, body: dict) -> None:
        with self._send_lock:
            sock = self._out.get(to)
            if sock is None:
                sock = self._out[to] = socket.create_connection(
                    self.peers[to], timeout=10.0)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            _send_frame(sock, {"src": 0, "body": body})

    def submit(self, to: int, reads, appends: Dict[int, int], req,
               ephemeral: bool = False, want_phases: bool = False,
               tenant: str = "", priority: str = "") -> None:
        body = {"type": "submit", "req": req, "reads": list(reads),
                "appends": {str(k): v for k, v in appends.items()}}
        if ephemeral:
            body["kind"] = "ephemeral"
        if want_phases:
            body["phases"] = True
        if tenant:
            body["tenant"] = tenant
        if priority:
            body["priority"] = priority
        self._send(to, body)

    def qos_backoff_us(self, reply_body: dict, attempt: int = 1,
                       rng=None) -> int:
        """Honor a QoS nack's `retry_after_us` hint with decorrelating
        jitter: hint * 2^(attempt-1), plus 0..50% extra so a shed burst of
        clients does not reconverge on the same instant."""
        base = int(reply_body.get("retry_after_us") or 10_000)
        base = min(2_000_000, base * (2 ** max(0, attempt - 1)))
        if rng is None:
            import random as _random
            rng = _random
        return base + int(rng.random() * 0.5 * base)

    def recv(self, timeout_s: float = 30.0) -> Optional[dict]:
        import queue
        try:
            return self.inbox.get(timeout=timeout_s)
        except queue.Empty:
            return None

    def fetch_metrics(self, to: int, timeout_s: float = 15.0
                      ) -> Optional[dict]:
        """Pull node `to`'s obs snapshot over the frame transport (use only
        when no submit replies are outstanding — stray frames between the
        request and its reply are consumed and dropped)."""
        req = f"metrics-{to}"
        try:
            self._send(to, {"type": "metrics", "req": req})
        except OSError:
            return None
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            frame = self.recv(min(1.0, timeout_s))
            if frame is None:
                continue
            body = frame.get("body", {})
            if body.get("type") == "metrics_reply" and body.get("req") == req:
                return body.get("snapshot")
        return None

    def fetch_flight(self, to: int, txn=None, limit: int = 200,
                     timeout_s: float = 15.0) -> Optional[dict]:
        """Pull node `to`'s flight-recorder view over the frame transport
        (same quiet-channel caveat as fetch_metrics)."""
        req = f"flight-{to}"
        frame = {"type": "flight", "req": req, "limit": limit}
        if txn is not None:
            frame["txn"] = txn
        try:
            self._send(to, frame)
        except OSError:
            return None
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            got = self.recv(min(1.0, timeout_s))
            if got is None:
                continue
            body = got.get("body", {})
            if body.get("type") == "flight_reply" and body.get("req") == req:
                return body
        return None

    def fetch_audit(self, to: int, timeout_s: float = 15.0
                    ) -> Optional[dict]:
        """Pull node `to`'s replica-state audit view over the frame
        transport (same quiet-channel caveat as fetch_metrics)."""
        req = f"audit-{to}"
        try:
            self._send(to, {"type": "audit", "req": req})
        except OSError:
            return None
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            got = self.recv(min(1.0, timeout_s))
            if got is None:
                continue
            body = got.get("body", {})
            if body.get("type") == "audit_reply" and body.get("req") == req:
                return body.get("audit")
        return None

    def fetch_top(self, to: int, timeout_s: float = 15.0) -> Optional[dict]:
        """Pull node `to`'s protocol-CPU top-verbs waterfall + loop-health
        view over the frame transport (same quiet-channel caveat as
        fetch_metrics)."""
        req = f"top-{to}"
        try:
            self._send(to, {"type": "top", "req": req})
        except OSError:
            return None
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            got = self.recv(min(1.0, timeout_s))
            if got is None:
                continue
            body = got.get("body", {})
            if body.get("type") == "top_reply" and body.get("req") == req:
                return body.get("top")
        return None

    # ------------------------------------------------------ live elasticity --
    def fetch_topology(self, to: int, timeout_s: float = 15.0
                       ) -> Optional[dict]:
        """Pull node `to`'s current topology spec over the frame transport
        (same quiet-channel caveat as fetch_metrics)."""
        req = f"topology-{to}-{time.monotonic_ns()}"
        try:
            self._send(to, {"type": "topology", "req": req})
        except OSError:
            return None
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            got = self.recv(min(1.0, timeout_s))
            if got is None:
                continue
            body = got.get("body", {})
            if body.get("type") == "topology_reply" \
                    and body.get("req") == req:
                return body.get("topology")
        return None

    def refresh_topology(self, contact: int = 1,
                         timeout_s: float = 15.0) -> Optional[dict]:
        """Re-learn routing after a reshard: without this the client keeps
        submitting against the pre-reshard ownership map forever (the
        static-topology caching bug the elasticity lane pins)."""
        spec = self.fetch_topology(contact, timeout_s=timeout_s)
        if spec is not None:
            self.topology_spec = spec
        return spec

    def owner_of(self, token: int) -> int:
        """First replica of the shard owning `token` under the freshest
        topology spec this client fetched (node 1 before any refresh)."""
        spec = self.topology_spec
        if spec:
            for start, end, nodes in spec["shards"]:
                if start <= token < end and nodes:
                    return nodes[0]
        return 1

    def install_epoch(self, epoch: int, shards, peers=None, contact: int = 1,
                      timeout_s: float = 30.0, geo=None) -> Optional[dict]:
        """Admin-plane epoch proposal: `shards` is [[start, end, [nodes]],
        ...], `peers` optionally [[id, host, port], ...] (a 4th element
        tags the peer's DC) for members joining in this epoch; `geo`
        optionally ships a whole GeoProfile (or its to_spec dict) so one
        contact installs the latency matrix cluster-wide.  The install is
        journaled there before the ack and gossips to every member."""
        req = f"epoch-{epoch}-{contact}"
        topo = {"epoch": int(epoch),
                "shards": [[int(s), int(e), [int(n) for n in nodes]]
                           for s, e, nodes in shards]}
        if peers:
            topo["peers"] = [[int(p[0]), str(p[1]), int(p[2])]
                             + ([str(p[3])] if len(p) > 3 else [])
                             for p in peers]
        if geo is not None:
            topo["geo"] = geo.to_spec() if hasattr(geo, "to_spec") else geo
        self._send(contact, {"type": "epoch", "req": req, "topology": topo})
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            got = self.recv(min(1.0, timeout_s))
            if got is None:
                continue
            body = got.get("body", {})
            if body.get("type") == "epoch_ok" and body.get("req") == req:
                return body
        return None

    def wait_epoch(self, epoch: int, nodes=None,
                   timeout_s: float = 30.0) -> bool:
        """Poll topology frames until every node in `nodes` (default: all)
        reports `epoch` (installed via gossip/fetch, one admin contact)."""
        remaining = set(nodes if nodes is not None
                        else range(1, len(self.procs) + 1))
        deadline = time.monotonic() + timeout_s
        while remaining and time.monotonic() < deadline:
            for n in sorted(remaining):
                spec = self.fetch_topology(n, timeout_s=5.0)
                if spec is not None and spec.get("epoch", 0) >= epoch:
                    remaining.discard(n)
            if remaining:
                time.sleep(0.1)
        return not remaining

    def add_node(self, cpu: Optional[int] = None) -> int:
        """Spawn a fresh journal-backed worker joining the live cluster.
        It builds the founders' epoch-1 topology (owning nothing) and only
        acquires ranges once an installed epoch assigns them — at which
        point it bootstraps over this same transport.  Returns its id."""
        import json as _json
        import subprocess
        import sys as _sys
        node_id = len(self.procs) + 1  # ids stay contiguous for close()
        (port,) = _free_ports(1)
        self.peers[node_id] = ("127.0.0.1", port)
        spec_peers = {str(i): list(p) for i, p in self.peers.items()}
        spec = {"id": node_id, "peers": spec_peers,
                "n_shards": self.n_shards,
                "topology_ids": list(self._seed_ids)}
        if cpu is not None:
            spec["cpu"] = cpu
        proc = subprocess.Popen(
            [_sys.executable, "-m", "accord_tpu.host.tcp",
             _json.dumps(spec)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
        self.procs.append(proc)
        line = proc.stdout.readline()  # ready marker
        assert line.strip(), "tcp worker failed to start"
        return node_id

    def peer_specs(self, ids=None):
        """[[id, host, port], ...] for an install_epoch peers field."""
        return [[i, self.peers[i][0], self.peers[i][1]]
                for i in (ids if ids is not None else sorted(
                    n for n in self.peers if n > 0))]

    def drain_node(self, node_id: int,
                   timeout_s: float = 60.0) -> Optional[dict]:
        """Retire `node_id`: fence new coordination there, let in-flight
        work hand off, wait the durability watermark, then ack."""
        req = f"drain-{node_id}"
        try:
            self._send(node_id, {"type": "drain", "req": req,
                                 "timeout_s": timeout_s})
        except OSError:
            return None
        deadline = time.monotonic() + timeout_s + 10.0
        while time.monotonic() < deadline:
            got = self.recv(min(1.0, timeout_s))
            if got is None:
                continue
            body = got.get("body", {})
            if body.get("type") == "drain_ok" and body.get("req") == req:
                return body
        return None

    def kill_node(self, node_id: int) -> None:
        """Process-death nemesis arm: SIGKILL the worker (its journal
        survives; restart_node brings it back from the WAL)."""
        self.procs[node_id - 1].kill()
        self.procs[node_id - 1].wait(timeout=10.0)
        # _out is shared with pacer/reshard-driver threads calling _send:
        # drop the lane under the same lock or a concurrent submit can
        # resurrect the dead socket mid-close
        with self._send_lock:
            sock = self._out.pop(node_id, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def restart_node(self, node_id: int,
                     topology_ids=None) -> None:
        """Respawn a killed worker on its original port: it replays its
        journal (epoch installs + bootstrap checkpoints included) before
        serving, resuming any interrupted bootstrap from the checkpointed
        coverage."""
        import json as _json
        import subprocess
        import sys as _sys
        spec_peers = {str(i): list(p) for i, p in self.peers.items()}
        spec = {"id": node_id, "peers": spec_peers,
                "n_shards": self.n_shards,
                "topology_ids": list(topology_ids if topology_ids is not None
                                     else self._seed_ids)}
        proc = subprocess.Popen(
            [_sys.executable, "-m", "accord_tpu.host.tcp",
             _json.dumps(spec)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
        self.procs[node_id - 1] = proc
        line = proc.stdout.readline()
        assert line.strip(), "tcp worker failed to restart"

    def close(self) -> None:
        for i in range(1, len(self.procs) + 1):
            try:
                self._send(i, {"type": "stop"})
            except OSError:
                pass
        self.running = False
        try:
            self.server.close()
        except OSError:
            pass
        with self._send_lock:
            socks = list(self._out.values())
            self._out.clear()
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        for p in self.procs:
            try:
                p.wait(timeout=5.0)
            except Exception:
                p.kill()


def main() -> None:
    """Worker-process entry: python -m accord_tpu.host.tcp '<spec json>'
    with spec = {"id": N, "peers": {"0": [host, port], ...}, "n_shards": S,
    "cpu": optional core to pin to}.  Prints one ready line (its realised
    port), serves until a stop frame."""
    import json as _json
    import sys as _sys
    spec = _json.loads(_sys.argv[1])
    cpu = spec.get("cpu")
    if cpu is not None and hasattr(os, "sched_setaffinity"):
        try:
            os.sched_setaffinity(0, {int(cpu)})
        except OSError:
            pass  # fewer cores than nodes: scheduling still works
    peers = {int(k): tuple(v) for k, v in spec["peers"].items()}
    host = TcpHost(spec["id"], peers, rf=spec.get("rf"),
                   n_shards=spec.get("n_shards", 4),
                   topology_ids=spec.get("topology_ids"))
    print(_json.dumps({"id": spec["id"],
                       "port": host.peers[spec["id"]][1]}), flush=True)

    def parent_watch():
        # the spawner holds our stdin pipe: EOF means it is gone — exit
        # rather than serve forever as an orphan
        _sys.stdin.read()
        host.running = False
        host._wakeup()

    threading.Thread(target=parent_watch, daemon=True).start()
    try:
        while host.running:
            time.sleep(0.05)
    finally:
        host.close()


if __name__ == "__main__":
    main()
