"""Named workload profiles: deterministic-seeded op streams.

A profile is a seeded generator of `Op`s — transport-neutral descriptions
(read tokens, appends, optional range windows, ephemeral flag) that the sim
runner turns into `Txn`s via `build_txn` and the TCP runner ships as submit
frames.  The four named profiles promote the device-kernel microbench
shapes (`bench.py --config zipf1m/rangestress/tpcc`) into end-to-end
protocol-path scenarios, plus the previously-uncovered ephemeral-read path:

  zipfian              hot-key-skewed read+append mix (Zipf 0.99), RMW-heavy
  range_mix            zipfian writes with ~1-in-3 range reads (stab mix)
  tpcc_neworder        TPC-C-style neworder: one hot district counter +
                       10 stock keys per txn, ~1% remote-warehouse
  ephemeral_read_heavy ~85% single-key reads on the EPHEMERAL_READ path
                       (never witnessed, single-round) + 15% writes
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from accord_tpu.utils.random_source import RandomSource


class Op:
    """One client operation, transport-neutral."""

    __slots__ = ("reads", "appends", "ranges", "ephemeral")

    def __init__(self, reads: Tuple[int, ...] = (),
                 appends: Optional[Dict[int, int]] = None,
                 ranges: Optional[Tuple[Tuple[int, int], ...]] = None,
                 ephemeral: bool = False):
        self.reads = tuple(reads)
        self.appends = dict(appends or {})
        self.ranges = ranges
        self.ephemeral = ephemeral
        if ephemeral:
            assert not self.appends and not ranges and len(self.reads) >= 1

    def __repr__(self):
        return (f"Op(reads={self.reads} appends={self.appends} "
                f"ranges={self.ranges} eph={self.ephemeral})")


def build_txn(op: Op):
    """An Op as the sim/in-process Txn (list-register semantics, like the
    burn's generator)."""
    from accord_tpu.impl.list_store import (ListQuery, ListRangeRead,
                                            ListRead, ListUpdate)
    from accord_tpu.primitives.keys import Key, Keys, Ranges
    from accord_tpu.primitives.timestamp import TxnKind
    from accord_tpu.primitives.txn import Txn

    if op.ranges is not None:
        ranges = Ranges.of(*op.ranges)
        return Txn(TxnKind.READ, ranges, read=ListRangeRead(ranges),
                   query=ListQuery())
    if op.ephemeral:
        keys = Keys.of(*op.reads)
        return Txn(TxnKind.EPHEMERAL_READ, keys, read=ListRead(keys),
                   query=ListQuery())
    all_tokens = set(op.reads) | set(op.appends)
    return Txn(
        TxnKind.WRITE if op.appends else TxnKind.READ,
        Keys.of(*all_tokens),
        read=ListRead(Keys.of(*op.reads)) if op.reads else None,
        query=ListQuery(),
        update=ListUpdate({Key(t): v for t, v in op.appends.items()})
        if op.appends else None)


class Profile:
    """Base: seeded op stream with a monotonically unique append counter
    (list-register values must be distinct for the verifiers)."""

    name = "base"

    def __init__(self, keys: int = 64, seed: int = 0):
        assert keys >= 8, "profiles need at least 8 tokens"
        self.keys = keys
        self.rng = RandomSource(seed)
        self.next_value = 0

    def _value(self) -> int:
        v = self.next_value
        self.next_value += 1
        return v

    def next_op(self) -> Op:  # pragma: no cover - abstract
        raise NotImplementedError


class ZipfianProfile(Profile):
    """Hot-key-skewed read+append mix: every witnessed txn touches 1-3
    Zipf(0.99) tokens; ~70% carry a write, RMWs read what they write."""

    name = "zipfian"

    def _token(self) -> int:
        return self.rng.next_zipf(self.keys)

    def next_op(self) -> Op:
        rng = self.rng
        tokens = sorted({self._token()
                         for _ in range(1 + rng.next_int(3))})
        if rng.next_float() < 0.7:
            appends = {t: self._value() for t in tokens
                       if rng.next_float() < 0.8} or \
                {tokens[0]: self._value()}
            reads = tuple(tokens) if rng.next_bool() else \
                tuple(t for t in tokens if t not in appends)
            return Op(reads=reads, appends=appends)
        return Op(reads=tuple(tokens))


class UniformProfile(ZipfianProfile):
    """The zipfian mix SHAPE (1-3 tokens, ~70% writes, RMWs read what
    they write) drawn over a UNIFORM keyspace: the conflict-light control
    for lanes that measure admission/scheduling rather than contention
    (slo-overload) — a skewed draw's hot-key dependency chains add an
    execution-side tail orthogonal to what those lanes test."""

    name = "uniform"

    def _token(self) -> int:
        return self.rng.next_int(self.keys)


class RangeMixProfile(ZipfianProfile):
    """The zipfian mix with ~1-in-3 range reads stabbing a token window
    (the protocol-path version of the rangestress microbench)."""

    name = "range_mix"

    def next_op(self) -> Op:
        rng = self.rng
        if rng.next_int(3) == 0:
            lo = rng.next_int(self.keys - 1)
            hi = min(self.keys,
                     lo + 1 + rng.next_int(1, max(2, self.keys // 4)))
            return Op(ranges=((lo, hi),))
        return super().next_op()


class TpccNewOrderProfile(Profile):
    """TPC-C-style neworder: each txn appends to its district's order
    counter (the classic contention point — districts are the hot low
    tokens) and touches `items` stock tokens, ~1% from a remote warehouse.
    Districts occupy the bottom eighth of the keyspace, stock the rest."""

    name = "tpcc_neworder"

    def __init__(self, keys: int = 64, seed: int = 0, warehouses: int = 4,
                 items: int = 10):
        super().__init__(keys=keys, seed=seed)
        self.n_district = max(2, keys // 8)
        self.warehouses = max(1, min(warehouses, self.n_district))
        self.items = items

    def next_op(self) -> Op:
        rng = self.rng
        w = rng.next_int(self.warehouses)
        per_w = self.n_district // self.warehouses
        district = w * per_w + rng.next_int(max(1, per_w))
        stock_span = self.keys - self.n_district
        stock = set()
        for _ in range(self.items):
            sw = rng.next_int(self.warehouses) \
                if rng.next_float() < 0.01 else w
            stock.add(self.n_district
                      + (sw * 7919 + rng.next_int(stock_span)) % stock_span)
        appends = {district: self._value()}
        for t in sorted(stock):
            appends[t] = self._value()
        return Op(reads=(district,), appends=appends)


class EphemeralReadHeavyProfile(Profile):
    """Read-heavy lane on the ephemeral-read path: ~85% single-key Zipf
    reads as EPHEMERAL_READ (single-round, never witnessed), 15% writes so
    the reads observe growing histories."""

    name = "ephemeral_read_heavy"

    def __init__(self, keys: int = 64, seed: int = 0,
                 read_ratio: float = 0.85):
        super().__init__(keys=keys, seed=seed)
        self.read_ratio = read_ratio

    def next_op(self) -> Op:
        rng = self.rng
        if rng.next_float() < self.read_ratio:
            return Op(reads=(rng.next_zipf(self.keys),), ephemeral=True)
        token = rng.next_zipf(self.keys)
        return Op(reads=(token,), appends={token: self._value()})


PROFILES = {p.name: p for p in (ZipfianProfile, UniformProfile,
                                RangeMixProfile, TpccNewOrderProfile,
                                EphemeralReadHeavyProfile)}


def make_profile(name: str, keys: int = 64, seed: int = 0,
                 **kwargs) -> Profile:
    try:
        cls = PROFILES[name]
    except KeyError:
        raise ValueError(f"unknown profile {name!r}; "
                         f"one of {sorted(PROFILES)}") from None
    return cls(keys=keys, seed=seed, **kwargs)
