"""Open-loop drivers: intended-start scheduling over the sim and TCP hosts.

Both runners share the measurement discipline that closed-loop bench lanes
cannot provide:

  * arrivals follow a pre-computed schedule (arrival.py) — completions
    never gate submissions, so a stalled coordinator backs work up instead
    of silently pausing the load;
  * every op's latency is measured from its INTENDED start (the schedule
    time), charging omitted time to the tail; the same acked ops measured
    from actual submit give the closed-loop comparison — the delta IS the
    coordinated omission;
  * acked ops join the PR-2 trace spans (obs/spans.phase_firsts) for
    per-phase attribution, plus a synthetic "admission" phase
    (coordination begin - intended start: client scheduling, any stall
    ahead of the coordinator, and pipeline queueing).

The sim runner (`run_open_loop_sim`) is fully deterministic — virtual-time
arrivals on the shared PendingQueue — and supports stall injection: during
[stall_at_us, stall_at_us+stall_us) submissions are HELD AT THE
COORDINATOR'S DOOR and released when the stall ends, the externally
observable behavior of a wedged event loop (a client cannot observe which
internal stage stalled, only that its op sat).  The TCP runner drives the
real multi-process cluster on the wall clock; per-phase data rides back on
submit replies (`want_phases`).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from accord_tpu.utils.random_source import RandomSource
from accord_tpu.workload.arrival import make_offsets_us
from accord_tpu.workload.profiles import build_txn, make_profile

# bounded exact-sample buffers: enough for sample-exact p99.9 at every
# realistic lane size, bounded against a runaway caller
MAX_SAMPLES = 1 << 17


class OpRecord:
    """One op's ledger row: intended vs actual submit vs end."""

    __slots__ = ("idx", "intended_us", "submit_us", "end_us", "outcome",
                 "phase_firsts")

    def __init__(self, idx: int, intended_us: int):
        self.idx = idx
        self.intended_us = intended_us
        self.submit_us: Optional[int] = None
        self.end_us: Optional[int] = None
        self.outcome: Optional[str] = None  # ack | shed | fail | None
        self.phase_firsts: Optional[list] = None  # [(phase, at_us)]


class OpenLoopResult:
    """Ledger + SLO report of one open-loop run."""

    def __init__(self, records: List[OpRecord], report: dict,
                 summary: Optional[dict], schedule: dict):
        self.records = records
        self.report = report
        self.summary = summary
        self.schedule = schedule

    @property
    def counts(self) -> Dict[str, int]:
        return self.report["counts"]


def _collect(records: List[OpRecord], offered_per_s: float,
             schedule: dict, summary: Optional[dict],
             t0_us: int) -> dict:
    """Fold the ledger into the SLO report (obs/report.slo_report)."""
    from accord_tpu.obs.report import slo_report
    from accord_tpu.obs.spans import phase_deltas

    open_lat: List[int] = []
    closed_lat: List[int] = []
    phases: Dict[str, List[int]] = {}
    counts = {"acked": 0, "shed": 0, "failed": 0, "pending": 0}
    last_end = t0_us
    for rec in records:
        if rec.outcome == "ack":
            counts["acked"] += 1
            last_end = max(last_end, rec.end_us)
            if len(open_lat) < MAX_SAMPLES:
                open_lat.append(max(0, rec.end_us - rec.intended_us))
                closed_lat.append(max(0, rec.end_us - rec.submit_us))
            firsts = rec.phase_firsts or []
            if firsts:
                # admission: intended start -> coordination begin (client
                # scheduling + stall + pipeline queue), then the span's
                # own milestone deltas
                begin_at = firsts[0][1]
                phases.setdefault("admission", []).append(
                    max(0, begin_at - rec.intended_us))
                for ph, dur in phase_deltas(firsts):
                    if ph != "end":
                        phases.setdefault(ph, []).append(dur)
        elif rec.outcome == "shed":
            counts["shed"] += 1
        elif rec.outcome == "fail":
            counts["failed"] += 1
        else:
            counts["pending"] += 1
    duration_s = max(1e-9, (last_end - t0_us) / 1e6)
    return slo_report(open_lat, closed_lat, phases, counts, offered_per_s,
                      duration_s, schedule=schedule, summary=summary)


# ------------------------------------------------------------- sim host ----

def run_open_loop_sim(profile: str = "zipfian", ops: int = 400,
                      rate_per_s: float = 400.0, schedule: str = "poisson",
                      seed: int = 0, nodes: int = 3, keys: int = 48,
                      n_shards: int = 4, pipeline: bool = True,
                      token_span: int = 1000,
                      stall_at_us: Optional[int] = None, stall_us: int = 0,
                      store_factory: Optional[Callable] = None,
                      profile_kwargs: Optional[dict] = None,
                      keep_cluster: bool = False) -> OpenLoopResult:
    """Deterministic open-loop run through the pipeline host in the sim:
    arrivals at virtual-time offsets, latencies in virtual microseconds.

    stall_at_us/stall_us: hold every submission landing inside the window
    until it closes (a stalled coordinator as the client observes one).
    Open-loop latency charges the hold (intended start predates it);
    closed-loop latency of the SAME run does not — the coordinated-
    omission demonstration (tests/test_workload.py)."""
    from accord_tpu.sim.cluster import SimCluster

    rng = RandomSource(seed)
    cluster = SimCluster(n_nodes=nodes, seed=rng.next_long(),
                         token_span=token_span, n_shards=n_shards,
                         pipeline=pipeline, store_factory=store_factory)
    cluster.start_durability_scheduling(shard_cycle_s=10.0)
    prof = make_profile(profile, keys=keys, seed=rng.next_long(),
                        **(profile_kwargs or {}))
    offsets = make_offsets_us(schedule, rate_per_s, ops,
                              seed=rng.next_long())
    origin_rng = rng.fork()
    t0_us = cluster.queue.clock.now_us
    records = [OpRecord(i, t0_us + off) for i, off in enumerate(offsets)]
    ops_list = [prof.next_op() for _ in range(ops)]
    settled = [0]
    stall_end_us = (t0_us + stall_at_us + stall_us
                    if stall_at_us is not None and stall_us > 0 else None)
    stall_begin_us = (t0_us + stall_at_us
                      if stall_end_us is not None else None)

    def submit(i: int) -> None:
        now = cluster.queue.clock.now_us
        if stall_end_us is not None and stall_begin_us <= now < stall_end_us:
            # coordinator wedged: the op sits until the stall clears
            cluster.queue.add(stall_end_us - now, lambda: submit(i))
            return
        rec = records[i]
        rec.submit_us = now
        origin = origin_rng.pick(cluster.live_node_ids())
        txn = build_txn(ops_list[i])

        def done(value, failure):
            from accord_tpu.pipeline.backpressure import Rejected
            rec.end_us = cluster.queue.clock.now_us
            settled[0] += 1
            if isinstance(failure, Rejected):
                rec.outcome = "shed"
            elif failure is not None:
                rec.outcome = "fail"
            elif value is not None:
                rec.outcome = "ack"
                from accord_tpu.obs.spans import phase_firsts, trace_key
                span = cluster.nodes[origin].obs.spans.get(
                    trace_key(value.txn_id))
                rec.phase_firsts = phase_firsts(span)
            else:
                rec.outcome = "fail"

        cluster.pipeline_submit(origin, txn).add_callback(done)

    for i, off in enumerate(offsets):
        cluster.queue.add(off, (lambda j: (lambda: submit(j)))(i))
    cluster.process_until(lambda: settled[0] >= ops, max_items=50_000_000)

    summary = cluster.metrics_snapshot()["summary"]
    sched = {"kind": schedule, "rate_per_s": rate_per_s, "ops": ops,
             "seed": seed, "host": "sim-pipeline" if pipeline else "sim"}
    if stall_end_us is not None:
        sched["stall_at_us"] = stall_at_us
        sched["stall_us"] = stall_us
    result = OpenLoopResult(records,
                            _collect(records, rate_per_s, sched, summary,
                                     t0_us),
                            summary, sched)
    if keep_cluster:
        result.cluster = cluster
    return result


# ------------------------------------------------------------- tcp host ----

def run_open_loop_tcp(profile: str = "zipfian", ops: int = 300,
                      rate_per_s: float = 100.0, schedule: str = "poisson",
                      seed: int = 7, nodes: int = 3, keys: int = 64,
                      n_shards: int = 4, want_phases: bool = True,
                      profile_kwargs: Optional[dict] = None,
                      settle_timeout_s: float = 60.0) -> OpenLoopResult:
    """Open-loop run over the REAL multi-process TCP cluster (wall clock).
    ACCORD_PIPELINE et al. are read by the node processes from the ambient
    environment — the caller chooses the host configuration.  Range ops are
    sim-only (the submit frame carries no range encoding)."""
    from accord_tpu.host.tcp import TcpClusterClient

    rng = RandomSource(seed)
    prof = make_profile(profile, keys=keys, seed=rng.next_long(),
                        **(profile_kwargs or {}))
    offsets = make_offsets_us(schedule, rate_per_s, ops,
                              seed=rng.next_long())
    ops_list = [prof.next_op() for _ in range(ops)]
    assert all(op.ranges is None for op in ops_list), \
        "range ops are sim-only (no wire encoding on the submit frame)"
    origin_rng = rng.fork()
    origins = [1 + origin_rng.next_int(nodes) for _ in range(ops)]

    client = TcpClusterClient(n_nodes=nodes, n_shards=n_shards)
    summary = None
    try:
        t0_us = int(time.time() * 1e6)
        records = [OpRecord(i, t0_us + off) for i, off in enumerate(offsets)]

        def handle(frame) -> bool:
            body = frame.get("body", {})
            if body.get("type") != "submit_reply":
                return False
            rec = records[body["req"]]
            rec.end_us = int(time.time() * 1e6)
            if body.get("ok"):
                rec.outcome = "ack"
                if body.get("phases"):
                    rec.phase_firsts = [(ph, at) for ph, at
                                        in body["phases"]]
            elif body.get("shed"):
                rec.outcome = "shed"
            else:
                rec.outcome = "fail"
            return True

        sent = pending = 0
        while sent < ops:
            due_us = records[sent].intended_us
            now_us = int(time.time() * 1e6)
            if now_us < due_us:
                frame = client.recv(min(0.05, (due_us - now_us) / 1e6))
                if frame is not None and handle(frame):
                    pending -= 1
                continue
            op = ops_list[sent]
            records[sent].submit_us = int(time.time() * 1e6)
            client.submit(origins[sent], op.reads, op.appends, sent,
                          ephemeral=op.ephemeral, want_phases=want_phases)
            sent += 1
            pending += 1
        deadline = time.monotonic() + settle_timeout_s
        while pending > 0 and time.monotonic() < deadline:
            frame = client.recv(1.0)
            if frame is not None and handle(frame):
                pending -= 1

        # obs snapshots AFTER the channel quiesces (fetch_metrics drops
        # stray frames); merged summary feeds fast_path_ratio into the row
        from accord_tpu.obs.report import merge_node_snapshots
        snaps = [client.fetch_metrics(i) for i in range(1, nodes + 1)]
        merged = merge_node_snapshots([s for s in snaps if s])
        summary = merged["summary"] if merged["nodes"] else None
    finally:
        client.close()

    sched = {"kind": schedule, "rate_per_s": rate_per_s, "ops": ops,
             "seed": seed, "host": "tcp"}
    return OpenLoopResult(records,
                          _collect(records, rate_per_s, sched, summary,
                                   t0_us),
                          summary, sched)


# --------------------------------------------------------- reshard lane ----

def _window_stats(recs: List[OpRecord]) -> dict:
    """Ack rate + open-loop quantiles of one reshard window's records."""
    lat = sorted(max(0, r.end_us - r.intended_us) for r in recs
                 if r.outcome == "ack")

    def q(p: float) -> Optional[int]:
        return lat[min(len(lat) - 1, int(p * len(lat)))] if lat else None
    n = len(recs)
    return {"count": n, "acked": len(lat),
            "shed": sum(1 for r in recs if r.outcome == "shed"),
            "failed": sum(1 for r in recs if r.outcome == "fail"),
            "ack_rate": round(len(lat) / n, 4) if n else None,
            "open_p50_us": q(0.50), "open_p99_us": q(0.99)}


def _reshard_report(records: List[OpRecord], t0_us: int, begin_us: int,
                    end_us: int, bucket_us: int = 1_000_000) -> dict:
    """Fold the ledger around the reshard window: per-window stats,
    1s-bucket availability dip, and time-to-SLO-recovery measured from the
    moment the reshard began (bucket ack rate back >= 95% AND bucket open
    p99 back under max(2x the before-window p99, 100ms))."""
    windows = {
        "before": _window_stats([r for r in records
                                 if r.intended_us < begin_us]),
        "during": _window_stats([r for r in records
                                 if begin_us <= r.intended_us < end_us]),
        "after": _window_stats([r for r in records
                                if r.intended_us >= end_us]),
    }
    buckets: Dict[int, list] = {}
    for r in records:
        b = (r.intended_us - t0_us) // bucket_us
        tot_ack = buckets.setdefault(b, [0, 0, []])
        tot_ack[0] += 1
        if r.outcome == "ack":
            tot_ack[1] += 1
            tot_ack[2].append(max(0, r.end_us - r.intended_us))
    begin_b = (begin_us - t0_us) // bucket_us
    base_p99 = windows["before"]["open_p99_us"] or 0
    thresh_us = max(2 * base_p99, 100_000)
    dip_rates = [ack / tot for b, (tot, ack, _l) in sorted(buckets.items())
                 if b >= begin_b and tot > 0]
    before_rate = windows["before"]["ack_rate"] or 0.0
    recovery_s = None
    for b in sorted(buckets):
        if b < begin_b:
            continue
        tot, ack, lats = buckets[b]
        if tot == 0:
            continue
        lats.sort()
        p99 = lats[min(len(lats) - 1, int(0.99 * len(lats)))] if lats else 0
        if ack / tot >= 0.95 and p99 <= thresh_us:
            recovery_s = round(max(0.0, (t0_us + b * bucket_us - begin_us)
                                   / 1e6), 3)
            break
    min_rate = round(min(dip_rates), 4) if dip_rates else None
    return {
        "windows": windows,
        "availability": {
            "before_ack_rate": before_rate,
            "min_bucket_ack_rate": min_rate,
            "dip_pct": round(max(0.0, (before_rate - (min_rate or 0.0)))
                             * 100.0, 2) if min_rate is not None else None,
            "bucket_s": bucket_us / 1e6,
        },
        "time_to_slo_recovery_s": recovery_s,
    }


def run_reshard_tcp(profile: str = "zipfian", ops: int = 2400,
                    rate_per_s: float = 80.0, schedule: str = "poisson",
                    seed: int = 13, nodes: int = 3, keys: int = 48,
                    n_shards: int = 4, reshard_at_frac: float = 0.33,
                    want_phases: bool = True,
                    settle_timeout_s: float = 90.0,
                    drain_retiring: bool = True) -> OpenLoopResult:
    """The slo-reshard lane: open-loop zipfian over the live TCP cluster
    with a FULL membership change mid-window — a fresh journal-backed node
    joins and bootstraps under load (admin epoch install, one contact,
    gossip convergence), the client re-learns routing from a topology
    frame, and the founding node drains (coordination fenced, in-flight
    handed off, durability watermark awaited) and is retired.

    The ledger is folded around the reshard window into before/during/
    after ack-rate + open-loop p99, a 1s-bucket availability dip, and
    time-to-SLO-recovery; afterwards every acked append is re-read from
    the surviving membership (zero-lost-acks) and the per-node audit
    views are collected (cross-replica digest agreement at quiesce).

    Admin traffic and submit replies share the client's single reply
    inbox: the paced loop stashes non-submit frames into a dict the
    driver thread polls, and the driver only ever sends (socket writes
    are lock-serialized in TcpClusterClient._send)."""
    import threading

    from accord_tpu.host.maelstrom import TOKEN_SPAN
    from accord_tpu.host.tcp import TcpClusterClient

    rng = RandomSource(seed)
    prof = make_profile(profile, keys=keys, seed=rng.next_long())
    offsets = make_offsets_us(schedule, rate_per_s, ops,
                              seed=rng.next_long())
    ops_list = [prof.next_op() for _ in range(ops)]
    assert all(op.ranges is None for op in ops_list), \
        "range ops are sim-only (no wire encoding on the submit frame)"
    span_us = offsets[-1] if offsets else 0

    client = TcpClusterClient(n_nodes=nodes, n_shards=n_shards)
    admin_replies: Dict[str, dict] = {}
    events: List[list] = []  # [label, wall_us] markers from the driver
    driver_err: List[BaseException] = []
    retiring = 1

    def now_us() -> int:
        return int(time.time() * 1e6)

    def mark(label: str) -> None:
        events.append([label, now_us()])

    def admin_wait(req: str, timeout_s: float) -> dict:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            body = admin_replies.pop(req, None)
            if body is not None:
                return body
            time.sleep(0.01)
        raise TimeoutError(f"no admin reply for {req}")

    def node_at_epoch(nid: int, epoch: int, deadline: float) -> dict:
        k = 0
        while time.monotonic() < deadline:
            k += 1
            req = f"rs-topo-{nid}-{k}"
            try:
                client._send(nid, {"type": "topology", "req": req})
                spec = admin_wait(req, 2.0).get("topology") or {}
            except (TimeoutError, OSError):
                continue
            if spec.get("epoch", 0) >= epoch:
                return spec
            time.sleep(0.1)
        raise TimeoutError(f"node {nid} never reached epoch {epoch}")

    def reshard_driver(t0_us: int) -> None:
        try:
            target_us = t0_us + int(reshard_at_frac * span_us)
            while now_us() < target_us:
                time.sleep(0.01)
            mark("reshard_begin")
            joined = client.add_node()
            mark("node_added")
            # epoch 2 replaces the retiring founder with the joiner:
            # replicas rotated over the surviving membership, same even
            # token split every host transport uses
            ids2 = sorted([i for i in range(1, nodes + 1)
                           if i != retiring] + [joined])
            rf = min(3, len(ids2))
            width = TOKEN_SPAN // n_shards
            shards = [[i * width,
                       TOKEN_SPAN if i == n_shards - 1 else (i + 1) * width,
                       [ids2[(i + j) % len(ids2)] for j in range(rf)]]
                      for i in range(n_shards)]
            req = "rs-epoch-2"
            client._send(retiring, {
                "type": "epoch", "req": req,
                "topology": {"epoch": 2, "shards": shards,
                             "peers": [[joined] +
                                       list(client.peers[joined])]}})
            admin_wait(req, 30.0)
            mark("epoch_acked")
            deadline = time.monotonic() + 60.0
            spec = None
            for nid in ids2 + [retiring]:
                spec = node_at_epoch(nid, 2, deadline)
            mark("epoch_converged")
            # routing refresh: the paced loop routes by owner_of, which
            # reads this spec — without it the client submits against the
            # pre-reshard ownership map forever
            client.topology_spec = spec
            mark("routing_refreshed")
            if drain_retiring:
                req = f"rs-drain-{retiring}"
                client._send(retiring, {"type": "drain", "req": req,
                                        "timeout_s": 45.0})
                body = admin_wait(req, 60.0)
                mark("drain_ok" if body.get("durable") else "drain_undurable")
                client.kill_node(retiring)
                mark("retired")
            mark("reshard_end")
        except BaseException as e:  # noqa: BLE001
            driver_err.append(e)
            mark("reshard_failed")

    summary = None
    audit_views = {}
    lost: List[tuple] = []
    acked_appends = 0
    verified_keys = 0
    try:
        t0_us = now_us()
        records = [OpRecord(i, t0_us + off) for i, off in enumerate(offsets)]

        def handle(frame) -> bool:
            body = frame.get("body", {})
            typ = body.get("type")
            if typ != "submit_reply":
                if typ in ("epoch_ok", "topology_reply", "drain_ok"):
                    admin_replies[body.get("req")] = body
                return False
            req = body.get("req")
            if not isinstance(req, int):
                return False
            rec = records[req]
            rec.end_us = now_us()
            if body.get("ok"):
                rec.outcome = "ack"
                if body.get("phases"):
                    rec.phase_firsts = [(ph, at) for ph, at
                                        in body["phases"]]
            elif body.get("shed"):
                rec.outcome = "shed"
            else:
                rec.outcome = "fail"
            return True

        driver = threading.Thread(target=reshard_driver, args=(t0_us,),
                                  daemon=True)
        driver.start()

        sent = pending = 0
        while sent < ops:
            due_us = records[sent].intended_us
            now = now_us()
            if now < due_us:
                frame = client.recv(min(0.05, (due_us - now) / 1e6))
                if frame is not None and handle(frame):
                    pending -= 1
                continue
            op = ops_list[sent]
            tok0 = next(iter(op.reads), None)
            if tok0 is None and op.appends:
                tok0 = next(iter(op.appends))
            records[sent].submit_us = now_us()
            try:
                client.submit(client.owner_of(tok0 or 0), op.reads,
                              op.appends, sent, ephemeral=op.ephemeral,
                              want_phases=want_phases)
            except OSError:
                records[sent].end_us = now_us()
                records[sent].outcome = "fail"
                sent += 1
                continue
            sent += 1
            pending += 1
        deadline = time.monotonic() + settle_timeout_s
        while pending > 0 and time.monotonic() < deadline:
            frame = client.recv(1.0)
            if frame is not None and handle(frame):
                pending -= 1
        # keep pumping the shared inbox while the driver finishes — its
        # admin replies (epoch_ok / topology_reply / drain_ok) only reach
        # the stash through handle()
        deadline = time.monotonic() + 120.0
        while driver.is_alive() and time.monotonic() < deadline:
            frame = client.recv(0.2)
            if frame is not None and handle(frame):
                pending -= 1
        driver.join(timeout=5.0)

        # zero-lost-acks: every acked append must be readable from the
        # surviving membership (final reads through the refreshed routing)
        acked_by_key: Dict[int, List[int]] = {}
        for i, rec in enumerate(records):
            if rec.outcome == "ack":
                for tok, val in ops_list[i].appends.items():
                    acked_by_key.setdefault(tok, []).append(val)
                    acked_appends += 1
        final_reads: Dict[int, list] = {}
        outstanding = set()
        for tok in acked_by_key:
            req = f"fr-{tok}"
            client.submit(client.owner_of(tok), [tok], {}, req)
            outstanding.add(req)
        deadline = time.monotonic() + 60.0
        while outstanding and time.monotonic() < deadline:
            frame = client.recv(1.0)
            if frame is None:
                continue
            body = frame.get("body", {})
            req = body.get("req")
            if body.get("type") == "submit_reply" and req in outstanding:
                outstanding.discard(req)
                if body.get("ok"):
                    for tok, vals in (body.get("reads") or {}).items():
                        final_reads[int(tok)] = vals
        for tok, vals in sorted(acked_by_key.items()):
            got = final_reads.get(tok)
            if got is None:
                lost.append((tok, "unread", len(vals)))
                continue
            verified_keys += 1
            for val in vals:
                if val not in got:
                    lost.append((tok, "missing", val))

        # audit agreement at quiesce: the cross-replica digest rounds are
        # watermark-negotiated, so any recorded divergence is real
        live = sorted(n for n in range(1, len(client.procs) + 1)
                      if not (drain_retiring and n == retiring))
        for nid in live:
            view = client.fetch_audit(nid, timeout_s=10.0)
            if view:
                audit_views[nid] = len(view.get("divergences") or [])
        from accord_tpu.obs.report import merge_node_snapshots
        snaps = [client.fetch_metrics(n, timeout_s=10.0) for n in live]
        merged = merge_node_snapshots([s for s in snaps if s])
        summary = merged["summary"] if merged["nodes"] else None
    finally:
        client.close()

    if driver_err:
        raise RuntimeError(f"reshard driver failed: {driver_err[0]!r}; "
                           f"events={events}") from driver_err[0]
    marks = dict((label, at) for label, at in events)
    begin_us = marks.get("reshard_begin", t0_us)
    end_us = marks.get("reshard_end", begin_us)
    sched = {"kind": schedule, "rate_per_s": rate_per_s, "ops": ops,
             "seed": seed, "host": "tcp-reshard"}
    report = _collect(records, rate_per_s, sched, summary, t0_us)
    reshard = _reshard_report(records, t0_us, begin_us, end_us)
    reshard["events"] = [[label, round((at - t0_us) / 1e6, 3)]
                         for label, at in events]
    reshard["lost_acks"] = len(lost)
    reshard["lost_detail"] = lost[:16]
    reshard["acked_appends"] = acked_appends
    reshard["verified_keys"] = verified_keys
    reshard["audit"] = {"divergences_by_node": audit_views,
                        "agree": all(v == 0 for v in audit_views.values())
                        and bool(audit_views)}
    reshard["joined_node"] = nodes + 1
    reshard["retired_node"] = retiring if drain_retiring else None
    report["reshard"] = reshard
    return OpenLoopResult(records, report, summary, sched)
