"""Open-loop drivers: intended-start scheduling over the sim and TCP hosts.

Both runners share the measurement discipline that closed-loop bench lanes
cannot provide:

  * arrivals follow a pre-computed schedule (arrival.py) — completions
    never gate submissions, so a stalled coordinator backs work up instead
    of silently pausing the load;
  * every op's latency is measured from its INTENDED start (the schedule
    time), charging omitted time to the tail; the same acked ops measured
    from actual submit give the closed-loop comparison — the delta IS the
    coordinated omission;
  * acked ops join the PR-2 trace spans (obs/spans.phase_firsts) for
    per-phase attribution, plus a synthetic "admission" phase
    (coordination begin - intended start: client scheduling, any stall
    ahead of the coordinator, and pipeline queueing).

The sim runner (`run_open_loop_sim`) is fully deterministic — virtual-time
arrivals on the shared PendingQueue — and supports stall injection: during
[stall_at_us, stall_at_us+stall_us) submissions are HELD AT THE
COORDINATOR'S DOOR and released when the stall ends, the externally
observable behavior of a wedged event loop (a client cannot observe which
internal stage stalled, only that its op sat).  The TCP runner drives the
real multi-process cluster on the wall clock; per-phase data rides back on
submit replies (`want_phases`).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from accord_tpu.utils.random_source import RandomSource
from accord_tpu.workload.arrival import make_offsets_us
from accord_tpu.workload.profiles import Op, build_txn, make_profile

# bounded exact-sample buffers: enough for sample-exact p99.9 at every
# realistic lane size, bounded against a runaway caller
MAX_SAMPLES = 1 << 17


class OpRecord:
    """One op's ledger row: intended vs actual submit vs end."""

    __slots__ = ("idx", "intended_us", "submit_us", "end_us", "outcome",
                 "phase_firsts")

    def __init__(self, idx: int, intended_us: int):
        self.idx = idx
        self.intended_us = intended_us
        self.submit_us: Optional[int] = None
        self.end_us: Optional[int] = None
        self.outcome: Optional[str] = None  # ack | shed | fail | None
        self.phase_firsts: Optional[list] = None  # [(phase, at_us)]


class OpenLoopResult:
    """Ledger + SLO report of one open-loop run."""

    def __init__(self, records: List[OpRecord], report: dict,
                 summary: Optional[dict], schedule: dict):
        self.records = records
        self.report = report
        self.summary = summary
        self.schedule = schedule

    @property
    def counts(self) -> Dict[str, int]:
        return self.report["counts"]


def _collect(records: List[OpRecord], offered_per_s: float,
             schedule: dict, summary: Optional[dict],
             t0_us: int) -> dict:
    """Fold the ledger into the SLO report (obs/report.slo_report)."""
    from accord_tpu.obs.report import slo_report
    from accord_tpu.obs.spans import phase_deltas

    open_lat: List[int] = []
    closed_lat: List[int] = []
    phases: Dict[str, List[int]] = {}
    counts = {"acked": 0, "shed": 0, "failed": 0, "pending": 0}
    last_end = t0_us
    for rec in records:
        if rec.outcome == "ack":
            counts["acked"] += 1
            last_end = max(last_end, rec.end_us)
            if len(open_lat) < MAX_SAMPLES:
                open_lat.append(max(0, rec.end_us - rec.intended_us))
                closed_lat.append(max(0, rec.end_us - rec.submit_us))
            firsts = rec.phase_firsts or []
            if firsts:
                # admission: intended start -> coordination begin (client
                # scheduling + stall + pipeline queue), then the span's
                # own milestone deltas
                begin_at = firsts[0][1]
                phases.setdefault("admission", []).append(
                    max(0, begin_at - rec.intended_us))
                for ph, dur in phase_deltas(firsts):
                    if ph != "end":
                        phases.setdefault(ph, []).append(dur)
        elif rec.outcome == "shed":
            counts["shed"] += 1
        elif rec.outcome == "fail":
            counts["failed"] += 1
        else:
            counts["pending"] += 1
    duration_s = max(1e-9, (last_end - t0_us) / 1e6)
    return slo_report(open_lat, closed_lat, phases, counts, offered_per_s,
                      duration_s, schedule=schedule, summary=summary)


# ------------------------------------------------------------- sim host ----

def run_open_loop_sim(profile: str = "zipfian", ops: int = 400,
                      rate_per_s: float = 400.0, schedule: str = "poisson",
                      seed: int = 0, nodes: int = 3, keys: int = 48,
                      n_shards: int = 4, pipeline: bool = True,
                      token_span: int = 1000,
                      stall_at_us: Optional[int] = None, stall_us: int = 0,
                      store_factory: Optional[Callable] = None,
                      profile_kwargs: Optional[dict] = None,
                      keep_cluster: bool = False) -> OpenLoopResult:
    """Deterministic open-loop run through the pipeline host in the sim:
    arrivals at virtual-time offsets, latencies in virtual microseconds.

    stall_at_us/stall_us: hold every submission landing inside the window
    until it closes (a stalled coordinator as the client observes one).
    Open-loop latency charges the hold (intended start predates it);
    closed-loop latency of the SAME run does not — the coordinated-
    omission demonstration (tests/test_workload.py)."""
    from accord_tpu.sim.cluster import SimCluster

    rng = RandomSource(seed)
    cluster = SimCluster(n_nodes=nodes, seed=rng.next_long(),
                         token_span=token_span, n_shards=n_shards,
                         pipeline=pipeline, store_factory=store_factory)
    cluster.start_durability_scheduling(shard_cycle_s=10.0)
    prof = make_profile(profile, keys=keys, seed=rng.next_long(),
                        **(profile_kwargs or {}))
    offsets = make_offsets_us(schedule, rate_per_s, ops,
                              seed=rng.next_long())
    origin_rng = rng.fork()
    t0_us = cluster.queue.clock.now_us
    records = [OpRecord(i, t0_us + off) for i, off in enumerate(offsets)]
    ops_list = [prof.next_op() for _ in range(ops)]
    settled = [0]
    stall_end_us = (t0_us + stall_at_us + stall_us
                    if stall_at_us is not None and stall_us > 0 else None)
    stall_begin_us = (t0_us + stall_at_us
                      if stall_end_us is not None else None)

    def submit(i: int) -> None:
        now = cluster.queue.clock.now_us
        if stall_end_us is not None and stall_begin_us <= now < stall_end_us:
            # coordinator wedged: the op sits until the stall clears
            cluster.queue.add(stall_end_us - now, lambda: submit(i))
            return
        rec = records[i]
        rec.submit_us = now
        origin = origin_rng.pick(cluster.live_node_ids())
        txn = build_txn(ops_list[i])

        def done(value, failure):
            from accord_tpu.pipeline.backpressure import Rejected
            rec.end_us = cluster.queue.clock.now_us
            settled[0] += 1
            if isinstance(failure, Rejected):
                rec.outcome = "shed"
            elif failure is not None:
                rec.outcome = "fail"
            elif value is not None:
                rec.outcome = "ack"
                from accord_tpu.obs.spans import phase_firsts, trace_key
                span = cluster.nodes[origin].obs.spans.get(
                    trace_key(value.txn_id))
                rec.phase_firsts = phase_firsts(span)
            else:
                rec.outcome = "fail"

        cluster.pipeline_submit(origin, txn).add_callback(done)

    for i, off in enumerate(offsets):
        cluster.queue.add(off, (lambda j: (lambda: submit(j)))(i))
    cluster.process_until(lambda: settled[0] >= ops, max_items=50_000_000)

    summary = cluster.metrics_snapshot()["summary"]
    sched = {"kind": schedule, "rate_per_s": rate_per_s, "ops": ops,
             "seed": seed, "host": "sim-pipeline" if pipeline else "sim"}
    if stall_end_us is not None:
        sched["stall_at_us"] = stall_at_us
        sched["stall_us"] = stall_us
    result = OpenLoopResult(records,
                            _collect(records, rate_per_s, sched, summary,
                                     t0_us),
                            summary, sched)
    if keep_cluster:
        result.cluster = cluster
    return result


# ------------------------------------------------------------- wan lane ----

class WanRec(OpRecord):
    """OpRecord + the decided commit path (fast|slow) for windowed
    fast-path-ratio measurement."""

    __slots__ = ("path",)

    def __init__(self, idx: int, intended_us: int):
        super().__init__(idx, intended_us)
        self.path: Optional[str] = None


def wan_window_ratios(records: List["WanRec"], t0_us: int,
                      begin_us: int, end_us: int) -> Dict[str, dict]:
    """Fast-path ratio split into before/during/after a [begin, end)
    virtual-time window (offsets from t0): the degrade-then-recover
    surface of the DC-partition arm.  Ops are bucketed by SUBMIT time —
    an op submitted during the window pays the partition regardless of
    when it finally settles."""
    out = {}
    for name, lo, hi in (("before", 0, begin_us),
                         ("during", begin_us, end_us),
                         ("after", end_us, None)):
        recs = [r for r in records
                if r.submit_us is not None
                and r.submit_us - t0_us >= lo
                and (hi is None or r.submit_us - t0_us < hi)]
        fast = sum(1 for r in recs if r.path == "fast")
        slow = sum(1 for r in recs if r.path == "slow")
        out[name] = {"ops": len(recs), "fast": fast, "slow": slow,
                     "fast_path_ratio": (round(fast / (fast + slow), 4)
                                         if fast + slow else None)}
    return out


def run_wan_sim(electorate=None, origin: int = 1, ops: int = 200,
                rate_per_s: float = 30.0, schedule: str = "poisson",
                seed: int = 0, hub: int = 4, keys: int = 240,
                n_shards: int = 2, profile: str = "uniform",
                geo=None, partition=None,
                keep_cluster: bool = False) -> OpenLoopResult:
    """Deterministic open-loop WAN scenario: a geo-placed sim cluster
    (default topology/geo.wan3_profile — a hub DC holding the full slow
    quorum plus three single-node DCs at 50/100/160 ms RTT) driven from a
    PINNED origin node, so one run measures one (electorate, coordinator
    placement) configuration.  `electorate` narrows every shard's
    fast-path electorate (None = all replicas); latencies are virtual
    microseconds against the profile's injected matrix, and each acked
    op records its decided commit path (WanRec.path) so fast-path ratio
    can be windowed.

    partition: optional (dc, begin_us, end_us) — sever that whole DC for
    [begin, end) after t0 via DcPartitionNemesis.partition_now/heal_now,
    the deterministic degrade-then-recover arm (flight kinds
    dc_partition_begin/heal mark the window on every node's ring)."""
    from accord_tpu.sim.cluster import SimCluster
    from accord_tpu.sim.network import DcPartitionNemesis
    from accord_tpu.topology.geo import wan3_profile

    if geo is None:
        geo = wan3_profile(hub)
    nodes = len(geo.node_dc)
    rng = RandomSource(seed)
    cluster = SimCluster(n_nodes=nodes, seed=rng.next_long(),
                         n_shards=n_shards, rf=nodes, geo=geo,
                         electorate=electorate)
    prof = make_profile(profile, keys=keys, seed=rng.next_long())
    offsets = make_offsets_us(schedule, rate_per_s, ops,
                              seed=rng.next_long())
    t0_us = cluster.queue.clock.now_us
    records = [WanRec(i, t0_us + off) for i, off in enumerate(offsets)]
    ops_list = [prof.next_op() for _ in range(ops)]
    settled = [0]
    nemesis = None
    if partition is not None:
        dc, begin_us, end_us = partition
        nemesis = DcPartitionNemesis(cluster.network, cluster.queue,
                                     rng.fork(), geo)
        cluster.queue.add(begin_us, lambda: nemesis.partition_now(dc))
        cluster.queue.add(end_us, nemesis.heal_now)

    def submit(i: int) -> None:
        rec = records[i]
        rec.submit_us = cluster.queue.clock.now_us
        txn = build_txn(ops_list[i])

        def done(value, failure):
            rec.end_us = cluster.queue.clock.now_us
            settled[0] += 1
            if failure is not None or value is None:
                rec.outcome = "fail"
                return
            rec.outcome = "ack"
            from accord_tpu.obs.spans import phase_firsts, trace_key
            span = cluster.nodes[origin].obs.spans.get(
                trace_key(value.txn_id))
            if span is not None:
                rec.phase_firsts = phase_firsts(span)
                rec.path = span.path

        cluster.node(origin).coordinate(txn).add_callback(done)

    for i, off in enumerate(offsets):
        cluster.queue.add(off, (lambda j: (lambda: submit(j)))(i))
    cluster.process_until(lambda: settled[0] >= ops, max_items=50_000_000)

    summary = cluster.metrics_snapshot()["summary"]
    sched = {"kind": schedule, "rate_per_s": rate_per_s, "ops": ops,
             "seed": seed, "host": "sim-wan", "origin": origin,
             "origin_dc": geo.dc_of(origin),
             "electorate": sorted(electorate) if electorate else None}
    report = _collect(records, rate_per_s, sched, summary, t0_us)
    if partition is not None:
        dc, begin_us, end_us = partition
        report["partition"] = {"dc": dc, "begin_us": begin_us,
                               "end_us": end_us,
                               "windows": wan_window_ratios(
                                   records, t0_us, begin_us, end_us)}
    result = OpenLoopResult(records, report, summary, sched)
    result.geo = geo
    if keep_cluster:
        result.cluster = cluster
    return result


# ------------------------------------------------------------- tcp host ----

def run_open_loop_tcp(profile: str = "zipfian", ops: int = 300,
                      rate_per_s: float = 100.0, schedule: str = "poisson",
                      seed: int = 7, nodes: int = 3, keys: int = 64,
                      n_shards: int = 4, want_phases: bool = True,
                      profile_kwargs: Optional[dict] = None,
                      settle_timeout_s: float = 60.0) -> OpenLoopResult:
    """Open-loop run over the REAL multi-process TCP cluster (wall clock).
    ACCORD_PIPELINE et al. are read by the node processes from the ambient
    environment — the caller chooses the host configuration.  Range ops are
    sim-only (the submit frame carries no range encoding)."""
    from accord_tpu.host.tcp import TcpClusterClient

    rng = RandomSource(seed)
    prof = make_profile(profile, keys=keys, seed=rng.next_long(),
                        **(profile_kwargs or {}))
    offsets = make_offsets_us(schedule, rate_per_s, ops,
                              seed=rng.next_long())
    ops_list = [prof.next_op() for _ in range(ops)]
    assert all(op.ranges is None for op in ops_list), \
        "range ops are sim-only (no wire encoding on the submit frame)"
    origin_rng = rng.fork()
    origins = [1 + origin_rng.next_int(nodes) for _ in range(ops)]

    client = TcpClusterClient(n_nodes=nodes, n_shards=n_shards)
    summary = None
    try:
        t0_us = int(time.time() * 1e6)
        records = [OpRecord(i, t0_us + off) for i, off in enumerate(offsets)]

        def handle(frame) -> bool:
            body = frame.get("body", {})
            if body.get("type") != "submit_reply":
                return False
            rec = records[body["req"]]
            rec.end_us = int(time.time() * 1e6)
            if body.get("ok"):
                rec.outcome = "ack"
                if body.get("phases"):
                    rec.phase_firsts = [(ph, at) for ph, at
                                        in body["phases"]]
            elif body.get("shed"):
                rec.outcome = "shed"
            else:
                rec.outcome = "fail"
            return True

        sent = pending = 0
        while sent < ops:
            due_us = records[sent].intended_us
            now_us = int(time.time() * 1e6)
            if now_us < due_us:
                frame = client.recv(min(0.05, (due_us - now_us) / 1e6))
                if frame is not None and handle(frame):
                    pending -= 1
                continue
            op = ops_list[sent]
            records[sent].submit_us = int(time.time() * 1e6)
            client.submit(origins[sent], op.reads, op.appends, sent,
                          ephemeral=op.ephemeral, want_phases=want_phases)
            sent += 1
            pending += 1
        deadline = time.monotonic() + settle_timeout_s
        while pending > 0 and time.monotonic() < deadline:
            frame = client.recv(1.0)
            if frame is not None and handle(frame):
                pending -= 1

        # obs snapshots AFTER the channel quiesces (fetch_metrics drops
        # stray frames); merged summary feeds fast_path_ratio into the row
        from accord_tpu.obs.report import merge_node_snapshots
        snaps = [client.fetch_metrics(i) for i in range(1, nodes + 1)]
        merged = merge_node_snapshots([s for s in snaps if s])
        summary = merged["summary"] if merged["nodes"] else None
    finally:
        client.close()

    sched = {"kind": schedule, "rate_per_s": rate_per_s, "ops": ops,
             "seed": seed, "host": "tcp"}
    return OpenLoopResult(records,
                          _collect(records, rate_per_s, sched, summary,
                                   t0_us),
                          summary, sched)


# -------------------------------------------------------- overload lane ----

class OverloadRec(OpRecord):
    """One overload-lane op: the ledger row plus its QoS identity and the
    client-side retry trail (attempts, nacks, whether the retry honored
    the server's `retry_after_us` hint)."""

    __slots__ = ("window", "tenant", "priority", "attempts", "qos_nacks",
                 "honored", "retried")

    def __init__(self, idx: int, intended_us: int, window: int,
                 tenant: str, priority: str):
        super().__init__(idx, intended_us)
        self.window = window
        self.tenant = tenant
        self.priority = priority
        self.attempts = 0
        self.qos_nacks = 0
        self.honored = 0   # resubmits that waited >= the hinted delay
        self.retried = 0


def _probe_capacity(client, prof, origin_rng, nodes: int, ops: int,
                    concurrency: int, timeout_s: float = 60.0) -> dict:
    """Closed-loop capacity probe: `concurrency` outstanding ops, next
    submitted on each completion — the classic saturation measurement the
    open-loop sweep's multipliers are anchored to.  Probes submit as
    `high` so the armed QoS tier cannot nack them: the probe must measure
    what the node can DO, not what the tenant buckets provision."""
    t0 = time.monotonic()
    sent = done = acked = 0
    pending = 0
    deadline = t0 + timeout_s
    while done < ops and time.monotonic() < deadline:
        while sent < ops and pending < concurrency:
            op = prof.next_op()
            client.submit(1 + origin_rng.next_int(nodes), op.reads,
                          op.appends, f"probe-{sent}", priority="high")
            sent += 1
            pending += 1
        frame = client.recv(1.0)
        if frame is None:
            continue
        body = frame.get("body", {})
        if body.get("type") == "submit_reply" and \
                str(body.get("req", "")).startswith("probe-"):
            pending -= 1
            done += 1
            if body.get("ok"):
                acked += 1
    duration_s = max(1e-9, time.monotonic() - t0)
    return {"ops": ops, "concurrency": concurrency, "acked": acked,
            "duration_s": round(duration_s, 3),
            "per_s": round(acked / duration_s, 1)}


def _overload_window_stats(recs: List["OverloadRec"], multiplier: float,
                           rate_per_s: float, t0_us: int,
                           span_us: int) -> dict:
    """Fold one sweep window's ledger: goodput vs offered, per-class
    open-loop quantiles, shed rate, and the retry-after honor trail.
    Goodput counts acks landing INSIDE the arrival span over that span —
    the steady-state service rate; the drain tail (late retries settling
    after arrivals stop) is reported separately so windows with different
    retry-tail shapes stay comparable."""
    n = len(recs)
    last_end = max([r.end_us for r in recs if r.end_us is not None],
                   default=t0_us)
    span_s = max(1e-9, span_us / 1e6)
    acked = sum(1 for r in recs if r.outcome == "ack")
    acked_in_span = sum(1 for r in recs if r.outcome == "ack"
                        and r.end_us <= t0_us + span_us)
    submit_span_s = max(1e-9, (max(r.submit_us or t0_us for r in recs)
                               - t0_us) / 1e6) if recs else 1e-9
    classes: Dict[str, dict] = {}
    for pri in ("high", "normal", "best_effort"):
        sub = [r for r in recs if r.priority == pri]
        lat = sorted(max(0, r.end_us - r.intended_us) for r in sub
                     if r.outcome == "ack")

        def q(p: float) -> Optional[int]:
            return lat[min(len(lat) - 1, int(p * len(lat)))] if lat else None
        classes[pri] = {
            "count": len(sub), "acked": len(lat),
            "shed": sum(1 for r in sub if r.outcome == "shed"),
            "open_p50_us": q(0.50), "open_p99_us": q(0.99)}
    nacks = sum(r.qos_nacks for r in recs)
    retried = sum(r.retried for r in recs)
    honored = sum(r.honored for r in recs)
    pooled = sorted(max(0, r.end_us - r.intended_us) for r in recs
                    if r.outcome == "ack")

    def pq(p: float) -> Optional[int]:
        return pooled[min(len(pooled) - 1,
                          int(p * len(pooled)))] if pooled else None
    return {
        "multiplier": multiplier,
        "offered_per_s": round(rate_per_s, 1),
        "actual_offered_per_s": round(n / submit_span_s, 1),
        "ops": n,
        "acked": acked,
        "shed": sum(1 for r in recs if r.outcome == "shed"),
        # sheds applied at the client by flow suppression (never sent;
        # attempts == 0) — a subset of "shed", split out for transparency
        "client_shed": sum(1 for r in recs
                           if r.outcome == "shed" and r.attempts == 0),
        "failed": sum(1 for r in recs if r.outcome == "fail"),
        "pending": sum(1 for r in recs if r.outcome is None),
        "goodput_per_s": round(acked_in_span / span_s, 1),
        "drain_s": round(max(0.0, (last_end - t0_us - span_us) / 1e6), 3),
        "open_p50_us": pq(0.50), "open_p99_us": pq(0.99),
        "shed_rate": round(sum(1 for r in recs if r.outcome == "shed")
                           / n, 4) if n else 0.0,
        "qos_nacks": nacks,
        "retries": retried,
        "retry_honor_rate": round(honored / retried, 4) if retried else None,
        "classes": classes,
    }


def run_overload_tcp(profile: str = "uniform", schedule: str = "poisson",
                     seed: int = 23, nodes: int = 3, keys: int = 64,
                     n_shards: int = 4,
                     multipliers=(0.5, 1.0, 2.0, 3.0, 5.0, 7.0, 10.0),
                     window_s: float = 6.0, max_window_ops: int = 9000,
                     probe_ops: int = 200, probe_concurrency: int = 8,
                     capacity_per_s: Optional[float] = None,
                     high_frac: float = 0.15, normal_frac: float = 0.35,
                     max_retries: int = 2, gap_s: float = 1.5,
                     settle_timeout_s: float = 30.0,
                     want_phases: bool = True) -> OpenLoopResult:
    """The slo-overload lane: an open-loop sweep over the live TCP cluster
    from below to far past its measured capacity, with mixed tenants and
    priority classes, the client honoring every QoS nack's
    `retry_after_us` hint (jittered exponential backoff, bounded retries).

    Sequence: (1) closed-loop capacity probe anchors the multipliers;
    (2) one paced open-loop window per multiplier.  The `high` class is a
    FIXED-RATE foreground — `high_frac` of CAPACITY, constant across
    windows — while the bulk tiers (`normal_frac` `normal`, rest
    `best_effort`, across tenants t0..t2) scale with the offered
    multiplier.  That is what an SLO-protection test measures: a constant
    paying workload whose latency must hold while background load runs
    away.  (If high scaled with the multiplier, its tail at 10x would be
    dominated by high-vs-high key conflicts — which admission can never
    shed — and the measurement would say nothing about the QoS tier.)
    The default profile is UNIFORM, deliberately: this lane measures the
    ADMISSION tier, and a skewed profile's hot-key dependency chains add
    an execution-side tail (a high txn must wait for every uncommitted
    conflicting predecessor to commit — a wait no admission policy can
    shed, since those predecessors were already admitted) that drowns the
    signal being tested.  Conflict-heavy latency behavior has its own
    lanes (slo-mixed, slo-zipf1m).  Each window is drained to quiescence
    with a decay gap before the next so one window's pressure does not
    bleed into the next's ledger; (3) the full ledger folds into
    the standard SLO report plus an `overload` section: the
    goodput-vs-offered curve, per-class open-loop p99, shed rate,
    retry-after honor rate, and the exact client-side accounting identity
    (acked + shed + failed + pending == submitted, per window).

    An op nacked by QoS admission is retried after at least the hinted
    delay (open-loop latency still charges from the ORIGINAL intended
    start, so honored backoff is paid by the tail, not hidden); an op
    whose retry budget is exhausted settles as shed.  The node processes
    read ACCORD_QOS* from the ambient environment — the caller arms the
    tier, this driver only exercises it."""
    import heapq

    from accord_tpu.host.tcp import TcpClusterClient

    import random as _random

    rng = RandomSource(seed)
    prof = make_profile(profile, keys=keys, seed=rng.next_long())
    origin_rng = rng.fork()
    backoff_rng = _random.Random(seed ^ 0xBACC0FF)  # stdlib .random() API
    mix_rng = rng.fork()

    def now_us() -> int:
        return int(time.time() * 1e6)

    client = TcpClusterClient(n_nodes=nodes, n_shards=n_shards)
    all_records: List[OverloadRec] = []
    windows: List[dict] = []
    summary = None
    t0_us = now_us()
    try:
        probe = _probe_capacity(client, prof, origin_rng, nodes,
                                probe_ops, probe_concurrency)
        capacity = capacity_per_s if capacity_per_s else probe["per_s"]
        if capacity <= 0:
            raise RuntimeError(f"capacity probe found a dead cluster: "
                               f"{probe}")
        time.sleep(gap_s)

        for widx, mult in enumerate(multipliers):
            rate = capacity * mult
            ops = min(max_window_ops, max(40, int(rate * window_s)))
            offsets = make_offsets_us(schedule, rate, ops,
                                      seed=rng.next_long())
            # fresh profile on a DISJOINT token range per window: the
            # list registers are append-only, so re-touching the probe's
            # (or an earlier window's) hot keys would grow every read
            # reply all sweep long and later windows would measure list
            # length, not overload behavior
            tok_off = (widx + 1) * keys
            wprof = make_profile(profile, keys=keys, seed=rng.next_long())
            ops_list = []
            for _ in range(ops):
                op = wprof.next_op()
                ops_list.append(Op(
                    reads=tuple(t + tok_off for t in op.reads),
                    appends={t + tok_off: v
                             for t, v in op.appends.items()},
                    ephemeral=op.ephemeral))
            origins = [1 + origin_rng.next_int(nodes) for _ in range(ops)]
            base = (widx + 1) * 1_000_000
            t0w = now_us()
            recs: List[OverloadRec] = []
            # high is high_frac of CAPACITY, not of offered load: the
            # foreground stays constant while the bulk flood scales
            p_high = min(1.0, high_frac / mult) if mult > 0 else high_frac
            for i, off in enumerate(offsets):
                roll = mix_rng.next_float()
                pri = ("high" if roll < p_high
                       else "normal" if roll < p_high + normal_frac
                       else "best_effort")
                recs.append(OverloadRec(i, t0w + off, widx,
                                        f"t{mix_rng.next_int(3)}", pri))
            by_req = {base + i: recs[i] for i in range(ops)}

            def submit(i: int) -> None:
                rec = recs[i]
                rec.attempts += 1
                if rec.submit_us is None:
                    rec.submit_us = now_us()
                op = ops_list[i]
                client.submit(origins[i], op.reads, op.appends, base + i,
                              want_phases=want_phases, tenant=rec.tenant,
                              priority=rec.priority)

            retryq: list = []  # (due_us, req, nack_at_us, hint_us)
            unfinished = ops
            # client-side flow control: a qos nack's retry_after_us is
            # honored for the whole (origin, tenant, priority) FLOW, not
            # just the nacked op — new bulk-tier ops of a suppressed flow
            # are shed at the client without a round trip.  This is the
            # other half of admission control: without it the nack flood
            # itself saturates the host boundary at deep overload and
            # every class pays the queueing tax.  high is never
            # suppressed (the server never sheds it).  Retries are still
            # sent on their own backoff — they are the probes that
            # refresh the hint.
            suppress_until: Dict[tuple, int] = {}

            def handle(frame) -> bool:
                nonlocal unfinished
                body = frame.get("body", {})
                if body.get("type") != "submit_reply":
                    return False
                rec = by_req.get(body.get("req"))
                if rec is None:
                    return False  # stale frame from a previous window
                if rec.outcome is not None:
                    return False
                if body.get("ok"):
                    rec.end_us = now_us()
                    rec.outcome = "ack"
                    if body.get("phases"):
                        rec.phase_firsts = [(ph, at) for ph, at
                                            in body["phases"]]
                    unfinished -= 1
                    return True
                if body.get("qos"):
                    rec.qos_nacks += 1
                    if rec.priority != "high":
                        flow = (origins[rec.idx], rec.tenant, rec.priority)
                        until = now_us() + int(
                            body.get("retry_after_us") or 0)
                        if until > suppress_until.get(flow, 0):
                            suppress_until[flow] = until
                    # best_effort gets one fewer retry than the paying
                    # classes: its nacks at deep overload are near-certain
                    # to repeat, and the attempt flood is load too
                    budget = (max_retries if rec.priority != "best_effort"
                              else max(0, max_retries - 1))
                    if rec.attempts <= budget:
                        hint = int(body.get("retry_after_us") or 0)
                        back = client.qos_backoff_us(
                            body, attempt=rec.attempts, rng=backoff_rng)
                        heapq.heappush(retryq,
                                       (now_us() + back, base + rec.idx,
                                        now_us(), hint))
                        return True
                    rec.end_us = now_us()
                    rec.outcome = "shed"
                    unfinished -= 1
                    return True
                rec.end_us = now_us()
                rec.outcome = "shed" if body.get("shed") else "fail"
                unfinished -= 1
                return True

            sent = 0
            deadline = (time.monotonic() + (offsets[-1] if offsets else 0)
                        / 1e6 + settle_timeout_s)
            while unfinished > 0 and time.monotonic() < deadline:
                now = now_us()
                while retryq and retryq[0][0] <= now:
                    _due, req, nack_at, hint = heapq.heappop(retryq)
                    rec = by_req[req]
                    rec.retried += 1
                    if now - nack_at >= hint:
                        rec.honored += 1
                    submit(rec.idx)
                if sent < ops and now >= recs[sent].intended_us:
                    nrec = recs[sent]
                    if (nrec.priority != "high"
                            and suppress_until.get(
                                (origins[sent], nrec.tenant,
                                 nrec.priority), 0) > now):
                        # flow suppressed: client-side shed, attempts
                        # stays 0 (how window stats tell these apart)
                        nrec.end_us = now
                        nrec.outcome = "shed"
                        unfinished -= 1
                    else:
                        submit(sent)
                    sent += 1
                    # drain ready replies before the next arrival: when
                    # the client runs behind schedule it submits back to
                    # back, and without this the acks age unread in the
                    # inbox — inflating measured open-loop latency with
                    # client queueing, not server behavior
                    while True:
                        frame = client.recv(0)
                        if frame is None:
                            break
                        handle(frame)
                    continue
                next_due = min(
                    [recs[sent].intended_us] if sent < ops else [],
                    default=retryq[0][0] if retryq else now + 50_000)
                if retryq and retryq[0][0] < next_due:
                    next_due = retryq[0][0]
                frame = client.recv(
                    min(0.05, max(0.001, (next_due - now) / 1e6)))
                if frame is not None:
                    handle(frame)
            windows.append(_overload_window_stats(
                recs, mult, rate, t0w, offsets[-1] if offsets else 0))
            all_records.extend(recs)
            time.sleep(gap_s)  # let the lag EWMA decay between windows

        # obs snapshots AFTER the channel quiesces: the merged summary's
        # "qos" section carries the server-side accounting identity
        from accord_tpu.obs.report import merge_node_snapshots
        snaps = [client.fetch_metrics(i, timeout_s=10.0)
                 for i in range(1, nodes + 1)]
        merged = merge_node_snapshots([s for s in snaps if s])
        summary = merged["summary"] if merged["nodes"] else None
    finally:
        client.close()

    total = len(all_records)
    span_s = max(1e-9, (max((r.intended_us for r in all_records),
                            default=t0_us) - t0_us) / 1e6)
    sched = {"kind": schedule, "rate_per_s": round(total / span_s, 1),
             "ops": total, "seed": seed, "host": "tcp-overload"}
    report = _collect(all_records, total / span_s, sched, summary, t0_us)

    def _w(mult: float) -> Optional[dict]:
        for w in windows:
            if w["multiplier"] == mult:
                return w
        return None
    peak = max((w["goodput_per_s"] for w in windows), default=0.0)
    at5, uncontended = _w(5.0), _w(0.5)
    counts = {"submitted": total,
              "acked": sum(w["acked"] for w in windows),
              "shed": sum(w["shed"] for w in windows),
              "failed": sum(w["failed"] for w in windows),
              "pending": sum(w["pending"] for w in windows)}
    counts["exact"] = (counts["acked"] + counts["shed"] + counts["failed"]
                       + counts["pending"] == counts["submitted"])
    retried = sum(w["retries"] for w in windows)
    honored = sum(r.honored for r in all_records)
    report["overload"] = {
        "capacity_probe": probe,
        "capacity_per_s": capacity,
        "windows": windows,
        "peak_goodput_per_s": peak,
        "goodput_at_5x_frac_of_peak":
            round(at5["goodput_per_s"] / peak, 4) if at5 and peak else None,
        # the uncontended baseline for the high class is the 0.5x window's
        # POOLED open-loop p99: nothing sheds there, so priority classes
        # are exchangeable and the pooled quantile is the same distribution
        # at ~10x the sample size of the high slice alone
        "high_p99_uncontended_us": (uncontended or {}).get("open_p99_us"),
        "high_p99_at_5x_us":
            (at5 or {}).get("classes", {}).get("high", {})
            .get("open_p99_us"),
        "retry_honor_rate": round(honored / retried, 4) if retried else None,
        "accounting": counts,
        "server_qos": (summary or {}).get("qos"),
    }
    return OpenLoopResult(all_records, report, summary, sched)


# --------------------------------------------------------- reshard lane ----

def _window_stats(recs: List[OpRecord]) -> dict:
    """Ack rate + open-loop quantiles of one reshard window's records."""
    lat = sorted(max(0, r.end_us - r.intended_us) for r in recs
                 if r.outcome == "ack")

    def q(p: float) -> Optional[int]:
        return lat[min(len(lat) - 1, int(p * len(lat)))] if lat else None
    n = len(recs)
    return {"count": n, "acked": len(lat),
            "shed": sum(1 for r in recs if r.outcome == "shed"),
            "failed": sum(1 for r in recs if r.outcome == "fail"),
            "ack_rate": round(len(lat) / n, 4) if n else None,
            "open_p50_us": q(0.50), "open_p99_us": q(0.99)}


def _reshard_report(records: List[OpRecord], t0_us: int, begin_us: int,
                    end_us: int, bucket_us: int = 1_000_000) -> dict:
    """Fold the ledger around the reshard window: per-window stats,
    1s-bucket availability dip, and time-to-SLO-recovery measured from the
    moment the reshard began (bucket ack rate back >= 95% AND bucket open
    p99 back under max(2x the before-window p99, 100ms))."""
    windows = {
        "before": _window_stats([r for r in records
                                 if r.intended_us < begin_us]),
        "during": _window_stats([r for r in records
                                 if begin_us <= r.intended_us < end_us]),
        "after": _window_stats([r for r in records
                                if r.intended_us >= end_us]),
    }
    buckets: Dict[int, list] = {}
    for r in records:
        b = (r.intended_us - t0_us) // bucket_us
        tot_ack = buckets.setdefault(b, [0, 0, []])
        tot_ack[0] += 1
        if r.outcome == "ack":
            tot_ack[1] += 1
            tot_ack[2].append(max(0, r.end_us - r.intended_us))
    begin_b = (begin_us - t0_us) // bucket_us
    base_p99 = windows["before"]["open_p99_us"] or 0
    thresh_us = max(2 * base_p99, 100_000)
    dip_rates = [ack / tot for b, (tot, ack, _l) in sorted(buckets.items())
                 if b >= begin_b and tot > 0]
    before_rate = windows["before"]["ack_rate"] or 0.0
    recovery_s = None
    for b in sorted(buckets):
        if b < begin_b:
            continue
        tot, ack, lats = buckets[b]
        if tot == 0:
            continue
        lats.sort()
        p99 = lats[min(len(lats) - 1, int(0.99 * len(lats)))] if lats else 0
        if ack / tot >= 0.95 and p99 <= thresh_us:
            recovery_s = round(max(0.0, (t0_us + b * bucket_us - begin_us)
                                   / 1e6), 3)
            break
    min_rate = round(min(dip_rates), 4) if dip_rates else None
    return {
        "windows": windows,
        "availability": {
            "before_ack_rate": before_rate,
            "min_bucket_ack_rate": min_rate,
            "dip_pct": round(max(0.0, (before_rate - (min_rate or 0.0)))
                             * 100.0, 2) if min_rate is not None else None,
            "bucket_s": bucket_us / 1e6,
        },
        "time_to_slo_recovery_s": recovery_s,
    }


def run_reshard_tcp(profile: str = "zipfian", ops: int = 2400,
                    rate_per_s: float = 80.0, schedule: str = "poisson",
                    seed: int = 13, nodes: int = 3, keys: int = 48,
                    n_shards: int = 4, reshard_at_frac: float = 0.33,
                    want_phases: bool = True,
                    settle_timeout_s: float = 90.0,
                    drain_retiring: bool = True) -> OpenLoopResult:
    """The slo-reshard lane: open-loop zipfian over the live TCP cluster
    with a FULL membership change mid-window — a fresh journal-backed node
    joins and bootstraps under load (admin epoch install, one contact,
    gossip convergence), the client re-learns routing from a topology
    frame, and the founding node drains (coordination fenced, in-flight
    handed off, durability watermark awaited) and is retired.

    The ledger is folded around the reshard window into before/during/
    after ack-rate + open-loop p99, a 1s-bucket availability dip, and
    time-to-SLO-recovery; afterwards every acked append is re-read from
    the surviving membership (zero-lost-acks) and the per-node audit
    views are collected (cross-replica digest agreement at quiesce).

    Admin traffic and submit replies share the client's single reply
    inbox: the paced loop stashes non-submit frames into a dict the
    driver thread polls, and the driver only ever sends (socket writes
    are lock-serialized in TcpClusterClient._send)."""
    import threading

    from accord_tpu.host.maelstrom import TOKEN_SPAN
    from accord_tpu.host.tcp import TcpClusterClient

    rng = RandomSource(seed)
    prof = make_profile(profile, keys=keys, seed=rng.next_long())
    offsets = make_offsets_us(schedule, rate_per_s, ops,
                              seed=rng.next_long())
    ops_list = [prof.next_op() for _ in range(ops)]
    assert all(op.ranges is None for op in ops_list), \
        "range ops are sim-only (no wire encoding on the submit frame)"
    span_us = offsets[-1] if offsets else 0

    client = TcpClusterClient(n_nodes=nodes, n_shards=n_shards)
    admin_replies: Dict[str, dict] = {}
    events: List[list] = []  # [label, wall_us] markers from the driver
    driver_err: List[BaseException] = []
    retiring = 1

    def now_us() -> int:
        return int(time.time() * 1e6)

    def mark(label: str) -> None:
        events.append([label, now_us()])

    def admin_wait(req: str, timeout_s: float) -> dict:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            body = admin_replies.pop(req, None)
            if body is not None:
                return body
            time.sleep(0.01)
        raise TimeoutError(f"no admin reply for {req}")

    def node_at_epoch(nid: int, epoch: int, deadline: float) -> dict:
        k = 0
        while time.monotonic() < deadline:
            k += 1
            req = f"rs-topo-{nid}-{k}"
            try:
                client._send(nid, {"type": "topology", "req": req})
                spec = admin_wait(req, 2.0).get("topology") or {}
            except (TimeoutError, OSError):
                continue
            if spec.get("epoch", 0) >= epoch:
                return spec
            time.sleep(0.1)
        raise TimeoutError(f"node {nid} never reached epoch {epoch}")

    def reshard_driver(t0_us: int) -> None:
        try:
            target_us = t0_us + int(reshard_at_frac * span_us)
            while now_us() < target_us:
                time.sleep(0.01)
            mark("reshard_begin")
            joined = client.add_node()
            mark("node_added")
            # epoch 2 replaces the retiring founder with the joiner:
            # replicas rotated over the surviving membership, same even
            # token split every host transport uses
            ids2 = sorted([i for i in range(1, nodes + 1)
                           if i != retiring] + [joined])
            rf = min(3, len(ids2))
            width = TOKEN_SPAN // n_shards
            shards = [[i * width,
                       TOKEN_SPAN if i == n_shards - 1 else (i + 1) * width,
                       [ids2[(i + j) % len(ids2)] for j in range(rf)]]
                      for i in range(n_shards)]
            req = "rs-epoch-2"
            client._send(retiring, {
                "type": "epoch", "req": req,
                "topology": {"epoch": 2, "shards": shards,
                             "peers": [[joined] +
                                       list(client.peers[joined])]}})
            admin_wait(req, 30.0)
            mark("epoch_acked")
            deadline = time.monotonic() + 60.0
            spec = None
            for nid in ids2 + [retiring]:
                spec = node_at_epoch(nid, 2, deadline)
            mark("epoch_converged")
            # routing refresh: the paced loop routes by owner_of, which
            # reads this spec — without it the client submits against the
            # pre-reshard ownership map forever
            client.topology_spec = spec
            mark("routing_refreshed")
            if drain_retiring:
                req = f"rs-drain-{retiring}"
                client._send(retiring, {"type": "drain", "req": req,
                                        "timeout_s": 45.0})
                body = admin_wait(req, 60.0)
                mark("drain_ok" if body.get("durable") else "drain_undurable")
                client.kill_node(retiring)
                mark("retired")
            mark("reshard_end")
        except BaseException as e:  # noqa: BLE001
            driver_err.append(e)
            mark("reshard_failed")

    summary = None
    audit_views = {}
    lost: List[tuple] = []
    acked_appends = 0
    verified_keys = 0
    try:
        t0_us = now_us()
        records = [OpRecord(i, t0_us + off) for i, off in enumerate(offsets)]

        def handle(frame) -> bool:
            body = frame.get("body", {})
            typ = body.get("type")
            if typ != "submit_reply":
                if typ in ("epoch_ok", "topology_reply", "drain_ok"):
                    admin_replies[body.get("req")] = body
                return False
            req = body.get("req")
            if not isinstance(req, int):
                return False
            rec = records[req]
            rec.end_us = now_us()
            if body.get("ok"):
                rec.outcome = "ack"
                if body.get("phases"):
                    rec.phase_firsts = [(ph, at) for ph, at
                                        in body["phases"]]
            elif body.get("shed"):
                rec.outcome = "shed"
            else:
                rec.outcome = "fail"
            return True

        driver = threading.Thread(target=reshard_driver, args=(t0_us,),
                                  daemon=True)
        driver.start()

        sent = pending = 0
        while sent < ops:
            due_us = records[sent].intended_us
            now = now_us()
            if now < due_us:
                frame = client.recv(min(0.05, (due_us - now) / 1e6))
                if frame is not None and handle(frame):
                    pending -= 1
                continue
            op = ops_list[sent]
            tok0 = next(iter(op.reads), None)
            if tok0 is None and op.appends:
                tok0 = next(iter(op.appends))
            records[sent].submit_us = now_us()
            try:
                client.submit(client.owner_of(tok0 or 0), op.reads,
                              op.appends, sent, ephemeral=op.ephemeral,
                              want_phases=want_phases)
            except OSError:
                records[sent].end_us = now_us()
                records[sent].outcome = "fail"
                sent += 1
                continue
            sent += 1
            pending += 1
        deadline = time.monotonic() + settle_timeout_s
        while pending > 0 and time.monotonic() < deadline:
            frame = client.recv(1.0)
            if frame is not None and handle(frame):
                pending -= 1
        # keep pumping the shared inbox while the driver finishes — its
        # admin replies (epoch_ok / topology_reply / drain_ok) only reach
        # the stash through handle()
        deadline = time.monotonic() + 120.0
        while driver.is_alive() and time.monotonic() < deadline:
            frame = client.recv(0.2)
            if frame is not None and handle(frame):
                pending -= 1
        driver.join(timeout=5.0)

        # zero-lost-acks: every acked append must be readable from the
        # surviving membership (final reads through the refreshed routing)
        acked_by_key: Dict[int, List[int]] = {}
        for i, rec in enumerate(records):
            if rec.outcome == "ack":
                for tok, val in ops_list[i].appends.items():
                    acked_by_key.setdefault(tok, []).append(val)
                    acked_appends += 1
        final_reads: Dict[int, list] = {}
        outstanding = set()
        for tok in acked_by_key:
            req = f"fr-{tok}"
            client.submit(client.owner_of(tok), [tok], {}, req)
            outstanding.add(req)
        deadline = time.monotonic() + 60.0
        while outstanding and time.monotonic() < deadline:
            frame = client.recv(1.0)
            if frame is None:
                continue
            body = frame.get("body", {})
            req = body.get("req")
            if body.get("type") == "submit_reply" and req in outstanding:
                outstanding.discard(req)
                if body.get("ok"):
                    for tok, vals in (body.get("reads") or {}).items():
                        final_reads[int(tok)] = vals
        for tok, vals in sorted(acked_by_key.items()):
            got = final_reads.get(tok)
            if got is None:
                lost.append((tok, "unread", len(vals)))
                continue
            verified_keys += 1
            for val in vals:
                if val not in got:
                    lost.append((tok, "missing", val))

        # audit agreement at quiesce: the cross-replica digest rounds are
        # watermark-negotiated, so any recorded divergence is real
        live = sorted(n for n in range(1, len(client.procs) + 1)
                      if not (drain_retiring and n == retiring))
        for nid in live:
            view = client.fetch_audit(nid, timeout_s=10.0)
            if view:
                audit_views[nid] = len(view.get("divergences") or [])
        from accord_tpu.obs.report import merge_node_snapshots
        snaps = [client.fetch_metrics(n, timeout_s=10.0) for n in live]
        merged = merge_node_snapshots([s for s in snaps if s])
        summary = merged["summary"] if merged["nodes"] else None
    finally:
        client.close()

    if driver_err:
        raise RuntimeError(f"reshard driver failed: {driver_err[0]!r}; "
                           f"events={events}") from driver_err[0]
    marks = dict((label, at) for label, at in events)
    begin_us = marks.get("reshard_begin", t0_us)
    end_us = marks.get("reshard_end", begin_us)
    sched = {"kind": schedule, "rate_per_s": rate_per_s, "ops": ops,
             "seed": seed, "host": "tcp-reshard"}
    report = _collect(records, rate_per_s, sched, summary, t0_us)
    reshard = _reshard_report(records, t0_us, begin_us, end_us)
    reshard["events"] = [[label, round((at - t0_us) / 1e6, 3)]
                         for label, at in events]
    reshard["lost_acks"] = len(lost)
    reshard["lost_detail"] = lost[:16]
    reshard["acked_appends"] = acked_appends
    reshard["verified_keys"] = verified_keys
    reshard["audit"] = {"divergences_by_node": audit_views,
                        "agree": all(v == 0 for v in audit_views.values())
                        and bool(audit_views)}
    reshard["joined_node"] = nodes + 1
    reshard["retired_node"] = retiring if drain_retiring else None
    report["reshard"] = reshard
    return OpenLoopResult(records, report, summary, sched)
